#!/bin/bash
# TPU recovery watcher, round 20: eighteen configs want on-chip
# records (greens from r07-r17 carry over; chordax-tower joins the
# want list). Wait for the chip to be free, probe the remote-compile
# service (dead since round 4: connection-refused on its port while
# cached programs kept executing), and when it answers, run the
# configs without a green record one at a time into
# BENCH_ATTEMPT_r20.jsonl (bench's _record_lkg promotes each green
# on-chip record into BENCH_LKG.json). On-chip attempts keep the
# --trace device-timeline archiving (now into BENCH_TRACE_r20). All
# prior gates stay (wire-isolated binary >= 3x JSON keys/s at <= 1/2
# p50, traced chain, havoc scenario matrix >= 99% availability, pulse
# + fastlane + fuse + lens + mesh + elastic + edge smokes, zero
# retraces).
# NEW in round 20 (chordax-tower): a TOWER SMOKE pre-bench gate — the
# fleet-observability plane against a real 4-process ring: collector
# + fleet-wide exemplar capture costing <= 1.05x the closed-loop p50,
# ONE hedged cross-shard request stitched into a Chrome export with
# pid lanes from >= 2 child processes (byte-identical re-stitch),
# slow-trace ranking served entirely from the incremental span pool
# (ZERO retraces), a seeded whole-process partition producing a
# merged incident timeline ordered plan_installed -> breaker_open ->
# slo_breach -> rejoin -> slo_recovered, black-box canary
# availability within 1 point of an independent mirror measurement,
# zero steady-state retraces in every process — must pass on CPU
# before anything claims the chip. The smoke's stitched trace +
# incident timeline archive next to this round's records. The
# want-list headline stays the fuse on-chip record + the IDA A/B +
# the lens cost table + the mesh/elastic/edge process records, now
# joined by the tower config's overhead A/B + stitched-trace +
# incident record. Never kills anything mid-TPU-work; every probe
# and bench attempt runs to completion (a blocked fresh-shape jit
# takes ~25 min to fail — that is the probe's cost when the service
# is down, accepted).
cd /root/repo
log() { echo "[tpu_watch] $1 $(date -u +%H:%M:%S)" >> tpu_watch.log; }
log "round-20 watcher start (eighteen configs + wire/havoc/pulse/fastlane/fuse/lens/mesh/elastic/edge/tower smoke gates)"

needed() {  # configs without a green record yet (r07-r17 greens count)
  python - <<'EOF'
import json
ok = set()
for attempt in ("BENCH_ATTEMPT_r07.jsonl", "BENCH_ATTEMPT_r08.jsonl",
                "BENCH_ATTEMPT_r09.jsonl", "BENCH_ATTEMPT_r10.jsonl",
                "BENCH_ATTEMPT_r11.jsonl", "BENCH_ATTEMPT_r12.jsonl",
                "BENCH_ATTEMPT_r13.jsonl", "BENCH_ATTEMPT_r14.jsonl",
                "BENCH_ATTEMPT_r15.jsonl", "BENCH_ATTEMPT_r16.jsonl",
                "BENCH_ATTEMPT_r17.jsonl", "BENCH_ATTEMPT_r20.jsonl"):
    try:
        for line in open(attempt):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("config") and rec.get("value") is not None:
                ok.add(rec["config"])
    except FileNotFoundError:
        pass
want = ["chord16", "ida", "dhash", "dhash_sharded", "lookup_1m",
        "sweep_10m", "serve", "gateway", "repair", "membership",
        "pulse", "fastlane", "fuse", "lens", "mesh", "elastic",
        "edge", "tower"]
print(" ".join(c for c in want if c not in ok))
EOF
}

for i in $(seq 1 80); do
  # Phase 0 each cycle: never contend with a bench holding the chip.
  while pgrep -f "python bench.py" > /dev/null; do
    sleep 60
  done
  CONFIGS=$(needed)
  if [ -z "$CONFIGS" ]; then
    log "all eighteen configs recorded green — done"
    exit 0
  fi
  log "attempt $i; pending: $CONFIGS"
  # chordax-lint gate (ISSUE 3, grown through ISSUE 18: all seven
  # passes — trace/gspmd+registry/locks/metrics/epochs/lifecycle/
  # verbs): a finding means this tree is not the code we want
  # hardware evidence for — fail the cycle before any bench touches
  # the chip. CPU-pinned so the gate never claims the TPU (same
  # etiquette as the dryrun respawn). The machine-readable findings
  # artifact archives next to this round's bench records either way,
  # so a red gate leaves evidence of WHAT drifted, not just that
  # something did.
  if ! JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      python -m p2p_dhts_tpu.analysis --strict \
        --json "LINT_r${i}.json" >> tpu_watch.log 2>&1; then
    log "chordax-lint gate FAILED - fix findings before benching (see LINT_r${i}.json)"
    sleep 300
    continue
  fi
  # Gateway smoke (ISSUE 4 + ISSUE 8 + ISSUE 9): the RPC->gateway->
  # engine front door must pass its CPU smoke — the tracing-enabled
  # closed loop (p50 within 10% of untraced), the linked
  # RPC->gateway->engine->batch span chain over the BINARY transport,
  # both-transport side-by-side numbers, and the hard chordax-wire
  # gate (wire-isolated batched path: binary >= 3x JSON keys/s at
  # <= 1/2 p50) — before any bench touches the chip.
  if ! JAX_PLATFORMS=cpu python bench.py --config gateway --smoke \
      >> tpu_watch.log 2>&1; then
    log "gateway smoke FAILED - fix the front door before benching"
    sleep 300
    continue
  fi
  # Repair smoke (ISSUE 6): quorum-PUT parity, churned-pair convergence
  # and zero repair-path retraces must hold on CPU before the repair
  # config (or anything else) claims the chip.
  if ! JAX_PLATFORMS=cpu python bench.py --config repair --smoke \
      >> tpu_watch.log 2>&1; then
    log "repair smoke FAILED - fix the control plane before benching"
    sleep 300
    continue
  fi
  # Membership smoke (ISSUE 7): >=99% availability through the churn
  # storm, zero churn-path retraces, bounded convergence and oracle
  # ownership parity must hold on CPU before anything claims the chip.
  if ! JAX_PLATFORMS=cpu python bench.py --config membership --smoke \
      >> tpu_watch.log 2>&1; then
    log "membership smoke FAILED - fix the churn plane before benching"
    sleep 300
    continue
  fi
  # Havoc smoke (ISSUE 10): the fault-injection scenario matrix must
  # hold — >=99% availability under lossy wire and a flapping ring,
  # byte-identical same-seed fault schedules, the poison lane failing
  # alone, 100% readable post-fault, zero retraces — on CPU before
  # anything claims the chip.
  if ! JAX_PLATFORMS=cpu python bench.py --config havoc --smoke \
      >> tpu_watch.log 2>&1; then
    log "havoc smoke FAILED - fix the degradation machinery before benching"
    sleep 300
    continue
  fi
  # Pulse smoke (ISSUE 11): continuous telemetry must hold — sampler
  # overhead <= 5% p50 on the gateway closed loop, SLO verdicts OK on
  # the healthy run and BREACH -> flight incident -> recovery under
  # the seeded lossy-wire scenario (polled over the PULSE verb
  # mid-bench), one linked digest->diff->heal repair trace, zero
  # retraces — on CPU before anything claims the chip. The sampled
  # series artifact lands next to this round's records.
  mkdir -p BENCH_TRACE_r20
  if ! JAX_PLATFORMS=cpu \
      CHORDAX_PULSE_SERIES=BENCH_TRACE_r20/pulse_series_smoke.json \
      python bench.py --config pulse --smoke \
      >> tpu_watch.log 2>&1; then
    log "pulse smoke FAILED - fix the telemetry plane before benching"
    sleep 300
    continue
  fi
  # Fastlane smoke (ISSUE 12): the zero-copy serving path must hold —
  # wire-isolated 1M-key vector >= 3x JSON keys/s at <= 1/2 p50, a
  # real 1M-key binary vector RPC with ZERO per-key python and
  # direct-engine parity, Zipf hot-key cache hit rate > 80% with
  # cache-hit p50 under the uncached round trip, the PUT-invalidation
  # check, and zero retraces — on CPU before anything claims the chip.
  if ! JAX_PLATFORMS=cpu python bench.py --config fastlane --smoke \
      >> tpu_watch.log 2>&1; then
    log "fastlane smoke FAILED - fix the zero-copy path before benching"
    sleep 300
    continue
  fi
  # Fuse smoke (ISSUE 13): the multi-kind super-batch path must hold —
  # mixed fs/get/fi closed loop >= 1.25x the unfused kind-by-kind
  # drain at equal-or-better p50, byte-exact three-kind parity inside
  # one fused batch, the FIFO straddle assert (a put splits the fused
  # read groups), zero retraces, and the IDA backend registry decoding
  # byte-identical fragments (pallas timing skipped on CPU with its
  # interpret-mode reason) — on CPU before anything claims the chip.
  if ! JAX_PLATFORMS=cpu python bench.py --config fuse --smoke \
      >> tpu_watch.log 2>&1; then
    log "fuse smoke FAILED - fix the fused dispatch before benching"
    sleep 300
    continue
  fi
  # Lens smoke (ISSUE 14): the cost-accounting/capacity plane must
  # hold — accounting overhead <= 5% closed-loop p50 vs the disabled
  # baseline, headroom within 2x of measured saturation keys/s,
  # non-empty cost table + warmup-only compile-cause ledger with zero
  # retraces, CAPACITY verb + lens.* pulse series polled live — on
  # CPU before anything claims the chip. The smoke's profile report
  # (Chrome export + rendered per-kind cost breakdown) archives next
  # to this round's records.
  if ! JAX_PLATFORMS=cpu \
      CHORDAX_LENS_PROFILE=BENCH_TRACE_r20/lens_profile_smoke \
      python bench.py --config lens --smoke \
      >> tpu_watch.log 2>&1; then
    log "lens smoke FAILED - fix the cost/capacity plane before benching"
    sleep 300
    continue
  fi
  # Mesh smoke (ISSUE 15): the multi-process topology must hold — a
  # real 4-process localhost ring bootstrapped over JOIN_RING/
  # HEARTBEAT, byte-exact forwarded-vs-local parity over 1000 keys,
  # the coalesced forward path >= 3x the per-key-forward baseline at
  # equal-or-better p50 (and >= 0.5x the local path), >= 99%
  # availability while one whole process is havoc-partitioned and
  # rejoins, zero steady-state retraces in EVERY process polled over
  # HEALTH — on CPU before anything claims the chip.
  if ! JAX_PLATFORMS=cpu python bench.py --config mesh --smoke \
      >> tpu_watch.log 2>&1; then
    log "mesh smoke FAILED - fix the sharded topology before benching"
    sleep 300
    continue
  fi
  # Elastic smoke (ISSUE 16): the autoscaling control plane must hold
  # — the REAL RingPolicy splits the hammered ring 1->2 (churn-grow +
  # heal-first + ONE atomic swap), merges it back when the load
  # stops, >= 99% availability under the probing reader the whole
  # ramp, every acked write byte-readable after the merge, EXACTLY 2
  # executed actions (flap suppression), the seeded decision ledger
  # replaying digest-identical, zero steady-state retraces on every
  # engine the policy built — on CPU before anything claims the
  # chip. The smoke's ledger archives next to this round's records.
  if ! JAX_PLATFORMS=cpu \
      CHORDAX_ELASTIC_LEDGER=BENCH_TRACE_r20/elastic_ledger_smoke.json \
      python bench.py --config elastic --smoke \
      >> tpu_watch.log 2>&1; then
    log "elastic smoke FAILED - fix the control plane before benching"
    sleep 300
    continue
  fi
  # Edge smoke (ISSUE 17): the zero-hop client SDK must hold — 1000-key
  # routed-vs-forwarded byte parity with every process's gateway
  # forward counters frozen across the routed run (the hop is deleted,
  # not hidden), client-routed keys/s beating the gateway-forwarded
  # baseline at equal-or-better p50, the hedged tail run cutting p99
  # under a seeded 4% server stall while hedging <= ~5% of requests,
  # the stale-route storm (a live JOIN re-split mid-burst) healing in
  # ONE refresh round per client at >= 99% availability with zero
  # steady-state refresh traffic after convergence, zero retraces in
  # every process polled over HEALTH — on CPU before anything claims
  # the chip.
  if ! JAX_PLATFORMS=cpu python bench.py --config edge --smoke \
      >> tpu_watch.log 2>&1; then
    log "edge smoke FAILED - fix the client rim before benching"
    sleep 300
    continue
  fi
  # Tower smoke (ISSUE 20): the fleet-observability plane must hold —
  # collector + fleet-wide exemplar capture <= 1.05x the closed-loop
  # p50, one hedged cross-shard request stitched into a Chrome export
  # with pid lanes from >= 2 child processes (byte-identical
  # re-stitch), slow-trace ranking from the incremental pool with
  # ZERO retraces, the seeded whole-process partition producing a
  # merged incident timeline ordered plan_installed -> breaker_open
  # -> slo_breach -> rejoin -> slo_recovered, canary availability
  # within 1 point of the independent mirror, zero steady-state
  # retraces in every process — on CPU before anything claims the
  # chip. The smoke's stitched trace + incident timeline archive next
  # to this round's records.
  if ! JAX_PLATFORMS=cpu python bench.py --config tower --smoke \
      >> tpu_watch.log 2>&1; then
    log "tower smoke FAILED - fix the observability plane before benching"
    sleep 300
    continue
  fi
  cp -f TOWER_TRACE.json BENCH_TRACE_r20/tower_trace_smoke.json \
      2>/dev/null || true
  cp -f TOWER_TIMELINE.md BENCH_TRACE_r20/tower_timeline_smoke.md \
      2>/dev/null || true
  # Gentle compile-service probe: tiny jit with a fresh shape (a salted
  # length so the persistent cache can't mask a dead service).
  if python - >> tpu_watch.log 2>&1 <<EOF
import jax, jax.numpy as jnp, numpy as np
x = jnp.arange(2000 + $i)          # new shape each try -> forces a compile
y = jax.jit(lambda v: (v * 3 + 1).cumsum())(x)
assert int(np.asarray(y)[-1]) >= 0
print("compile service OK")
EOF
  then
    mkdir -p BENCH_TRACE_r20
    for c in $CONFIGS; do
      log "running --config $c (device trace -> BENCH_TRACE_r20/$c)"
      # The pulse config archives its sampled series + verdicts, the
      # lens config its ANALYZED profile (Chrome export + per-kind
      # cost-breakdown markdown), and the elastic config its decision
      # ledger (ring tier + mesh tier), next to this round's records
      # (the mid-bench PULSE/HEALTH/CAPACITY polls are inside the
      # configs themselves).
      CHORDAX_PULSE_SERIES="BENCH_TRACE_r20/pulse_series_$c.json" \
        CHORDAX_LENS_PROFILE="BENCH_TRACE_r20/lens_profile_$c" \
        CHORDAX_ELASTIC_LEDGER="BENCH_TRACE_r20/elastic_ledger_$c.json" \
        python bench.py --config "$c" --trace "BENCH_TRACE_r20" \
        >> BENCH_ATTEMPT_r20.jsonl 2>> BENCH_ATTEMPT_r20.err
      log "config $c rc=$?"
      if [ "$c" = "tower" ]; then
        # The tower config's stitched trace + incident timeline are
        # the record's evidence — archive them with the round.
        cp -f TOWER_TRACE.json BENCH_TRACE_r20/tower_trace.json \
            2>/dev/null || true
        cp -f TOWER_TIMELINE.md BENCH_TRACE_r20/tower_timeline.md \
            2>/dev/null || true
      fi
      # Digest the round's trajectory after each record lands: the
      # stale-flagged table is the artifact a reviewer reads first.
      python -m p2p_dhts_tpu.lens.bench_report \
        --out BENCH_TRACE_r20/trajectory.md >> tpu_watch.log 2>&1
    done
  else
    log "compile service still down"
  fi
  sleep 300
done
log "gave up"

#!/bin/bash
# TPU tunnel watcher: probe gently until the backend comes back, then run
# the full benchmark immediately (VERDICT r3 #1 — capture hardware numbers
# the moment the wedged claim clears). Never kills a probe mid-work: each
# attempt runs to completion (a wedged claim blocks ~25 min then errors).
cd /root/repo
for i in $(seq 1 40); do
  echo "[tpu_watch] attempt $i $(date -u +%H:%M:%S)" >> tpu_watch.log
  if python -c "import jax; jax.devices()" >> tpu_watch.log 2>&1; then
    echo "[tpu_watch] BACKEND UP $(date -u +%H:%M:%S) — running bench" >> tpu_watch.log
    python bench.py > BENCH_ATTEMPT_r04.jsonl 2> BENCH_ATTEMPT_r04.err
    echo "[tpu_watch] bench rc=$? $(date -u +%H:%M:%S)" >> tpu_watch.log
    exit 0
  fi
  sleep 300
done
echo "[tpu_watch] gave up $(date -u +%H:%M:%S)" >> tpu_watch.log

#!/bin/bash
# TPU recovery watcher: wait for the current bench process to exit, then
# probe the remote-compile service (the component that died mid-run this
# round: 127.0.0.1:8083 connection-refused while plain executions kept
# working) and rerun the configs that failed, one at a time, appending to
# the attempt files. Never kills anything mid-TPU-work; every probe and
# bench attempt runs to completion.
cd /root/repo
log() { echo "[tpu_watch] $1 $(date -u +%H:%M:%S)" >> tpu_watch.log; }

# Phase 0: wait out any bench already holding the chip.
while pgrep -f "python bench.py" > /dev/null; do
  sleep 60
done
log "chip free"

needed() {  # configs without a successful record yet
  python - <<'EOF'
import json
ok = set()
try:
    for line in open("BENCH_ATTEMPT_r04.jsonl"):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("config") and rec.get("value") is not None:
            ok.add(rec["config"])
except FileNotFoundError:
    pass
# ida re-measures if its record predates the pallas field
redo_ida = True
try:
    for line in open("BENCH_ATTEMPT_r04.jsonl"):
        rec = json.loads(line)
        if rec.get("config") == "ida" and "decode_pallas_mb_s" in rec \
                and rec.get("decode_pallas_mb_s") is not None:
            redo_ida = False
except Exception:
    pass
want = ["dhash_sharded", "lookup_1m", "sweep_10m"]
if redo_ida:
    want.insert(0, "ida")
print(" ".join(c for c in want if c not in ok or c == "ida"))
EOF
}

for i in $(seq 1 60); do
  CONFIGS=$(needed)
  if [ -z "$CONFIGS" ]; then
    log "all configs recorded — done"
    exit 0
  fi
  log "attempt $i; pending: $CONFIGS"
  # Gentle compile-service probe: tiny jit with a fresh shape.
  if python - >> tpu_watch.log 2>&1 <<EOF
import jax, jax.numpy as jnp, numpy as np
x = jnp.arange(1000 + $i)          # new shape each try -> forces a compile
y = jax.jit(lambda v: (v * 3 + 1).sum())(x)
assert int(np.asarray(y)) == sum(3 * k + 1 for k in range(1000 + $i))
print("compile service OK")
EOF
  then
    for c in $CONFIGS; do
      log "running --config $c"
      python bench.py --config "$c" >> BENCH_ATTEMPT_r04.jsonl 2>> BENCH_ATTEMPT_r04.err
      log "config $c rc=$?"
    done
  else
    log "compile service still down"
  fi
  sleep 300
done
log "gave up"

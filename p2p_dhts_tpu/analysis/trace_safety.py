"""Pass 1 — trace-safety lint (pure AST, no jax import).

Walks the package for jit-boundary hazards. Scope is deliberately
syntactic: functions *decorated* with `jax.jit` / `shard_map` (directly
or through `functools.partial`) are "jit contexts"; everything lexically
inside one — including nested `def`s, whose parameters are loop-body
carries and therefore traced — is checked. Helpers that are only
*called* from jit code are out of scope (the jaxpr pass covers what
actually traces); the decorated surface is where the repo's contracts
live and where a Python-level hazard is unambiguous.

Rules:

  trace-branch          Python `if`/`while`/`for` over a traced value
                        inside a jit body — trace-time concretization
                        (ConcretizationTypeError at best, silent
                        shape-specialized retrace at worst). Access to
                        static attributes (.shape/.ndim/.dtype/.size)
                        is exempt.
  host-sync             `.item()`, `jax.device_get`, `np.asarray` /
                        `np.array`, or `float()`/`int()`/`bool()` over a
                        traced value inside a jit body: a device->host
                        sync (or trace-time failure) on the hot path.
  scalar-closure        `jax.jit(f)(...)` immediately invoked, or a
                        `jax.jit(...)` wrapper constructed inside a
                        `for`/`while` body: a FRESH jit wrapper per
                        call/iteration defeats the trace cache — the
                        shape/dtype-driven steady-state retrace class
                        the ServeEngine's trace counter guards at
                        runtime; this catches it at review time.
  shardmap-import       importing `jax.experimental.shard_map` (or
                        `jax.shard_map`) anywhere but compat.py —
                        bypasses the check_vma<->check_rep version gate
                        that un-broke seven modules on jax 0.4.x.
  module-jnp-constant   module-scope `jnp.*(...)` constant: initializes
                        the default backend at import time — fatal in
                        driver processes whose TPU runtime is unusable
                        (the core/ring.py `_BIG` rule, mechanized).
  bare-except           `except Exception:` / bare `except:` — replace
                        with typed handling or suppress with a reason.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Sequence, Set

from p2p_dhts_tpu.analysis.common import (Finding, dotted_name as _dotted,
                                          repo_rel)

PASS = "trace-safety"

#: Attribute reads on a traced value that are static at trace time.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type"}

#: numpy module aliases (host-sync rule).
_NP_NAMES = {"np", "numpy"}


def _is_jit_ref(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit", "pjit", "jax.pjit")


def _is_shard_map_ref(node: ast.AST) -> bool:
    d = _dotted(node)
    return d is not None and (d == "shard_map"
                              or d.endswith(".shard_map"))


def _const_str_seq(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
        return out
    return []


def _jit_decoration(fn: ast.AST) -> Optional[Set[str]]:
    """If `fn` is decorated as a jit/shard_map body, return the set of
    STATIC argument names (empty set for shard_map); else None."""
    for dec in getattr(fn, "decorator_list", []):
        if _is_jit_ref(dec) or _is_shard_map_ref(dec):
            return set()
        if isinstance(dec, ast.Call):
            callee = dec.func
            if _is_jit_ref(callee) or _is_shard_map_ref(callee):
                return _static_names(fn, dec)
            if _dotted(callee) in ("functools.partial", "partial"):
                if dec.args and (_is_jit_ref(dec.args[0])
                                 or _is_shard_map_ref(dec.args[0])):
                    return _static_names(fn, dec)
    return None


def _static_names(fn: ast.AST, call: ast.Call) -> Set[str]:
    static: Set[str] = set()
    argnames = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            static.update(_const_str_seq(kw.value))
        elif kw.arg == "static_argnums":
            nums = []
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)]
            for i in nums:
                if 0 <= i < len(argnames):
                    static.add(argnames[i])
    return static


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def _uses_traced(expr: ast.AST, traced: Set[str]) -> bool:
    """Does `expr` read a traced name outside a static-attribute access?"""
    if isinstance(expr, ast.Attribute) and expr.attr in _STATIC_ATTRS:
        return False
    if isinstance(expr, ast.Name):
        return expr.id in traced
    if isinstance(expr, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
        # `x is (not) None` is an identity check on pytree STRUCTURE —
        # tracers never intercept `is`; the jax idiom for optional
        # fields (state.fingers is None) and defaulted args.
        return False
    if isinstance(expr, ast.Call):
        d = _dotted(expr.func)
        if d == "len":
            # len() resolves through __len__ -> shape[0]: static.
            return False
        if d in ("range", "enumerate", "isinstance", "type"):
            return any(_uses_traced(a, traced) for a in expr.args)
    return any(_uses_traced(child, traced)
               for child in ast.iter_child_nodes(expr))


class _JitBodyChecker(ast.NodeVisitor):
    """Checks one jit-context function body (nested defs included)."""

    def __init__(self, rel: str, traced: Set[str],
                 findings: List[Finding]):
        self.rel = rel
        self.traced = set(traced)
        self.findings = findings

    def _flag(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(
            Finding(self.rel, node.lineno, rule, msg, PASS))

    # nested defs: parameters are traced loop-body carries
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        inner = _JitBodyChecker(self.rel, self.traced | set(
            _param_names(node)), self.findings)
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        inner = _JitBodyChecker(self.rel, self.traced | set(
            _param_names(node)), self.findings)
        inner.visit(node.body)

    def visit_If(self, node: ast.If) -> None:
        if _uses_traced(node.test, self.traced):
            self._flag(node, "trace-branch",
                       "Python `if` over a traced value inside a jit "
                       "body; use jnp.where / lax.cond")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if _uses_traced(node.test, self.traced):
            self._flag(node, "trace-branch",
                       "Python `while` over a traced value inside a jit "
                       "body; use lax.while_loop")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if _uses_traced(node.iter, self.traced):
            self._flag(node, "trace-branch",
                       "Python `for` over a traced value inside a jit "
                       "body; use lax.scan / lax.fori_loop")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if isinstance(node.func, ast.Attribute) and node.func.attr == \
                "item" and not node.args:
            self._flag(node, "host-sync",
                       ".item() inside a jit body forces a device->host "
                       "sync / trace-time concretization")
        elif d in ("jax.device_get", "device_get"):
            self._flag(node, "host-sync",
                       "jax.device_get inside a jit body is a host sync")
        elif d is not None and any(
                d == f"{m}.{fn}" for m in _NP_NAMES
                for fn in ("asarray", "array")):
            self._flag(node, "host-sync",
                       f"{d} inside a jit body pulls the value to host "
                       "(or fails at trace time); use jnp")
        elif d in ("float", "int", "bool") and any(
                _uses_traced(a, self.traced) for a in node.args):
            self._flag(node, "host-sync",
                       f"{d}() over a traced value inside a jit body is "
                       "a trace-time concretization")
        self.generic_visit(node)


class _ModuleChecker(ast.NodeVisitor):
    def __init__(self, rel: str, findings: List[Finding]):
        self.rel = rel
        self.findings = findings
        self._loop_depth = 0
        self._is_compat = os.path.basename(rel) == "compat.py"

    def _flag(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(
            Finding(self.rel, node.lineno, rule, msg, PASS))

    # -- imports -----------------------------------------------------------
    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if self._is_compat:
            return
        mod = node.module or ""
        names = {a.name for a in node.names}
        if mod == "jax.experimental.shard_map" or (
                mod in ("jax", "jax.experimental")
                and "shard_map" in names):
            self._flag(node, "shardmap-import",
                       "import shard_map via p2p_dhts_tpu.compat (the "
                       "check_vma<->check_rep version gate), not "
                       f"directly from {mod!r}")

    def visit_Import(self, node: ast.Import) -> None:
        if self._is_compat:
            return
        for a in node.names:
            if a.name.startswith("jax.experimental.shard_map"):
                self._flag(node, "shardmap-import",
                           "import shard_map via p2p_dhts_tpu.compat, "
                           "not jax.experimental.shard_map")

    # -- except handlers ----------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        t = node.type
        if t is None or (isinstance(t, ast.Name)
                         and t.id == "Exception"):
            what = "bare `except:`" if t is None else "`except Exception:`"
            self._flag(node, "bare-except",
                       f"{what} swallows unrelated failures; type the "
                       "exception or suppress with a reason")
        self.generic_visit(node)

    # -- loops (for the jit-in-loop half of scalar-closure) ------------------
    def visit_For(self, node: ast.For) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_While(self, node: ast.While) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    # -- calls ---------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Call) and _is_jit_ref(node.func.func):
            self._flag(node, "scalar-closure",
                       "jax.jit(...)(...) builds a FRESH jit wrapper per "
                       "call — every invocation retraces; hoist the "
                       "jitted callable")
        elif _is_jit_ref(node.func) and self._loop_depth > 0:
            self._flag(node, "scalar-closure",
                       "jax.jit(...) constructed inside a loop body — a "
                       "new wrapper (and trace cache) per iteration; "
                       "hoist it out of the loop")
        self.generic_visit(node)

    # -- function defs: dispatch jit-context bodies --------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        static = _jit_decoration(node)
        if static is not None:
            traced = set(_param_names(node)) - static
            checker = _JitBodyChecker(self.rel, traced, self.findings)
            for stmt in node.body:
                checker.visit(stmt)
            # scalar-closure / import checks still apply inside.
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _check_module_constants(tree: ast.Module, rel: str,
                            findings: List[Finding]) -> None:
    for stmt in tree.body:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and (d.startswith("jnp.")
                          or d.startswith("jax.numpy.")):
                    findings.append(Finding(
                        rel, node.lineno, "module-jnp-constant",
                        f"module-scope {d}(...) creates a concrete "
                        "device array at import time — initializes the "
                        "default backend (see core/ring.py:_BIG)", PASS))


def run(paths: Iterable[str], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        rel = repo_rel(path, root)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError) as exc:
            findings.append(Finding(rel, 1, "lint-suppression",
                                    f"unparseable file: {exc}", PASS))
            continue
        _ModuleChecker(rel, findings).visit(tree)
        _check_module_constants(tree, rel, findings)
    return findings


def run_default(root: str,
                files: Optional[Sequence[str]] = None) -> List[Finding]:
    from p2p_dhts_tpu.analysis.common import package_files
    return run(files if files is not None else package_files(root), root)

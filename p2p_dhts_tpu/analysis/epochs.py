"""Pass 5 (epochs): the epoch-monotonicity contract, mechanically.

The route fabric's one load-bearing ordering rule — "stale gossip never
applies backwards" — lives in three idioms today: RouteTable.apply's
`if epoch <= self._epoch: return False` guard, the mesh peer's
`int(epoch) > current` staleness beacon, and the edge cache's twin.
Nothing stopped a fourth install site from assigning an epoch field
unguarded, or from flipping `>` to `>=` and re-applying equal-epoch
docs forever. This pass pins both:

  * `epoch-unguarded-write` — an AST dataflow check over every
    `self.<attr> = ...` where the attribute is epoch/generation-bearing
    (`_epoch`, `routes_epoch`, `_generation`, ...): outside `__init__`
    the write must either be a monotonic self-increment
    (`self._epoch += 1` / `self._epoch = self._epoch + 1`) or be
    dominated by an ORDERED epoch compare earlier in the same function
    (the guard-then-install shape). Mirror/latch fields that follow an
    authoritative table's epoch by design opt out with the standard
    `chordax-lint: disable=epoch-unguarded-write` comment (reasoned).
  * `epoch-compare-drift` — every ordered compare against a
    self-rooted epoch attribute is normalized to "incoming OP current"
    (Gt/LtE == the strict family, GtE/Lt == the equal-accepting
    family); mixing families across install sites is exactly the
    `>` vs `>=` drift that re-applies same-epoch documents on one path
    and drops them on another, so the minority family is flagged.
    Equality tests (`==`/`!=` change-detection latches, the gateway
    cache's fill-drop) are not ordering claims and never fire.

Pure AST, package-wide (no module registry to forget to append to).
This module never imports jax.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from p2p_dhts_tpu.analysis.common import (Finding, KNOWN_RULES,
                                          package_files, repo_rel)

PASS = "epochs"

KNOWN_RULES.add("epoch-unguarded-write")
KNOWN_RULES.add("epoch-compare-drift")

#: Attribute/name shapes that carry epoch-ordered state.
_EPOCH_ATTR_RE = re.compile(r"epoch|generation", re.IGNORECASE)

_ORDERED_OPS = (ast.Gt, ast.GtE, ast.Lt, ast.LtE)


def _is_epoch_name(name: str) -> bool:
    return bool(_EPOCH_ATTR_RE.search(name))


def _mentions_epoch(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and _is_epoch_name(sub.attr):
            return True
        if isinstance(sub, ast.Name) and _is_epoch_name(sub.id):
            return True
    return False


def _self_rooted(node: ast.AST) -> bool:
    """True when `node` contains an attribute chain rooted at `self`
    whose terminal attribute is epoch-bearing (`self._epoch`,
    `self.table.epoch`, ...) — the "current" side of a compare."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and _is_epoch_name(sub.attr):
            root = sub
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == "self":
                return True
    return False


def _is_self_epoch_target(tgt: ast.AST) -> Optional[str]:
    if isinstance(tgt, ast.Attribute) and \
            isinstance(tgt.value, ast.Name) and tgt.value.id == "self" \
            and _is_epoch_name(tgt.attr):
        return tgt.attr
    return None


def _is_monotonic_increment(stmt: ast.stmt, attr: str) -> bool:
    """`self.<attr> += k` or `self.<attr> = self.<attr> + k`."""
    if isinstance(stmt, ast.AugAssign) and isinstance(stmt.op, ast.Add):
        return True
    value = getattr(stmt, "value", None)
    if value is None:
        return False
    for sub in ast.walk(value):
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Add):
            for side in (sub.left, sub.right):
                if _is_self_epoch_target(side) == attr:
                    return True
    return False


class _CompareSite:
    __slots__ = ("rel", "line", "family", "snippet")

    def __init__(self, rel: str, line: int, family: str, snippet: str):
        self.rel = rel
        self.line = line
        self.family = family    # "strict" | "equal"
        self.snippet = snippet


def _classify_compare(node: ast.Compare) -> Optional[str]:
    """The boundary family of one ordered epoch compare, normalized to
    "incoming OP current" ("strict" for Gt/LtE, "equal" for GtE/Lt),
    or None when the compare is not an epoch-ordering claim."""
    if len(node.ops) != 1 or not isinstance(node.ops[0], _ORDERED_OPS):
        return None
    left, right = node.left, node.comparators[0]
    left_cur, right_cur = _self_rooted(left), _self_rooted(right)
    if left_cur == right_cur:
        return None  # both (or neither) sides look authoritative
    if not (_mentions_epoch(left) or _mentions_epoch(right)):
        return None
    op = node.ops[0]
    if left_cur:
        # current OP incoming — flip so incoming is on the left.
        op = {ast.Gt: ast.Lt, ast.Lt: ast.Gt,
              ast.GtE: ast.LtE, ast.LtE: ast.GtE}[type(op)]()
    if isinstance(op, (ast.Gt, ast.LtE)):
        return "strict"
    return "equal"


def _scan_file(path: str, rel: str,
               findings: List[Finding],
               compares: List[_CompareSite]) -> None:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        tree = ast.parse(src, filename=path)
    except (OSError, SyntaxError):
        return
    src_lines = src.splitlines()

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # Ordered epoch compares anywhere in the function, by line —
        # the guard set a later write may be dominated by.
        guard_lines: List[int] = []
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Compare):
                fam = _classify_compare(sub)
                if fam is None and len(sub.ops) == 1 and \
                        isinstance(sub.ops[0], _ORDERED_OPS) and \
                        _mentions_epoch(sub):
                    # Ordered + epoch-flavored but unclassifiable
                    # (e.g. two locals): still a guard for the
                    # dominance check, just not a drift datapoint.
                    guard_lines.append(sub.lineno)
                elif fam is not None:
                    guard_lines.append(sub.lineno)
                    snippet = ""
                    if 0 < sub.lineno <= len(src_lines):
                        snippet = src_lines[sub.lineno - 1].strip()
                    compares.append(
                        _CompareSite(rel, sub.lineno, fam, snippet))

        if fn.name == "__init__":
            continue  # construction-time seeding is not an install
        for stmt in ast.walk(fn):
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for tgt in targets:
                    attr = _is_self_epoch_target(tgt)
                    if attr is None:
                        continue
                    if _is_monotonic_increment(stmt, attr):
                        continue
                    if any(g <= stmt.lineno for g in guard_lines):
                        continue
                    findings.append(Finding(
                        rel, stmt.lineno, "epoch-unguarded-write",
                        f"write to epoch-bearing field self.{attr} in "
                        f"{fn.name}() is neither a monotonic increment "
                        f"nor dominated by an ordered epoch compare — "
                        f"stale gossip could apply backwards",
                        PASS))


def run(files: Sequence[str], root: str) -> List[Finding]:
    findings: List[Finding] = []
    compares: List[_CompareSite] = []
    for path in files:
        _scan_file(path, repo_rel(path, root), findings, compares)

    by_family: Dict[str, List[_CompareSite]] = {}
    for site in compares:
        by_family.setdefault(site.family, []).append(site)
    if len(by_family) > 1:
        # Mixed boundary families: flag the minority (a tie flags the
        # equal-accepting side — "stale gossip never applies backwards"
        # is the strict canonical rule).
        strict = by_family.get("strict", [])
        equal = by_family.get("equal", [])
        minority, majority = (strict, equal) if len(strict) < len(equal) \
            else (equal, strict)
        example = majority[0]
        for site in minority:
            findings.append(Finding(
                site.rel, site.line, "epoch-compare-drift",
                f"epoch compare `{site.snippet}` uses the "
                f"{site.family}-boundary family while "
                f"{len(majority)} install site(s) use the other "
                f"(e.g. {example.rel}:{example.line} "
                f"`{example.snippet}`) — same-epoch documents apply "
                f"on one path and drop on another",
                PASS))
    return sorted(set(findings))


def run_default(root: str) -> List[Finding]:
    return run(package_files(root), root)

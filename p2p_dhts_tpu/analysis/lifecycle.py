"""Pass 6 (lifecycle): zombie loops and stale telemetry, mechanically.

Two bug classes this repo has fixed by hand more than once (PR 14/16:
PacedLoops haunting the HEALTH registry; PR 8/15/16: per-ring/per-dest
metric keys surviving retirement) become lint findings:

  * `loop-close-missing` — a class that constructs a thread-backed
    worker (a PacedLoop subclass, `threading.Thread`, or a WirePool)
    onto `self` must define or inherit a reachable `close`/`stop`;
    otherwise nothing can ever retire the worker it started. The
    PacedLoop class table is DISCOVERED (package-wide subclass walk),
    not listed — a new loop subclass is covered the moment it exists.
  * `loop-leak` — a function-local construction site (bench stages,
    dryrun phases, helpers) that builds a loop, `.start()`s it, and
    neither stops/closes/joins it nor lets the handle escape (return /
    yield / attribute / container / call argument) leaks a live thread
    with no reachable off switch.
  * `telemetry-retire-missing` — every README metric-inventory row
    whose dynamic suffix is IDENTITY-scoped (`<ring>`, `<pair>`,
    `<dest>`, `<addr>`, `<peer>`, `<shard>`, `<a>`-`<b>`) must be
    covered by a
    retirement site: a `remove_prefix` call whose (f-string) pattern
    reaches the identity segment. Interpolations of loop variables
    over literal/module-constant string tuples are EXPANDED
    (`for fam in MEMBERSHIP_FAMS: remove_prefix(f"membership.{fam}.…")`
    covers each family precisely), so the check is exact, not
    prefix-sloppy. Bounded vocabularies (`<op>`, `<kind>`, `<slo>`,
    `<site>`, `<CMD>`, `<cause>`, `<bucket>`, `<engine>`) are config-
    chosen, not member-identity, and are exempt by placeholder name.

Pure AST + README parse, package-wide. This module never imports jax.
"""

from __future__ import annotations

import ast
import itertools
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from p2p_dhts_tpu.analysis.common import (Finding, KNOWN_RULES,
                                          package_files, repo_rel)
from p2p_dhts_tpu.analysis.metric_keys import (INVENTORY_HEADING,
                                               WILD, _BACKTICK_RE)

PASS = "lifecycle"

KNOWN_RULES.add("loop-close-missing")
KNOWN_RULES.add("loop-leak")
KNOWN_RULES.add("telemetry-retire-missing")

#: Thread-backed worker roots: classes transitively extending these
#: (or direct constructions of them) start OS threads that outlive the
#: constructing frame.
LOOP_ROOTS = {"PacedLoop", "Thread", "Timer", "WirePool"}

#: Method-name verbs that count as a reachable off switch. Matched as
#: whole words (`close`, `stop`, `kill`, `_stop_maintenance`,
#: `shutdown_workers`) so reference-parity names still register.
LIFECYCLE_VERBS = {"close", "stop", "shutdown", "kill", "cancel"}

#: Placeholder NAMES that scope a key to a member identity — rings,
#: repair pairs, wire destinations, mesh peers — whose departure must
#: retire the key. Everything else (`<op>`, `<kind>`, `<slo>`, ...) is
#: a bounded, config-chosen vocabulary.
IDENTITY_PLACEHOLDERS = {"ring", "rid", "pair", "dest", "addr", "peer",
                         "member", "shard", "a", "b"}

_PLACEHOLDER_NAME_RE = re.compile(r"<([^<>]*)>")


def _is_lifecycle_method(name: str) -> bool:
    words = name.strip("_").split("_")
    return any(w in LIFECYCLE_VERBS for w in words)

#: Expansion cap for interpolation products (defensive; the real
#: registries are tens of entries).
_MAX_EXPANSION = 512


# ---------------------------------------------------------------------------
# loop-class discovery + lifecycle coverage
# ---------------------------------------------------------------------------

class _ClassInfo:
    __slots__ = ("rel", "line", "bases", "methods", "loop_ctors")

    def __init__(self, rel: str, line: int, bases: List[str],
                 methods: Set[str],
                 loop_ctors: List[Tuple[str, int]]):
        self.rel = rel
        self.line = line
        self.bases = bases
        self.methods = methods
        self.loop_ctors = loop_ctors  # (ctor name, line) self-assigns


def _last_part(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _collect_classes(files: Sequence[str], root: str
                     ) -> Dict[str, _ClassInfo]:
    out: Dict[str, _ClassInfo] = {}
    for path in files:
        rel = repo_rel(path, root)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = [b for b in (_last_part(x) for x in node.bases)
                     if b is not None]
            methods = {s.name for s in node.body
                       if isinstance(s, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            out.setdefault(node.name, _ClassInfo(
                rel, node.lineno, bases, methods, []))
    return out


def _loop_class_names(classes: Dict[str, _ClassInfo]) -> Set[str]:
    loops = set(LOOP_ROOTS)
    changed = True
    while changed:
        changed = False
        for name, info in classes.items():
            if name not in loops and any(b in loops for b in info.bases):
                loops.add(name)
                changed = True
    return loops


def _provides_lifecycle(name: str, classes: Dict[str, _ClassInfo]) -> bool:
    seen: Set[str] = set()
    stack = [name]
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        if cur in ("Thread", "Timer"):
            return True  # stdlib Thread carries join(); Timer cancel()
        info = classes.get(cur)
        if info is None:
            continue
        if any(_is_lifecycle_method(m) for m in info.methods):
            return True
        stack.extend(info.bases)
    return False


def _scan_owners_and_leaks(files: Sequence[str], root: str,
                           classes: Dict[str, _ClassInfo],
                           loop_names: Set[str],
                           findings: List[Finding]) -> None:
    for path in files:
        rel = repo_rel(path, root)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self_ctors: List[Tuple[str, int]] = []
                for sub in ast.walk(node):
                    if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        continue
                    value = sub.value
                    if not isinstance(value, ast.Call):
                        continue
                    ctor = _last_part(value.func)
                    if ctor not in loop_names:
                        continue
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for tgt in targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            self_ctors.append((ctor, sub.lineno))
                if self_ctors and not _provides_lifecycle(node.name,
                                                          classes):
                    ctor, line = self_ctors[0]
                    findings.append(Finding(
                        rel, line, "loop-close-missing",
                        f"class {node.name} constructs a thread-backed "
                        f"{ctor} but neither defines nor inherits "
                        f"close/stop — nothing can retire the worker "
                        f"it starts", PASS))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_function_leaks(node, rel, loop_names, findings)


def _scan_function_leaks(fn: ast.AST, rel: str, loop_names: Set[str],
                         findings: List[Finding]) -> None:
    # Local loop handles: name -> (ctor, line).
    local: Dict[str, Tuple[str, int]] = {}
    for stmt in fn.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            ctor = _last_part(stmt.value.func)
            if ctor in loop_names and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                local[stmt.targets[0].id] = (ctor, stmt.lineno)
    if not local:
        return
    started: Set[str] = set()
    stopped: Set[str] = set()
    escaped: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in local:
            name, meth = node.func.value.id, node.func.attr
            if meth == "start":
                started.add(name)
            elif meth == "join" or _is_lifecycle_method(meth):
                stopped.add(name)
            continue
        # Any other appearance of the handle is an escape: returned,
        # yielded, stored, passed on — someone else may own shutdown.
        for sub in ast.walk(node) if isinstance(
                node, (ast.Return, ast.Yield, ast.YieldFrom, ast.Call,
                       ast.Assign, ast.AugAssign, ast.AnnAssign,
                       ast.Dict, ast.List, ast.Tuple, ast.Set)) else ():
            if isinstance(sub, ast.Name) and sub.id in local and \
                    isinstance(sub.ctx, ast.Load):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.value is sub:
                    continue  # the receiver of a method call, not an arg
                escaped.add(sub.id)
    for name in sorted(started - stopped - escaped):
        ctor, line = local[name]
        findings.append(Finding(
            rel, line, "loop-leak",
            f"{ctor} `{name}` is started here but never "
            f"stopped/closed/joined and the handle does not escape — "
            f"a leaked live thread with no off switch", PASS))


# ---------------------------------------------------------------------------
# telemetry retirement coverage
# ---------------------------------------------------------------------------

def _module_str_constants(tree: ast.Module) -> Dict[str, List[str]]:
    """Module-level NAME = "lit" / NAME = ("lit", ...) bindings."""
    out: Dict[str, List[str]] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1 \
                or not isinstance(stmt.targets[0], ast.Name):
            continue
        v = stmt.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            out[stmt.targets[0].id] = [v.value]
        elif isinstance(v, (ast.Tuple, ast.List)) and v.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in v.elts):
            out[stmt.targets[0].id] = [e.value for e in v.elts]
    return out


def _iter_domain(it: ast.AST,
                 consts: Dict[str, List[str]]) -> Optional[List[str]]:
    """The literal string values a `for VAR in <iter>` ranges over:
    a tuple/list of constants, or a module-level constant tuple."""
    if isinstance(it, (ast.Tuple, ast.List)) and it.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in it.elts):
        return [e.value for e in it.elts]
    if isinstance(it, ast.Name) and it.id in consts:
        return consts[it.id]
    return None


def _expand_pattern(node: ast.AST, domains: Dict[str, List[str]],
                    consts: Dict[str, List[str]]) -> List[str]:
    """Every concrete shape of a retirement-key argument: literal
    pieces verbatim, interpolations of resolvable loop variables /
    module constants expanded, everything else one `<*>` wildcard."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if not isinstance(node, ast.JoinedStr):
        return []
    piece_choices: List[List[str]] = []
    for piece in node.values:
        if isinstance(piece, ast.Constant) and isinstance(piece.value, str):
            piece_choices.append([piece.value])
        elif isinstance(piece, ast.FormattedValue):
            v = piece.value
            if isinstance(v, ast.Name) and v.id in domains:
                piece_choices.append(domains[v.id])
            elif isinstance(v, ast.Name) and v.id in consts:
                piece_choices.append(consts[v.id])
            else:
                piece_choices.append([WILD])
        else:
            return []
    total = 1
    for c in piece_choices:
        total *= max(len(c), 1)
        if total > _MAX_EXPANSION:
            return ["".join(c[0] for c in piece_choices)]
    return ["".join(combo)
            for combo in itertools.product(*piece_choices)]


def retirement_patterns(files: Sequence[str], root: str
                        ) -> List[Tuple[str, str, int]]:
    """(pattern, rel, line) per remove_prefix call in the scan set."""
    out: List[Tuple[str, str, int]] = []
    for path in files:
        rel = repo_rel(path, root)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        consts = _module_str_constants(tree)

        def visit(node: ast.AST, domains: Dict[str, List[str]]) -> None:
            # Loop-variable domains are scoped to their enclosing For:
            # the same name ranging over different registries in
            # sibling loops must not bleed between call sites.
            if isinstance(node, (ast.For, ast.AsyncFor)) and \
                    isinstance(node.target, ast.Name):
                dom = _iter_domain(node.iter, consts)
                inner = dict(domains)
                if dom is not None:
                    inner[node.target.id] = dom
                for child in node.body:
                    visit(child, inner)
                for child in node.orelse:
                    visit(child, domains)
                return
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "remove_prefix" and node.args:
                for pat in _expand_pattern(node.args[0], domains,
                                           consts):
                    out.append((pat, rel, node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child, domains)

        visit(tree, {})
    return out


def _segments(key: str) -> List[str]:
    return key.split(".")


def _seg_match(pat_seg: str, key_seg: str) -> bool:
    return pat_seg == key_seg or pat_seg == WILD or key_seg == WILD


def _covers(pattern: str, key_segs: List[str], ident_idx: int) -> bool:
    """remove_prefix(pattern) retires the family `key_segs` iff the
    pattern prefix-matches segmentwise AND reaches the first identity
    segment (a shorter prefix would be a wholesale wipe of unrelated
    families, not this family's retirement)."""
    p = _segments(pattern)
    if len(p) < ident_idx + 1 or len(p) > len(key_segs):
        return False
    return all(_seg_match(a, b) for a, b in zip(p, key_segs))


def _inventory_rows(readme_path: str) -> List[Tuple[str, int]]:
    """(raw key, line) rows from the README metric-key inventory."""
    rows: List[Tuple[str, int]] = []
    try:
        with open(readme_path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return rows
    in_section = False
    for i, line in enumerate(lines, 1):
        if line.strip().startswith("#"):
            in_section = line.strip() == INVENTORY_HEADING
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        m = _BACKTICK_RE.search(line)
        if m is not None and "." in m.group(1):
            rows.append((m.group(1).strip(), i))
    return rows


def _identity_segment_index(raw_key: str) -> Optional[int]:
    """Index of the first dotted segment carrying an identity-scoped
    placeholder, or None when the key has none."""
    for i, seg in enumerate(_segments(raw_key)):
        names = _PLACEHOLDER_NAME_RE.findall(seg)
        if any(n in IDENTITY_PLACEHOLDERS for n in names):
            return i
    return None


def _normalize(raw_key: str) -> List[str]:
    return _segments(_PLACEHOLDER_NAME_RE.sub(WILD, raw_key))


def retirement_findings(files: Sequence[str], root: str,
                        readme_path: str) -> List[Finding]:
    rows = _inventory_rows(readme_path)
    patterns = [p for p, _, _ in retirement_patterns(files, root)]
    findings: List[Finding] = []
    rel_readme = repo_rel(readme_path, root)
    for raw, line in rows:
        idx = _identity_segment_index(raw)
        if idx is None:
            continue
        key_segs = _normalize(raw)
        if not any(_covers(p, key_segs, idx) for p in patterns):
            findings.append(Finding(
                rel_readme, line, "telemetry-retire-missing",
                f"identity-scoped metric family {raw!r} has no "
                f"retirement path — no remove_prefix site reaches its "
                f"identity segment, so the keys outlive the "
                f"ring/pair/peer that wrote them", PASS))
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def run(files: Sequence[str], root: str,
        readme_path: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    classes = _collect_classes(files, root)
    loop_names = _loop_class_names(classes)
    _scan_owners_and_leaks(files, root, classes, loop_names, findings)
    readme = readme_path if readme_path is not None \
        else os.path.join(root, "README.md")
    findings.extend(retirement_findings(files, root, readme))
    return sorted(set(findings))


def run_default(root: str) -> List[Finding]:
    return run(package_files(root), root)

"""Shared findings model + inline-suppression machinery for chordax-lint.

Every analyzer pass (trace_safety, gspmd, lockcheck) reports `Finding`
rows; the CLI (and the pytest/dryrun gates) render them and exit
nonzero when any UNSUPPRESSED finding remains — the CI-gate contract.

Suppression syntax (mandatory reason, enforced):

    x = thing()  # chordax-lint: disable=bare-except -- why it is safe

A standalone comment line suppresses the next non-comment source line
(so multi-line statements can carry the annotation above themselves):

    # chordax-lint: disable=gspmd-associative-scan -- per-shard only
    carried = jax.lax.associative_scan(...)

A suppression without a `-- reason` tail does not suppress anything and
is itself reported as a `lint-suppression` finding: silent opt-outs are
exactly the rot this gate exists to stop.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Rules a suppression comment may name. Passes register theirs at
#: import; `lint-suppression` itself is never suppressible.
KNOWN_RULES = {
    # pass 1 — trace safety
    "trace-branch", "host-sync", "scalar-closure", "shardmap-import",
    "module-jnp-constant", "bare-except",
    # pass 2 — GSPMD miscompile patterns
    "gspmd-concat-of-slices", "gspmd-associative-scan",
    "gspmd-dynamic-slice-traced-start",
    # pass 3 — lock discipline
    "lock-order-cycle", "lock-held-across-blocking", "lock-reacquire",
    "lock-module-uncovered", "lock-module-stale",
    # pass 2 — registry coverage
    "gspmd-kernel-untraced",
    # pass 4 — metric-key doc drift
    "metric-key-undocumented", "metric-key-stale",
    # pass 5 — epoch monotonicity
    "epoch-unguarded-write", "epoch-compare-drift",
    # pass 6 — lifecycle / telemetry retirement
    "loop-close-missing", "loop-leak", "telemetry-retire-missing",
    # pass 7 — wire-contract drift
    "verb-unreachable", "verb-undocumented", "verb-stale",
    "verb-unregistered", "field-undocumented", "field-stale",
    # meta
    "lint-suppression", "baseline-missing-reason", "baseline-stale",
}

#: Rules the baseline diff mode may NOT absorb: suppression hygiene
#: and the baseline's own integrity findings must stay un-maskable.
UNBASELINEABLE = {"lint-suppression", "baseline-missing-reason",
                  "baseline-stale"}

#: Default baseline filename, resolved against the scan root.
BASELINE_NAME = "analysis_baseline.json"

_SUPPRESS_RE = re.compile(
    r"#\s*chordax-lint:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(.+?))?\s*$")


def dotted_name(node) -> Optional[str]:
    """'jax.experimental.shard_map' for a nested Attribute/Name AST
    node, else None — the one shared resolver for every AST pass."""
    import ast
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One analyzer hit, anchored to source. `path` is repo-relative."""

    path: str
    line: int
    rule: str
    message: str
    pass_name: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.message} "
                f"({self.pass_name})")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class SuppressionIndex:
    """Per-file map line -> set of suppressed rules, built from the
    inline comments; malformed suppressions surface as findings."""

    def __init__(self) -> None:
        self._by_file: Dict[str, Dict[int, set]] = {}
        self.problems: List[Finding] = []

    def add_file(self, path: str, rel: str,
                 text: Optional[str] = None) -> None:
        if rel in self._by_file:
            return
        if text is None:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    text = fh.read()
            except OSError:
                self._by_file[rel] = {}
                return
        self._by_file[rel] = self._parse(rel, text)

    def _parse(self, rel: str, text: str) -> Dict[int, set]:
        lines = text.splitlines()
        out: Dict[int, set] = {}
        for i, raw in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.problems.append(Finding(
                    rel, i, "lint-suppression",
                    "suppression without a `-- reason` tail suppresses "
                    "nothing; state why the finding is safe", "meta"))
                continue
            unknown = rules - KNOWN_RULES
            if unknown or "lint-suppression" in rules:
                bad = sorted(unknown | (rules & {"lint-suppression"}))
                self.problems.append(Finding(
                    rel, i, "lint-suppression",
                    f"suppression names unknown/unsuppressible rule(s) "
                    f"{bad}", "meta"))
                rules -= set(bad)
            if not rules:
                continue
            target = i
            if raw.lstrip().startswith("#"):
                # Standalone comment: covers the next non-comment line.
                j = i + 1
                while j <= len(lines) and (
                        not lines[j - 1].strip()
                        or lines[j - 1].lstrip().startswith("#")):
                    j += 1
                target = j
            out.setdefault(target, set()).update(rules)
        return out

    def suppressed(self, finding: Finding) -> bool:
        return finding.rule in self._by_file.get(
            finding.path, {}).get(finding.line, set())


def repo_rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:  # different drive (windows); keep absolute
        return path


def package_files(root: str,
                  subdirs: Sequence[str] = ("p2p_dhts_tpu",),
                  extra: Sequence[str] = ("__graft_entry__.py", "bench.py"),
                  ) -> List[str]:
    """The shipped-tree scan set: the package + top-level entry points.
    tests/ and fixture corpora are deliberately excluded — they hold
    seeded violations."""
    out: List[str] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    for name in extra:
        p = os.path.join(root, name)
        if os.path.exists(p):
            out.append(p)
    return out


def apply_suppressions(findings: Iterable[Finding], root: str,
                       index: Optional[SuppressionIndex] = None
                       ) -> Tuple[List[Finding], int, SuppressionIndex]:
    """Split raw findings into (unsuppressed + suppression-problems,
    n_suppressed, index). Files referenced by findings are lazily added
    to the index so Pass-2/3 findings (attributed by file:line, not by
    an AST walk) honor the same inline syntax."""
    index = index if index is not None else SuppressionIndex()
    kept: List[Finding] = []
    n_sup = 0
    for f in sorted(set(findings)):
        index.add_file(os.path.join(root, f.path), f.path)
        if index.suppressed(f):
            n_sup += 1
        else:
            kept.append(f)
    kept.extend(index.problems)
    return sorted(set(kept)), n_sup, index


def apply_baseline(findings: Iterable[Finding], root: str,
                   baseline_path: Optional[str] = None
                   ) -> Tuple[List[Finding], int, List[Finding]]:
    """Diff mode: drop findings recorded in the baseline file so
    `--strict` gates only NEW findings — the legacy-burn-down valve.

    Returns (kept, n_baselined, problems). Every entry is an object
    `{"path", "rule", "reason"}` (optional `"line"` pins one site) and
    the reason is mandatory: a reasonless entry yields a
    `baseline-missing-reason` finding, an entry matching nothing
    yields `baseline-stale` — the file can only shrink, never rot.
    A missing baseline file is simply no baseline."""
    if baseline_path is None:
        baseline_path = os.path.join(root, BASELINE_NAME)
    rel = repo_rel(baseline_path, root)
    problems: List[Finding] = []
    try:
        with open(baseline_path, "r", encoding="utf-8") as fh:
            entries = json.load(fh)
    except OSError:
        return sorted(set(findings)), 0, []
    except ValueError as exc:
        problems.append(Finding(
            rel, 1, "baseline-missing-reason",
            f"unparseable baseline file: {exc}", "meta"))
        return sorted(set(findings)), 0, problems
    if not isinstance(entries, list):
        problems.append(Finding(
            rel, 1, "baseline-missing-reason",
            "baseline must be a JSON list of "
            "{path, rule, reason[, line]} objects", "meta"))
        entries = []

    valid: List[Optional[dict]] = []
    for i, entry in enumerate(entries):
        reason = entry.get("reason") if isinstance(entry, dict) else None
        if not isinstance(entry, dict) or \
                not str(reason or "").strip():
            problems.append(Finding(
                rel, 1, "baseline-missing-reason",
                f"baseline entry #{i} ({entry!r}) has no reason — "
                f"zero silent baseline entries; state why the finding "
                f"is tolerated", "meta"))
            valid.append(None)
        else:
            valid.append(entry)

    def _matches(entry: dict, f: Finding) -> bool:
        if f.rule in UNBASELINEABLE or entry.get("rule") != f.rule:
            return False
        if str(entry.get("path", "")).replace("\\", "/") != \
                f.path.replace(os.sep, "/"):
            return False
        return "line" not in entry or int(entry["line"]) == f.line

    kept: List[Finding] = []
    used = [False] * len(valid)
    n_baselined = 0
    for f in sorted(set(findings)):
        hit = None
        for i, entry in enumerate(valid):
            if entry is not None and _matches(entry, f):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used[hit] = True
            n_baselined += 1
    for i, entry in enumerate(valid):
        if entry is not None and not used[i]:
            problems.append(Finding(
                rel, 1, "baseline-stale",
                f"baseline entry {{path: {entry.get('path')!r}, rule: "
                f"{entry.get('rule')!r}}} matches no current finding — "
                f"delete it (the baseline only shrinks)", "meta"))
    return kept, n_baselined, problems


def render_report(findings: Sequence[Finding], n_suppressed: int,
                  passes: Sequence[str]) -> str:
    lines = [f.render() for f in findings]
    lines.append(f"chordax-lint: {len(findings)} finding(s), "
                 f"{n_suppressed} suppressed "
                 f"(passes: {', '.join(passes)})")
    return "\n".join(lines)


def json_report(findings: Sequence[Finding], n_suppressed: int,
                passes: Sequence[str]) -> str:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return json.dumps({
        "version": 1,
        "passes": list(passes),
        "suppressed": n_suppressed,
        "counts": counts,
        "findings": [f.as_dict() for f in findings],
    }, indent=2, sort_keys=True)

"""CLI: `python -m p2p_dhts_tpu.analysis [--strict] [--json PATH]
[--passes trace,gspmd,locks,...] [--root DIR] [--baseline PATH]`.

--strict is the CI-gate mode: exit 1 on any unsuppressed finding
(exit 2 on an internal analyzer error). Without it the run is
informational and always exits 0 unless the analyzer itself breaks.

The gspmd pass needs a backend to trace against; a fresh CLI process
self-provisions the unit suite's virtual 8-device CPU mesh (env set
BEFORE jax imports, plus the config-level pin the axon sitecustomize
makes necessary — see tests/conftest.py).
"""

from __future__ import annotations

import argparse
import os
import sys


def _provision_cpu_mesh() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    import jax
    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m p2p_dhts_tpu.analysis",
        description="chordax-lint: trace-safety, GSPMD-miscompile and "
                    "lock-discipline analyzer")
    parser.add_argument("--strict", action="store_true",
                        help="exit nonzero on any unsuppressed finding "
                             "(the CI gate)")
    parser.add_argument("--json", metavar="PATH", default=None,
                        help="write the machine-readable report here "
                             "('-' for stdout)")
    parser.add_argument("--passes",
                        default="trace,gspmd,locks,metrics,epochs,"
                                "lifecycle,verbs",
                        help="comma list from {trace,gspmd,locks,"
                             "metrics,epochs,lifecycle,verbs}")
    parser.add_argument("--root", default=None,
                        help="repo root (default: the checkout this "
                             "package lives in)")
    parser.add_argument("--baseline", metavar="PATH", default=None,
                        help="baseline file for diff mode (default: "
                             "<root>/analysis_baseline.json when "
                             "present); only NEW findings gate")
    args = parser.parse_args(argv)

    from p2p_dhts_tpu import analysis

    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    unknown = set(passes) - set(analysis.ALL_PASSES)
    if unknown:
        parser.error(f"unknown pass(es): {sorted(unknown)}")

    if "gspmd" in passes and "jax" not in sys.modules:
        _provision_cpu_mesh()

    try:
        findings, n_sup = analysis.run_all(root=args.root, passes=passes,
                                           baseline=args.baseline)
    # chordax-lint: disable=bare-except -- CLI boundary: an analyzer crash must become exit 2, not a traceback
    except Exception as exc:
        print(f"chordax-lint: internal analyzer error: {exc!r}",
              file=sys.stderr)
        return 2

    if args.json:
        report = analysis.json_report(findings, n_sup, passes)
        if args.json == "-":
            print(report)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(report + "\n")
    print(analysis.render_report(findings, n_sup, passes))
    if findings and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

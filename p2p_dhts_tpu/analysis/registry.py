"""Default Pass-2 kernel registry: the public device kernels, traced at
tiny shapes under the dryrun's simulated 8-device mesh layout.

Shapes are deliberately minimal (16-peer ring, batch 8) — jaxpr pattern
scanning is shape-independent, so small traces keep the gate cheap
(~2 s total, no XLA compiles). When >= 8 devices are available (the
unit suite's virtual CPU mesh, or the CLI's self-provisioned one) the
ring state is placed row-sharded over "peer" and the key batch over
"data", mirroring `__graft_entry__._dryrun_impl`; with fewer devices
the same kernels trace unsharded — the taint seeding (any array with a
shardable axis) is identical either way.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from p2p_dhts_tpu.analysis.common import (Finding, KNOWN_RULES,
                                          dotted_name as _dotted,
                                          package_files, repo_rel)
from p2p_dhts_tpu.analysis.gspmd import KernelSpec

KNOWN_RULES.add("gspmd-kernel-untraced")


def default_kernels() -> List[KernelSpec]:
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from p2p_dhts_tpu.config import RingConfig
    from p2p_dhts_tpu.core import churn, ring
    from p2p_dhts_tpu.dhash import store as dstore
    from p2p_dhts_tpu.ops import u128

    rng = np.random.RandomState(7)

    def rand_ids(n):
        return [int.from_bytes(rng.bytes(16), "little") for _ in range(n)]

    n_peers, batch = 16, 8
    state_m = ring.build_ring(rand_ids(n_peers),
                              RingConfig(finger_mode="materialized"))
    state_c = ring.build_ring(rand_ids(n_peers),
                              RingConfig(finger_mode="computed"))
    keys = ring.keys_from_ints(rand_ids(batch))
    starts = jnp.zeros(batch, jnp.int32)

    mesh = None
    devs = jax.devices()
    if len(devs) >= 8 and devs[0].platform == "cpu":
        mesh = Mesh(np.asarray(devs[:8]).reshape(2, 4), ("data", "peer"))
        from p2p_dhts_tpu.core.sharded import shard_ring
        state_m = shard_ring(state_m, mesh, axis="peer")
        state_c = shard_ring(state_c, mesh, axis="peer")
        keys = jax.device_put(keys, NamedSharding(mesh, P("data", None)))
        starts = jax.device_put(starts, NamedSharding(mesh, P("data")))

    store = dstore.empty_store(capacity=16 * batch, max_segments=4)
    segments = jnp.zeros((batch, 4, 10), jnp.int32)
    lengths = jnp.full((batch,), 4, jnp.int32)
    churn_rows = jnp.asarray([1, 3], jnp.int32)
    join_ids = jnp.asarray(
        np.frombuffer(rng.bytes(16 * 2), dtype="<u4").reshape(-1, 4).copy())

    specs = [
        KernelSpec("core.ring.find_successor[materialized]",
                   ring.find_successor, (state_m, keys, starts)),
        KernelSpec("core.ring.find_successor[computed]",
                   ring.find_successor, (state_c, keys, starts)),
        KernelSpec("core.ring.find_successor_gathered_pred",
                   ring.find_successor_gathered_pred,
                   (state_m, keys, starts)),
        KernelSpec("core.ring.find_successor_unroll2",
                   ring.find_successor_unroll2, (state_m, keys, starts)),
        KernelSpec("core.ring.get_n_successors",
                   lambda s, k, st: ring.get_n_successors(s, k, st, 3),
                   (state_m, keys, starts)),
        KernelSpec("core.ring.owner_of", ring.owner_of, (state_m, keys)),
        KernelSpec("core.ring.placement_converged",
                   ring.placement_converged, (state_m,)),
        KernelSpec("core.ring.next_alive_map",
                   ring.next_alive_map, (state_m,)),
        KernelSpec("core.ring.materialize_converged_fingers",
                   lambda s: ring.materialize_converged_fingers(s, 16),
                   (state_c,)),
        KernelSpec("core.churn.fail", churn.fail, (state_m, churn_rows)),
        KernelSpec("core.churn.leave", churn.leave, (state_m, churn_rows)),
        KernelSpec("core.churn.join", churn.join, (state_m, join_ids)),
        KernelSpec("core.churn.stabilize_sweep",
                   churn.stabilize_sweep, (state_m,)),
        KernelSpec("dhash.store.create_batch",
                   lambda *a: dstore.create_batch(*a),
                   (state_m, store, keys, segments, lengths, starts)),
        KernelSpec("dhash.store.read_batch",
                   lambda *a: dstore.read_batch(*a),
                   (state_m, store, keys)),
        KernelSpec("dhash.store.placement_owners",
                   lambda s, k, st: dstore.placement_owners(s, k, st, 3),
                   (state_m, keys, starts)),
        KernelSpec("ops.u128.ring_successor",
                   u128.ring_successor,
                   (state_m.ids, keys, state_m.n_valid)),
        KernelSpec("ops.u128.searchsorted",
                   u128.searchsorted,
                   (state_m.ids, keys, state_m.n_valid)),
        # The serve/gateway finger kernel (serve.ServeEngine's
        # "finger_index" kind — the RPC FINGER_INDEX command's device
        # path): entry index = bit_length((key - start) mod 2^128) - 1,
        # the ONE closed-form copy the per-kind and fused paths share.
        KernelSpec("serve.finger_index",
                   ring.finger_index_batch, (keys, keys)),
    ]

    # The chordax-repair kernels (ISSUE 6): the Merkle-diff comparison
    # (digest two stores, level-compare, extract the delta key-set) and
    # the duplicate-index re-pair pass — the anti-entropy device path a
    # GSPMD miscompile would silently corrupt.
    from p2p_dhts_tpu.dhash.antientropy import store_index
    from p2p_dhts_tpu.repair import kernels as rk
    store_b = dstore.empty_store(capacity=16 * batch, max_segments=4)

    def merkle_delta(sa, sb):
        ia, ib = store_index(sa), store_index(sb)
        leaf_diff, nodes = rk.merkle_diff(ia, ib)
        cand, ok = rk.delta_scan(sa, leaf_diff)
        return leaf_diff, nodes, cand, ok

    specs += [
        KernelSpec("repair.merkle_delta", merkle_delta, (store, store_b)),
        KernelSpec("repair.reindex_duplicates",
                   lambda s, st: rk.reindex_duplicates(s, st, 3, 2),
                   (state_m, store)),
    ]

    # The chordax-membership kernels (ISSUE 7): the mixed-op churn
    # batch (join/leave/fail rows over a capacity-padded state) and the
    # paced stabilize round — the elasticity device path a GSPMD
    # miscompile would silently corrupt mid-storm.
    from p2p_dhts_tpu.membership import OP_FAIL, OP_JOIN, OP_LEAVE
    from p2p_dhts_tpu.membership import kernels as mk
    state_cap = ring.build_ring(rand_ids(n_peers),
                                RingConfig(finger_mode="materialized"),
                                capacity=mk.padded_capacity(n_peers + 4))
    churn_ops = jnp.asarray(
        np.asarray([OP_JOIN, OP_JOIN, OP_FAIL, OP_FAIL, OP_LEAVE,
                    OP_FAIL, OP_JOIN, OP_LEAVE][:batch], np.int32))
    churn_lanes = jnp.asarray(
        np.frombuffer(rng.bytes(16 * batch),
                      dtype="<u4").reshape(-1, 4).copy())

    specs += [
        KernelSpec("membership.churn_apply", mk.churn_apply,
                   (state_cap, churn_ops, churn_lanes)),
        KernelSpec("membership.stabilize_sweep", mk.stabilize_round,
                   (state_cap,)),
    ]

    # The chordax-fuse kernels (ISSUE 13): the multi-kind super-batch
    # programs (the ServeEngine's fused dispatch path — one program
    # answering a mixed FIND_SUCCESSOR/GET/FINGER_INDEX burst) and the
    # selectable IDA decode backends — the new hot-path entry points a
    # GSPMD miscompile would silently corrupt. The fused specs ALSO
    # cover the cross-module edge the fused queue introduced
    # (serve -> ring + store under one jit); the lock-order half of
    # that audit rides lockcheck.DEFAULT_LOCK_MODULES (serve.py /
    # gateway/* / ops/ida_backend.py).
    from p2p_dhts_tpu.ops import ida_backend
    dec_rows = jnp.zeros((batch, 10, 8), jnp.int32)
    dec_idx = jnp.broadcast_to(
        jnp.arange(1, 11, dtype=jnp.int32), (batch, 10))

    specs += [
        KernelSpec("core.ring.fused_lookup",
                   ring.fused_lookup_batch,
                   (state_m, keys, starts, keys, keys)),
        KernelSpec("serve.fused_read",
                   lambda s, st, k, r: dstore.fused_read_batch(
                       s, st, k, r, k, k, k),
                   (state_m, store, keys, starts)),
        KernelSpec("ops.ida_backend.decode[dot]",
                   lambda r, i: ida_backend.decode_body(r, i, 257,
                                                        "dot"),
                   (dec_rows, dec_idx)),
        KernelSpec("ops.ida_backend.decode[mac]",
                   lambda r, i: ida_backend.decode_body(r, i, 257,
                                                        "mac"),
                   (dec_rows, dec_idx)),
    ]

    # Registry-coverage closure (ISSUE 18): every remaining public
    # jit'd kernel with a cheap CPU trace — the maintenance family,
    # the Merkle index pair, the anti-entropy reconcile round, the IDA
    # encode/decode surface, the store-carrying churn batch, and the
    # device-side genesis build. coverage_findings() FAILS the gate
    # when a new public jit'd kernel lands without a spec here (or a
    # reasoned gspmd-kernel-untraced exemption at its def site).
    from p2p_dhts_tpu import ida
    from p2p_dhts_tpu.dhash import maintenance as dmaint
    from p2p_dhts_tpu.dhash import merkle as dmerkle
    from p2p_dhts_tpu.dhash.antientropy import reconcile

    mask8 = jnp.ones((batch,), bool)
    enc_segments = jnp.zeros((batch, 4, 10), jnp.int32)
    uni_rows = jnp.zeros((batch, 10, 4), jnp.int32)
    uni_idx = jnp.arange(1, 11, dtype=jnp.int32)

    specs += [
        KernelSpec("core.ring.ring_genesis",
                   lambda l: ring.ring_genesis(l), (join_ids,)),
        KernelSpec("dhash.maintenance.global_maintenance",
                   lambda r, s: dmaint.global_maintenance(
                       r, s, jnp.zeros_like(s.holder)),
                   (state_m, store)),
        KernelSpec("dhash.maintenance.local_maintenance",
                   lambda r, s: dmaint.local_maintenance(
                       r, s, jnp.zeros_like(s.holder)),
                   (state_m, store)),
        KernelSpec("dhash.maintenance.remap_holders",
                   dmaint.remap_holders, (state_m.ids, state_m, store)),
        KernelSpec("dhash.maintenance.leave_handover",
                   dmaint.leave_handover, (state_m, store, churn_rows)),
        KernelSpec("dhash.maintenance.presence_matrix",
                   lambda r, s, k, st: dmaint.presence_matrix(r, s, k,
                                                              st),
                   (state_m, store, keys, starts)),
        KernelSpec("dhash.merkle.build_index",
                   lambda k, mask: dmerkle.build_index(k, mask),
                   (churn_lanes, mask8)),
        KernelSpec("dhash.merkle.diff_indices",
                   lambda ka, kb, mask: dmerkle.diff_indices(
                       dmerkle.build_index(ka, mask),
                       dmerkle.build_index(kb, mask)),
                   (churn_lanes, churn_lanes, mask8)),
        KernelSpec("dhash.antientropy.reconcile",
                   lambda sa, sb: reconcile(sa, sb), (store, store_b)),
        KernelSpec("membership.churn_apply_store",
                   mk.churn_apply_store,
                   (state_cap, churn_ops, churn_lanes, store)),
        KernelSpec("ida.encode_kernel",
                   lambda s: ida.encode_kernel(s, 14, 10, 257),
                   (enc_segments,)),
        KernelSpec("ida.decode_kernel",
                   lambda r, i: ida.decode_kernel(r, i, 257),
                   (uni_rows, dec_idx)),
        KernelSpec("ida.decode_kernel_dot",
                   lambda r, i: ida.decode_kernel_dot(r, i, 257),
                   (uni_rows, dec_idx)),
        KernelSpec("ida.decode_kernel_uniform",
                   lambda r, i: ida.decode_kernel_uniform(r, i, 257),
                   (uni_rows, uni_idx)),
    ]

    if mesh is not None:
        from p2p_dhts_tpu.core import sharded as csh
        specs.append(KernelSpec(
            "core.sharded.find_successor_sharded",
            lambda s, k, st: csh.find_successor_sharded(s, k, st, mesh),
            (state_m, keys, starts)))
        specs.append(KernelSpec(
            "core.sharded.owner_of_sharded",
            lambda s, k: csh.owner_of_sharded(s, k, mesh),
            (state_m, keys)))

    return specs


# ---------------------------------------------------------------------------
# registry coverage audit (gspmd-kernel-untraced)
# ---------------------------------------------------------------------------

_PASS = "gspmd"
_JIT_TAILS = ("jit", "pjit")


def _is_jit_decorator(dec: ast.expr) -> bool:
    """`@jax.jit`, `@jit`, `@pjit`, or `@functools.partial(jax.jit, ...)`."""
    name = _dotted(dec)
    if name and name.split(".")[-1] in _JIT_TAILS:
        return True
    if isinstance(dec, ast.Call):
        fname = _dotted(dec.func) or ""
        if fname.split(".")[-1] in _JIT_TAILS:
            return True
        if fname.split(".")[-1] == "partial" and dec.args:
            aname = _dotted(dec.args[0]) or ""
            return aname.split(".")[-1] in _JIT_TAILS
    return False


def _covered_refs(registry_path: str, root: str) -> Set[Tuple[str, str]]:
    """(repo-relative module path, function name) pairs the registry
    references — via `alias.func` attribute access on an imported
    module alias, or by importing the function directly."""
    try:
        with open(registry_path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=registry_path)
    except (OSError, SyntaxError):
        return set()

    def _mod_rel(dotted_mod: str) -> Optional[str]:
        base = os.path.join(root, *dotted_mod.split("."))
        if os.path.exists(base + ".py"):
            return repo_rel(base + ".py", root)
        init = os.path.join(base, "__init__.py")
        if os.path.exists(init):
            return repo_rel(init, root)
        return None

    aliases: Dict[str, str] = {}          # local alias -> module rel path
    covered: Set[Tuple[str, str]] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom) or not node.module:
            continue
        for alias in node.names:
            local = alias.asname or alias.name
            sub = _mod_rel(node.module + "." + alias.name)
            if sub is not None:
                aliases[local] = sub
                continue
            mod = _mod_rel(node.module)
            if mod is not None:
                covered.add((mod, alias.name))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in aliases:
            covered.add((aliases[node.value.id], node.attr))
    return covered


def coverage_findings(root: str,
                      registry_path: Optional[str] = None) -> List[Finding]:
    """Assert every PUBLIC jit'd kernel in the package is traced by the
    registry (or carries a reasoned
    `chordax-lint: disable=gspmd-kernel-untraced` exemption, applied
    by the standard suppression machinery). The
    registry, like DEFAULT_LOCK_MODULES, is a reviewed declaration the
    tree is audited against — appending to it cannot be forgotten
    silently."""
    if registry_path is None:
        registry_path = __file__
    covered = _covered_refs(registry_path, root)
    findings: List[Finding] = []
    for path in package_files(root, extra=()):
        rel = repo_rel(path, root)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name.startswith("_"):
                continue
            if not any(_is_jit_decorator(d) for d in node.decorator_list):
                continue
            if (rel.replace(os.sep, "/"), node.name) in {
                    (m.replace(os.sep, "/"), n) for m, n in covered}:
                continue
            findings.append(Finding(
                rel, node.lineno, "gspmd-kernel-untraced",
                f"public jit'd kernel {node.name}() is not traced by "
                f"the gspmd registry — a GSPMD miscompile in it would "
                f"ship silently; add a KernelSpec or a reasoned "
                f"exemption", _PASS))
    return sorted(findings)

"""Pass 2 — GSPMD miscompile detector (jaxpr pattern scan).

jax 0.4.x's SPMD partitioner miscompiles a small, known set of HLO
patterns on sharded operands under GSPMD *auto-sharding* — the bug
class this repo has been bitten by twice (PR 2's two_phase_hop_loop
merge and next_alive_map extension; the placement_converged
associative_scan residual). This pass traces the public device kernels
to jaxprs under a simulated 8-device mesh (the dryrun's layout:
ring-state rows sharded over "peer", key batches over "data") and
scans every equation — recursing through pjit/while/cond/scan — for
those patterns:

  gspmd-concat-of-slices        `concatenate` where at least one input
                                is a slice of a sharded-axis operand
                                and the inputs do NOT all slice the
                                same source array (a same-source
                                concat-of-slices is the jnp.roll
                                rotation idiom, which partitions
                                correctly — the dryrun is the
                                evidence). The partitioner can sum the
                                merged output across an unrelated mesh
                                axis; rewrite as dynamic-update-slice.
  gspmd-associative-scan        `lax.associative_scan` over sharded
                                data: its lowering IS an interleave of
                                concat-of-slices, and auto-sharding
                                miscomputes it (placement_converged,
                                pre-fix). Rewrite as a roll-and-select
                                doubling reduction or an explicit
                                shard_map scan.
  gspmd-dynamic-slice-traced-start
                                `dynamic_slice` whose start indices
                                derive from batch/table (sharded) data
                                rather than replicated scalars — the
                                partitioner cannot prove the slice
                                stays shard-local.

"Sharded" is tracked as a conservative taint: every array argument
with a shardable axis (ndim >= 1) seeds taint — exactly the set
auto-sharding is free to partition — and taint propagates through
every equation. Replicated scalars (n_valid and friends) stay clean,
so e.g. ring_genesis-style `dynamic_slice(ids, (n_valid - 1, 0), ...)`
does not fire. Explicit `shard_map` bodies are SKIPPED: they are
manually partitioned and the GSPMD partitioner never sees them (the
repo's production sharded path is unaffected by this bug class by
construction).

Findings carry the file:line of the offending primitive's *user* source
(jax-internal frames are filtered), so a hit inside a library helper
points at the helper's line, and inline suppressions at that line work
exactly like the AST pass's.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from p2p_dhts_tpu.analysis.common import Finding, repo_rel

PASS = "gspmd"

#: Primitives whose output is (a view of) a slice of their first input —
#: provenance carriers for the concat-of-slices rule.
_SLICE_PRIMS = {"slice", "dynamic_slice"}
_VIEW_PRIMS = {"squeeze", "reshape", "convert_element_type",
               "broadcast_in_dim", "rev"}


@dataclasses.dataclass
class KernelSpec:
    """One public kernel to trace: `fn(*args)` must be traceable by
    jax.make_jaxpr. Array args with ndim >= 1 seed the sharded taint."""

    name: str
    fn: Callable
    args: Tuple[Any, ...]


class _SourceLines:
    """Cached source-line reads for rule classification."""

    def __init__(self) -> None:
        self._cache: Dict[str, List[str]] = {}

    def line(self, path: str, lineno: int) -> str:
        if path not in self._cache:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    self._cache[path] = fh.read().splitlines()
            except OSError:
                self._cache[path] = []
        lines = self._cache[path]
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1]
        return ""


def _user_frame(eqn) -> Optional[Tuple[str, int]]:
    try:
        from jax._src import source_info_util
        fr = source_info_util.user_frame(eqn.source_info)
    # chordax-lint: disable=bare-except -- source-info layout differs across jax versions; a missing frame just drops attribution
    except Exception:
        return None
    if fr is None:
        return None
    return fr.file_name, fr.start_line


class _JaxprScanner:
    def __init__(self, root: str, kernel: str):
        self.root = root
        self.kernel = kernel
        self.findings: set = set()
        self.src = _SourceLines()

    # -- finding emission ----------------------------------------------------
    def _emit(self, eqn, rule: str, msg: str) -> None:
        loc = _user_frame(eqn)
        if loc is None:
            return  # jax-internal only: nothing actionable to point at
        path, line = loc
        self.findings.add(Finding(
            repo_rel(path, self.root), line, rule,
            f"{msg} [kernel {self.kernel}]", PASS))

    def _classify_concat(self, eqn) -> Tuple[str, str]:
        loc = _user_frame(eqn)
        text = self.src.line(*loc) if loc else ""
        if "associative_scan" in text:
            return ("gspmd-associative-scan",
                    "associative_scan over sharded data lowers to "
                    "concat-of-slices, which jax 0.4.x GSPMD "
                    "auto-sharding miscompiles; rewrite as a "
                    "roll+select doubling reduction or an explicit "
                    "shard_map scan")
        return ("gspmd-concat-of-slices",
                "concatenate of slice(s) on a sharded operand — jax "
                "0.4.x's SPMD partitioner can sum the output across an "
                "unrelated mesh axis under auto-sharding; use "
                "dynamic-update-slice (see two_phase_hop_loop's merge)")

    # -- core walk -----------------------------------------------------------
    def scan(self, closed_jaxpr, taint_in: Sequence[bool]) -> List[bool]:
        return self._scan_jaxpr(closed_jaxpr.jaxpr, list(taint_in))

    def _scan_jaxpr(self, jaxpr, taint_in: List[bool]) -> List[bool]:
        from jax.core import Literal

        taint: Dict[Any, bool] = {}
        prov: Dict[Any, Any] = {}  # var -> source var it is a slice of

        for var in jaxpr.constvars:
            taint[var] = False
        for var, t in zip(jaxpr.invars, taint_in):
            taint[var] = bool(t)

        def t_of(v) -> bool:
            if isinstance(v, Literal):
                return False
            return taint.get(v, False)

        def p_of(v):
            if isinstance(v, Literal):
                return None
            return prov.get(v)

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            in_taint = [t_of(v) for v in eqn.invars]
            any_taint = any(in_taint)

            if name == "shard_map":
                # Manually partitioned: GSPMD never touches the body.
                for v in eqn.outvars:
                    taint[v] = any_taint
                continue

            sub = self._sub_jaxprs(eqn, in_taint)
            if sub is not None:
                out_taint = sub
                for v, t in zip(eqn.outvars, out_taint):
                    taint[v] = t or any_taint
                continue

            # -- pattern rules --------------------------------------------
            if name == "concatenate" and any_taint and len(eqn.invars) > 1:
                provs = [p_of(v) for v in eqn.invars]
                has_slice = any(p is not None for p in provs)
                same_source = (has_slice
                               and all(p is not None for p in provs)
                               and len({id(p) for p in provs}) == 1)
                if has_slice and not same_source:
                    rule, msg = self._classify_concat(eqn)
                    self._emit(eqn, rule, msg)
            elif name == "dynamic_slice":
                starts = eqn.invars[1:]
                if any(t_of(v) for v in starts):
                    self._emit(
                        eqn, "gspmd-dynamic-slice-traced-start",
                        "dynamic_slice start index derives from "
                        "sharded (batch/table) data — non-replicated "
                        "starts miscompile under GSPMD auto-sharding; "
                        "gather by index instead")

            # -- provenance + taint propagation ---------------------------
            if name in _SLICE_PRIMS and eqn.invars:
                src_v = eqn.invars[0]
                base = p_of(src_v)
                prov[eqn.outvars[0]] = base if base is not None else src_v
            elif name in _VIEW_PRIMS and eqn.invars:
                base = p_of(eqn.invars[0])
                if base is not None:
                    prov[eqn.outvars[0]] = base
            for v in eqn.outvars:
                taint[v] = any_taint

        return [t_of(v) for v in jaxpr.outvars]

    def _sub_jaxprs(self, eqn, in_taint: List[bool]
                    ) -> Optional[List[bool]]:
        """Descend into call-like primitives; returns outvar taint, or
        None when the primitive has no sub-jaxpr to walk."""
        name = eqn.primitive.name
        p = eqn.params
        if name == "pjit" and "jaxpr" in p:
            return self._scan_closed(p["jaxpr"], in_taint)
        if name == "while":
            cn, bn = p["cond_nconsts"], p["body_nconsts"]
            carry = in_taint[cn + bn:]
            body_consts = in_taint[cn:cn + bn]
            # Fixpoint over the carry: taint injected by the body flows
            # back around the loop. Monotone boolean taint over k carry
            # slots converges in at most k rounds (each round taints at
            # least one more slot or is stable).
            for _ in range(len(carry) + 1):
                out = self._scan_closed(p["body_jaxpr"],
                                        body_consts + carry)
                new = [a or b for a, b in zip(carry, out)]
                if new == carry:
                    break
                carry = new
            self._scan_closed(p["cond_jaxpr"], in_taint[:cn] + carry)
            return carry
        if name == "scan":
            nc, ncar = p["num_consts"], p["num_carry"]
            consts = in_taint[:nc]
            carry = in_taint[nc:nc + ncar]
            xs = in_taint[nc + ncar:]
            out: List[bool] = []
            for _ in range(len(carry) + 1):  # monotone: <= k rounds
                out = self._scan_closed(p["jaxpr"], consts + carry + xs)
                new = [a or b for a, b in zip(carry, out[:ncar])]
                if new == carry:
                    break
                carry = new
            return carry + out[ncar:]
        if name == "cond":
            ops = in_taint[1:]
            outs = None
            for br in p["branches"]:
                o = self._scan_closed(br, ops)
                outs = o if outs is None else [a or b
                                               for a, b in zip(outs, o)]
            return outs
        for key in ("call_jaxpr", "fun_jaxpr"):
            if key in p:
                return self._scan_closed(p[key], in_taint)
        return None

    def _scan_closed(self, closed, in_taint: List[bool]) -> List[bool]:
        inner = getattr(closed, "jaxpr", closed)
        n = len(inner.invars)
        padded = (list(in_taint) + [False] * n)[:n]
        return self._scan_jaxpr(inner, padded)


def analyze_kernel(spec: KernelSpec, root: str) -> List[Finding]:
    """Trace one kernel and scan its jaxpr for the known-bad patterns."""
    import jax

    closed = jax.make_jaxpr(spec.fn)(*spec.args)
    leaves = jax.tree_util.tree_leaves(spec.args)
    taint = [getattr(leaf, "ndim", 0) >= 1 for leaf in leaves]
    scanner = _JaxprScanner(root, spec.name)
    scanner.scan(closed, taint)
    return sorted(scanner.findings)


def run(specs: Sequence[KernelSpec], root: str) -> List[Finding]:
    findings: set = set()
    for spec in specs:
        findings.update(analyze_kernel(spec, root))
    return sorted(findings)


def run_default(root: str) -> List[Finding]:
    from p2p_dhts_tpu.analysis.registry import default_kernels
    return run(default_kernels(), root)

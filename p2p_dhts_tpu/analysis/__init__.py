"""chordax-lint: seven-pass static analysis for the repo's hard-bug
classes, with a CLI (`python -m p2p_dhts_tpu.analysis`) and CI gates.

  Pass 1  trace-safety     AST: jit-boundary hazards (Python control
                           flow over traced values, host syncs,
                           per-call jit wrappers, shard_map imports
                           bypassing compat.py, bare excepts).
  Pass 2  gspmd            jaxpr: the known jax-0.4.x GSPMD miscompile
                           patterns (concat-of-slices on sharded axes,
                           associative_scan under auto-sharding,
                           dynamic_slice with traced starts), traced
                           over the registered kernels on a simulated
                           8-device mesh; the registry itself is
                           audited — every public jit'd kernel must be
                           traced or carry a reasoned exemption.
  Pass 3  lock-discipline  static lock-order graph + blocking-call
                           audit over every lock-bearing module (the
                           module list is DISCOVERED, and the curated
                           DEFAULT_LOCK_MODULES tuple is audited
                           against the discovery); an opt-in runtime
                           watchdog (CHORDAX_LOCK_CHECK=1) verifies
                           the order during soaks.
  Pass 4  metrics          metric-key doc-drift gate (chordax-scope):
                           every dotted key recorded in code must
                           appear in README.md's metric-key inventory
                           table, and every inventory row must still
                           have a recording site.
  Pass 5  epochs           epoch-monotonicity contract: every write to
                           an epoch/generation-bearing field must be a
                           monotonic increment or guard-dominated, and
                           ordered epoch compares must agree on one
                           boundary family (`>` vs `>=` drift).
  Pass 6  lifecycle        zombie-loop + stale-telemetry classes: every
                           loop/thread/pool starter must have a
                           reachable stop, and every identity-suffixed
                           metric family must have a retirement path.
  Pass 7  verbs            wire-contract drift gate: registered verbs
                           must be exercised and documented, documented
                           verbs must exist, envelope header fields and
                           README's vocabulary cannot drift either way.

Inline suppressions: `# chordax-lint: disable=<rule> -- <reason>`
(reason mandatory; see analysis.common). `run_all` is the library
entry the pytest session gate and the dryrun scan stage call. An
`analysis_baseline.json` at the root is applied as a diff valve —
only NEW findings gate; every baseline entry needs a reason and stale
entries are themselves findings.

This package imports jax only inside Pass 2 — the other passes (and
the runtime watchdog) stay importable in processes whose accelerator
runtime is unusable, the same hygiene rule as `__graft_entry__`.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

from p2p_dhts_tpu.analysis.common import (  # noqa: F401
    Finding,
    SuppressionIndex,
    apply_baseline,
    apply_suppressions,
    json_report,
    package_files,
    render_report,
)

ALL_PASSES = ("trace", "gspmd", "locks", "metrics", "epochs",
              "lifecycle", "verbs")


def default_root() -> str:
    """The repo checkout this package is installed in."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_all(root: Optional[str] = None,
            passes: Sequence[str] = ALL_PASSES,
            files: Optional[Sequence[str]] = None,
            baseline: Optional[str] = None,
            ) -> Tuple[List[Finding], int]:
    """Run the selected passes over the shipped tree; returns
    (unsuppressed findings incl. suppression-hygiene and baseline
    problems, n_suppressed — inline suppressions plus baselined).

    `files` restricts the scan set and is only meaningful for the
    AST-driven trace pass; the locks pass scans its discovered
    module list and the gspmd pass traces the IMPORTED package's
    kernels regardless, so combining `files` with those passes would
    silently analyze files the caller never named.

    `baseline` names the diff-mode baseline file; by default
    `<root>/analysis_baseline.json` is applied when present (a missing
    file is simply no baseline — see common.apply_baseline)."""
    if files is not None and set(passes) - {"trace"}:
        raise ValueError(
            "run_all(files=...) only supports passes=('trace',); the "
            "other passes scan discovered module/registry sets")
    root = root if root is not None else default_root()
    scan_files = list(files) if files is not None else package_files(root)
    raw: List[Finding] = []
    if "trace" in passes:
        from p2p_dhts_tpu.analysis import trace_safety
        raw.extend(trace_safety.run(scan_files, root))
    if "locks" in passes:
        from p2p_dhts_tpu.analysis import lockcheck
        raw.extend(lockcheck.run_default(root))
    if "gspmd" in passes:
        from p2p_dhts_tpu.analysis import gspmd, registry
        raw.extend(gspmd.run_default(root))
        raw.extend(registry.coverage_findings(root))
    if "metrics" in passes:
        from p2p_dhts_tpu.analysis import metric_keys
        raw.extend(metric_keys.run_default(root))
    if "epochs" in passes:
        from p2p_dhts_tpu.analysis import epochs
        raw.extend(epochs.run_default(root))
    if "lifecycle" in passes:
        from p2p_dhts_tpu.analysis import lifecycle
        raw.extend(lifecycle.run_default(root))
    if "verbs" in passes:
        from p2p_dhts_tpu.analysis import verbs
        raw.extend(verbs.run_default(root))
    # Index EVERY scanned file up front, not just files with findings:
    # a reasonless or unknown-rule suppression in an otherwise-clean
    # file must still surface as a lint-suppression finding, or stale
    # opt-outs rot silently (the documented contract).
    from p2p_dhts_tpu.analysis.common import SuppressionIndex, repo_rel
    index = SuppressionIndex()
    for path in scan_files:
        index.add_file(path, repo_rel(path, root))
    findings, n_sup, _ = apply_suppressions(raw, root, index)
    findings, n_baselined, problems = apply_baseline(
        findings, root, baseline_path=baseline)
    findings = sorted(set(findings) | set(problems))
    return findings, n_sup + n_baselined

"""Pass 7 (verbs): both-directions wire-contract drift gate.

The protocol surface is three handler installs (the gateway's
`update_handlers({...})` map, the two overlay peers' `handlers()`
dicts) plus an envelope vocabulary of ALLCAPS header fields
(DEADLINE_MS, TRACE, FWD, ROUTES_EPOCH, MESH, ...). Pass 4 proved the
discipline for metric keys: extract reality from the AST, extract the
contract from README, and flag drift in BOTH directions. This pass
applies it to the wire:

  * `verb-unreachable`   — a verb registered in the package has no
    client call site (`{"COMMAND": "X"}` literal) anywhere in the
    package, tests, bench, or the graft harness: dead protocol
    surface nobody can regress-test.
  * `verb-undocumented`  — a registered verb missing from README's
    `#### Verbs` table (or, for the gateway, from the
    `GATEWAY_COMMANDS` declaration tuple next to its install).
  * `verb-stale`         — a README `#### Verbs` row (or a
    `GATEWAY_COMMANDS` entry) naming a verb nothing registers.
  * `verb-unregistered`  — a non-test client site sends a verb no
    handler install anywhere claims: the request can only ever come
    back `unknown command`. Tests are exempt (they probe exactly that
    error path with fabricated verbs).
  * `field-undocumented` — an envelope field used on the wire that is
    missing from README's `#### Header fields` table.
  * `field-stale`        — a documented header field no code reads or
    writes.

"Used on the wire" means: a non-`COMMAND` ALLCAPS key of a request
dict literal (a dict literal that carries a `"COMMAND"` key), an
ALLCAPS key read/written/popped on a message-shaped receiver
(req/resp/out/msg/base/envelope/header names), or the value of a
module-level `*_KEY = "ALLCAPS"` constant (trace.py's
`WIRE_KEY = "TRACE"`). `CHORDAX_*`/`JAX_*`/`XLA_*` names are
environment variables, not wire fields, and are excluded.

Pure AST + README text; this module never imports jax.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from p2p_dhts_tpu.analysis.common import (Finding, KNOWN_RULES,
                                          package_files, repo_rel)
from p2p_dhts_tpu.analysis.metric_keys import _BACKTICK_RE

PASS = "verbs"

for _rule in ("verb-unreachable", "verb-undocumented", "verb-stale",
              "verb-unregistered", "field-undocumented", "field-stale"):
    KNOWN_RULES.add(_rule)

#: README headings the canonical vocabulary lives under (both inside
#: the `### Wire-verb vocabulary` section of the chordax-lint docs).
VERBS_HEADING = "#### Verbs"
FIELDS_HEADING = "#### Header fields"

_ALLCAPS_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
_ENV_PREFIX_RE = re.compile(r"^(CHORDAX_|JAX_|XLA_|TPU_)")
#: Variable names that carry wire envelopes (requests on the way out,
#: handler args on the way in, response dicts on the way back).
_RECEIVER_RE = re.compile(r"req|resp|msg|out|base|envelope|header", re.I)
#: Accessor methods on a message dict whose first string arg is a field.
_DICT_ACCESSORS = ("get", "pop", "setdefault")

Site = Tuple[str, int]  # (repo-relative path, line)


def _is_field_name(name: object) -> bool:
    return (isinstance(name, str) and name != "COMMAND"
            and bool(_ALLCAPS_RE.match(name))
            and not _ENV_PREFIX_RE.match(name))


class WireSurface:
    """Everything pass 7 extracts from one tree scan."""

    def __init__(self) -> None:
        #: verb -> first install site inside the package proper.
        self.registered: Dict[str, Site] = {}
        #: verbs installed anywhere scanned (package + bench + graft) —
        #: the "someone answers this" set for the unregistered check.
        self.known: Set[str] = set()
        #: verb -> client sites ({"COMMAND": "X"} literals), all files.
        self.clients: Dict[str, List[Site]] = {}
        #: verb -> client sites outside tests/ (held to verb-unregistered).
        self.package_clients: Dict[str, List[Site]] = {}
        #: field -> first use site inside the package proper.
        self.fields: Dict[str, Site] = {}
        #: GATEWAY_COMMANDS-style declaration tuples: verb -> site.
        self.declared: Dict[str, Site] = {}


def _handler_dicts(tree: ast.AST):
    """Yield every handler-map dict literal: the argument of an
    `update_handlers({...})` call, or a dict returned from a function
    named `handlers`."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "update_handlers" and node.args and \
                isinstance(node.args[0], ast.Dict):
            yield node.args[0]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node.name == "handlers":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and \
                        isinstance(sub.value, ast.Dict):
                    yield sub.value


def _scan_file(path: str, rel: str, in_package: bool, in_tests: bool,
               surface: WireSurface) -> None:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return

    # -- handler installs --------------------------------------------------
    for hmap in _handler_dicts(tree):
        for key in hmap.keys:
            if isinstance(key, ast.Constant) and \
                    isinstance(key.value, str) and \
                    _ALLCAPS_RE.match(key.value):
                surface.known.add(key.value)
                if in_package:
                    surface.registered.setdefault(
                        key.value, (rel, key.lineno))

    # -- GATEWAY_COMMANDS-style declaration tuples -------------------------
    if in_package:
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.endswith("_COMMANDS") \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        surface.declared.setdefault(
                            elt.value, (rel, elt.lineno))

    # -- client call sites + envelope fields -------------------------------
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            verb: Optional[str] = None
            for key, val in zip(node.keys, node.values):
                if isinstance(key, ast.Constant) and \
                        key.value == "COMMAND" and \
                        isinstance(val, ast.Constant) and \
                        isinstance(val.value, str):
                    verb = val.value
            if verb is None:
                continue
            site = (rel, node.lineno)
            self_clients = surface.clients.setdefault(verb, [])
            self_clients.append(site)
            if not in_tests:
                surface.package_clients.setdefault(verb, []).append(site)
            if in_package:
                for key in node.keys:
                    if isinstance(key, ast.Constant) and \
                            _is_field_name(key.value):
                        surface.fields.setdefault(
                            key.value, (rel, key.lineno))

        if not in_package:
            continue
        # Reads/writes/pops on message-shaped receivers.
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                _RECEIVER_RE.search(node.value.id) and \
                isinstance(node.slice, ast.Constant) and \
                _is_field_name(node.slice.value):
            surface.fields.setdefault(
                node.slice.value, (rel, node.lineno))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _DICT_ACCESSORS and \
                isinstance(node.func.value, ast.Name) and \
                _RECEIVER_RE.search(node.func.value.id) and \
                node.args and isinstance(node.args[0], ast.Constant) and \
                _is_field_name(node.args[0].value):
            surface.fields.setdefault(
                node.args[0].value, (rel, node.lineno))
        # Module-level wire-key constants: WIRE_KEY = "TRACE".
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.endswith("_KEY") and \
                isinstance(node.value, ast.Constant) and \
                _is_field_name(node.value.value):
            surface.fields.setdefault(
                node.value.value, (rel, node.lineno))


def extract_surface(files: Sequence[str], root: str) -> WireSurface:
    """Scan `files` (the package set) plus tests/ for the wire surface."""
    surface = WireSurface()
    test_files = sorted(
        glob.glob(os.path.join(root, "tests", "**", "*.py"),
                  recursive=True))
    for path in list(files) + test_files:
        rel = repo_rel(path, root)
        in_tests = rel.startswith("tests" + os.sep) or \
            rel.startswith("tests/")
        in_package = rel.replace(os.sep, "/").startswith("p2p_dhts_tpu/")
        _scan_file(path, rel, in_package, in_tests, surface)
    return surface


def _doc_table(readme_path: str, heading: str) -> Dict[str, int]:
    """First backticked cell of each table row under `heading` ->
    1-based README line. Empty when the README/section is missing."""
    rows: Dict[str, int] = {}
    try:
        with open(readme_path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return rows
    in_section = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if stripped == heading:
            in_section = True
            continue
        if in_section and stripped.startswith("#"):
            break
        if in_section and stripped.startswith("|"):
            m = _BACKTICK_RE.search(stripped)
            if m:
                rows.setdefault(m.group(1), i)
    return rows


def run(files: Sequence[str], root: str,
        readme_path: Optional[str] = None) -> List[Finding]:
    if readme_path is None:
        readme_path = os.path.join(root, "README.md")
    surface = extract_surface(files, root)
    doc_verbs = _doc_table(readme_path, VERBS_HEADING)
    doc_fields = _doc_table(readme_path, FIELDS_HEADING)
    readme_rel = repo_rel(readme_path, root)

    findings: List[Finding] = []

    for verb, (rel, line) in sorted(surface.registered.items()):
        if verb not in surface.clients:
            findings.append(Finding(
                rel, line, "verb-unreachable",
                f"registered verb '{verb}' has no client call site "
                f"(no {{\"COMMAND\": \"{verb}\"}} literal in the "
                f"package, tests, bench, or graft harness) — dead "
                f"protocol surface nobody can regress-test", PASS))
        if verb not in doc_verbs:
            findings.append(Finding(
                rel, line, "verb-undocumented",
                f"registered verb '{verb}' is missing from README's "
                f"`{VERBS_HEADING}` vocabulary table", PASS))
        # Gateway declaration-tuple sync: an installed gateway verb
        # must appear in GATEWAY_COMMANDS (same-module declaration).
        if surface.declared and verb not in surface.declared and \
                any(d[0] == rel for d in surface.declared.values()):
            findings.append(Finding(
                rel, line, "verb-undocumented",
                f"verb '{verb}' is installed but missing from the "
                f"*_COMMANDS declaration tuple in {rel}", PASS))

    for verb, line in sorted(doc_verbs.items()):
        if verb not in surface.registered:
            findings.append(Finding(
                readme_rel, line, "verb-stale",
                f"README documents wire verb '{verb}' but no handler "
                f"install registers it", PASS))
    for verb, (rel, line) in sorted(surface.declared.items()):
        if verb not in surface.registered:
            findings.append(Finding(
                rel, line, "verb-stale",
                f"*_COMMANDS declares verb '{verb}' but no handler "
                f"install registers it", PASS))

    for verb, sites in sorted(surface.package_clients.items()):
        if verb not in surface.known:
            rel, line = sites[0]
            findings.append(Finding(
                rel, line, "verb-unregistered",
                f"client sends verb '{verb}' but no handler install "
                f"anywhere registers it — the request can only come "
                f"back `unknown command`", PASS))

    for field, (rel, line) in sorted(surface.fields.items()):
        if field not in doc_fields:
            findings.append(Finding(
                rel, line, "field-undocumented",
                f"wire header field '{field}' is missing from "
                f"README's `{FIELDS_HEADING}` vocabulary table", PASS))
    for field, line in sorted(doc_fields.items()):
        if field not in surface.fields:
            findings.append(Finding(
                readme_rel, line, "field-stale",
                f"README documents wire header field '{field}' but "
                f"no code reads or writes it", PASS))

    return sorted(set(findings))


def run_default(root: str) -> List[Finding]:
    return run(package_files(root), root)

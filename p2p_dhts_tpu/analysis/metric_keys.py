"""Pass 4 (metrics): the metric-key doc-drift gate (chordax-scope).

Four subsystems record dotted metric keys (`serve.*`, `gateway.*`,
`repair.*`, `membership.*`, `rpc.*`) and dashboards/tests read them by
name; nothing used to stop a new key (or a renamed one) from silently
forking the namespace. This pass pins code and docs to each other:

  * CODE -> DOC: every dotted key recorded in the shipped tree
    (literal or f-string first argument to a Metrics recorder —
    inc / gauge / observe / observe_hist / observe_hist_many) must
    appear in README.md's "Metric-key inventory" table, with f-string
    interpolations normalized to one `<*>` wildcard segment (so
    ``f"gateway.requests.{op}.{rid}"`` matches the documented
    ``gateway.requests.<op>.<ring>``).
  * DOC -> CODE: every inventory row must still have a recording site,
    so the table cannot rot into folklore.

Non-literal key arguments (a plain variable) are out of scope by
construction — the registry's own internals pass names through — and
the scan only considers keys with at least one dot, which is the
package's universal key shape.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from p2p_dhts_tpu.analysis.common import (Finding, KNOWN_RULES,
                                          package_files, repo_rel)

PASS = "metrics"

KNOWN_RULES.add("metric-key-undocumented")
KNOWN_RULES.add("metric-key-stale")

#: Metrics recorder method names whose first argument is a key
#: (`timed` is the context-manager form of `observe`).
RECORDERS = ("inc", "gauge", "observe", "observe_hist",
             "observe_hist_many", "timed")

#: The README heading the inventory table lives under.
INVENTORY_HEADING = "### Metric-key inventory"

#: One wildcard segment in a normalized pattern.
WILD = "<*>"

_PLACEHOLDER_RE = re.compile(r"<[^<>]*>")
_BACKTICK_RE = re.compile(r"`([^`]+)`")


def _literal_pattern(node: ast.AST) -> Optional[str]:
    """The normalized key pattern of a recorder's first argument:
    a str constant verbatim, an f-string with every interpolation
    replaced by `<*>`, None for anything unresolvable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for piece in node.values:
            if isinstance(piece, ast.Constant) and \
                    isinstance(piece.value, str):
                parts.append(piece.value)
            elif isinstance(piece, ast.FormattedValue):
                parts.append(WILD)
            else:
                return None
        return "".join(parts)
    return None


def extract_code_patterns(path: str) -> List[Tuple[str, int]]:
    """(pattern, line) per recorder call with a resolvable dotted key
    in one file. Self-scan exclusions: the Metrics class itself (whose
    internals pass caller-supplied names through) is in metrics.py,
    where every recorder's first parameter is `name` — those sites
    have non-literal args and drop out naturally."""
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in RECORDERS \
                and node.args:
            pattern = _literal_pattern(node.args[0])
            if pattern is not None and "." in pattern:
                out.append((pattern, node.lineno))
        # PacedLoop sites hand their round-failure counter key to the
        # base as `failure_metric=...` — the base records it through a
        # variable, so the key's ONE literal home is the kwarg.
        for kw in node.keywords:
            if kw.arg != "failure_metric":
                continue
            pattern = _literal_pattern(kw.value)
            if pattern is not None and "." in pattern:
                out.append((pattern, node.lineno))
    return out


def normalize_doc_pattern(key: str) -> str:
    """`gateway.requests.<op>.<ring>` -> `gateway.requests.<*>.<*>`."""
    return _PLACEHOLDER_RE.sub(WILD, key)


def inventory_patterns(readme_path: str) -> Dict[str, int]:
    """{normalized pattern: line} from the README inventory table
    (first backticked cell of each table row under the inventory
    heading, up to the next heading)."""
    out: Dict[str, int] = {}
    try:
        with open(readme_path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return out
    in_section = False
    for i, line in enumerate(lines, 1):
        if line.strip().startswith("#"):
            in_section = line.strip() == INVENTORY_HEADING
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        m = _BACKTICK_RE.search(line)
        if m is None:
            continue
        key = m.group(1).strip()
        if "." not in key:
            continue
        out.setdefault(normalize_doc_pattern(key), i)
    return out


def run(files, root: str) -> List[Finding]:
    readme = os.path.join(root, "README.md")
    documented = inventory_patterns(readme)
    findings: List[Finding] = []
    if not documented:
        findings.append(Finding(
            path="README.md", line=1, rule="metric-key-stale",
            message=f"no {INVENTORY_HEADING!r} table found — the "
                    f"metric-key namespace has no inventory to gate "
                    f"against", pass_name=PASS))
        return findings
    seen_patterns: Dict[str, Tuple[str, int]] = {}
    for path in files:
        for pattern, line in extract_code_patterns(path):
            seen_patterns.setdefault(pattern, (path, line))
            if pattern not in documented:
                findings.append(Finding(
                    path=repo_rel(path, root), line=line,
                    rule="metric-key-undocumented",
                    message=f"metric key {pattern!r} is recorded here "
                            f"but missing from README.md's metric-key "
                            f"inventory", pass_name=PASS))
    for pattern, line in sorted(documented.items(),
                                key=lambda kv: kv[1]):
        if pattern not in seen_patterns:
            findings.append(Finding(
                path="README.md", line=line, rule="metric-key-stale",
                message=f"inventory row {pattern!r} has no recording "
                        f"site left in the shipped tree — drop the row "
                        f"or restore the key", pass_name=PASS))
    return findings


def run_default(root: str) -> List[Finding]:
    return run(package_files(root), root)

"""Pass 3 — lock discipline: static order graph + runtime watchdog.

The threaded serving layer (ServeEngine's dispatcher/completer pair,
the RPC server's worker pool, the finger table's degrade state) has
exactly two documented failure classes: acquiring locks in inconsistent
order across threads (deadlock), and holding a lock across a blocking
call (convoy / stall — the "callers MUST NOT hold locks the completion
of other requests needs" rule in serve.py's docstring).

Static half (pure AST, no imports of the analyzed code):

  * discovers lock objects — `self._x = threading.Lock()/RLock()`,
    module-level locks, `threading.Condition(lock)` associations, plus
    `queue.Queue` / `threading.Thread` / `threading.Event` attributes
    (their .get/.put/.join/.wait are blocking);
  * walks each function with the syntactic `with <lock>:` nesting as
    the held-set, recording acquisition-order edges, and follows
    same-module calls ONE level deep through per-function summaries
    (locks a callee acquires, whether it blocks). Cross-module calls
    are out of scope — the runtime watchdog covers those;
  * reports: `lock-order-cycle` (every acquisition edge on a cycle,
    anchored at its `with` line), `lock-held-across-blocking` (sleep,
    socket I/O, queue get/put, thread join, Condition/Event wait,
    device sync via np.asarray/device_get/block_until_ready — waiting
    on a Condition is exempt when the ONLY held lock is the
    condition's own, which wait() releases), and `lock-reacquire`
    (nested `with` on a non-reentrant Lock).

Runtime half (opt-in, `CHORDAX_LOCK_CHECK=1` at import of
`p2p_dhts_tpu`, or `WATCHDOG.install()` from a test): patches
`threading.Lock`/`threading.RLock` so every lock created AFTER install
is wrapped with creation-site bookkeeping. Each thread keeps its held
stack; acquiring B while holding A records the site-level edge A->B,
and an edge whose reverse was ever observed is a violation — the
dynamic twin of the static order graph, catching the cross-module and
data-dependent orders the AST cannot see. `WATCHDOG.assert_clean()` is
the soak-test hook. This module never imports jax.
"""

from __future__ import annotations

import ast
import os
import sys
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from p2p_dhts_tpu.analysis.common import (Finding, KNOWN_RULES,
                                          dotted_name as _dotted,
                                          package_files, repo_rel)

PASS = "lock-discipline"

KNOWN_RULES.add("lock-module-uncovered")
KNOWN_RULES.add("lock-module-stale")

#: The threaded serving layer — the default static-analysis surface.
#: The gateway front door (ISSUE 4) is part of it: its documented lock
#: order (router/backend/admission locks are LEAVES, never held across
#: engine calls — gateway/router.py docstring) is audited here.
#: This tuple is a reviewed DECLARATION, not the source of coverage:
#: discover_lock_modules() scans the whole package for lock/thread/
#: queue constructors, and registry_findings() fails the gate when a
#: lock-bearing module is missing here (lock-module-uncovered) or a
#: listed module stopped constructing any (lock-module-stale).
DEFAULT_LOCK_MODULES = (
    os.path.join("p2p_dhts_tpu", "serve.py"),
    os.path.join("p2p_dhts_tpu", "metrics.py"),
    os.path.join("p2p_dhts_tpu", "net", "rpc.py"),
    os.path.join("p2p_dhts_tpu", "net", "wire.py"),
    os.path.join("p2p_dhts_tpu", "net", "native_rpc.py"),
    os.path.join("p2p_dhts_tpu", "overlay", "finger_table.py"),
    os.path.join("p2p_dhts_tpu", "overlay", "jax_bridge.py"),
    os.path.join("p2p_dhts_tpu", "overlay", "chord_peer.py"),
    os.path.join("p2p_dhts_tpu", "overlay", "database.py"),
    os.path.join("p2p_dhts_tpu", "overlay", "remote_peer.py"),
    os.path.join("p2p_dhts_tpu", "gateway", "router.py"),
    os.path.join("p2p_dhts_tpu", "gateway", "admission.py"),
    os.path.join("p2p_dhts_tpu", "gateway", "cache.py"),
    os.path.join("p2p_dhts_tpu", "gateway", "frontend.py"),
    os.path.join("p2p_dhts_tpu", "repair", "scheduler.py"),
    os.path.join("p2p_dhts_tpu", "repair", "replication.py"),
    os.path.join("p2p_dhts_tpu", "membership", "manager.py"),
    os.path.join("p2p_dhts_tpu", "trace.py"),
    os.path.join("p2p_dhts_tpu", "health.py"),
    os.path.join("p2p_dhts_tpu", "havoc.py"),
    os.path.join("p2p_dhts_tpu", "pulse.py"),
    os.path.join("p2p_dhts_tpu", "ops", "ida_backend.py"),
    os.path.join("p2p_dhts_tpu", "lens", "__init__.py"),
    os.path.join("p2p_dhts_tpu", "mesh", "routes.py"),
    os.path.join("p2p_dhts_tpu", "mesh", "plane.py"),
    os.path.join("p2p_dhts_tpu", "mesh", "peer.py"),
    os.path.join("p2p_dhts_tpu", "elastic", "ledger.py"),
    os.path.join("p2p_dhts_tpu", "elastic", "policy.py"),
    os.path.join("p2p_dhts_tpu", "mesh", "fold.py"),
    os.path.join("p2p_dhts_tpu", "edge", "routes.py"),
    os.path.join("p2p_dhts_tpu", "edge", "hedge.py"),
    os.path.join("p2p_dhts_tpu", "edge", "client.py"),
    os.path.join("p2p_dhts_tpu", "tower", "collector.py"),
    os.path.join("p2p_dhts_tpu", "analysis", "lockcheck.py"),
)

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond",
               "Queue": "queue", "Thread": "thread", "Event": "event",
               "Semaphore": "lock", "BoundedSemaphore": "lock"}

#: Dotted call names that block the calling thread outright.
_BLOCKING_CALLS = {"time.sleep", "sleep", "socket.create_connection",
                   "subprocess.run", "subprocess.check_call",
                   "subprocess.check_output", "jax.device_get",
                   "np.asarray", "numpy.asarray",
                   "jax.block_until_ready"}

#: Method names that block regardless of receiver (socket I/O, device
#: sync). `.wait`/`.get`/`.put`/`.join` are resolved against the
#: discovered attribute kinds instead — `.get` on a dict or `.join` on
#: a str must not fire.
_BLOCKING_METHODS = {"accept", "recv", "recv_into", "sendall", "connect",
                     "block_until_ready"}


def _ctor_kind(call: ast.AST) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    d = _dotted(call.func)
    if d is None:
        return None
    base = d.rsplit(".", 1)[-1]
    return _LOCK_CTORS.get(base)


class _ModuleModel:
    """Discovered lock/queue/thread attributes + function summaries."""

    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.base = os.path.splitext(os.path.basename(rel))[0]
        # key -> kind ("lock"/"rlock"/"cond"/"queue"/"thread"/"event")
        self.kinds: Dict[str, str] = {}
        # condition key -> its underlying lock key (None = private)
        self.cond_lock: Dict[str, Optional[str]] = {}
        self.functions: Dict[str, ast.AST] = {}
        self._discover(tree)

    # keys: "<base>:<Class>.<attr>" or "<base>:<global>"
    def attr_key(self, cls: Optional[str], attr: str) -> str:
        return f"{self.base}:{cls}.{attr}" if cls else f"{self.base}:{attr}"

    def _discover(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._note_assign(stmt, None)
            elif isinstance(stmt, ast.FunctionDef):
                self.functions[stmt.name] = stmt
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, ast.FunctionDef):
                        self.functions[f"{stmt.name}.{sub.name}"] = sub
                        for node in ast.walk(sub):
                            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                                self._note_assign(node, stmt.name)

    def _note_assign(self, stmt, cls: Optional[str]) -> None:
        value = stmt.value
        kind = _ctor_kind(value)
        if kind is None:
            return
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for tgt in targets:
            attr = None
            if isinstance(tgt, ast.Name) and cls is None:
                attr = tgt.id
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self" and cls is not None:
                attr = tgt.attr
            if attr is None:
                continue
            key = self.attr_key(cls, attr)
            self.kinds[key] = kind
            if kind == "cond":
                lock_key = None
                if value.args:
                    lk = self._lock_expr_key(value.args[0], cls)
                    lock_key = lk
                self.cond_lock[key] = lock_key

    def _lock_expr_key(self, expr: ast.AST, cls: Optional[str]
                       ) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return self.attr_key(cls, expr.attr)
        if isinstance(expr, ast.Name):
            key = self.attr_key(None, expr.id)
            return key if key in self.kinds else None
        return None


class _FnSummary:
    __slots__ = ("acquires", "blocking")

    def __init__(self) -> None:
        self.acquires: Set[str] = set()
        self.blocking: Optional[str] = None  # description of first block


class _LockWalker:
    """Per-function walk with the syntactic held-set."""

    def __init__(self, model: _ModuleModel, cls: Optional[str],
                 summaries: Dict[str, _FnSummary],
                 edges: Dict[Tuple[str, str], List[Tuple[str, int]]],
                 findings: List[Finding]):
        self.model = model
        self.cls = cls
        self.summaries = summaries
        self.edges = edges
        self.findings = findings

    def _flag(self, line: int, rule: str, msg: str) -> None:
        self.findings.append(
            Finding(self.model.rel, line, rule, msg, PASS))

    def _resolve(self, expr: ast.AST) -> Optional[Tuple[str, str]]:
        key = self.model._lock_expr_key(expr, self.cls)
        if key is None:
            return None
        kind = self.model.kinds.get(key)
        if kind in ("lock", "rlock"):
            return key, kind
        return None

    def walk_function(self, fn: ast.AST) -> None:
        self._walk(fn.body, [])

    # -- statement recursion -------------------------------------------------
    def _walk(self, stmts: Sequence[ast.stmt],
              held: List[Tuple[str, str]]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in stmt.items:
                    res = self._resolve(item.context_expr)
                    if res is None:
                        self._scan_calls(item.context_expr, held)
                        continue
                    key, kind = res
                    if kind == "lock" and any(h == key for h, _ in held):
                        self._flag(stmt.lineno, "lock-reacquire",
                                   f"nested `with` on non-reentrant "
                                   f"lock {key} (already held) "
                                   f"deadlocks")
                    for h, _ in held:
                        if h != key:
                            self.edges.setdefault((h, key), []).append(
                                (self.model.rel, stmt.lineno))
                    held.append((key, kind))
                    pushed += 1
                self._walk(stmt.body, held)
                for _ in range(pushed):
                    held.pop()
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(stmt.body, [])  # runs later, on its own stack
            elif isinstance(stmt, ast.If):
                self._scan_calls(stmt.test, held)
                self._walk(stmt.body, held)
                self._walk(stmt.orelse, held)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_calls(stmt.iter, held)
                self._walk(stmt.body, held)
                self._walk(stmt.orelse, held)
            elif isinstance(stmt, ast.While):
                self._scan_calls(stmt.test, held)
                self._walk(stmt.body, held)
                self._walk(stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, held)
                for h in stmt.handlers:
                    self._walk(h.body, held)
                self._walk(stmt.orelse, held)
                self._walk(stmt.finalbody, held)
            elif isinstance(stmt, ast.ClassDef):
                continue
            else:
                self._scan_calls(stmt, held)

    # -- call classification --------------------------------------------------
    def _scan_calls(self, node: ast.AST,
                    held: List[Tuple[str, str]]) -> None:
        for call in ast.walk(node):
            if isinstance(call, ast.Call):
                self._on_call(call, held)

    def _attr_kind_of_receiver(self, func: ast.Attribute
                               ) -> Optional[Tuple[str, str]]:
        key = self.model._lock_expr_key(func.value, self.cls)
        if key is None:
            return None
        kind = self.model.kinds.get(key)
        return (key, kind) if kind else None

    def _blocking_desc(self, call: ast.Call,
                       held: List[Tuple[str, str]]
                       ) -> Optional[Tuple[str, bool]]:
        """(description, is_exempt_condition_wait) or None."""
        d = _dotted(call.func)
        if d in _BLOCKING_CALLS:
            return d, False
        if isinstance(call.func, ast.Attribute):
            meth = call.func.attr
            if meth in _BLOCKING_METHODS:
                return f".{meth}()", False
            rk = self._attr_kind_of_receiver(call.func)
            if rk is not None:
                key, kind = rk
                if kind == "cond" and meth in ("wait", "wait_for"):
                    assoc = self.model.cond_lock.get(key)
                    others = [h for h, _ in held if h != assoc]
                    if not others:
                        return None  # wait() releases the only held lock
                    return (f"{key}.wait() (releases only {assoc}; "
                            f"still holding {others})", False)
                if kind == "queue" and meth in ("get", "put", "join"):
                    return f"{key}.{meth}()", False
                if kind == "thread" and meth == "join":
                    return f"{key}.join()", False
                if kind == "event" and meth == "wait":
                    return f"{key}.wait()", False
        return None

    def _on_call(self, call: ast.Call,
                 held: List[Tuple[str, str]]) -> None:
        if not held:
            return
        desc = self._blocking_desc(call, held)
        if desc is not None:
            self._flag(call.lineno, "lock-held-across-blocking",
                       f"blocking call {desc[0]} while holding "
                       f"{[h for h, _ in held]}")
            return
        # One-level closure through same-module calls.
        summary = self._callee_summary(call)
        if summary is None:
            return
        for key in summary.acquires:
            if key not in {h for h, _ in held}:
                for h, _ in held:
                    if h != key:
                        self.edges.setdefault((h, key), []).append(
                            (self.model.rel, call.lineno))
        if summary.blocking is not None:
            self._flag(call.lineno, "lock-held-across-blocking",
                       f"call blocks ({summary.blocking}) while holding "
                       f"{[h for h, _ in held]}")

    def _callee_summary(self, call: ast.Call) -> Optional[_FnSummary]:
        func = call.func
        name = None
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "self" \
                and self.cls is not None:
            name = f"{self.cls}.{func.attr}"
        elif isinstance(func, ast.Name):
            name = func.id
        if name is None:
            return None
        return self.summaries.get(name)


def _summarize(model: _ModuleModel) -> Dict[str, _FnSummary]:
    out: Dict[str, _FnSummary] = {}
    for qual, fn in model.functions.items():
        cls = qual.split(".")[0] if "." in qual else None
        s = _FnSummary()
        sink: List[Finding] = []
        walker = _LockWalker(model, cls, {}, {}, sink)

        def collect(stmts):
            for stmt in stmts:
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            res = walker._resolve(item.context_expr)
                            if res is not None:
                                s.acquires.add(res[0])
                    elif isinstance(node, ast.Call) and s.blocking is None:
                        d = walker._blocking_desc(node, [("?", "lock")])
                        if d is not None:
                            s.blocking = d[0]

        collect(fn.body)
        out[qual] = s
        if "." in qual:
            out.setdefault(qual.split(".", 1)[1], s)
    return out


def _edges_on_cycles(edges: Dict[Tuple[str, str], List[Tuple[str, int]]]
                     ) -> List[Tuple[Tuple[str, str], Tuple[str, int]]]:
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)

    def reachable(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
        return False

    out = []
    for (a, b), sites in edges.items():
        if reachable(b, a):
            for site in sites:
                out.append(((a, b), site))
    return out


def run(paths: Sequence[str], root: str) -> List[Finding]:
    findings: List[Finding] = []
    edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
    for path in paths:
        rel = repo_rel(path, root)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError) as exc:
            findings.append(Finding(rel, 1, "lint-suppression",
                                    f"unparseable file: {exc}", PASS))
            continue
        model = _ModuleModel(rel, tree)
        summaries = _summarize(model)
        for qual, fn in model.functions.items():
            cls = qual.split(".")[0] if "." in qual else None
            _LockWalker(model, cls, summaries, edges,
                        findings).walk_function(fn)
    for (a, b), (rel, line) in _edges_on_cycles(edges):
        findings.append(Finding(
            rel, line, "lock-order-cycle",
            f"acquiring {b} while holding {a} lies on a lock-order "
            f"cycle — another path acquires these in the reverse "
            f"order; pick one global order", PASS))
    return findings


def discover_lock_modules(root: str) -> Dict[str, int]:
    """Scan the whole package for lock/thread/queue constructor calls:
    repo-relative path -> first construction line. This is the ground
    truth `DEFAULT_LOCK_MODULES` is audited against — the tuple is a
    reviewed declaration, not the source of coverage."""
    out: Dict[str, int] = {}
    for path in package_files(root, extra=()):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name in _LOCK_CTORS:
                rel = repo_rel(path, root)
                if rel not in out or node.lineno < out[rel]:
                    out[rel] = node.lineno
    return out


def _registry_line() -> int:
    """Line of the DEFAULT_LOCK_MODULES definition (stale-entry anchor)."""
    try:
        with open(_THIS_FILE, "r", encoding="utf-8") as fh:
            for i, line in enumerate(fh, start=1):
                if line.startswith("DEFAULT_LOCK_MODULES"):
                    return i
    except OSError:
        pass
    return 1


def registry_findings(root: str,
                      discovered: Optional[Dict[str, int]] = None
                      ) -> List[Finding]:
    """Audit DEFAULT_LOCK_MODULES against the discovered lock surface:
    a lock-bearing module missing from the tuple is uncovered (the
    manual-append failure mode), a listed module with no constructor
    left is stale."""
    if discovered is None:
        discovered = discover_lock_modules(root)
    listed = {p.replace(os.sep, "/") for p in DEFAULT_LOCK_MODULES}
    self_rel = os.path.join("p2p_dhts_tpu", "analysis", "lockcheck.py")
    findings: List[Finding] = []
    for rel, line in sorted(discovered.items()):
        if rel.replace(os.sep, "/") not in listed:
            findings.append(Finding(
                rel, line, "lock-module-uncovered",
                f"{rel} constructs locks/threads/queues but is missing "
                f"from DEFAULT_LOCK_MODULES — the static lock pass "
                f"never audits it", PASS))
    discovered_norm = {r.replace(os.sep, "/") for r in discovered}
    for rel in sorted(listed - discovered_norm):
        findings.append(Finding(
            self_rel, _registry_line(), "lock-module-stale",
            f"DEFAULT_LOCK_MODULES lists {rel} but the module no "
            f"longer constructs any lock/thread/queue", PASS))
    return findings


def run_default(root: str) -> List[Finding]:
    discovered = discover_lock_modules(root)
    rels = sorted({p for p in DEFAULT_LOCK_MODULES
                   if os.path.exists(os.path.join(root, p))} |
                  set(discovered))
    paths = [os.path.join(root, p) for p in rels]
    findings = run([p for p in paths if os.path.exists(p)], root)
    findings.extend(registry_findings(root, discovered))
    return findings


# ---------------------------------------------------------------------------
# runtime watchdog (CHORDAX_LOCK_CHECK=1)
# ---------------------------------------------------------------------------

_THIS_FILE = os.path.abspath(__file__)


def _creation_site() -> str:
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _THIS_FILE and \
                not fn.replace("\\", "/").endswith("/threading.py"):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class _WatchedLockBase:
    _reentrant = False

    def __init__(self, inner, site: str, dog: "LockOrderWatchdog"):
        self._inner = inner
        self._site = site
        self._dog = dog

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._dog._note_acquire(self)
        return got

    def release(self):
        self._dog._note_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        # Delegate everything else to the real lock: stdlib modules
        # poke CPython-specific surface at IMPORT time (e.g.
        # concurrent.futures.thread registers
        # _global_shutdown_lock._at_fork_reinit with os.register_at_fork)
        # and a wrapper that hides it breaks those imports under
        # CHORDAX_LOCK_CHECK=1. Guarded through __dict__ so a
        # half-constructed wrapper can't recurse.
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<watched {type(self._inner).__name__} @ {self._site}>"


class _WatchedLock(_WatchedLockBase):
    pass


class _WatchedRLock(_WatchedLockBase):
    _reentrant = True

    # Condition() wires these through when present; delegating keeps a
    # watched RLock usable as a Condition's lock with exact semantics
    # (full release on wait), while the bookkeeping tracks the handoff.
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        n = self._dog._drop_all(self)
        return self._inner._release_save(), n

    def _acquire_restore(self, state):
        inner_state, n = state
        self._inner._acquire_restore(inner_state)
        self._dog._note_acquire(self, count=n)


class LockOrderWatchdog:
    """Site-level lock-order verifier. Install wraps every lock created
    afterwards; violations accumulate in `.violations` (never raised in
    line — a watchdog must not alter the code under test mid-flight)."""

    def __init__(self) -> None:
        self._orig: Optional[tuple] = None
        self._tls = threading.local()
        self._reg_lock: Optional[threading.Lock] = None
        self._edges: Dict[Tuple[str, str], str] = {}
        self._reported: Set[frozenset] = set()
        # Every thread's held-stack, keyed by thread id: the release
        # path needs to reach the ACQUIRER's stack when a plain Lock is
        # legally handed off and released by a different thread.
        self._stacks: Dict[int, List[_WatchedLockBase]] = {}
        self.violations: List[dict] = []

    # -- lifecycle -----------------------------------------------------------
    @property
    def installed(self) -> bool:
        return self._orig is not None

    def install(self) -> "LockOrderWatchdog":
        if self._orig is not None:
            return self
        owner = getattr(threading.Lock, "_chordax_watchdog", None)
        if owner is not None:
            # Refusing loudly beats double-wrapping: snapshotting an
            # already-patched factory as "orig" would make THIS dog's
            # registry lock itself watched and every lock double
            # wrapped — which detonates as unbounded re-entrancy
            # during thread bootstrap. Reuse the installed singleton
            # (the CHORDAX_LOCK_CHECK=1 path) instead.
            raise RuntimeError(
                "a LockOrderWatchdog is already installed; reuse it "
                "(p2p_dhts_tpu.analysis.lockcheck.WATCHDOG) instead "
                "of installing a second one")
        self._orig = (threading.Lock, threading.RLock)
        self._reg_lock = self._orig[0]()  # a REAL, unwatched lock
        dog = self
        orig_lock, orig_rlock = self._orig

        def lock_factory():
            return _WatchedLock(orig_lock(), _creation_site(), dog)

        def rlock_factory():
            return _WatchedRLock(orig_rlock(), _creation_site(), dog)

        lock_factory._chordax_watchdog = dog
        rlock_factory._chordax_watchdog = dog
        threading.Lock = lock_factory
        threading.RLock = rlock_factory
        return self

    def uninstall(self) -> None:
        if self._orig is None:
            return
        threading.Lock, threading.RLock = self._orig
        self._orig = None

    def reset(self) -> None:
        with self._reg():
            self._edges.clear()
            self._reported.clear()
            self.violations.clear()

    def _reg(self):
        # Late-bound so reset() before install() still works.
        if self._reg_lock is None:
            self._reg_lock = threading.Lock() if self._orig is None \
                else self._orig[0]()
        return self._reg_lock

    # -- bookkeeping ---------------------------------------------------------
    def _stack(self) -> List[_WatchedLockBase]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
            with self._reg():
                self._stacks[threading.get_ident()] = st
        return st

    def _note_acquire(self, lock: _WatchedLockBase, count: int = 1) -> None:
        # Re-entrancy guard: the bookkeeping itself may touch locks
        # (e.g. interpreter internals during thread bootstrap acquire
        # watched Event locks before the thread is registered);
        # recursing back in here would be unbounded. Inner acquisitions
        # skip bookkeeping — strictly lossy, never wrong.
        if getattr(self._tls, "busy", False):
            return
        self._tls.busy = True
        try:
            self._note_acquire_inner(lock, count)
        finally:
            self._tls.busy = False

    def _note_acquire_inner(self, lock: _WatchedLockBase,
                            count: int) -> None:
        stack = self._stack()
        held_sites = {id(h): h._site for h in stack if h is not lock}
        new_edges = []
        for site in set(held_sites.values()):
            if site != lock._site:
                new_edges.append((site, lock._site))
        stack.extend([lock] * count)
        if not new_edges:
            return
        # get_ident(), NOT current_thread(): the latter constructs a
        # _DummyThread for unregistered threads, whose Event.set()
        # acquires another watched lock mid-bookkeeping.
        thread = f"tid:{threading.get_ident()}"
        with self._reg():
            for edge in new_edges:
                rev = (edge[1], edge[0])
                pair = frozenset(edge)
                if rev in self._edges and pair not in self._reported:
                    self._reported.add(pair)
                    self.violations.append({
                        "edge": edge,
                        "reverse_first_seen_in": self._edges[rev],
                        "thread": thread,
                    })
                self._edges.setdefault(edge, thread)

    def _note_release(self, lock: _WatchedLockBase) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return
        # Not held by THIS thread: a plain Lock may legally be acquired
        # in one thread and released in another (handoff). Purge the
        # stale entry from the acquirer's stack, or every later
        # acquisition there records phantom order edges (and possibly
        # false violations). GIL-atomic list del; a concurrently-read
        # snapshot in _note_acquire can at worst miss one bookkeeping
        # edge, never corrupt.
        with self._reg():
            stacks = list(self._stacks.values())
        for st in stacks:
            for i in range(len(st) - 1, -1, -1):
                if st[i] is lock:
                    del st[i]
                    return

    def _drop_all(self, lock: _WatchedLockBase) -> int:
        stack = self._stack()
        n = sum(1 for h in stack if h is lock)
        stack[:] = [h for h in stack if h is not lock]
        return n

    # -- assertions ----------------------------------------------------------
    def assert_clean(self) -> None:
        if self.violations:
            lines = [
                f"  {v['edge'][0]} -> {v['edge'][1]} (thread "
                f"{v['thread']}; reverse order first seen in thread "
                f"{v['reverse_first_seen_in']})"
                for v in self.violations]
            raise AssertionError(
                "lock-order violations observed at runtime:\n"
                + "\n".join(lines))


#: Process singleton the CHORDAX_LOCK_CHECK=1 hook installs.
WATCHDOG = LockOrderWatchdog()

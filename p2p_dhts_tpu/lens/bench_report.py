"""chordax-lens bench-trajectory report (ISSUE 14 satellite): render
the repo's scattered performance evidence — `BENCH_r*.json` round
records, `BENCH_LKG.json` last-known-good rows, `SOAK_RESULTS.jsonl`
— into ONE markdown trajectory table with stale rows flagged VISIBLY.

The standing "stale CPU smoke" caveat (ROADMAP: no TPU has answered
since round 2; BENCH_LKG's serving-stack rows are stale-marked CPU
placeholders) keeps hiding inside JSON `"stale": true` fields that
nobody reads; this report makes it impossible to miss: every stale or
value-less row renders with a `** STALE **` marker and the summary
line counts them.

CLI:  python -m p2p_dhts_tpu.lens.bench_report [--root DIR] [--out F.md]
      (also reachable as `python bench.py --report`)
API:  render_trajectory(root) -> str
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

STALE_MARK = "** STALE **"


def _fmt_value(rec: dict) -> str:
    v = rec.get("value")
    if v is None:
        return "—"
    unit = rec.get("unit") or ""
    return f"{v:g} {unit}".strip()


def _is_stale(rec: dict) -> bool:
    """A row is stale when it says so, when it carries no live value,
    or when its only numbers are a replayed last-known-good."""
    return bool(rec.get("stale")) or rec.get("value") is None \
        or "last_known_good" in rec


def load_rounds(root: str) -> Dict[str, dict]:
    """{round label: {config: record}} from every BENCH_r*.json. Each
    round file holds a driver envelope whose `parsed` field is the
    bench's summary record (configs inlined when present)."""
    out: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        label = os.path.splitext(os.path.basename(path))[0]
        try:
            with open(path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            continue
        configs = parsed.get("configs")
        if isinstance(configs, list):
            out[label] = {r.get("config", "?"): r for r in configs
                          if isinstance(r, dict)}
        else:
            out[label] = {parsed.get("config", "headline"): parsed}
    return out


def load_lkg(root: str) -> Dict[str, dict]:
    try:
        with open(os.path.join(root, "BENCH_LKG.json"), "r",
                  encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    return doc if isinstance(doc, dict) else {}


def load_soak(root: str) -> List[dict]:
    rows: List[dict] = []
    try:
        with open(os.path.join(root, "SOAK_RESULTS.jsonl"), "r",
                  encoding="utf-8") as fh:
            for line in fh:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return rows


def render_trajectory(root: str = ".") -> str:
    rounds = load_rounds(root)
    lkg = load_lkg(root)
    soak = load_soak(root)
    out: List[str] = ["# chordax bench trajectory", ""]

    n_stale = 0
    out += ["## Last known good (BENCH_LKG.json)", ""]
    if lkg:
        out += ["| config | value | device | when | status |",
                "|---|---|---|---|---|"]
        for config in sorted(lkg):
            rec = lkg[config]
            if not isinstance(rec, dict):
                continue
            stale = _is_stale(rec)
            n_stale += stale
            out.append(
                f"| `{config}` | {_fmt_value(rec)} | "
                f"{rec.get('device', '?')} | {rec.get('utc', '?')} | "
                + (STALE_MARK if stale else "green") + " |")
    else:
        out.append("_no BENCH_LKG.json_")

    out += ["", "## Round records (BENCH_r*.json)", ""]
    if rounds:
        out += ["| round | config | value | device | status |",
                "|---|---|---|---|---|"]
        for label in sorted(rounds):
            for config in sorted(rounds[label]):
                rec = rounds[label][config]
                stale = _is_stale(rec)
                n_stale += stale
                out.append(
                    f"| {label} | `{config}` | {_fmt_value(rec)} | "
                    f"{rec.get('device', '?')} | "
                    + (STALE_MARK if stale else "green") + " |")
    else:
        out.append("_no BENCH_r*.json round records_")

    out += ["", "## Soak results (SOAK_RESULTS.jsonl)", ""]
    if soak:
        n_pass = sum(1 for r in soak if r.get("outcome") == "passed")
        n_fail = len(soak) - n_pass
        last = max((r.get("utc", "") for r in soak), default="?")
        out.append(f"{len(soak)} soak rows: {n_pass} passed, "
                   f"{n_fail} not-passed; newest {last}.")
        if n_fail:
            out += ["", "| test | outcome | when |", "|---|---|---|"]
            for r in soak:
                if r.get("outcome") != "passed":
                    out.append(f"| `{r.get('test', '?')}` | "
                               f"{r.get('outcome', '?')} | "
                               f"{r.get('utc', '?')} |")
    else:
        out.append("_no SOAK_RESULTS.jsonl_")

    out += ["",
            f"**{n_stale} stale/value-less row(s)** — every one marked "
            f"`{STALE_MARK.strip('* ')}` above is a replayed "
            f"placeholder or CPU smoke awaiting fresh on-chip "
            f"evidence, not a live hardware record.", ""]
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m p2p_dhts_tpu.lens.bench_report",
        description="bench/soak trajectory table with stale rows "
                    "flagged")
    ap.add_argument("--root", default=".",
                    help="repo root holding the BENCH_* artifacts")
    ap.add_argument("--out", default=None,
                    help="write the markdown here (default: stdout)")
    args = ap.parse_args(argv)
    text = render_trajectory(args.root)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""chordax-lens: device cost accounting read side — capacity/headroom
model + continuous profiling (ISSUE 14).

chordax-scope (ISSUE 8) can trace a request and chordax-pulse
(ISSUE 11) can rate a counter, but neither answers the two questions
the elastic arc turns on: "how much device time does each kind/bucket
actually cost?" and "how much headroom does a ring have left?" — the
Dapper/Monarch-style gap between event tracing and continuous RESOURCE
accounting. The write side lives in `serve.py` (always-on per-(kind,
bucket) dispatch-cost EWMAs, padding-waste lane accounting, the
compile-cause ledger, the queue-delay signal); this package is the
read side:

  * `CapacityModel` — the pure window math: two engine
    `cost_snapshot()`s plus the wall dt in between yield the ring's
    BUSY FRACTION (device time consumed / wall time), its observed
    SERVICE RATE (keys per device-second at the window's actual kind
    mix — the "keys/s this ring can absorb at 100% duty" estimate,
    EWMA-smoothed across windows, cost-table fallback when the window
    was idle), the derived HEADROOM (absorbable minus currently
    absorbed, floored at zero), the window's mean QUEUE DELAY (the
    saturation signal: a ring whose device is keeping up has ~zero
    queue delay no matter how busy), and a 0/1 SATURATED verdict.
    Every input and output is a plain number, so tests hand-compute
    the whole closed loop.
  * `LensLoop` — a `health.PacedLoop` driving the model over every
    ring a gateway serves: each tick deltas the engines' monotonic
    accumulators and publishes `lens.busy.<ring>`,
    `lens.capacity_keys_s.<ring>`, `lens.headroom.<ring>`,
    `lens.saturated.<ring>` gauges and the `lens.queue_delay_ms.<ring>`
    histogram — pulse series for free (the sampler tracks the `lens.`
    prefix) and SLO-selectable (a latency SLO can bound the queue
    delay). Rings that leave the router retire their lens keys on the
    next tick (the PR-8 stale-telemetry rule). `update()` is the
    deterministic foreground tick; `capacity_report()` is the CAPACITY
    wire verb's payload — the exact subscription surface the
    chordax-elastic policy loop will consume.
  * `ProfilerLoop` — OPT-IN continuous profiling: a PacedLoop that
    periodically captures a bounded `metrics.device_trace` window into
    a rotated on-disk directory (`window-NNNNNN`, newest `max_windows`
    kept), so a long soak always holds a recent device timeline
    without unbounded disk growth. Off by default — nothing profiles
    unless a loop is constructed and started. The digestion half is
    `python -m p2p_dhts_tpu.lens.report` (per-kind cost breakdown from
    a Chrome export) and `python -m p2p_dhts_tpu.lens.bench_report`
    (the bench/soak trajectory table).

LOCK ORDER: `LensLoop._lock` is a LEAF — never held across an engine
call, a metrics call, or a router call (snapshots are collected first,
the model computed, then results stored under the leaf and published
outside it). This module never imports jax (device_trace degrades on
its own).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Dict, List, Optional

from p2p_dhts_tpu.health import HealthRegistry, PacedLoop
from p2p_dhts_tpu.metrics import METRICS, Metrics, device_trace

#: EWMA smoothing for the cross-window service-rate estimate: the
#: loaded windows dominate, one idle tick cannot wipe the capacity
#: estimate (an idle window contributes no observation at all).
RATE_EWMA_ALPHA = 0.5

#: Saturation verdict thresholds: busy fraction at/above SAT_BUSY or
#: window mean queue delay at/above `saturation_delay_ms` flips
#: `lens.saturated.<ring>` to 1.
SAT_BUSY = 0.85
DEFAULT_SATURATION_DELAY_MS = 50.0

#: The lens gauge/hist families one ring owns (retired together when
#: the ring leaves the router).
_RING_KEY_FAMILIES = ("lens.busy", "lens.capacity_keys_s",
                      "lens.headroom", "lens.saturated",
                      "lens.queue_delay_ms")


class CapacityModel:
    """Window math for ONE ring: feed consecutive `cost_snapshot()`s
    (monotonic accumulators) with their wall timestamps; read the
    derived row. Stateless between rings — the LensLoop owns one per
    ring id."""

    def __init__(self, *, alpha: float = RATE_EWMA_ALPHA,
                 sat_busy: float = SAT_BUSY,
                 saturation_delay_ms: float =
                 DEFAULT_SATURATION_DELAY_MS):
        self.alpha = float(alpha)
        self.sat_busy = float(sat_busy)
        self.saturation_delay_ms = float(saturation_delay_ms)
        self._prev: Optional[dict] = None
        self._prev_t: Optional[float] = None
        self.service_rate: Optional[float] = None
        self.row: Optional[dict] = None

    @staticmethod
    def table_rate(cost_table: Dict[str, Dict[int, dict]]
                   ) -> Optional[float]:
        """Cold-start fallback: the best observed per-lane service
        rate the engine's cost table implies (bucket lanes over the
        bucket's EWMA dispatch time, best across kinds/buckets) —
        what the model reports before any loaded window exists."""
        best = None
        for kind, buckets in cost_table.items():
            for bucket, row in buckets.items():
                if not bucket or row.get("ewma_ms", 0) <= 0:
                    continue
                rate = bucket / (row["ewma_ms"] / 1e3)
                if best is None or rate > best:
                    best = rate
        return best

    def observe(self, snap: dict, t: float,
                cost_table: Optional[Dict[str, Dict[int, dict]]] = None
                ) -> Optional[dict]:
        """One window: returns the derived row (None on the seeding
        observation). All math is arithmetic on the snapshot deltas —
        hand-computable, the test contract."""
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = snap, t
        if prev is None or prev_t is None or t <= prev_t:
            return None
        dt = t - prev_t
        d_dev = max(snap["device_time_s"] - prev["device_time_s"], 0.0)
        d_live = max(snap["lanes_live"] - prev["lanes_live"], 0)
        d_pad = max(snap["lanes_padded"] - prev["lanes_padded"], 0)
        busy = min(d_dev / dt, 1.0)
        current_rate = d_live / dt
        if d_dev > 1e-9 and d_live > 0:
            observed = d_live / d_dev
            self.service_rate = (
                observed if self.service_rate is None
                else self.service_rate
                + self.alpha * (observed - self.service_rate))
        elif self.service_rate is None and cost_table:
            self.service_rate = self.table_rate(cost_table)
        capacity = self.service_rate
        headroom = (max(capacity - current_rate, 0.0)
                    if capacity is not None else None)
        d_qd_n = snap["queue_delay_n"] - prev["queue_delay_n"]
        queue_delay_ms = (
            (snap["queue_delay_sum_ms"] - prev["queue_delay_sum_ms"])
            / d_qd_n if d_qd_n > 0 else 0.0)
        saturated = int(busy >= self.sat_busy
                        or queue_delay_ms >= self.saturation_delay_ms)
        # The window's kind mix, by device-time share — the "at the
        # current kind mix" qualifier on the headroom estimate.
        # Normalized by the per-kind SUM (per-kind totals count full
        # dispatch intervals; the busy union de-overlaps, so the two
        # denominators differ under pipelining).
        mix: Dict[str, float] = {}
        kind_deltas = {
            kind: tot - prev["device_time_by_kind"].get(kind, 0.0)
            for kind, tot in snap["device_time_by_kind"].items()}
        kind_total = sum(v for v in kind_deltas.values() if v > 0)
        if kind_total > 1e-9:
            for kind, d in kind_deltas.items():
                if d / kind_total > 1e-6:
                    mix[kind] = round(d / kind_total, 4)
        self.row = {
            "t": t,
            "window_s": round(dt, 6),
            "busy": round(busy, 6),
            "current_keys_s": round(current_rate, 3),
            "capacity_keys_s": (round(capacity, 3)
                                if capacity is not None else None),
            "headroom_keys_s": (round(headroom, 3)
                                if headroom is not None else None),
            "queue_delay_ms": round(queue_delay_ms, 4),
            "saturated": saturated,
            "mix": mix,
            "lanes_live": d_live,
            "lanes_padded": d_pad,
            "queue_depth": snap.get("queue_depth", 0),
        }
        return self.row


class LensLoop(PacedLoop):
    """The per-gateway capacity/headroom loop: one CapacityModel per
    registered ring, ticked over the engines' cost snapshots.
    `start()` runs it as a background PacedLoop (self-registered in
    health.HEALTH like every paced loop — the HEALTH verb reports it
    for free); `update()` is the deterministic foreground tick tests,
    the dryrun and the bench drive. Attach to a gateway
    (`gateway.attach_lens(loop)`) so the CAPACITY wire verb serves
    `capacity_report()`."""

    def __init__(self, gateway, *, metrics: Optional[Metrics] = None,
                 interval_s: float = 1.0,
                 saturation_delay_ms: float =
                 DEFAULT_SATURATION_DELAY_MS,
                 rate_alpha: float = RATE_EWMA_ALPHA,
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 10.0,
                 stale_after_s: Optional[float] = None,
                 registry: Optional[HealthRegistry] = None):
        mets = metrics if metrics is not None else METRICS
        PacedLoop.__init__(
            self, name="lens", kind="lens",
            interval_s=interval_s, interval_idle_s=interval_s,
            backoff_base_s=backoff_base_s, backoff_cap_s=backoff_cap_s,
            metrics=mets, failure_metric="lens.update_failures",
            thread_name="lens-capacity", registry=registry)
        self.gateway = gateway
        self.saturation_delay_ms = float(saturation_delay_ms)
        self.rate_alpha = float(rate_alpha)
        #: Row age beyond which capacity_report marks it STALE (the
        #: typed unreachable/aged marker a policy tick can trust
        #: without string parsing). Default: three update intervals.
        self.stale_after_s = float(
            stale_after_s if stale_after_s is not None
            else 3.0 * float(interval_s))
        self._lock = threading.Lock()  # LEAF: models + rows only
        self._models: Dict[str, CapacityModel] = {}
        self._rows: Dict[str, dict] = {}
        self._updated_t: Optional[float] = None

    def _round(self) -> None:
        self.update()

    def update(self, now: Optional[float] = None) -> Dict[str, dict]:
        """One capacity tick over every registered ring. `now`
        (monotonic-like seconds) is injectable so tests hand-compute
        windows; production ticks use time.monotonic(). Driven by ONE
        thread at a time (the loop thread, or a foreground driver
        while the loop is not started) — the PulseSampler rule.
        Returns {ring id: derived row} for rings past their seeding
        window."""
        t = time.monotonic() if now is None else float(now)
        # Engine snapshots are collected OUTSIDE our leaf lock.
        snaps: Dict[str, tuple] = {}
        backends, _ = self.gateway.router.snapshot()
        for backend in backends:
            snap_fn = getattr(backend.engine, "cost_snapshot", None)
            if snap_fn is None:
                continue  # stub/foreign engines have no cost plane
            table_fn = getattr(backend.engine, "cost_table", None)
            snaps[backend.ring_id] = (
                snap_fn(), table_fn() if table_fn is not None else None)
        rows: Dict[str, dict] = {}
        retired: List[str] = []
        with self._lock:
            for rid in [r for r in self._models if r not in snaps]:
                del self._models[rid]
                self._rows.pop(rid, None)
                retired.append(rid)
            for rid, (snap, table) in snaps.items():
                model = self._models.get(rid)
                if model is None:
                    model = self._models[rid] = CapacityModel(
                        alpha=self.rate_alpha,
                        saturation_delay_ms=self.saturation_delay_ms)
                row = model.observe(snap, t, table)
                if row is not None:
                    self._rows[rid] = row
                    rows[rid] = row
            self._updated_t = t
        # Publishing happens OUTSIDE the leaf (metrics owns its own).
        for rid in retired:
            for family in _RING_KEY_FAMILIES:
                self.metrics.remove_prefix(f"{family}.{rid}")
            self.metrics.inc("lens.rings_retired")
        for rid, row in rows.items():
            self.metrics.gauge(f"lens.busy.{rid}", row["busy"])
            if row["capacity_keys_s"] is not None:
                self.metrics.gauge(f"lens.capacity_keys_s.{rid}",
                                   row["capacity_keys_s"])
            if row["headroom_keys_s"] is not None:
                self.metrics.gauge(f"lens.headroom.{rid}",
                                   row["headroom_keys_s"])
            self.metrics.gauge(f"lens.saturated.{rid}",
                               row["saturated"])
            self.metrics.observe_hist(f"lens.queue_delay_ms.{rid}",
                                      row["queue_delay_ms"])
        self.rounds += 1
        self.mark_round()
        self.metrics.inc("lens.updates")
        return rows

    # -- read side (CAPACITY verb / elastic loop / tests) --------------------
    def headroom(self, ring_id: str) -> Optional[float]:
        """The latest `lens.headroom.<ring>` estimate — keys/s this
        ring can still absorb at the current kind mix (None before
        the first loaded window)."""
        with self._lock:
            row = self._rows.get(ring_id)
        return row["headroom_keys_s"] if row is not None else None

    def rows(self) -> Dict[str, dict]:
        with self._lock:
            return {rid: dict(row) for rid, row in self._rows.items()}

    def capacity_report(self) -> dict:
        """The CAPACITY verb payload: every ring's derived capacity
        row — the elastic policy loop's one-call decision input. Each
        row is age-stamped against the LAST update tick (`age_s` =
        updated_t - row t; recorded timestamps only, no wall clock, so
        a replayed stream ages identically) and carries the typed
        `stale` flag once older than `stale_after_s` — a ring whose
        model stopped producing rows (a wedged engine, a ring mid-
        retirement) reads as STALE last-good data, never as fresh zero
        capacity."""
        with self._lock:
            updated_t = self._updated_t
            rows = {rid: dict(row)
                    for rid, row in self._rows.items()}
        for row in rows.values():
            age = (max(float(updated_t) - float(row.get("t", updated_t)),
                       0.0) if updated_t is not None else 0.0)
            row["age_s"] = round(age, 6)
            row["stale"] = bool(age > self.stale_after_s)
        return {
            "updated_t": updated_t,
            "interval_s": self.interval_s,
            "stale_after_s": self.stale_after_s,
            "rings": rows,
        }


class ProfilerLoop(PacedLoop):
    """Opt-in continuous profiling: every `interval_s`, capture one
    bounded `metrics.device_trace` window (`capture_s` long) into
    `directory/window-NNNNNN`, keeping only the newest `max_windows`
    on disk (rotation — a week-long soak holds a recent timeline, not
    a full week of profiles). `tracer` is injectable for tests (any
    `tracer(path)` context manager); the default degrades to a no-op
    wherever jax.profiler is unsupported, exactly like the bench's
    `--trace`. OFF by default: nothing profiles unless a loop is
    constructed AND started; `capture()` is the deterministic
    foreground form."""

    def __init__(self, directory: str, *, capture_s: float = 1.0,
                 max_windows: int = 4, interval_s: float = 30.0,
                 tracer=None, metrics: Optional[Metrics] = None,
                 registry: Optional[HealthRegistry] = None):
        mets = metrics if metrics is not None else METRICS
        PacedLoop.__init__(
            self, name="lens-profiler", kind="lens",
            interval_s=interval_s, interval_idle_s=interval_s,
            backoff_base_s=1.0, backoff_cap_s=60.0,
            metrics=mets, failure_metric="lens.profile_failures",
            thread_name="lens-profiler", registry=registry)
        self.directory = str(directory)
        self.capture_s = float(capture_s)
        self.max_windows = int(max_windows)
        self._tracer = tracer if tracer is not None else device_trace
        # Numbering resumes past any windows a PREVIOUS process left
        # in the directory: restarting at 0 would make _rotate (which
        # keeps the lexically-newest names) delete every fresh capture
        # while preserving the stale high-numbered ones.
        self._window_n = 0
        self._captured = 0
        for path in self.windows():
            tail = os.path.basename(path).rsplit("-", 1)[-1]
            try:
                self._window_n = max(self._window_n, int(tail) + 1)
            except ValueError:
                self._window_n = max(self._window_n, 1)

    def _round(self) -> None:
        self.capture()

    def capture(self) -> str:
        """One profiling window; returns the window path (which may
        not exist when the platform's profiler degraded to a no-op).
        The capture sleep is interruptible by close() — a stopping
        loop never pins its thread for a full window."""
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory,
                            f"window-{self._window_n:06d}")
        self._window_n += 1
        self._captured += 1
        with self._tracer(path):
            self._stop_ev.wait(self.capture_s)
        self._rotate()
        self.rounds += 1
        self.mark_round()
        self.metrics.inc("lens.profile_windows")
        self.metrics.gauge("lens.profile_window_count",
                           len(self.windows()))
        return path

    def windows(self) -> List[str]:
        """On-disk window paths, oldest first."""
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.startswith("window-"))
        except OSError:
            return []
        return [os.path.join(self.directory, n) for n in names]

    def _rotate(self) -> None:
        ws = self.windows()
        while len(ws) > self.max_windows:
            victim = ws.pop(0)
            if os.path.isdir(victim):
                shutil.rmtree(victim, ignore_errors=True)
            else:
                try:
                    os.remove(victim)
                except OSError:
                    pass

    def status(self) -> dict:
        return {
            "directory": self.directory,
            "capture_s": self.capture_s,
            "max_windows": self.max_windows,
            "captured": self._captured,
            "on_disk": len(self.windows()),
            "running": self.thread.is_alive(),
        }

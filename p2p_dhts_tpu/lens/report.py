"""chordax-lens profile report: digest a Chrome trace export (the
chordax-scope `SpanStore.export_chrome()` document — the same file the
watcher archives next to each bench record) into a per-kind
cost-breakdown table, so an archived timeline is ANALYZED, not just a
raw artifact (ROADMAP item 4: "profile the traced device timeline and
attack what it shows").

Three views, one markdown document:

  * PER-KIND BATCH COST — every `serve.batch.<kind>` span grouped by
    kind: dispatch count, total/mean duration, share of all batch
    time, mean fill. The "what does each kind actually cost" table.
  * DISPATCH-STAGE DECOMPOSITION — the batch sub-spans
    (`serve.coalesce` / `serve.bucket_pad` / `serve.device_dispatch` /
    `serve.deliver`) summed: where a batch's wall time goes (a
    matmul-bound profile shows device_dispatch dominating; a
    host-bound one shows the pads/delivery).
  * REQUEST-PATH SHARE — `serve.request.<kind>` spans per kind:
    count + mean end-to-end latency (submit -> fan-out, queue wait
    included) — the caller's view next to the device's.

Fused batches (`serve.batch.fused`) additionally split their time by
the `lane_share` annotation each fused span carries (ISSUE 14
satellite), so fused device time attributes back to the kinds that
rode it.

CLI:  python -m p2p_dhts_tpu.lens.report --chrome TRACE.json [--out R.md]
API:  report_from_chrome(doc) / report_from_store(span_store) -> str
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

_BATCH_PREFIX = "serve.batch."
_REQUEST_PREFIX = "serve.request."
_STAGES = ("serve.coalesce", "serve.bucket_pad",
           "serve.device_dispatch", "serve.deliver")


def _rows(doc: dict) -> List[dict]:
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("not a Chrome trace document: no traceEvents")
    return [ev for ev in events if isinstance(ev, dict)]


def cost_breakdown(doc: dict) -> dict:
    """The numeric digest of one Chrome export (durations in ms)."""
    batches: Dict[str, dict] = {}
    stages: Dict[str, dict] = {}
    requests: Dict[str, dict] = {}
    fused_attrib: Dict[str, float] = {}
    for ev in _rows(doc):
        name = ev.get("name", "")
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        args = ev.get("args") or {}
        if name.startswith(_BATCH_PREFIX):
            kind = name[len(_BATCH_PREFIX):]
            row = batches.setdefault(
                kind, {"n": 0, "total_ms": 0.0, "fill_sum": 0.0,
                       "fill_n": 0})
            row["n"] += 1
            row["total_ms"] += dur_ms
            if isinstance(args.get("fill"), (int, float)):
                row["fill_sum"] += float(args["fill"])
                row["fill_n"] += 1
            share = args.get("lane_share")
            if kind == "fused" and isinstance(share, dict):
                for k, s in share.items():
                    try:
                        fused_attrib[k] = fused_attrib.get(k, 0.0) + \
                            dur_ms * float(s)
                    except (TypeError, ValueError):
                        continue
        elif name in _STAGES:
            row = stages.setdefault(name, {"n": 0, "total_ms": 0.0})
            row["n"] += 1
            row["total_ms"] += dur_ms
        elif name.startswith(_REQUEST_PREFIX):
            kind = name[len(_REQUEST_PREFIX):]
            row = requests.setdefault(kind, {"n": 0, "total_ms": 0.0})
            row["n"] += 1
            row["total_ms"] += dur_ms
    return {"batches": batches, "stages": stages,
            "requests": requests, "fused_attribution": fused_attrib}


def _fmt(v: float) -> str:
    return f"{v:.3f}"


def render_markdown(breakdown: dict, title: str = "chordax-lens "
                    "profile report") -> str:
    """The human half: one markdown document per digest."""
    out: List[str] = [f"# {title}", ""]
    batches = breakdown["batches"]
    total_batch_ms = sum(r["total_ms"] for r in batches.values())
    out.append("## Per-kind batch cost")
    out.append("")
    if batches:
        out.append("| kind | batches | total ms | mean ms | share | "
                   "mean fill |")
        out.append("|---|---|---|---|---|---|")
        for kind in sorted(batches,
                           key=lambda k: -batches[k]["total_ms"]):
            r = batches[kind]
            share = (r["total_ms"] / total_batch_ms * 100
                     if total_batch_ms else 0.0)
            fill = (r["fill_sum"] / r["fill_n"]
                    if r["fill_n"] else None)
            out.append(
                f"| `{kind}` | {r['n']} | {_fmt(r['total_ms'])} | "
                f"{_fmt(r['total_ms'] / r['n'])} | {share:.1f}% | "
                + (f"{fill:.3f} |" if fill is not None else "n/a |"))
    else:
        out.append("_no serve.batch spans in this export_")
    fused = breakdown["fused_attribution"]
    if fused:
        out += ["", "## Fused batch time, attributed by lane share",
                "", "| kind | attributed ms |", "|---|---|"]
        for kind in sorted(fused, key=lambda k: -fused[k]):
            out.append(f"| `{kind}` | {_fmt(fused[kind])} |")
    stages = breakdown["stages"]
    if stages:
        stage_total = sum(r["total_ms"] for r in stages.values())
        out += ["", "## Dispatch-stage decomposition", "",
                "| stage | spans | total ms | share |", "|---|---|---|---|"]
        for name in _STAGES:
            r = stages.get(name)
            if r is None:
                continue
            share = (r["total_ms"] / stage_total * 100
                     if stage_total else 0.0)
            out.append(f"| `{name}` | {r['n']} | "
                       f"{_fmt(r['total_ms'])} | {share:.1f}% |")
    requests = breakdown["requests"]
    if requests:
        out += ["", "## Request-path latency (submit -> fan-out)", "",
                "| kind | requests | mean ms |", "|---|---|---|"]
        for kind in sorted(requests,
                           key=lambda k: -requests[k]["total_ms"]):
            r = requests[kind]
            out.append(f"| `{kind}` | {r['n']} | "
                       f"{_fmt(r['total_ms'] / r['n'])} |")
    out.append("")
    return "\n".join(out)


def report_from_chrome(doc: dict, title: str = "chordax-lens profile "
                       "report") -> str:
    return render_markdown(cost_breakdown(doc), title)


def report_from_store(store, title: str = "chordax-lens profile "
                      "report (live SpanStore)") -> str:
    """Digest a live chordax-scope SpanStore (no file round trip)."""
    return report_from_chrome(json.loads(store.export_chrome()), title)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m p2p_dhts_tpu.lens.report",
        description="per-kind cost breakdown of a Chrome trace export")
    ap.add_argument("--chrome", required=True,
                    help="Chrome trace-event JSON "
                         "(SpanStore.export_chrome output)")
    ap.add_argument("--out", default=None,
                    help="write the markdown here (default: stdout)")
    ap.add_argument("--title", default=None)
    args = ap.parse_args(argv)
    with open(args.chrome, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    text = report_from_chrome(
        doc, args.title if args.title is not None
        else f"chordax-lens profile report — {args.chrome}")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""chordax-scope: unified health/introspection plane for background loops.

Three pieces, each answering one operability question:

  * `PacedLoop` — THE shared run/backoff/stall base for every paced
    background control loop (the ROADMAP PR-7 open item): jittered
    start, one round per wake, jittered exponential backoff on a failed
    round, converged/stalled-aware idle pacing, and an interruptible
    Event wait holding no locks. `repair/scheduler.py`'s
    `_PairLoop`/`_DriftLoop` and `membership/manager.py`'s
    `MembershipManager` are all subclasses — one loop body, three
    subsystems, no behavior change (their pre-consolidation tests are
    the regression net). Every PacedLoop self-registers (weakly) in the
    HealthRegistry at construction.
  * `HealthRegistry` — "is this background loop healthy?" in ONE call:
    `snapshot()` reports every live loop's rounds, failure count,
    backoff state, token-bucket level, converged/stalled flags and
    last-round age. Weak references: a loop that was never closed (test
    debris) disappears from the snapshot with its last reference
    instead of pinning the registry forever. The gateway's HEALTH wire
    verb serves this remotely.
  * `FlightRecorder` — a bounded structured event ring (the
    reference's 32-entry RequestLog generalized): subsystems append
    {timestamp, subsystem, event, fields} dicts at notable moments
    (handler errors, admission rejections, ring health transitions,
    loop round failures), and `dump_on_error()` / `dump_text()` replay
    the tail when something goes wrong — the first stack frame of any
    incident. tests/conftest.py attaches the tail to failed tests;
    bench.py's per-config firewall prints it.

LOCK ORDER: `HealthRegistry._lock` and `FlightRecorder._lock` are
LEAVES — never held across any call out of this module; `PacedLoop`
adds only `_life_lock` (start/close bookkeeping, leaf). This module
never imports jax.
"""

from __future__ import annotations

import logging
import random
import sys
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from p2p_dhts_tpu.metrics import METRICS, Metrics

logger = logging.getLogger(__name__)


class HealthRegistry:
    """Weak registry of live PacedLoops; snapshot() is the one-call
    health view (and the HEALTH wire verb's payload)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loops: Dict[int, "weakref.ref[PacedLoop]"] = {}

    def register(self, loop: "PacedLoop") -> None:
        with self._lock:
            self._loops[id(loop)] = weakref.ref(loop)

    def unregister(self, loop: "PacedLoop") -> None:
        with self._lock:
            self._loops.pop(id(loop), None)

    def loops(self) -> List["PacedLoop"]:
        with self._lock:
            refs = list(self._loops.items())
        out = []
        dead = []
        for key, ref in refs:
            loop = ref()
            if loop is None:
                dead.append(key)
            else:
                out.append(loop)
        if dead:
            with self._lock:
                for key in dead:
                    self._loops.pop(key, None)
        return out

    def snapshot(self, include_net: bool = False) -> Dict[str, dict]:
        """{unique loop name: health dict}. Name collisions (two
        schedulers over the same pair in one process) disambiguate
        with a #k suffix instead of silently shadowing. With
        `include_net`, one extra `"net"` row (kind "net") carries the
        process's wire-breaker / connection-flow-control / quarantine
        state (chordax-pulse, ISSUE 11 — the PR-10 "pollable by the
        watcher" thread), so one snapshot() answers both "are the
        loops healthy" and "is the transport degrading"."""
        out: Dict[str, dict] = {}
        for loop in self.loops():
            name = loop.name
            k = 2
            while name in out:
                name = f"{loop.name}#{k}"
                k += 1
            out[name] = loop.health()
        if include_net:
            name = "net"
            k = 2
            while name in out:
                name = f"net#{k}"
                k += 1
            out[name] = net_snapshot()
        return out


#: The process-wide registry the HEALTH verb serves (loops register
#: here by default; tests may construct their own).
HEALTH = HealthRegistry()


def net_snapshot(metrics: Optional[Metrics] = None) -> dict:
    """The transport-degradation state in one row (chordax-pulse,
    ISSUE 11 — closing the PR-10 open thread): every destination's
    dial circuit-breaker state (`rpc.wire.breaker.*`'s live twin),
    every live server's connection flow-control occupancy, the BUSY
    shed counters, and the engine's poison-quarantine count. Lazy
    imports: health must stay importable without the net stack."""
    m = metrics if metrics is not None else METRICS
    from p2p_dhts_tpu.net import rpc as rpc_mod
    from p2p_dhts_tpu.net import wire as wire_mod
    return {
        "kind": "net",
        "wire_breakers": wire_mod.breaker_snapshot(),
        "flow_control": rpc_mod.flow_control_snapshot(),
        "busy": {
            "rejected": m.counter("rpc.server.busy_rejected"),
            "dropped": m.counter("rpc.server.busy_dropped"),
            "client_seen": m.counter("rpc.client.busy"),
        },
        "quarantined": m.counter("serve.quarantined"),
    }


class PacedLoop:
    """Base for one background control loop: run / backoff / stall.

    Subclasses implement `_round()` (one unit of work; exceptions are
    counted, logged, and backed off) and may override `_busy()` (True
    -> active `interval_s` pacing, False -> `interval_idle_s`). The
    base owns: the thread (created at construction, started by
    `start()`), the jittered start, the failure/backoff accounting
    (`failures`, `backoff_s`, `last_error`), the `converged`/`stalled`
    flags idle pacing reads, and health snapshotting. `extra_stop` is
    a second Event that also stops the loop (a scheduler's global stop
    next to the loop's own)."""

    def __init__(self, *, name: str, kind: str,
                 interval_s: float, interval_idle_s: float,
                 backoff_base_s: float, backoff_cap_s: float,
                 metrics: Optional[Metrics] = None,
                 failure_metric: Optional[str] = None,
                 extra_stop: Optional[threading.Event] = None,
                 bucket=None, thread_name: Optional[str] = None,
                 registry: Optional[HealthRegistry] = None):
        self.name = str(name)
        self.loop_kind = str(kind)
        self.interval_s = float(interval_s)
        self.interval_idle_s = float(interval_idle_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.metrics = metrics if metrics is not None else METRICS
        self.failure_metric = failure_metric
        self.bucket = bucket  # TokenBucket or None (health reports it)
        self._stop_ev = threading.Event()
        self._extra_stop = extra_stop
        self._life_lock = threading.Lock()
        self._loop_started = False
        self.failures = 0
        self.backoff_s = 0.0
        self.last_error: Optional[str] = None
        self.rounds = 0
        self.converged = False
        self.stalled = False
        self._last_round_t: Optional[float] = None
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name=thread_name if thread_name is not None else self.name)
        self._registry = registry if registry is not None else HEALTH
        self._registry.register(self)

    # -- subclass hooks ------------------------------------------------------
    def _round(self) -> None:
        raise NotImplementedError

    def _busy(self) -> bool:
        """Active-pacing predicate the post-round wait reads; the
        default idles a converged or stalled loop."""
        return not (self.converged or self.stalled)

    # -- pacing core ---------------------------------------------------------
    def _should_stop(self) -> bool:
        return self._stop_ev.is_set() or (
            self._extra_stop is not None and self._extra_stop.is_set())

    def _wait_s(self) -> float:
        if self.backoff_s:
            return self.backoff_s
        return self.interval_s if self._busy() else self.interval_idle_s

    def mark_round(self) -> None:
        """Stamp a completed round (foreground drivers — run_once /
        step — call this so health's last-round age is honest even
        when the background thread never runs)."""
        self._last_round_t = time.monotonic()

    def _record_failure(self, exc: BaseException) -> None:
        self.failures += 1
        self.last_error = f"{type(exc).__name__}: {exc}"
        if self.failure_metric:
            self.metrics.inc(self.failure_metric)
        base = min(self.backoff_base_s * (2 ** (self.failures - 1)),
                   self.backoff_cap_s)
        # Jittered, never fixed: N loops that saw the same failure must
        # not re-converge in lockstep (the net/rpc.py retry rule).
        self.backoff_s = random.uniform(base * 0.5, base)
        FLIGHT.record(self.loop_kind, "round_failure", loop=self.name,
                      failures=self.failures, error=self.last_error,
                      backoff_s=round(self.backoff_s, 3))
        logger.warning("%s loop %s round failed (%s); backing off %.2fs",
                       self.loop_kind, self.name, self.last_error,
                       self.backoff_s, exc_info=exc)

    def _run(self) -> None:
        # Jittered start so N loops never fire in lockstep.
        self._stop_ev.wait(random.uniform(0, self.interval_s))
        while not self._should_stop():
            try:
                self._round()
                self.failures = 0
                self.backoff_s = 0.0
                self.last_error = None
            # chordax-lint: disable=bare-except -- the control loop must survive any round failure; it is counted, logged and backed off
            except Exception as exc:  # noqa: BLE001 — backoff + retry
                self._record_failure(exc)
            self.mark_round()
            self._stop_ev.wait(self._wait_s())

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "PacedLoop":
        with self._life_lock:
            if self._loop_started:
                return self
            if self._stop_ev.is_set():
                raise RuntimeError(f"{self.name} loop is closed")
            self._loop_started = True
        self.thread.start()
        return self

    def stop(self) -> None:
        """Signal the loop to exit (non-blocking) and drop it from the
        health registry."""
        self._stop_ev.set()
        self._registry.unregister(self)

    def close(self, timeout: float = 30.0) -> None:
        self.stop()
        if self.thread.is_alive():
            self.thread.join(timeout)
            if self.thread.is_alive():
                raise TimeoutError(
                    f"{self.loop_kind} loop {self.name!r} did not stop "
                    f"within {timeout}s")

    # -- introspection -------------------------------------------------------
    def health(self) -> dict:
        """One loop's health row: the unified plane's unit record."""
        age = (round(time.monotonic() - self._last_round_t, 3)
               if self._last_round_t is not None else None)
        return {
            "kind": self.loop_kind,
            "running": self.thread.is_alive(),
            "rounds": self.rounds,
            "failures": self.failures,
            "backoff_s": round(self.backoff_s, 3),
            "converged": self.converged,
            "stalled": self.stalled,
            "tokens": (round(self.bucket.tokens, 1)
                       if self.bucket is not None else None),
            "last_error": self.last_error,
            "last_round_age_s": age,
        }


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded structured event ring: the RequestLog generalized from
    "last 32 parsed requests on one server" to "last N notable events
    across every subsystem in the process".

    TWO rings, by signal class: `record()` feeds the MAIN ring
    (incidents — handler errors, health transitions, rejections, loop
    failures); `record_routine()` feeds a smaller CHATTER ring (per-
    request traffic, e.g. a logging-enabled server's request feed), so
    a few thousand routine rows can never evict the incident context
    dump-on-error exists to replay."""

    #: Retained incident events (newest win); small enough to read whole.
    DEFAULT_CAPACITY = 1024
    #: Retained routine/chatter events.
    CHATTER_CAPACITY = 128

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 chatter_capacity: int = CHATTER_CAPACITY):
        self._buf: deque = deque(maxlen=int(capacity))
        self._chatter: deque = deque(maxlen=int(chatter_capacity))
        self._lock = threading.Lock()
        self._recorded = 0
        self._routine_recorded = 0

    def _item(self, subsystem: str, event: str, fields: dict) -> dict:
        item = {"t": time.time(), "subsystem": str(subsystem),
                "event": str(event)}
        if fields:
            item.update(fields)
        return item

    def record(self, subsystem: str, event: str, **fields) -> None:
        """Append one MAIN-ring event, stamped with a stable monotonic
        sequence number (`seq`, chordax-tower ISSUE 20) next to its
        wall timestamp `t` — the since-cursor `recent_since` pulls
        advance through, duplicate-free across polls and robust to
        ring eviction."""
        item = self._item(subsystem, event, fields)
        with self._lock:
            item["seq"] = self._recorded
            self._recorded += 1
            self._buf.append(item)

    def record_routine(self, subsystem: str, event: str,
                       **fields) -> None:
        """Per-request / high-volume chatter: retained separately so
        it cannot evict incident events."""
        item = self._item(subsystem, event, fields)
        with self._lock:
            self._routine_recorded += 1
            self._chatter.append(item)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def recorded(self) -> int:
        """Total MAIN-ring events ever recorded (eviction-independent)."""
        with self._lock:
            return self._recorded

    @property
    def routine_recorded(self) -> int:
        with self._lock:
            return self._routine_recorded

    def recent(self, n: Optional[int] = None,
               subsystem: Optional[str] = None,
               routine: bool = False) -> List[dict]:
        with self._lock:
            out = list(self._chatter if routine else self._buf)
        if subsystem is not None:
            out = [e for e in out if e["subsystem"] == subsystem]
        return out if n is None else out[-int(n):]

    def recent_since(self, since: int, n: Optional[int] = None
                     ) -> Tuple[List[dict], int, int]:
        """Incremental MAIN-ring pull: `(events, next_seq, gap)` for
        every retained event with seq >= since, oldest first, at most
        `n`. `gap` counts events the ring evicted before the cursor
        read them (eviction-visible, never a silent skip); `next_seq`
        resumes exactly after the last returned event — the HEALTH
        verb's SINCE form (chordax-tower ISSUE 20). Seqs are
        contiguous in the ring, so the slice is one traversal."""
        since = max(int(since), 0)
        with self._lock:
            buf = list(self._buf)
            total = self._recorded
        oldest = total - len(buf)
        start = max(since, oldest)
        gap = start - since if since < oldest else 0
        out = buf[start - oldest:]
        if n is not None:
            out = out[:max(int(n), 0)]
        out = [dict(e) for e in out]
        return out, start + len(out), gap

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._chatter.clear()

    def dump_text(self, n: int = 50) -> str:
        """Human-readable tail, newest last — what dump-on-error
        prints."""
        lines = []
        for e in self.recent(n):
            extra = " ".join(
                f"{k}={e[k]!r}" for k in e
                if k not in ("t", "subsystem", "event"))
            stamp = time.strftime("%H:%M:%S", time.localtime(e["t"]))
            lines.append(f"{stamp} [{e['subsystem']}] {e['event']}"
                         + (f" {extra}" if extra else ""))
        return "\n".join(lines)


#: The process-wide recorder every subsystem feeds.
FLIGHT = FlightRecorder()


class dump_on_error:
    """Context manager: on ANY exception, print the flight recorder's
    tail (label + last `n` events) to `stream` before re-raising — the
    bench firewall's and the tests' incident dump."""

    def __init__(self, label: str = "", n: int = 50, stream=None,
                 recorder: Optional[FlightRecorder] = None):
        self.label = label
        self.n = int(n)
        self.stream = stream
        self.recorder = recorder if recorder is not None else FLIGHT

    def __enter__(self) -> "dump_on_error":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            out = self.stream if self.stream is not None else sys.stderr
            tail = self.recorder.dump_text(self.n)
            print(f"# chordax flight recorder"
                  + (f" ({self.label})" if self.label else "")
                  + f" — last {min(self.n, len(self.recorder))} "
                  f"events:", file=out)
            if tail:
                print(tail, file=out)
            # chordax-havoc (ISSUE 10): if a fault plan is (or was
            # just) active, the incident is only reproducible WITH its
            # seed + per-site step cursors — print them next to the
            # tail so any chaos failure can be replayed from the log
            # alone (describe_for_incident falls back to the last
            # UNINSTALLED plan: the failure usually unwound through
            # `injected()`'s finally before this dump runs).
            try:
                from p2p_dhts_tpu import havoc as _havoc
                line = _havoc.describe_for_incident()
                if line:
                    print(f"# {line}", file=out)
            # chordax-lint: disable=bare-except -- incident reporting must never mask the original failure
            except Exception:
                pass
        return False  # never suppress

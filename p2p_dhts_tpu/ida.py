"""Rabin Information Dispersal Algorithm, TPU-native.

Capability twin of the reference's ``src/ida`` stack (ida.{h,cpp},
data_fragment.{h,cpp}, data_block.{h,cpp}): split a byte string into
zero-padded length-m segments, encode them to n fragment rows with a
Vandermonde matrix mod prime p, reconstruct from any m rows.

Where the reference loops scalar inner products per fragment
(ida.cpp:59-73), here encode/decode are batched matmuls:

    encode:  [B, n, m] @ [B, m, S] mod p   (one matmul for a whole batch)
    decode:  vandermonde_inverse(indices) @ fragments, transposed back

Parity quirks deliberately reproduced (see SURVEY.md §7 quirks catalog):
  * decode strips trailing all-zero segments, then trailing zeros of the
    final segment (ida.cpp:143-154) — binary payloads ending in 0x00 are
    corrupted by design; ``DataBlock.decode`` strips NULs again
    (data_block.cpp:91-94).
  * fragment JSON wire form packs values as fixed-width base-64,
    ceil(log64 p) digits each, custom A-Za-z0-9+/ alphabet
    (data_fragment.cpp:49-62,98-132).
  * the text form writes "m n p idx:v1 v2 ..." but the text *parser* reads
    the prefix as "n m p idx" (data_fragment.cpp:74-86 vs :20-32) — an
    asymmetric round-trip in the reference, faithfully mirrored and
    documented here.
  * fragment indices are 1-based (FragsFromMatrix, data_fragment.cpp:171-179).
  * ``DataBlock`` reconstructed from >= m fragments re-encodes to regenerate
    all n rows (data_block.cpp:30-54).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import math
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .config import IdaParams
from .ops import modp

BASE64_ALPHABET = (
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/"
)
_B64_INDEX = {c: i for i, c in enumerate(BASE64_ALPHABET)}


# ---------------------------------------------------------------------------
# segmenting (host side — bytes in, int arrays out)
# ---------------------------------------------------------------------------

def split_to_segments(data: bytes, m: int) -> np.ndarray:
    """bytes -> [S, m] int32, zero-padded tail (ref: SplitToSegments,
    ida.cpp:177-190). Empty input yields [0, m]."""
    arr = np.frombuffer(data, dtype=np.uint8).astype(np.int32)
    n_seg = -(-len(arr) // m) if len(arr) else 0
    padded = np.zeros(n_seg * m, dtype=np.int32)
    padded[: len(arr)] = arr
    return padded.reshape(n_seg, m)


def strip_decoded(segments: np.ndarray) -> bytes:
    """Re-join decoded segments to bytes with the reference's stripping.

    Ref: ida.cpp:143-161 — drop trailing all-zero segments, then trailing
    zeros of the last remaining segment. The reference loops without a
    bounds check (UB on all-zero input); here all-zero input yields b"".
    """
    segs: List[np.ndarray] = [np.asarray(s) for s in segments]
    while segs and not np.any(segs[-1]):
        segs.pop()
    if not segs:
        return b""
    last = segs[-1]
    nz = np.nonzero(last)[0]
    segs[-1] = last[: nz[-1] + 1]
    return (np.concatenate(segs) & 0xFF).astype(np.uint8).tobytes()


# ---------------------------------------------------------------------------
# jitted kernels — batched over blocks
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n", "m", "p"))
def encode_kernel(segments: jax.Array, n: int, m: int, p: int) -> jax.Array:
    """[..., S, m] int32 segments -> [..., n, S] int32 fragment rows.

    fragment[i][j] = <enc_row_i, segment_j> mod p (ref: ida.cpp:59-73),
    i.e. E[n, m] @ segments^T — one MXU matmul over any batch of blocks.
    """
    enc = jnp.asarray(modp.vandermonde_matrix(n, m, p))
    seg_t = jnp.swapaxes(segments, -1, -2)  # [..., m, S]
    return modp.mod_matmul(jnp.broadcast_to(enc, segments.shape[:-2] + (n, m)), seg_t, p)


@functools.partial(jax.jit, static_argnames=("p",))
def decode_kernel(rows: jax.Array, indices: jax.Array, p: int) -> jax.Array:
    """Invert encoding: [..., m, S] rows with [..., m] 1-based indices
    -> [..., S, m] segments.

    Ref: ida.cpp:120-141 (uses the *first m* fragments passed; callers
    slice). The inverse Vandermonde is computed in-graph so decodes with
    heterogeneous index sets batch together.

    DEFAULT PATH resolves through the ops.ida_backend registry
    (chordax-fuse, ISSUE 13) AT TRACE TIME — the same moment the old
    hardcoded platform split fired, so unconfigured behavior is
    byte-identical to rounds 5-12:
      * TPU -> "mac", the VPU multiply-accumulate. Lowering the
        per-block tiny [m, m] @ [m, S] through dot_general pads every
        batch element to full MXU systolic tiles — measured 93.3 MB/s
        on v5e against 22 GB/s encode (BENCH_ATTEMPT_r04.jsonl).
      * CPU -> "dot", dot_general. XLA:CPU has no tile-padding cliff
        and runs the batched tiny dot at full speed, while the
        unrolled MAC measured ~250x slower there (BENCH_NOTES_r05:
        100.7 vs 0.4 MB/s at the bench shape).
    Override with ida_backend.set_backend(...) or
    CHORDAX_IDA_BACKEND=dot|mac|pallas|auto BEFORE the first decode
    traces (this jit's cache does not key on the knob; for a per-call
    choice use ida_backend.decode). The dot path stays callable as
    ``decode_kernel_dot`` and bench.py measures every backend
    side-by-side on whatever platform it runs.
    """
    from p2p_dhts_tpu.ops import ida_backend
    return ida_backend.decode_body(rows, indices, p,
                                   ida_backend.resolve())


@functools.partial(jax.jit, static_argnames=("p",))
def decode_kernel_dot(rows: jax.Array, indices: jax.Array,
                      p: int) -> jax.Array:
    """decode_kernel pinned to the "dot" registry backend — the
    pre-round-5 default, kept as the measured fallback (bench.py
    reports it as decode_dot_mb_s). On batched tiny shapes the MXU
    pads ~99% of each tile (the 93 MB/s cliff). ONE body: the registry
    owns every decode implementation (chordax-fuse), so the paths can
    never fork."""
    from p2p_dhts_tpu.ops import ida_backend
    return ida_backend.decode_body(rows, indices, p, "dot")


@functools.partial(jax.jit, static_argnames=("p",))
def decode_kernel_uniform(rows: jax.Array, indices: jax.Array,
                          p: int) -> jax.Array:
    """decode_kernel for a batch sharing ONE index set: [B, m, S] rows +
    [m] 1-based indices -> [B, S, m] segments.

    The no-failure read path: when the first m fragment holders all
    respond, every block decodes from indices 1..m, so the inverse
    Vandermonde is computed ONCE and the matmul has a broadcast LHS —
    the same shape XLA flattens into a dense MXU matmul for encode
    (22 GB/s measured) instead of the batched-tiny-matmul padding cliff
    (93 MB/s). Callers fall back to decode_kernel when index sets differ
    per block (post-failure reads)."""
    inv = modp.vandermonde_inverse(indices, p)           # [m, m]
    out = modp.mod_matmul(
        jnp.broadcast_to(inv, rows.shape[:-2] + inv.shape), rows, p)
    return jnp.swapaxes(out, -1, -2)                     # [..., S, m]




# ---------------------------------------------------------------------------
# host API — the reference's IDA class surface
# ---------------------------------------------------------------------------

class IDA:
    """Parameterized encoder/decoder (ref: class IDA, ida.h:43-121).

    Invariants n > m, p > n enforced (ida.cpp:48-57) via IdaParams.
    ``backend="jax"`` routes the matmuls through the jitted kernels;
    ``backend="numpy"`` is the host fallback for tiny one-off blocks where
    device dispatch overhead dominates.
    """

    def __init__(self, n: int = 14, m: int = 10, p: int = 257,
                 backend: str = "jax"):
        self.params = IdaParams(n=n, m=m, p=p)  # validates n > m, p > n, p prime
        if p <= 255:
            # This class encodes BYTE payloads: segment values span [0, 255]
            # and decode recovers them only mod p, so p < 257 silently
            # corrupts data (256 is not prime). The reference never hits
            # this because every caller keeps p=257 (dhash_peer.cpp:14-16).
            raise ValueError(
                f"byte-payload IDA requires p >= 257, got p={p}")
        self.n, self.m, self.p = n, m, p
        if backend not in ("jax", "numpy"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.encoding_matrix = modp.vandermonde_matrix(n, m, p)

    # -- encode ------------------------------------------------------------
    def encode(self, data: bytes) -> np.ndarray:
        """bytes -> [n, S] int32 fragment matrix (ref: IDA::Encode)."""
        segments = split_to_segments(data, self.m)
        if segments.shape[0] == 0:
            return np.zeros((self.n, 0), dtype=np.int32)
        if self.backend == "jax":
            return np.asarray(
                encode_kernel(jnp.asarray(segments), self.n, self.m, self.p)
            )
        return (self.encoding_matrix.astype(np.int64) @ segments.T.astype(np.int64)
                % self.p).astype(np.int32)

    def encode_plaintext(self, text: str) -> np.ndarray:
        """Ref: IDA::EncodePlaintext (ida.cpp:75-78) — bytes of the string."""
        return self.encode(text.encode("utf-8"))

    # -- decode ------------------------------------------------------------
    def decode(self, rows: Sequence[Sequence[int]],
               indices: Sequence[int]) -> bytes:
        """>= m fragment rows + 1-based indices -> original bytes.

        Uses the first m rows like the reference (ida.cpp:127), applies the
        reference's trailing-zero stripping.
        """
        if len(rows) < self.m:
            raise ValueError(f"{self.m} frags are required to decode.")
        rows_m = np.asarray(rows[: self.m], dtype=np.int32)
        idx_m = np.asarray(indices[: self.m], dtype=np.int32)
        if len(set(idx_m.tolist())) != self.m:
            raise ValueError("fragment indices must be distinct")
        if rows_m.shape[1] == 0:
            return b""
        if self.backend == "jax":
            segments = np.asarray(
                decode_kernel(jnp.asarray(rows_m), jnp.asarray(idx_m), self.p)
            )
        else:
            inv = np.asarray(modp.vandermonde_inverse(idx_m, self.p))
            segments = ((inv.astype(np.int64) @ rows_m.astype(np.int64)) % self.p).T
        return strip_decoded(segments)

    def decode_fragments(self, frags: Sequence["DataFragment"]) -> bytes:
        """Ref: IDA::Decode(vector<DataFragment>) (ida.cpp:164-175)."""
        return self.decode([f.values for f in frags], [f.index for f in frags])

    # -- file helpers (ref: ida.cpp:80-118) --------------------------------
    def encode_file(self, path: str) -> np.ndarray:
        with open(path, "rb") as fh:
            return self.encode(fh.read())

    def encode_to_files(self, in_path: str, out_paths: Sequence[str]) -> None:
        if len(out_paths) != self.n:
            raise ValueError(f"Number of outfiles should be {self.n}")
        frags = frags_from_matrix(self.encode_file(in_path),
                                  self.n, self.m, self.p)
        for frag, out in zip(frags, out_paths):
            frag.write_to_file(out)


# ---------------------------------------------------------------------------
# DataFragment — one encoded row + wire forms
# ---------------------------------------------------------------------------

def _digits_per_val(p: int) -> int:
    """ceil(log64 p) — fixed digit width per value (data_fragment.cpp:59)."""
    return max(1, math.ceil(math.log(p) / math.log(64)))


def serialize_base64(values: Sequence[int], num_digits: int = 2) -> str:
    """Fixed-width custom base-64 (ref: SerializeToBase64,
    data_fragment.cpp:98-115)."""
    out = []
    limit = 64 ** num_digits
    for val in values:
        val = int(val)
        if val < 0 or val >= limit:
            raise ValueError(f"Cannot encode {val}: outside [0, {limit})")
        digits = []
        for _ in range(num_digits):
            digits.append(BASE64_ALPHABET[val % 64])
            val //= 64
        out.extend(reversed(digits))
    return "".join(out)


def parse_base64(text: str, num_digits: int = 2) -> List[int]:
    """Inverse of serialize_base64 (ref: ParseFromBase64,
    data_fragment.cpp:118-132)."""
    vals = []
    for i in range(0, len(text), num_digits):
        el = 0
        for j in range(num_digits):
            el = el * 64 + _B64_INDEX[text[i + j]]
        vals.append(el)
    return vals


@dataclasses.dataclass
class DataFragment:
    """One encoded row + its 1-based index + IDA params.

    Ref: class DataFragment (data_fragment.h:18-100); defaults n=14 m=10
    p=257 (data_fragment.h:31).
    """

    values: List[int]
    index: int
    n: int = 14
    m: int = 10
    p: int = 257

    # -- JSON wire form (the RPC format) -----------------------------------
    def to_json(self) -> dict:
        """Ref: DataFragment::ToJson (data_fragment.cpp:49-62)."""
        return {
            "M": self.m, "N": self.n, "P": self.p, "INDEX": self.index,
            "FRAGMENT": serialize_base64(self.values, _digits_per_val(self.p)),
        }

    @classmethod
    def from_json(cls, obj: dict) -> "DataFragment":
        """Ref: DataFragment(const Json::Value&) (data_fragment.cpp:11-18)."""
        p = int(obj["P"])
        return cls(
            values=parse_base64(obj["FRAGMENT"], _digits_per_val(p)),
            index=int(obj["INDEX"]),
            n=int(obj["N"]), m=int(obj["M"]), p=p,
        )

    # -- text form (quirk-faithful asymmetric round-trip) ------------------
    def to_text(self) -> str:
        """Writes "m n p idx:v1 v2 ...\\n" (ref: operator std::string,
        data_fragment.cpp:74-86). NOTE the prefix order m-first."""
        vals = " ".join(str(int(v)) for v in self.values)
        return f"{self.m} {self.n} {self.p} {self.index}:{vals}\n"

    @classmethod
    def from_text(cls, text: str) -> "DataFragment":
        """Parses the prefix as "n m p idx" (ref: data_fragment.cpp:20-32) —
        the reference swaps n/m relative to to_text; mirrored for wire
        parity and pinned by tests."""
        prefix, _, body = text.strip().partition(":")
        n, m, p, idx = (int(tok) for tok in prefix.split(" "))
        vals = [int(tok) for tok in body.split(" ")] if body else []
        return cls(values=vals, index=idx, n=n, m=m, p=p)

    # -- file round-trip (ref: data_fragment.cpp:34-47,181-196) ------------
    def write_to_file(self, path: str) -> bool:
        try:
            with open(path, "w") as fh:
                json.dump(self.to_json(), fh)
            return True
        except OSError:
            return False

    @classmethod
    def from_file(cls, path: str) -> "DataFragment":
        with open(path) as fh:
            return cls.from_json(json.load(fh))

    def __eq__(self, other: object) -> bool:
        """Equality is values + index only (data_fragment.cpp:88-91)."""
        if not isinstance(other, DataFragment):
            return NotImplemented
        return list(self.values) == list(other.values) and self.index == other.index

    def __lt__(self, other: "DataFragment") -> bool:
        return self.index < other.index


def frags_from_matrix(matrix: np.ndarray, n: int = 14, m: int = 10,
                      p: int = 257) -> List[DataFragment]:
    """[n, S] matrix -> n fragments with 1-based indices
    (ref: FragsFromMatrix, data_fragment.cpp:171-179)."""
    return [
        DataFragment(values=[int(v) for v in matrix[i]], index=i + 1,
                     n=n, m=m, p=p)
        for i in range(matrix.shape[0])
    ]


# ---------------------------------------------------------------------------
# DataBlock — value container for DHash
# ---------------------------------------------------------------------------

class DataBlock:
    """A stored value as n fragments (ref: class DataBlock, data_block.h:21-103).

    Construct from a string/bytes (encode) or from >= m fragments
    (decode then re-encode all n, data_block.cpp:30-54).
    """

    def __init__(self, data: Optional[bytes] = None, n: int = 14, m: int = 10,
                 p: int = 257,
                 fragments: Optional[Sequence[DataFragment]] = None,
                 backend: str = "jax"):
        self.n, self.m, self.p = n, m, p
        self.ida = IDA(n, m, p, backend=backend)
        if data is not None:
            if isinstance(data, str):
                # surrogateescape mirrors decode(): binary payloads that
                # crossed the overlay as lone-surrogate text (upload_file's
                # round-trip, chord_peer.py:240-250) re-encode to their
                # original bytes instead of raising.
                data = data.encode("utf-8", "surrogateescape")
            self.original = data
            self.fragments = frags_from_matrix(self.ida.encode(data), n, m, p)
        elif fragments is not None:
            self.original = self.ida.decode_fragments(list(fragments))
            self.fragments = frags_from_matrix(
                self.ida.encode(self.original), n, m, p)
        else:
            raise ValueError("DataBlock needs data or fragments")

    @classmethod
    def from_json(cls, obj: dict, backend: str = "jax") -> "DataBlock":
        """Ref: DataBlock(const Json::Value&) (data_block.cpp:17-28)."""
        frags = [DataFragment.from_json(f) for f in obj["FRAGMENTS"]]
        return cls(n=int(obj["N"]), m=int(obj["M"]), p=int(obj["P"]),
                   fragments=frags, backend=backend)

    def to_json(self) -> dict:
        return {
            "N": self.n, "M": self.m, "P": self.p,
            "FRAGMENTS": [f.to_json() for f in self.fragments],
        }

    def decode(self) -> str:
        """Original as text, stripping trailing NULs
        (ref: DataBlock::Decode, data_block.cpp:81-97)."""
        return self.original.rstrip(b"\x00").decode("utf-8", errors="surrogateescape")

    def decode_bytes(self) -> bytes:
        return self.original.rstrip(b"\x00")

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, DataBlock):
            return NotImplemented
        return (self.original == other.original
                and self.fragments == other.fragments)

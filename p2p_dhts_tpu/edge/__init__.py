"""chordax-edge: the zero-hop client SDK (ISSUE 17).

The mesh (ISSUE 15) made every gateway routing-aware — but the CLIENT
stayed the reference's one-shot dumb socket, so every cross-shard key
paid a gateway forward hop the epoch-stamped route table already knew
how to skip. This package moves ownership resolution to the rim:

  client     edge.Client — the application entry point: resolves each
             key's owner against the cached route table and sends
             DIRECTLY to it (zero-hop), folds concurrent bursts per
             (destination, verb) through the shared mesh/fold.py core,
             hedges tail reads, and backs off BUSY owners.
  routes     RouteCache — the client-side epoch-stamped shard ->
             address table: one MESH_ROUTES pull to seed, NOT_OWNED
             piggybacked docs to self-heal, epochs never applied
             backwards.
  hedge      HedgePolicy — the adaptive per-destination p99 hedge
             timer + the ~5% fairness budget that keeps hedges from
             amplifying an overload.

When to use what: `edge.Client` for application traffic against a
mesh ring (it needs the MESH_ROUTES verb and the one-hop ``FWD``
protocol); the raw `net/rpc.py` Client for control-plane verbs,
single-process rings, and anything that must not carry a route cache.
This package never imports jax.
"""

from p2p_dhts_tpu.edge.client import Client, EdgeError, EdgeResult  # noqa: F401
from p2p_dhts_tpu.edge.hedge import HedgePolicy  # noqa: F401
from p2p_dhts_tpu.edge.routes import RouteCache  # noqa: F401

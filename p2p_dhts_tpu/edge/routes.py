"""The edge route cache: a client-side, epoch-stamped shard -> address
table (chordax-edge, ISSUE 17 — the zero-hop half).

The cache IS a `mesh.routes.RouteTable` with no self address (every
row resolves REMOTE — the rim is not a mesh peer), plus the client's
lifecycle around it:

  * SEED — one MESH_ROUTES pull from any configured gateway the first
    time a key needs resolving (lazy; a client that never sends never
    pulls);
  * SELF-HEAL — a NOT_OWNED bounce carries the owner's fresher table
    piggybacked (`install_doc`), and every mesh vector reply carries
    the serving process's ROUTES_EPOCH so a stale cache re-pulls even
    when its keys happened to land right (`observe_epoch`);
  * MONOTONIC — installs go through the table's epoch guard: stale
    gossip is dropped, never applied backwards.

Convergence contract (the bench gate): an operator re-split costs each
client at most ONE refresh round — the first bounced (or beaconed)
request installs the new table, every later resolve is zero-hop again.

LOCK ORDER: `RouteCache._lock` is a LEAF guarding refresh bookkeeping
only — never held across the MESH_ROUTES RPC (the pull runs unlocked;
the epoch guard makes concurrent pulls converge).
This module never imports jax.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from p2p_dhts_tpu.mesh.routes import Addr, RouteTable, addr_str
from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.net.rpc import Client, RpcError


class RouteCacheError(RuntimeError):
    """No gateway would serve MESH_ROUTES (cache cannot seed)."""


class RouteCache:
    """Client-side route table + its pull/install/observe lifecycle."""

    def __init__(self, gateways: Sequence[Addr],
                 metrics: Optional[Metrics] = None,
                 pull_timeout_s: float = 5.0):
        if not gateways:
            raise ValueError("RouteCache needs at least one gateway")
        self.gateways: List[Addr] = [(str(g[0]), int(g[1]))
                                     for g in gateways]
        self.metrics = metrics if metrics is not None else METRICS
        self.pull_timeout_s = float(pull_timeout_s)
        self.table = RouteTable()          # self_addr=None: all-remote
        self._lock = threading.Lock()      # LEAF: counters/rotation only
        self._pull_rr = 0                  # seed-gateway rotation cursor
        self._refreshes = 0

    # -- introspection -------------------------------------------------------
    @property
    def epoch(self) -> int:
        return self.table.epoch

    @property
    def refreshes(self) -> int:
        """MESH_ROUTES pulls performed (the convergence gate counts
        these: one re-split must cost at most one per client)."""
        with self._lock:
            return self._refreshes

    def addresses(self) -> List[Addr]:
        """Route-table gateways when seeded, the configured seed list
        before that (the hedger needs an alternate either way)."""
        addrs = self.table.addresses()
        return addrs if addrs else list(self.gateways)

    # -- lifecycle -----------------------------------------------------------
    def install_doc(self, doc: dict) -> bool:
        """Install a piggybacked MESH_ROUTES document (a NOT_OWNED
        bounce's fresher table). Epoch-guarded: returns True only when
        it was NEWER."""
        if self.table.apply_doc(doc):
            self.metrics.inc("edge.routes_installed")
            self.metrics.gauge("edge.route_epoch", self.table.epoch)
            return True
        return False

    def refresh(self, via: Optional[Addr] = None) -> bool:
        """One MESH_ROUTES pull — from `via` (the gateway whose reply
        told us we are stale) or the rotating seed list. Runs entirely
        unlocked; the table's epoch guard serializes installs."""
        candidates: List[Addr] = []
        if via is not None:
            candidates.append((str(via[0]), int(via[1])))
        with self._lock:
            rr = self._pull_rr
            self._pull_rr += 1
            self._refreshes += 1
        known = self.addresses()
        candidates.extend(known[(rr + i) % len(known)]
                          for i in range(len(known)))
        self.metrics.inc("edge.routes_refreshed")
        last_err: Optional[str] = None
        for addr in candidates:
            try:
                resp = Client.make_request(
                    addr[0], addr[1], {"COMMAND": "MESH_ROUTES"},
                    timeout=self.pull_timeout_s)
            except RpcError as exc:
                last_err = f"{addr_str(addr)}: {exc}"
                continue
            if not resp.get("SUCCESS") or not resp.get("ATTACHED"):
                last_err = f"{addr_str(addr)}: no mesh plane attached"
                continue
            fresher = self.table.apply_doc(resp)
            self.metrics.gauge("edge.route_epoch", self.table.epoch)
            return fresher
        raise RouteCacheError(
            f"MESH_ROUTES pull failed everywhere (last: {last_err})")

    def ensure(self) -> None:
        """Seed the cache (one pull) if it has never installed a map."""
        if len(self.table) == 0:
            self.refresh()

    def observe_epoch(self, seen_epoch: Optional[int],
                      via: Addr) -> None:
        """A reply carried the serving process's ROUTES_EPOCH: when it
        is ahead of ours, pull its table — the staleness beacon that
        heals a cache whose keys happened to land right anyway."""
        if seen_epoch is None:
            return
        if int(seen_epoch) > self.table.epoch:
            self.metrics.inc("edge.route_stale")
            try:
                self.refresh(via=via)
            except RouteCacheError:
                pass  # the next bounce (or beacon) retries the pull

    # -- resolution ----------------------------------------------------------
    def resolve(self, lanes: np.ndarray
                ) -> List[Tuple[Addr, np.ndarray]]:
        """Owner split for a whole [N, LANES] key array — seeds the
        cache on first use; every row resolves to a gateway address
        (the all-remote rim split)."""
        self.ensure()
        return self.table.split_lanes_all(lanes)

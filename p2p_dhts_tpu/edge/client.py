"""edge.Client: the zero-hop, coalescing, hedging client SDK
(chordax-edge, ISSUE 17 — the tentpole).

One application call runs four planes:

  1. ROUTE — resolve every key's owner against the cached
     epoch-stamped table (`edge/routes.py`) and send DIRECTLY to it
     with ``FWD: 1``: the owner answers from local ownership and
     bounces stale rows NOT_OWNED with its fresher table piggybacked —
     the client installs it and re-resolves the bounced rows exactly
     ONCE (the mesh plane's origin discipline, lifted to the rim).
  2. FOLD — concurrent bursts to the same (destination, verb) ride
     ONE packed-u128 vector RPC through the shared `mesh/fold.py`
     core (`edge.*` metrics, `edge.flush` span).
  3. HEDGE — a read still unanswered past the destination's adaptive
     p99 timer is re-issued WITHOUT ``FWD`` to an alternate gateway
     (which serves or forwards under the one-hop rule); first answer
     wins, the loser is cancelled (its late reply counts
     `rpc.wire.discarded`), and hedges stay under the ~5% fairness
     budget (`edge/hedge.py`).
  4. BACKOFF — a per-destination breaker honoring BUSY sheds and
     RingBusyError verdicts with jittered doubling cooldowns: rows
     owned by a shedding/dead gateway fail fast and alone; every
     other destination's rows are untouched.

`edge.request` is the trace ROOT: the chordax-scope chain of a routed
read is edge.request -> edge.flush -> rpc.client.<VERB> ->
rpc.server.<VERB> -> gateway.*, across processes.

LOCK ORDER: `Client._lock` (backoff table) is a LEAF — held for
state reads/updates only, never across an RPC, a wait, or another
lock. The hedged send runs entirely lock-free.
This module never imports jax.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from p2p_dhts_tpu import trace as trace_mod
from p2p_dhts_tpu.edge.hedge import HedgePolicy
from p2p_dhts_tpu.edge.routes import RouteCache
from p2p_dhts_tpu.health import FLIGHT
from p2p_dhts_tpu.keyspace import LANES, ints_to_lanes
from p2p_dhts_tpu.mesh.fold import FoldCore, FoldError
from p2p_dhts_tpu.mesh.routes import Addr, addr_str
from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.net import wire
from p2p_dhts_tpu.net.rpc import Client as RpcClient
from p2p_dhts_tpu.net.rpc import RpcError

#: Consecutive transport failures before a destination's backoff
#: window opens without a BUSY verdict (a dead owner must fail fast,
#: not burn one timeout per row-batch).
BACKOFF_THRESHOLD = 3

#: Jittered backoff window base/cap (doubles per consecutive open).
BACKOFF_BASE_S = 0.05
BACKOFF_CAP_S = 2.0

#: Private jitter stream (never the seeded global RNG — backoff noise
#: must not perturb seeded test/bench schedules).
_JITTER = random.Random()


class EdgeError(FoldError):
    """An edge request failed for (some of) its rows."""


class EdgeResult:
    """Row-aligned answers for one edge vector call. `failed` marks
    rows that carry no answer; `errors` maps "ip:port" -> message for
    every destination that failed (one dead owner fails only its
    rows)."""

    __slots__ = ("owners", "hops", "segments", "ok", "failed",
                 "errors")

    def __init__(self, n: int, verb: str) -> None:
        self.owners = (np.full(n, -1, np.int64)
                       if verb == "FIND_SUCCESSOR" else None)
        self.hops = (np.full(n, -1, np.int32)
                     if verb == "FIND_SUCCESSOR" else None)
        self.segments: Optional[List] = ([None] * n if verb == "GET"
                                         else None)
        self.ok = (np.zeros(n, dtype=bool) if verb == "GET" else None)
        self.failed = np.zeros(n, dtype=bool)
        self.errors: Dict[str, str] = {}

    @property
    def all_ok(self) -> bool:
        return not self.failed.any()


class _Backoff:
    """One destination's breaker row (client-lock guarded)."""

    __slots__ = ("fails", "until", "opens")

    def __init__(self) -> None:
        self.fails = 0
        self.until = 0.0
        self.opens = 0


class _EdgeCoalescer(FoldCore):
    """The rim identity of the shared fold core: `edge.*` metric keys,
    `edge.flush` spans, `edge-*` lane threads, and the hedged/
    breaker-guarded transport owned by the Client."""

    error_cls = EdgeError
    closed_msg = "edge client closed"
    span_name = "edge.flush"
    span_cat = "edge"
    thread_prefix = "edge"

    def __init__(self, owner: "Client", metrics: Optional[Metrics],
                 max_batch: int, retries: int):
        super().__init__(metrics=metrics, max_batch=max_batch,
                         retries=retries)
        self.owner = owner

    # -- metric identity (LITERAL keys — the doc-drift gate scans these) -----
    def _record_flush(self, n_keys: int, folded: int) -> None:
        self.metrics.inc("edge.batches")
        self.metrics.observe_hist("edge.batch_size", n_keys)
        if folded > 1:
            self.metrics.inc("edge.coalesced", folded - 1)

    def _record_error(self) -> None:
        self.metrics.inc("edge.errors")

    def _record_latency(self, dt: float) -> None:
        self.metrics.observe("edge.latency", dt)

    def _record_not_owner(self, k: int) -> None:
        self.metrics.inc("edge.not_owner", k)

    def _transport(self, dest: Tuple[str, int], verb: str, req: dict,
                   timeout: float,
                   deadline_at: Optional[float]) -> dict:
        return self.owner._send(dest, verb, req, timeout, deadline_at)


class Client:
    """The zero-hop client: route-cached, coalescing, hedging,
    backing off. One instance is a process-wide rim (thread-safe);
    `close()` drains the fold lanes."""

    def __init__(self, gateways: Sequence[Addr], *,
                 metrics: Optional[Metrics] = None,
                 max_batch: int = 4096, coalesce: bool = True,
                 retries: int = 1,
                 hedge: Optional[HedgePolicy] = None,
                 hedge_enabled: bool = True,
                 pull_timeout_s: float = 5.0,
                 request_fields: Optional[Dict[str, object]] = None):
        self.metrics = metrics if metrics is not None else METRICS
        self.routes = RouteCache(gateways, metrics=self.metrics,
                                 pull_timeout_s=pull_timeout_s)
        self.hedge = hedge if hedge is not None else HedgePolicy(
            metrics=self.metrics, enabled=hedge_enabled)
        self._fold = _EdgeCoalescer(self, self.metrics,
                                    max_batch if coalesce else 1,
                                    retries)
        # Per-client wire identity (chordax-tower, ISSUE 20): fields
        # stamped on every flushed RPC — the canary's probe client
        # passes {"NOCACHE": 1}. Folds never mix across Clients, so
        # the fields can never leak onto another caller's requests.
        if request_fields:
            self._fold.extra_fields = dict(request_fields)
        self._lock = threading.Lock()   # LEAF: the backoff table
        self._backoff: Dict[Tuple[str, int], _Backoff] = {}

    # -- public API ----------------------------------------------------------
    def find_successor(self, keys, starts=None,
                       deadline_ms: Optional[float] = None
                       ) -> EdgeResult:
        """Vector FIND_SUCCESSOR, client-routed: owners/hops row-
        aligned with `keys` ([N, LANES] uint32 lanes or a sequence of
        ints)."""
        return self._vector("FIND_SUCCESSOR", keys, starts,
                            deadline_ms)

    def get(self, keys,
            deadline_ms: Optional[float] = None) -> EdgeResult:
        """Vector DHash GET, client-routed: segments/ok row-aligned
        with `keys`."""
        return self._vector("GET", keys, None, deadline_ms)

    def set_coalesce(self, on: bool) -> None:
        """The SET_COALESCE A/B knob, client-side."""
        self._fold.set_coalesce(on)

    def close(self) -> None:
        self._fold.close()

    # -- the routed vector path ----------------------------------------------
    @staticmethod
    def _as_lanes(keys) -> np.ndarray:
        if isinstance(keys, np.ndarray) and keys.ndim == 2 \
                and keys.shape[1] == LANES:
            return np.ascontiguousarray(keys, dtype=np.uint32)
        return ints_to_lanes(int(k) for k in keys)

    def _vector(self, verb: str, keys, starts,
                deadline_ms: Optional[float]) -> EdgeResult:
        lanes = self._as_lanes(keys)
        n = lanes.shape[0]
        starts_arr = (None if starts is None
                      else np.ascontiguousarray(starts, np.int32))
        deadline_at = (time.perf_counter() + float(deadline_ms) / 1e3
                       if deadline_ms is not None else None)
        self.metrics.inc("edge.requests")
        self.metrics.inc("edge.keys", n)
        self.hedge.note_request()
        out = EdgeResult(n, verb)
        if n == 0:
            return out
        # The ROOT span of the cross-process chain: edge.request ->
        # edge.flush -> rpc.client.<VERB> -> rpc.server.<VERB> -> ...
        with trace_mod.span("edge.request", cat="edge", verb=verb,
                            n=n):
            plan = self.routes.resolve(lanes)
            if not plan:
                raise EdgeError("route cache is empty (no mesh?)")
            if len(plan) == 1:
                addr, rows = plan[0]
                self._dest_rows(verb, addr, lanes, starts_arr, rows,
                                deadline_at, out)
            else:
                # Destinations run CONCURRENTLY: the call costs
                # max(owner latency), never the sum — and each
                # worker's fold entry still coalesces with every
                # other caller's burst to that destination.
                from concurrent.futures import ThreadPoolExecutor
                ctx = trace_mod.current_raw()

                def one(item):
                    addr, rows = item
                    with trace_mod.activate(ctx):
                        self._dest_rows(verb, addr, lanes, starts_arr,
                                        rows, deadline_at, out)

                with ThreadPoolExecutor(
                        max_workers=min(len(plan), 8),
                        thread_name_prefix="edge-vec") as pool:
                    list(pool.map(one, plan))
        return out

    def _dest_rows(self, verb: str, addr: Addr, lanes: np.ndarray,
                   starts: Optional[np.ndarray], rows: np.ndarray,
                   deadline_at: Optional[float],
                   out: EdgeResult) -> None:
        """One destination's rows: fold-forward, then at most ONE
        install-and-re-resolve of whatever bounced NOT_OWNED. Writes
        into `out` row-slices are disjoint per destination — no lock
        needed."""
        sub_lanes = lanes[rows]
        sub_starts = starts[rows] if starts is not None else None
        try:
            res = self._fold.forward(addr, verb, sub_lanes, sub_starts,
                                     deadline_at)
        # chordax-lint: disable=bare-except -- one dead owner fails only its rows; every other destination's answers stand
        except Exception as exc:
            out.failed[rows] = True
            out.errors[addr_str(addr)] = str(exc)
            return
        self._merge(verb, out, rows, res, exclude=res.not_owned)
        self.routes.observe_epoch(res.routes_epoch, addr)
        if not res.not_owned:
            return
        # The owner's table is fresher: install the piggybacked doc,
        # re-resolve the bounced rows ONCE. A row that bounces again
        # (or re-resolves to the SAME stale owner) fails — route churn
        # faster than one refresh round is the caller's retry.
        self.metrics.inc("edge.retries")
        if res.routes_doc is not None:
            self.routes.install_doc(res.routes_doc)
        bounced = rows[np.asarray(sorted(res.not_owned), np.int64)]
        out.failed[bounced] = True
        replan = self.routes.table.split_lanes_all(lanes[bounced])
        for new_addr, rr in replan:
            j = bounced[rr]
            if new_addr == addr:
                out.errors[addr_str(addr)] = (
                    f"owner {addr_str(addr)} bounced {len(rr)} key(s) "
                    f"it still maps to itself")
                continue
            try:
                res2 = self._fold.forward(
                    new_addr, verb, lanes[j],
                    starts[j] if starts is not None else None,
                    deadline_at)
            # chordax-lint: disable=bare-except -- the single retry's failure stays a per-row verdict, never a client crash
            except Exception as exc:
                out.errors[addr_str(new_addr)] = str(exc)
                continue
            still = set(res2.not_owned)
            live = np.asarray([i for i in range(len(rr))
                               if i not in still], np.int64)
            self._merge(verb, out, j[live], res2, rows_slice=live)
            out.failed[j[live]] = False
            if still:
                out.errors[addr_str(new_addr)] = (
                    f"{len(still)} key(s) still unowned after one "
                    f"re-resolution (route churn)")

    @staticmethod
    def _merge(verb: str, out: EdgeResult, at: np.ndarray, res,
               exclude: Sequence[int] = (),
               rows_slice: Optional[np.ndarray] = None) -> None:
        """Copy one FoldResult (or its `rows_slice` subset) into the
        result rows `at`, skipping `exclude` (entry-relative bounced
        indices)."""
        if exclude:
            keep = np.asarray([i for i in range(len(at))
                               if i not in set(exclude)], np.int64)
            at = at[keep]
            src = keep
        elif rows_slice is not None:
            src = rows_slice
        else:
            src = np.arange(len(at))
        if len(at) == 0:
            return
        if verb == "FIND_SUCCESSOR":
            out.owners[at] = np.asarray(res.owners)[src]
            out.hops[at] = np.asarray(res.hops)[src]
        else:
            out.ok[at] = np.asarray(res.ok)[src]
            for i, j in zip(src, at):
                out.segments[int(j)] = res.segments[int(i)]

    # -- backoff (BUSY / RingBusyError / dead-owner breaker) -----------------
    def _backoff_admit(self, dest: Tuple[str, int]) -> None:
        now = time.monotonic()
        with self._lock:
            b = self._backoff.get(dest)
            blocked = b is not None and now < b.until
        if blocked:
            self.metrics.inc("edge.backoff.fastfail")
            raise EdgeError(
                f"destination {dest[0]}:{dest[1]} backing off "
                f"(BUSY/unreachable); retry after the window")

    def _backoff_ok(self, dest: Tuple[str, int]) -> None:
        with self._lock:
            b = self._backoff.pop(dest, None)
            was_open = b is not None and b.opens > 0
        if was_open:
            # chordax-tower (ISSUE 20): breaker transitions are
            # incident-timeline events — the flight ring (leaf lock of
            # its own, recorded OUTSIDE ours) is what the collector
            # pulls and the timeline orders.
            FLIGHT.record("edge", "breaker_close",
                          dest=f"{dest[0]}:{dest[1]}")

    def _backoff_fail(self, dest: Tuple[str, int],
                      busy: bool) -> None:
        """A BUSY/RingBusyError verdict opens the window immediately
        (the server TOLD us to go away); plain transport failures
        open it after BACKOFF_THRESHOLD in a row."""
        if busy:
            self.metrics.inc("edge.backoff.busy")
        with self._lock:
            b = self._backoff.setdefault(dest, _Backoff())
            b.fails += 1
            if not busy and b.fails < BACKOFF_THRESHOLD:
                return
            b.opens += 1
            base = min(BACKOFF_BASE_S * (2 ** (b.opens - 1)),
                       BACKOFF_CAP_S)
            # Jittered: N clients shed by the same gateway must not
            # come back in lockstep (the retry-storm rule).
            b.until = time.monotonic() + _JITTER.uniform(
                base * 0.5, base)
            fails = b.fails
        self.metrics.inc("edge.backoff.open")
        FLIGHT.record("edge", "breaker_open",
                      dest=f"{dest[0]}:{dest[1]}", fails=fails,
                      busy=bool(busy))

    @staticmethod
    def _is_busy_error(exc: BaseException) -> bool:
        """A shed verdict: the RPC BUSY envelope ("RPC server busy")
        or a RingBusyError the owner folded into its ERRORS reply."""
        msg = str(exc)
        return "busy" in msg.lower()

    # -- the guarded/hedged send (the fold core's transport) -----------------
    def _send(self, dest: Tuple[str, int], verb: str, req: dict,
              timeout: float, deadline_at: Optional[float]) -> dict:
        self._backoff_admit(dest)
        delay = self.hedge.delay_s(dest)
        try:
            if delay is None or delay >= timeout:
                resp = RpcClient.make_request(
                    dest[0], dest[1], req, timeout=timeout,
                    retries=self._fold.retries, deadline=deadline_at)
            else:
                resp = self._send_hedged(dest, verb, req, timeout,
                                         delay)
        # chordax-lint: disable=bare-except -- every failure shape feeds the breaker verdict before re-raising to the fold funnel
        except Exception as exc:
            self._backoff_fail(dest, busy=self._is_busy_error(exc))
            raise
        if not resp.get("SUCCESS") and \
                "busy" in str(resp.get("ERRORS", "")).lower():
            # The owner answered, but with a RingBusyError verdict:
            # an admission shed, not a route problem — open the
            # window so this destination's next rows fail fast.
            self._backoff_fail(dest, busy=True)
        else:
            self._backoff_ok(dest)
        return resp

    def _alternate(self, dest: Tuple[str, int]
                   ) -> Optional[Tuple[str, int]]:
        """The hedge target: the next route-table gateway after
        `dest` (id order) that is not itself backing off."""
        addrs = self.routes.addresses()
        if len(addrs) < 2:
            return None
        try:
            i = addrs.index((str(dest[0]), int(dest[1])))
        except ValueError:
            i = -1
        now = time.monotonic()
        for k in range(1, len(addrs)):
            cand = addrs[(i + k) % len(addrs)]
            if cand == dest:
                continue
            with self._lock:
                b = self._backoff.get(cand)
                blocked = b is not None and now < b.until
            if not blocked:
                return cand
        return None

    def _send_hedged(self, dest: Tuple[str, int], verb: str,
                     req: dict, timeout: float,
                     delay: float) -> dict:
        """Primary to the owner (FWD), and — past the adaptive timer,
        budget permitting — a hedge WITHOUT FWD to an alternate
        gateway. First answer wins; the loser is cancelled and its
        late reply counts `rpc.wire.discarded`. Legacy (JSON-only)
        destinations fall back to the plain blocking path: hedging
        needs the pipelined binary wire."""
        deadline = time.perf_counter() + timeout
        # Mirror rpc.Client.make_request: this span is the wire-level
        # client span, and ITS context rides the TRACE field (an
        # unsampled root rides the explicit not-sampled marker).
        with trace_mod.span(f"rpc.client.{verb}", cat="rpc",
                            peer=f"{dest[0]}:{dest[1]}",
                            hedged=1) as span_ctx:
            wire_req = dict(req)
            if span_ctx is not None:
                wire_req[trace_mod.WIRE_KEY] = span_ctx.to_wire()
            elif trace_mod.enabled():
                wire_req[trace_mod.WIRE_KEY] = \
                    trace_mod.UNSAMPLED_WIRE
            try:
                primary = wire.submit(dest[0], dest[1], wire_req)
            except wire.NegotiationFallback:
                return RpcClient.make_request(
                    dest[0], dest[1], req, timeout=timeout,
                    retries=self._fold.retries)
            if primary.wait_done(min(delay, timeout)):
                return self._settle(primary, deadline)
            # Timer passed with no answer: hedge if an alternate
            # exists and the fairness budget admits it.
            alt = self._alternate(dest)
            if alt is None or not self.hedge.admit():
                return self._settle(primary, deadline)
            self.metrics.inc("edge.hedges")
            hedge_req = dict(wire_req)
            hedge_req.pop("FWD", None)   # the alternate may forward
            try:
                rival = wire.submit(alt[0], alt[1], hedge_req)
            except (wire.NegotiationFallback, OSError,
                    RuntimeError):
                return self._settle(primary, deadline)
            # First answer wins. The poll alternates short waits on
            # the two events; 1 ms granularity is far below any
            # latency a hedge fires at.
            while time.perf_counter() < deadline:
                if primary.done():
                    rival.cancel()
                    return self._settle(primary, deadline)
                if rival.done():
                    primary.cancel()
                    self.metrics.inc("edge.hedge_wins")
                    return self._settle(rival, deadline)
                primary.wait_done(0.001)
                rival.wait_done(0.001)
            rival.cancel()
            return self._settle(primary, deadline)  # raises timeout

    @staticmethod
    def _settle(call: "wire.PendingCall", deadline: float) -> dict:
        """Consume one pending call's reply, translating transport
        and BUSY-envelope failures exactly as the rpc client does."""
        try:
            resp = call.wait(max(deadline - time.perf_counter(),
                                 0.001))
        except TimeoutError as exc:
            raise RpcError(f"RPC reply timed out: {exc}") from exc
        except (OSError, RuntimeError) as exc:
            raise RpcError(f"RPC transport failure: {exc}") from exc
        if resp.get("BUSY"):
            METRICS.inc("rpc.client.busy")
            raise RpcError(
                "RPC server busy (connection flow-control shed)")
        return resp

"""The edge hedge policy: adaptive per-destination tail timers under a
global fairness budget (chordax-edge, ISSUE 17 — the tail half).

A read whose primary gateway is having a bad moment (GC pause, queue
convoy, one slow device step) can be answered sooner by ANY other
gateway — under the one-hop rule an alternate either serves the keys
or forwards them once. The policy decides WHEN re-issuing is worth it
and HOW MUCH of it the fleet can afford:

  * TIMER — hedge only after the destination's observed p99 (the wire
    pool's per-destination latency reservoir, `dest_snapshot`), so a
    healthy destination is never hedged on the common path. Before
    enough samples exist the timer falls back to a configured floor —
    the policy never hedges blind below it.
  * BUDGET — hedges are admitted against a running ~5% fairness cap
    of REQUESTS SEEN (`ratio`): at most one hedge per 1/ratio
    requests, so hedging can never amplify an overload into a retry
    storm. Denials are counted, not queued.

LOCK ORDER: `HedgePolicy._lock` is a LEAF — pure counter bookkeeping,
never held across an RPC or a snapshot call.
This module never imports jax.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.net import wire

#: Default fairness cap: hedged traffic <= 5% of requests (the ISSUE
#: 17 acceptance bound).
DEFAULT_HEDGE_RATIO = 0.05

#: Timer floor (ms) — also the fallback while the destination's
#: latency reservoir is still filling.
DEFAULT_FLOOR_MS = 25.0

#: Reservoir samples required before the adaptive p99 takes over from
#: the floor.
DEFAULT_MIN_SAMPLES = 32


class HedgePolicy:
    """Per-destination hedge timers + the global hedge budget."""

    def __init__(self, metrics: Optional[Metrics] = None,
                 ratio: float = DEFAULT_HEDGE_RATIO,
                 floor_ms: float = DEFAULT_FLOOR_MS,
                 min_samples: int = DEFAULT_MIN_SAMPLES,
                 enabled: bool = True):
        self.metrics = metrics if metrics is not None else METRICS
        self.ratio = float(ratio)
        self.floor_ms = float(floor_ms)
        self.min_samples = int(min_samples)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()   # LEAF: budget counters only
        self._requests = 0
        self._hedges = 0

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"requests": self._requests, "hedges": self._hedges,
                    "ratio": self.ratio, "enabled": self.enabled}

    # -- the timer -----------------------------------------------------------
    def delay_s(self, dest: Tuple[str, int]) -> Optional[float]:
        """Seconds to wait on the primary before re-issuing, or None
        when hedging is off. Adaptive: the destination's observed p99
        once the reservoir holds `min_samples`, the floor before
        that — and never below the floor (a sub-floor p99 means the
        destination is fast; hedging it would be pure amplification)."""
        if not self.enabled:
            return None
        snap = wire.pool().dest_snapshot(dest[0], dest[1])
        p99 = snap.get("p99_ms")
        if p99 is None or snap.get("samples", 0) < self.min_samples:
            return self.floor_ms / 1e3
        return max(float(p99), self.floor_ms) / 1e3

    # -- the budget ----------------------------------------------------------
    def note_request(self) -> None:
        """Every edge request feeds the fairness denominator."""
        with self._lock:
            self._requests += 1

    def admit(self) -> bool:
        """Claim one hedge against the budget: admitted while hedges
        (including this one) stay within `ratio` of requests seen.
        A denial is final for this request — denials count
        `edge.hedge_capped`, they are never queued."""
        with self._lock:
            if (self._hedges + 1) <= self.ratio * self._requests:
                self._hedges += 1
                admitted = True
            else:
                admitted = False
        if not admitted:
            self.metrics.inc("edge.hedge_capped")
        return admitted

"""The mesh route table: the cluster-wide shard -> address map.

chordax-mesh (ISSUE 15) shards the 2^128 identifier circle across N
gateway PROCESSES exactly the way Chord shards it across peers: every
mesh peer carries a 128-bit id (keyspace.peer_id of its ip:port — the
reference's SHA1("ip:port") rule, abstract_chord_peer.cpp:13-28), and
the peer with id p owns the clockwise-inclusive range
(pred(p) + 1 .. p] — i.e. the owner of key k is the RING SUCCESSOR of
k among the live peer ids. That is byte-for-byte the reference's
StoredLocally rule (abstract_chord_peer.cpp:720-725) lifted one level,
from device rows to serving processes, and it is what
tests/test_mesh.py pins against tests/oracle.py across re-splits.

The table is VERSIONED: the membership plane's coordinator stamps each
recomputed split with a monotonically increasing EPOCH, peers install
a map only when its epoch is newer than theirs (stale gossip can never
roll a peer backwards), and a local `set_key_range` re-split bumps a
GENERATION counter so watchers can see an operator override that the
coordinator has not blessed yet. Lookups are lock-cheap: the vector
split classifies a whole [N, LANES] key array with one range mask per
peer (the chordax-fastlane rule — zero per-key python), and the
single-key owner is one bisect.

LOCK ORDER: `RouteTable._lock` is a LEAF — held only for table reads/
swaps, never across an RPC, an engine call, or any other lock.
This module never imports jax.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from p2p_dhts_tpu.keyspace import (KEYS_IN_RING, lanes_in_range_mask,
                                   peer_id)

#: An address is ("ip", port); the mesh key form "ip:port" joins them.
Addr = Tuple[str, int]


def addr_str(addr: Addr) -> str:
    return f"{addr[0]}:{addr[1]}"


def member_for(addr: Addr) -> int:
    """The mesh peer id of one gateway process: the reference's
    SHA1("ip:port") identity, so a process's shard is a pure function
    of where it listens."""
    return peer_id(addr[0], int(addr[1]))


class RouteTable:
    """Versioned shard -> address map with successor-rule ownership."""

    def __init__(self, self_addr: Optional[Addr] = None):
        self.self_addr: Optional[Addr] = (
            (str(self_addr[0]), int(self_addr[1]))
            if self_addr is not None else None)
        self._lock = threading.Lock()
        self._epoch = 0
        self._generation = 0
        self._ids: List[int] = []
        self._addrs: Dict[int, Addr] = {}

    # -- versioning ----------------------------------------------------------
    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def bump(self) -> int:
        """Record a LOCAL ownership change (an operator set_key_range
        the coordinator did not drive): the generation counter moves so
        route observers see the table is ahead of its blessed epoch."""
        with self._lock:
            self._generation += 1
            return self._generation

    def apply(self, peers: Dict[int, Addr], epoch: int) -> bool:
        """Install a coordinator-stamped map; returns True when it was
        NEWER (stale gossip is dropped, never applied backwards). An
        equal-epoch map is also dropped — the coordinator bumps the
        epoch on every recompute, so equal means already installed."""
        epoch = int(epoch)
        norm = {int(m) % KEYS_IN_RING: (str(a[0]), int(a[1]))
                for m, a in peers.items()}
        with self._lock:
            if epoch <= self._epoch:
                return False
            self._epoch = epoch
            self._generation = 0
            self._ids = sorted(norm)
            self._addrs = norm
        return True

    # -- snapshots -----------------------------------------------------------
    def peers(self) -> Dict[int, Addr]:
        with self._lock:
            return dict(self._addrs)

    def addresses(self) -> List[Addr]:
        """Every peer address in id order (self included)."""
        with self._lock:
            return [self._addrs[m] for m in self._ids]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ids)

    def shard_of(self, member: int) -> Optional[Tuple[int, int]]:
        """(lo, hi) clockwise-inclusive range the member owns, or None
        for an unknown member. A single-peer table owns everything."""
        member = int(member) % KEYS_IN_RING
        with self._lock:
            if member not in self._addrs:
                return None
            i = bisect.bisect_left(self._ids, member)
            pred = self._ids[(i - 1) % len(self._ids)]
        if pred == member:
            return ((member + 1) % KEYS_IN_RING, member)
        return ((pred + 1) % KEYS_IN_RING, member)

    # -- ownership -----------------------------------------------------------
    def owner(self, key_int: int) -> Optional[Tuple[int, Addr]]:
        """(member_id, addr) of the key's owner — the ring successor of
        the key among the table's ids (the oracle's _ring_successor
        rule) — or None for an empty table."""
        key_int = int(key_int) % KEYS_IN_RING
        with self._lock:
            if not self._ids:
                return None
            i = bisect.bisect_left(self._ids, key_int)
            mid = self._ids[i] if i < len(self._ids) else self._ids[0]
            return mid, self._addrs[mid]

    def is_local(self, key_int: int) -> bool:
        """True when the key's owner is THIS process (or the table is
        empty / self-less — an unrouted mesh serves everything
        locally, the single-process degenerate case)."""
        own = self.owner(key_int)
        if own is None or self.self_addr is None:
            return True
        return own[1] == self.self_addr

    def split_lanes(self, lanes: np.ndarray
                    ) -> Tuple[Optional[np.ndarray],
                               List[Tuple[Addr, np.ndarray]]]:
        """Classify a whole [N, LANES] uint32 key array:
        (local_rows, [(addr, row_indices)...]) where local_rows is
        None when EVERY row is local (the no-copy common case) and an
        index array (possibly empty) otherwise. One range mask per
        peer (peers are few; keys are many) — zero per-key python, the
        fastlane discipline. An empty table (or a table without a self
        address) is all-local."""
        n = lanes.shape[0]
        with self._lock:
            ids = list(self._ids)
            addrs = dict(self._addrs)
        if not ids or self.self_addr is None:
            return None, []
        assigned = np.full(n, -1, np.int32)
        for j, mid in enumerate(ids):
            i = bisect.bisect_left(ids, mid)
            pred = ids[(i - 1) % len(ids)]
            lo = (pred + 1) % KEYS_IN_RING if pred != mid \
                else (mid + 1) % KEYS_IN_RING
            mask = lanes_in_range_mask(lanes, lo, mid) & (assigned < 0)
            if mask.any():
                assigned[mask] = j
        # The shards tile the whole circle, so every row is assigned;
        # a defensive residue (impossible by construction) stays local.
        local_js = [j for j, mid in enumerate(ids)
                    if addrs[mid] == self.self_addr]
        local_mask = np.isin(assigned, local_js) | (assigned < 0)
        if local_mask.all():
            return None, []
        remote: List[Tuple[Addr, np.ndarray]] = []
        for j, mid in enumerate(ids):
            if j in local_js:
                continue
            rows = np.nonzero(assigned == j)[0]
            if rows.size:
                remote.append((addrs[mid], rows))
        return np.nonzero(local_mask)[0], remote

    def split_lanes_all(self, lanes: np.ndarray
                        ) -> List[Tuple[Addr, np.ndarray]]:
        """Classify a whole [N, LANES] uint32 key array for a CLIENT
        that is not itself a mesh peer (the chordax-edge rim): every
        row goes to its owning gateway — there is no local bucket.
        Returns [(addr, row_indices)...] in id order; an empty table
        returns [] (the edge treats that as "no routes yet" and pulls
        MESH_ROUTES before resolving). Same one-range-mask-per-peer
        discipline as split_lanes — zero per-key python."""
        n = lanes.shape[0]
        with self._lock:
            ids = list(self._ids)
            addrs = dict(self._addrs)
        if not ids or n == 0:
            return []
        assigned = np.full(n, -1, np.int32)
        for j, mid in enumerate(ids):
            i = bisect.bisect_left(ids, mid)
            pred = ids[(i - 1) % len(ids)]
            lo = (pred + 1) % KEYS_IN_RING if pred != mid \
                else (mid + 1) % KEYS_IN_RING
            mask = lanes_in_range_mask(lanes, lo, mid) & (assigned < 0)
            if mask.any():
                assigned[mask] = j
        # The shards tile the whole circle, so every row is assigned;
        # a defensive residue (impossible by construction) rides the
        # first peer so no row is ever silently dropped.
        if (assigned < 0).any():
            assigned[assigned < 0] = 0
        out: List[Tuple[Addr, np.ndarray]] = []
        for j, mid in enumerate(ids):
            rows = np.nonzero(assigned == j)[0]
            if rows.size:
                out.append((addrs[mid], rows))
        return out

    # -- wire form -----------------------------------------------------------
    def doc(self) -> dict:
        """The gossip/observability document the MESH_ROUTES verb
        serves: epoch + generation + one row per peer with its id,
        address, and derived shard bounds (hex — the overlay's Key
        serialization)."""
        with self._lock:
            ids = list(self._ids)
            addrs = dict(self._addrs)
            epoch = self._epoch
            gen = self._generation
        rows = []
        for i, mid in enumerate(ids):
            pred = ids[(i - 1) % len(ids)]
            lo = (pred + 1) % KEYS_IN_RING if pred != mid \
                else (mid + 1) % KEYS_IN_RING
            ip, port = addrs[mid]
            rows.append({"MEMBER": format(mid, "x"), "IP": ip,
                         "PORT": int(port), "LO": format(lo, "x"),
                         "HI": format(mid, "x"),
                         "SELF": addrs[mid] == self.self_addr})
        return {"EPOCH": epoch, "GENERATION": gen, "ROUTES": rows}

    def apply_doc(self, doc: dict) -> bool:
        """Install a MESH_ROUTES-shaped document (epoch-guarded)."""
        peers = {int(r["MEMBER"], 16): (str(r["IP"]), int(r["PORT"]))
                 for r in doc.get("ROUTES", ())}
        return self.apply(peers, int(doc.get("EPOCH", 0)))

"""The forward coalescer: per-destination micro-batching of cross-shard
misses (chordax-mesh, ISSUE 15 — the perf half of local-or-forward).

A mesh gateway that merely proxied every cross-shard key as its own RPC
would pay one frame encode/decode + one handler dispatch + one engine
slot PER KEY — the exact per-request overhead chordax-wire/fastlane
spent three PRs amortizing away. This module folds concurrent misses to
the SAME destination into ONE packed-u128 KEYS-vector RPC instead.

Since ISSUE 17 the fold/flush engine itself lives in `mesh/fold.py`
(the chordax-edge client rim shares it verbatim); this module is the
GATEWAY identity of that core — the `gateway.forward.*` metric keys,
the `mesh.forward` span, the `mesh-fwd-*` lane threads, and the plain
`Client.make_request` transport. See fold.py for the shared rules
(lane workers, min-deadline folding, first-entry trace root, the
one-hop ``FWD: 1`` / ``NOT_OWNED`` protocol).

BUSY shed replies and breaker fast-fails surface as the transport
RpcError every entry's waiter receives — the caller's retry policy
(gateway not-owner refresh, bench failover) owns what happens next.
The coalescer reports NOT_OWNED rows per entry; the mesh plane owns
the single refresh-and-retry.

LOCK ORDER: `_Lane._lock` and `ForwardCoalescer._lock` are LEAVES —
held only for queue/table bookkeeping, never across the RPC, an
encode, or a waiter wait. The flush runs entirely lock-free.
This module never imports jax.
"""

from __future__ import annotations

from p2p_dhts_tpu.mesh.fold import (DEFAULT_FOLD_WAIT_S, FOLD_VERBS,
                                    FoldCore, FoldError, FoldResult)

#: Verbs the coalescer knows how to batch (KEYS-vector read forms).
FORWARD_VERBS = FOLD_VERBS

#: Forward wait bound when the caller set no deadline (the gateway's
#: DEFAULT_WAIT_S rule: a forward must never park a worker forever).
DEFAULT_FORWARD_WAIT_S = DEFAULT_FOLD_WAIT_S


class ForwardError(FoldError):
    """The forwarded batch failed at the transport or the owner."""


#: One entry's slice of a flushed batch (fold.py owns the shape).
ForwardResult = FoldResult


class ForwardCoalescer(FoldCore):
    """Per-destination micro-batching front for cross-shard forwards:
    the gateway-side identity of the shared `FoldCore`."""

    error_cls = ForwardError
    closed_msg = "forward coalescer closed"
    span_name = "mesh.forward"
    span_cat = "mesh"
    thread_prefix = "mesh-fwd"
    verbs = FORWARD_VERBS
    default_wait_s = DEFAULT_FORWARD_WAIT_S

    # -- metric identity (LITERAL keys — the doc-drift gate scans these) -----
    def _record_flush(self, n_keys: int, folded: int) -> None:
        self.metrics.inc("gateway.forward.batches")
        self.metrics.inc("gateway.forward.keys", n_keys)
        self.metrics.observe_hist("gateway.forward.batch_size", n_keys)
        if folded > 1:
            self.metrics.inc("gateway.forward.coalesced", folded - 1)

    def _record_error(self) -> None:
        self.metrics.inc("gateway.forward.errors")

    def _record_latency(self, dt: float) -> None:
        self.metrics.observe("gateway.forward.latency", dt)

    def _record_not_owner(self, k: int) -> None:
        self.metrics.inc("gateway.forward.not_owner", k)

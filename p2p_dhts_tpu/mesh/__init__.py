"""chordax-mesh: multi-process sharded serving (ISSUE 15).

The horizontal-scale layer ROADMAP item 2 asked for: N gateway
PROCESSES each own a shard of the 2^128 keyspace (the Chord successor
rule over mesh peer ids — SHA1("ip:port"), the reference's identity),
every gateway answers ANY request via an ownership lookup →
local-or-forward split, and cross-shard forwarding rides the pooled/
pipelined binary wire with a per-destination FORWARD COALESCER that
folds concurrent single-key and vector misses into ONE packed-u128
KEYS-vector RPC (the fastlane zero-copy lane format end-to-end).

Modules:
  routes     RouteTable — versioned shard -> address map (epoch-
             guarded installs, successor-rule ownership, vectorized
             whole-array splits)
  coalescer  ForwardCoalescer — per-(destination, verb) micro-batching
             with deadline/TRACE propagation and BUSY/breaker handling
  plane      MeshPlane — the local-or-forward gateway attachment:
             FWD one-hop rule (the owner answers or errors; no forward
             chains), NOT_OWNED + piggybacked-routes refresh-retry,
             mesh-wide CAPACITY/HEALTH/PULSE merging, departed-peer
             telemetry/connection retirement
  peer       MeshPeer — the real JOIN_RING/HEARTBEAT driver (closes
             the PR-7 "no peer drives them" thread) with the
             KNOWN:false rejoin path; MeshCoordinator — the seed-side
             shard split over the control ring's MembershipManager
  serve      ``python -m p2p_dhts_tpu.mesh.serve`` — one mesh gateway
             process (the bench's 4-process localhost ring is four of
             these)

Importing this package never initializes a jax backend (the overlay
etiquette); device work happens only once requests flow.
"""

from p2p_dhts_tpu.mesh.coalescer import (  # noqa: F401
    ForwardCoalescer,
    ForwardError,
)
from p2p_dhts_tpu.mesh.peer import MeshCoordinator, MeshPeer  # noqa: F401
from p2p_dhts_tpu.mesh.plane import MeshPlane  # noqa: F401
from p2p_dhts_tpu.mesh.routes import (  # noqa: F401
    RouteTable,
    addr_str,
    member_for,
)

"""MeshPeer + MeshCoordinator: the mesh's membership plane (ISSUE 15).

The JOIN_RING / HEARTBEAT wire verbs have been wire-complete since
PR 7 — and until now NO peer drove them (the standing PR-7 open item).
`MeshPeer` is that peer: a health.PacedLoop that

  * bootstraps by JOIN_RING-ing a SEED gateway (IP+PORT form, so its
    mesh id is the reference's SHA1("ip:port") — the same id the
    RouteTable shards by),
  * HEARTBEATs every interval; the seed's reply piggybacks the
    coordinator's current ROUTES_EPOCH, and a peer whose table is
    older fetches MESH_ROUTES and installs it (gossip by pull — one
    tiny RPC only when the epoch actually moved),
  * rejoins when HEARTBEAT answers ``KNOWN: false`` (the failure
    detector applied our OP_FAIL while we were partitioned; the row
    must be re-joined, which resurrects it device-side — the PR-10
    post-heal rejoin path, now driven end-to-end over the wire),
  * backs off on RPC failure exactly like every other PacedLoop (a
    partitioned peer probes gently, never storms the seed).

`MeshCoordinator` is the seed-side half: it keeps the member -> address
book that JOIN_RING feeds (`Gateway.handle_join_ring` ->
`MeshPlane.note_peer`), subscribes to the control ring's
MembershipManager for APPLIED churn batches, and on any change to the
live membership recomputes the shard split (the Chord successor rule —
each peer owns (pred+1 .. id]), stamps it with the next epoch, and
installs it locally; peers pull it on their next heartbeat. Failure
detection is the REAL phi-accrual machinery from PR 7 — the
coordinator adds no second detector, it just reacts to the one the
membership plane already runs.

LOCK ORDER: `MeshCoordinator._lock` is a LEAF (address-book reads/
writes only; recompute reads the manager and calls apply_routes
outside it). MeshPeer holds no locks of its own.
This module never imports jax.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from p2p_dhts_tpu.health import PacedLoop
from p2p_dhts_tpu.mesh.routes import Addr, member_for
from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.net.rpc import Client, RpcError


class MeshPeer(PacedLoop):
    """One gateway process's membership driver: join, heartbeat,
    route-gossip, rejoin."""

    def __init__(self, plane, seed_addr: Addr, *,
                 heartbeat_s: float = 0.5,
                 ring_id: Optional[str] = None,
                 rpc_timeout_s: Optional[float] = None,
                 metrics: Optional[Metrics] = None):
        self.plane = plane
        self.seed_addr = (str(seed_addr[0]), int(seed_addr[1]))
        #: The seed's CONTROL ring (None = the seed's only attached
        #: membership manager, the single-manager wire convention).
        self.ring_id = ring_id
        self.member_id = plane.member_id
        self.joined = False
        self._was_live = False
        self.rpc_timeout_s = (float(rpc_timeout_s)
                              if rpc_timeout_s is not None
                              else max(2.0, heartbeat_s * 4))
        PacedLoop.__init__(
            self, name=f"mesh-peer:{plane.routes.self_addr[1]}",
            kind="mesh", interval_s=float(heartbeat_s),
            interval_idle_s=float(heartbeat_s),
            backoff_base_s=max(float(heartbeat_s) / 2, 0.05),
            backoff_cap_s=max(float(heartbeat_s) * 8, 2.0),
            metrics=metrics if metrics is not None else METRICS,
            failure_metric="mesh.peer_round_failures",
            thread_name=f"mesh-peer-{plane.routes.self_addr[1]}")

    # -- one membership round -------------------------------------------------
    def step(self) -> dict:
        """Join (or re-join) if needed, heartbeat, pull routes when
        the seed's epoch moved — the deterministic foreground form
        (the background loop runs exactly this)."""
        ip, port = self.plane.routes.self_addr
        if not self.joined:
            req = {"COMMAND": "JOIN_RING", "IP": ip, "PORT": port}
            if self.ring_id is not None:
                req["RING"] = self.ring_id
            resp = self._rpc(req)
            if resp.get("ACCEPTED"):
                self.joined = True
                if self._was_live:
                    self.metrics.inc("mesh.rejoins")
                else:
                    self.metrics.inc("mesh.peer_joins")
            return {"joined": self.joined, "epoch":
                    self.plane.routes.epoch}
        req = {"COMMAND": "HEARTBEAT",
               "MEMBER": format(self.member_id, "x")}
        if self.ring_id is not None:
            req["RING"] = self.ring_id
        resp = self._rpc(req)
        self.metrics.inc("mesh.heartbeats")
        if not resp.get("KNOWN"):
            # The detector failed us while we were unreachable and the
            # row was applied: JOIN again (resurrects the device row).
            self.joined = False
            self._was_live = True
            self.metrics.inc("mesh.rejoin_required")
            return self.step()
        self._was_live = True
        epoch = resp.get("ROUTES_EPOCH")
        if epoch is not None and int(epoch) > self.plane.routes.epoch:
            self.fetch_routes()
        return {"joined": True, "epoch": self.plane.routes.epoch}

    def fetch_routes(self) -> bool:
        """Pull MESH_ROUTES from the seed and install it (epoch-
        guarded — stale gossip drops on the floor)."""
        resp = self._rpc({"COMMAND": "MESH_ROUTES"})
        self.metrics.inc("mesh.routes_fetched")
        if not resp.get("ATTACHED"):
            raise RpcError("seed gateway has no mesh plane attached")
        return self.plane.apply_routes_doc(resp)

    def _rpc(self, req: dict) -> dict:
        resp = Client.make_request(self.seed_addr[0], self.seed_addr[1],
                                   req, timeout=self.rpc_timeout_s)
        if not resp.get("SUCCESS"):
            raise RpcError(f"seed {self.seed_addr[0]}:"
                           f"{self.seed_addr[1]} errored on "
                           f"{req['COMMAND']}: {resp.get('ERRORS')}")
        return resp

    def _round(self) -> None:
        self.step()
        self.mark_round()

    def _busy(self) -> bool:
        return True  # heartbeats never idle down


class MeshCoordinator:
    """Seed-side shard coordinator over the control ring's
    MembershipManager."""

    def __init__(self, plane, manager, *,
                 metrics: Optional[Metrics] = None):
        self.plane = plane
        self.manager = manager
        self.metrics = metrics if metrics is not None \
            else plane.metrics
        self._lock = threading.Lock()
        # Serializes epoch-read + apply: two concurrent recomputes
        # (the membership loop's applied listener racing a JOIN_RING
        # worker's note_peer) must not both stamp epoch N+1 — the
        # loser's map would be silently dropped by the route table's
        # monotonic-epoch guard even when it was computed from the
        # NEWER membership state.
        self._recompute_lock = threading.Lock()
        self._addrs: Dict[int, Addr] = {}
        with plane._lock:
            plane.coordinator = self
        manager.add_applied_listener(self._on_applied)

    # -- bootstrap ------------------------------------------------------------
    def register_self(self) -> None:
        """Enter the seed's own address + membership: the seed is a
        serving shard like any other, just one whose control plane is
        local."""
        ip, port = self.plane.routes.self_addr
        member = member_for((ip, port))
        self.note_peer(member, ip, port)
        self.manager.request_join(member)
        self.recompute()

    # -- address book ---------------------------------------------------------
    def note_peer(self, member: int, ip: str, port: int) -> None:
        member = int(member)
        with self._lock:
            changed = self._addrs.get(member) != (str(ip), int(port))
            self._addrs[member] = (str(ip), int(port))
        if changed:
            # A re-addressed (or first-seen) peer may already be alive
            # in the membership plane — recompute picks it up.
            self.recompute()

    def addresses(self) -> Dict[int, Addr]:
        with self._lock:
            return dict(self._addrs)

    # -- the shard split ------------------------------------------------------
    def _on_applied(self, rows) -> None:
        """The control ring applied a churn batch (join/fail/leave):
        the live membership moved, so the split recomputes and the
        epoch bumps — peers pull it on their next heartbeat."""
        self.recompute()

    def recompute(self, force: bool = False) -> bool:
        """Rebuild the shard map from (alive control-ring members ∩
        known addresses); install it with the NEXT epoch when it
        changed. Returns whether a new epoch was installed. The whole
        read-compute-install runs under _recompute_lock (membership
        state is re-read INSIDE it), so concurrent triggers serialize
        and the last installed map always reflects the newest
        membership the coordinator has seen. `force=True` installs a
        fresh epoch even when the peer SET is unchanged — the elastic
        mesh tier's lever after a spawn it must propagate immediately
        (every peer re-pulls routes on the epoch move) rather than
        waiting for a membership delta to coincide."""
        with self._recompute_lock:
            alive = set(self.manager.alive_ids())
            with self._lock:
                peers = {m: a for m, a in self._addrs.items()
                         if m in alive}
            if not peers:
                return False
            current = self.plane.routes.peers()
            if peers == current and not force:
                return False
            installed = self.plane.apply_routes(
                peers, self.plane.routes.epoch + 1)
        if installed:
            self.metrics.inc("mesh.resplits")
        return installed

"""One mesh gateway process: ``python -m p2p_dhts_tpu.mesh.serve``.

The unit the chordax-mesh bench composes four of: build a device ring
(one shard's serving backend), front it with a Gateway + RPC server
on one port, attach a MeshPlane, and drive membership — as the SEED
(control ring + MembershipManager + MeshCoordinator: the process
every peer joins and heartbeats) or as a PEER (a MeshPeer loop
JOIN_RING-ing the seed, heartbeating, and pulling routes when the
epoch moves).

Protocol with the parent (the bench / an operator script):

  * stdout line 1: ``MESH_READY {"port": ..., "member": "<hex>"}`` —
    emitted once the server answers and (seed) the initial routes are
    installed. Everything else logs to stderr.
  * stdin line ``RETIRE`` (chordax-elastic): stop heartbeating FIRST
    (so the leave cannot auto-rejoin), answer ``MESH_RETIRING``, wait
    to be excluded from the routes, drain every stored key to its new
    owner through the forwarding path, answer ``MESH_DRAINED <n>``,
    then await the EOF below.
  * stdin EOF = graceful shutdown (peer loop, plane, server, gateway,
    in that order), exit 0. SIGTERM stays the hard kill.

chordax-elastic flags: ``--lens`` attaches + starts a LensLoop (the
CAPACITY rows the mesh tier reads); ``--rebalance`` starts the
ShardRebalancer (post-re-split data motion — every elastic child runs
it); ``--elastic`` (seed only, implies both) starts the MeshPolicy
loop that spawns/retires children from live capacity,
``--elastic-ledger PATH`` archiving its decision ledger at shutdown.

Every process builds the SAME device-ring member set (--members-seed):
the mesh shards by ROUTE ownership, not ring content, so identical
rings make forwarded-vs-direct answers byte-comparable — exactly the
parity the bench gates on.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--seed", default=None, metavar="IP:PORT",
                    help="seed gateway to join; absent = BE the seed")
    ap.add_argument("--ring-peers", type=int, default=256)
    ap.add_argument("--members-seed", type=int, default=0x5EED)
    ap.add_argument("--store-capacity", type=int, default=4096)
    ap.add_argument("--smax", type=int, default=4)
    ap.add_argument("--bucket-min", type=int, default=8)
    ap.add_argument("--bucket-max", type=int, default=256)
    ap.add_argument("--heartbeat-s", type=float, default=0.25)
    ap.add_argument("--phi", type=float, default=3.0)
    ap.add_argument("--ctl-capacity", type=int, default=16,
                    help="seed only: control-ring capacity (max peers)")
    ap.add_argument("--lens", type=int, default=0,
                    help="attach + start a LensLoop (0/1)")
    ap.add_argument("--lens-interval-s", type=float, default=0.25)
    ap.add_argument("--rebalance", type=int, default=0,
                    help="start the elastic ShardRebalancer (0/1)")
    ap.add_argument("--elastic", type=int, default=0,
                    help="seed only: start the elastic MeshPolicy "
                         "(implies --lens --rebalance) (0/1)")
    ap.add_argument("--elastic-min-procs", type=int, default=1)
    ap.add_argument("--elastic-max-procs", type=int, default=4)
    ap.add_argument("--elastic-interval-s", type=float, default=1.0)
    ap.add_argument("--elastic-saturate-ticks", type=int, default=3)
    ap.add_argument("--elastic-idle-ticks", type=int, default=6)
    ap.add_argument("--elastic-cooldown-ticks", type=int, default=5)
    ap.add_argument("--elastic-seed", type=int, default=0x0E1A571C)
    ap.add_argument("--elastic-ledger", default="",
                    help="archive the decision ledger here at shutdown")
    ap.add_argument("--trace", type=int, default=0,
                    help="chordax-tower: enable trace recording so "
                         "TRACE_PULL has spans to serve (0/1)")
    ap.add_argument("--trace-sample", type=float, default=1.0,
                    help="root-span sample rate under --trace")
    ap.add_argument("--exemplars", type=int, default=0,
                    help="chordax-tower: capture (value, trace_id) "
                         "exemplars on latency hists (0/1)")
    args = ap.parse_args(argv)
    if args.elastic:
        args.lens = 1
        args.rebalance = 1

    import numpy as np

    from p2p_dhts_tpu.config import RingConfig
    from p2p_dhts_tpu.core.ring import build_ring
    from p2p_dhts_tpu.dhash.store import empty_store
    from p2p_dhts_tpu.gateway import Gateway, install_gateway_handlers
    from p2p_dhts_tpu.membership.kernels import padded_capacity
    from p2p_dhts_tpu.mesh.plane import MeshPlane
    from p2p_dhts_tpu.net import wire
    from p2p_dhts_tpu.net.rpc import Server

    # chordax-tower (ISSUE 20): the observed-fleet switches — tracing
    # feeds the TRACE_PULL collection verb, exemplars bridge latency
    # hists to trace ids. Both default OFF (the PR-14 discipline).
    if args.trace:
        from p2p_dhts_tpu import trace
        trace.enable(True, sample_rate=args.trace_sample)
    if args.exemplars:
        from p2p_dhts_tpu.metrics import METRICS
        METRICS.set_exemplars(True)

    rng = np.random.RandomState(args.members_seed)
    member_rows = [int.from_bytes(rng.bytes(16), "little")
                   for _ in range(args.ring_peers)]

    srv = Server(args.port, {}, host=args.host)
    self_addr = (args.host, srv.port)
    gw = Gateway(name=f"mesh-{srv.port}")
    gw.add_ring("shard",
                build_ring(member_rows,
                           RingConfig(finger_mode="materialized")),
                empty_store(args.store_capacity, args.smax),
                default=True, bucket_min=args.bucket_min,
                bucket_max=args.bucket_max, max_queue=65536,
                warmup=["find_successor", "dhash_get", "dhash_put"])
    plane = MeshPlane(gw, self_addr, ring_id="shard")
    install_gateway_handlers(srv, gw)
    srv.run_in_background()

    lens = None
    if args.lens:
        from p2p_dhts_tpu.lens import LensLoop
        lens = LensLoop(gw, interval_s=args.lens_interval_s)
        gw.attach_lens(lens)
        lens.start()
    rebalancer = None
    if args.rebalance:
        from p2p_dhts_tpu.elastic import ShardRebalancer
        rebalancer = ShardRebalancer(gw, plane, ring_id="shard")
        rebalancer.start()

    mgr = None
    coord = None
    peer = None
    policy = None
    if args.seed is None:
        # THE SEED: a tiny control ring whose members are the mesh
        # peers themselves (SHA1("ip:port") ids), driven by the REAL
        # PR-7 membership machinery — joins/heartbeats/phi detection —
        # with the coordinator recomputing the shard split on every
        # applied batch.
        from p2p_dhts_tpu.membership import MembershipManager
        from p2p_dhts_tpu.mesh.peer import MeshCoordinator
        from p2p_dhts_tpu.mesh.routes import member_for
        ctl_cap = padded_capacity(args.ctl_capacity)
        gw.add_ring("mesh-ctl",
                    build_ring([member_for(self_addr)],
                               RingConfig(finger_mode="materialized"),
                               capacity=ctl_cap),
                    bucket_min=4, bucket_max=16,
                    warmup=["churn_apply", "stabilize_sweep"])
        mgr = MembershipManager(
            gw, "mesh-ctl",
            heartbeat_interval_s=args.heartbeat_s,
            phi_threshold=args.phi, min_heartbeats=3,
            confirm_rounds=2, interval_s=args.heartbeat_s / 4,
            interval_idle_s=args.heartbeat_s,
            round_timeout_s=600.0)
        coord = MeshCoordinator(plane, mgr)
        coord.register_self()
        mgr.quiesce(max_rounds=8)
        mgr.start()
        if args.elastic:
            from p2p_dhts_tpu.elastic import MeshPolicy, PolicyConfig
            child_args = [
                "--ring-peers", str(args.ring_peers),
                "--members-seed", str(args.members_seed),
                "--store-capacity", str(args.store_capacity),
                "--smax", str(args.smax),
                "--bucket-min", str(args.bucket_min),
                "--bucket-max", str(args.bucket_max),
                "--heartbeat-s", str(args.heartbeat_s),
                "--lens", "1", "--rebalance", "1",
                "--lens-interval-s", str(args.lens_interval_s),
            ]
            policy = MeshPolicy(
                plane, coord, mgr, lens,
                child_args=child_args,
                config=PolicyConfig(
                    saturate_ticks=args.elastic_saturate_ticks,
                    idle_ticks=args.elastic_idle_ticks,
                    cooldown_ticks=args.elastic_cooldown_ticks,
                    min_rings=args.elastic_min_procs,
                    max_rings=args.elastic_max_procs),
                seed=args.elastic_seed,
                interval_s=args.elastic_interval_s)
            policy.start()
    else:
        from p2p_dhts_tpu.mesh.peer import MeshPeer
        ip, _, port = args.seed.rpartition(":")
        peer = MeshPeer(plane, (ip, int(port)),
                        heartbeat_s=args.heartbeat_s)
        peer.step()           # join NOW so READY means "in the mesh"
        peer.fetch_routes()
        peer.start()

    sys.stdout.write("MESH_READY " + json.dumps(
        {"port": srv.port, "member": format(plane.member_id, "x")})
        + "\n")
    sys.stdout.flush()

    try:
        while True:
            line = sys.stdin.readline()
            if not line:
                break  # parent closed the pipe: graceful shutdown
            if line.strip() == "RETIRE":
                # chordax-elastic retire: heartbeats STOP before the
                # ack so the seed's leave cannot observe a late
                # heartbeat and auto-rejoin us (the KNOWN:false rule).
                if peer is not None:
                    peer.stop()
                sys.stdout.write("MESH_RETIRING\n")
                sys.stdout.flush()
                from p2p_dhts_tpu.elastic import serve_retire
                drained = serve_retire(plane, peer, rebalancer)
                sys.stdout.write(f"MESH_DRAINED {drained}\n")
                sys.stdout.flush()
    except KeyboardInterrupt:
        pass
    finally:
        if policy is not None:
            if args.elastic_ledger:
                try:
                    policy.ledger.dump(args.elastic_ledger)
                # chordax-lint: disable=bare-except -- the archive is best-effort; shutdown must proceed
                except Exception:
                    pass
            policy.close()
        if rebalancer is not None:
            rebalancer.close()
        if lens is not None:
            lens.close()
        if peer is not None:
            peer.close()
        if mgr is not None:
            mgr.close()
        plane.close()
        srv.kill()
        gw.close()
        wire.reset_pool()
    return 0


if __name__ == "__main__":
    sys.exit(main())

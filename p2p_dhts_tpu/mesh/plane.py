"""MeshPlane: the local-or-forward split behind a mesh gateway's front
door (chordax-mesh, ISSUE 15 — the refactor ROADMAP item 2 named as the
horizontal-scale unlock).

One of these attaches to a Gateway (`gateway.attach_mesh`) and turns
the process-global front door into ONE SHARD of a multi-process
serving topology:

  request -> ownership lookup (RouteTable, the Chord successor rule
  over mesh peer ids) -> LOCAL  : the existing router/engine path,
                                  untouched — zero new cost when the
                                  key is ours;
                         REMOTE : the ForwardCoalescer folds it into a
                                  packed-u128 KEYS-vector RPC to the
                                  owner gateway over the pooled/
                                  pipelined binary wire.

ONE-HOP RULE: a forwarded request (``FWD: 1``) is answered by the owner
from LOCAL ownership only — keys the owner no longer owns come back as
``NOT_OWNED`` rows with the owner's fresher route table piggybacked,
never forwarded onward (no forward chains; tail latency stays one
extra hop, bounded). The ORIGIN applies the piggybacked routes and
re-resolves the bounced rows ONCE (a re-resolution is a fresh first
hop, not a chain); rows that still miss fail visibly.

Forwarded READ answers are NEVER memoized in the PR-12 hot-key cache:
the owner's writes invalidate the owner's epoch, not ours, so a cached
forwarded answer could serve stale bytes forever. Local answers keep
the cache exactly as before.

MESH-WIDE VERBS: CAPACITY / HEALTH / PULSE requests carrying
``MESH: true`` additionally collect every live route peer's own row
(bounded per-peer timeout; an unreachable peer reads as a TYPED
stale marker — ``{"STALE": true, "ERROR": ..., "AGE_S": ...,
"LAST_GOOD": <its previous answer>}`` — never a bare error string a
policy tick would have to parse), so the elastic loop's decision
input spans processes from any one gateway. A briefly-partitioned
peer therefore reads as "stale, last seen N seconds ago with THIS
capacity", not as zero capacity. Per-peer `mesh.*` telemetry retires with the peer when a
re-split drops it (the PR-8 stale-telemetry rule), and the departed
peer's pooled wire connections close with it.

LOCK ORDER: the plane itself holds only `_lock` (a LEAF guarding the
coordinator/stats references); routing reads go through RouteTable's
leaf lock and every forward runs lock-free. This module never imports
jax.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from p2p_dhts_tpu import trace as trace_mod
from p2p_dhts_tpu.keyspace import ints_to_lanes
from p2p_dhts_tpu.mesh.coalescer import ForwardCoalescer, ForwardError
from p2p_dhts_tpu.mesh.routes import Addr, RouteTable, addr_str, \
    member_for
from p2p_dhts_tpu.metrics import Metrics
from p2p_dhts_tpu.net import wire
from p2p_dhts_tpu.net.rpc import Client

#: Bounded per-peer wait when merging a mesh-wide verb: one dead peer
#: costs its row an error string, never the whole verb.
PEER_VERB_TIMEOUT_S = 3.0


class MeshPlane:
    """Local-or-forward ownership routing for one gateway process."""

    def __init__(self, gateway, self_addr: Addr,
                 ring_id: Optional[str] = None, *,
                 coalesce: bool = True,
                 forward_max_batch: int = 4096,
                 forward_retries: int = 1,
                 peer_verb_timeout_s: float = PEER_VERB_TIMEOUT_S,
                 metrics: Optional[Metrics] = None):
        self.gateway = gateway
        #: The local shard ring whose key_range tracks this process's
        #: shard (None = don't manage any ring's range).
        self.ring_id = ring_id
        self.routes = RouteTable(self_addr)
        self.member_id = member_for(self.routes.self_addr)
        self.metrics = metrics if metrics is not None \
            else gateway.metrics.base
        self.coalescer = ForwardCoalescer(
            metrics=self.metrics,
            max_batch=forward_max_batch if coalesce else 1,
            retries=forward_retries)
        self.peer_verb_timeout_s = float(peer_verb_timeout_s)
        self._lock = threading.Lock()
        # Last successful mesh-wide-verb answer per peer addr string
        # (monotonic timestamp, response), so an unreachable peer's
        # row can carry an age-stamped LAST_GOOD instead of nothing.
        # Guarded by _lock (a leaf — recorded AFTER the RPC returns,
        # never around it); evicted with the peer's other state when a
        # re-split drops it.
        self._last_good: Dict[str, Tuple[float, dict]] = {}
        self.coordinator = None   # set by MeshCoordinator
        self._applying = False    # reentrancy guard for our own
        #                         # set_key_range during apply_routes
        self._topo_cb = self._on_topology
        gateway.router.add_topology_listener(self._topo_cb)
        gateway.attach_mesh(self)

    # -- topology reactions ---------------------------------------------------
    def _on_topology(self, change: str) -> None:
        # An OPERATOR set_key_range (not one we applied ourselves) is
        # a local re-split the blessed route table has not seen yet:
        # bump the generation so MESH_ROUTES shows the divergence. The
        # PR-12 hot-key cache is already epoch-bumped by the router's
        # own listener, independent of this one.
        if change == "set_key_range" and not self._applying:
            self.routes.bump()
            self.metrics.inc("mesh.local_resplits")

    # -- route installation ---------------------------------------------------
    def apply_routes(self, peers: Dict[int, Addr], epoch: int) -> bool:
        old = {addr_str(a) for a in self.routes.addresses()}
        if not self.routes.apply(peers, epoch):
            return False
        self._after_routes_change(old)
        return True

    def apply_routes_doc(self, doc: dict) -> bool:
        old = {addr_str(a) for a in self.routes.addresses()}
        if not self.routes.apply_doc(doc):
            return False
        self._after_routes_change(old)
        return True

    def _after_routes_change(self, old_addrs: set) -> None:
        shard = self.routes.shard_of(self.member_id)
        if self.ring_id is not None:
            # Our own shard lands as the local ring's key_range — ONE
            # atomic swap (PR-7's set_key_range), which also fires the
            # router topology listeners and so epoch-bumps the PR-12
            # hot-key cache: no cached read survives a re-split.
            self._applying = True
            try:
                self.gateway.router.set_key_range(self.ring_id, shard)
            finally:
                self._applying = False
        new_addrs = {addr_str(a) for a in self.routes.addresses()}
        for a in sorted(old_addrs - new_addrs):
            # Departed-peer hygiene (the PR-8 retire rule, applied
            # mesh-wide): its telemetry keys leave the registry and
            # its pooled wire connections close.
            self.metrics.remove_prefix(f"mesh.peer_alive.{a}")
            ip, _, port = a.rpartition(":")
            wire.pool().close_dest((ip, int(port)))
            with self._lock:
                self._last_good.pop(a, None)
            self.metrics.inc("mesh.peers_retired")
        for a in sorted(new_addrs):
            self.metrics.gauge(f"mesh.peer_alive.{a}", 1.0)
        self.metrics.gauge("mesh.peers", len(new_addrs))
        self.metrics.gauge("mesh.route_epoch", self.routes.epoch)
        # chordax-tower (ISSUE 20): membership transitions are
        # incident-timeline events — each applied table lands in the
        # flight recorder with the epoch and the peer delta, so the
        # collector's merged timeline shows drops/rejoins in causal
        # order next to HAVOC installs and SLO crossings.
        from p2p_dhts_tpu.health import FLIGHT
        FLIGHT.record("mesh", "routes_applied",
                      epoch=self.routes.epoch, peers=len(new_addrs),
                      joined=sorted(new_addrs - old_addrs),
                      departed=sorted(old_addrs - new_addrs))

    def note_peer(self, member: int, ip: str, port: int) -> None:
        """JOIN_RING address capture: the frontend hands every joiner's
        (id, ip, port) here; the coordinator (when this process is the
        seed) folds it into the address book."""
        with self._lock:
            coord = self.coordinator
        if coord is not None:
            coord.note_peer(member, ip, port)

    # -- wire docs ------------------------------------------------------------
    def routes_doc(self) -> dict:
        return self.routes.doc()

    def mesh_status(self) -> dict:
        return {
            "self": addr_str(self.routes.self_addr),
            "member": format(self.member_id, "x"),
            "epoch": self.routes.epoch,
            "generation": self.routes.generation,
            "peers": len(self.routes),
        }

    # -- ownership ------------------------------------------------------------
    def owns_local(self, key_int: int) -> bool:
        return self.routes.is_local(key_int)

    def not_owner_error(self, key_int: int):
        """THE one-hop-rule error, single home (the frontend's
        FIND_SUCCESSOR/GET handlers and the PUT split all raise
        exactly this, so a bounce classifies identically on the wire
        whatever the verb)."""
        from p2p_dhts_tpu.gateway.router import RingUnavailableError
        return RingUnavailableError(
            f"mesh: not the owner of key {int(key_int):#x} (route "
            f"epoch {self.routes.epoch}); forwarded requests are "
            f"answered or errored, never re-forwarded")

    # -- FIND_SUCCESSOR -------------------------------------------------------
    def find_successor_vector(self, req: dict, lanes: np.ndarray,
                              dl, fwd: bool) -> dict:
        """The mesh body of the vector FIND_SUCCESSOR handler: local
        rows ride the gateway's zero-copy fast lane unchanged; remote
        rows coalesce per owner. Per-destination failure semantics
        mirror the per-ring rule: a dead owner fails only ITS rows,
        reported under RING_ERRORS as ``mesh:<addr>``."""
        n = lanes.shape[0]
        starts = req.get("STARTS")
        starts_arr = None
        if starts is not None and len(starts) > 0:
            starts_arr = np.asarray(starts, dtype=np.int32)
            if starts_arr.shape != (n,):
                raise ValueError("STARTS length must match KEYS")
        if fwd:
            return self._serve_forwarded(
                "FIND_SUCCESSOR", lanes, starts_arr, dl)
        local_rows, remote = self.routes.split_lanes(lanes)
        if local_rows is None:
            return self.gateway._handle_find_successor_fast(
                {"STARTS": starts_arr}, lanes, None, dl)
        owners = np.full(n, -1, np.int64)
        hops = np.full(n, -1, np.int32)
        rings = np.empty(n, dtype=object)
        rings[:] = ""
        ring_errors: Dict[str, str] = {}
        if local_rows.size:
            sub_starts = (starts_arr[local_rows]
                          if starts_arr is not None else None)
            out = self.gateway._handle_find_successor_fast(
                {"STARTS": sub_starts}, lanes[local_rows], None, dl)
            owners[local_rows] = np.asarray(out["OWNERS"], np.int64)
            hops[local_rows] = np.asarray(out["HOPS"], np.int32)
            for j, r in zip(local_rows, out["RINGS"]):
                rings[j] = r
        for addr, rows in remote:
            sub_starts = (starts_arr[rows]
                          if starts_arr is not None else None)
            o, h, _, _, failed, err = self._forward_read(
                "FIND_SUCCESSOR", addr, lanes[rows], sub_starts, dl)
            rings[rows] = f"mesh:{addr_str(addr)}"
            if err is not None:
                ring_errors[f"mesh:{addr_str(addr)}"] = err
            if o is not None:
                live = ~failed
                owners[rows[live]] = o[live]
                hops[rows[live]] = h[live]
        out = {"OWNERS": owners, "HOPS": hops, "RINGS": rings.tolist()}
        if ring_errors:
            out["RING_ERRORS"] = ring_errors
        return out

    def find_successor_one(self, k: int, start: int, dl
                           ) -> Tuple[int, int, str]:
        """(owner_row, hops, 'mesh:<addr>') for one REMOTE key — the
        single-key miss that rides the coalescer (folding with every
        concurrent miss to the same owner)."""
        own = self.routes.owner(k)
        assert own is not None  # caller checked owns_local first
        addr = own[1]
        lanes = ints_to_lanes([int(k)])
        starts = np.asarray([int(start)], np.int32)
        o, h, _, _, failed, err = self._forward_read(
            "FIND_SUCCESSOR", addr, lanes, starts, dl)
        if err is not None or o is None or bool(failed[0]):
            from p2p_dhts_tpu.gateway.router import RingUnavailableError
            raise RingUnavailableError(
                f"mesh forward to {addr_str(addr)} failed: "
                f"{err or 'owner bounced the key'}")
        return int(o[0]), int(h[0]), f"mesh:{addr_str(addr)}"

    # -- GET ------------------------------------------------------------------
    def get_vector(self, lanes: np.ndarray, dl, fwd: bool) -> dict:
        """The mesh body of the vector GET handler. The stacked
        SEGMENTS hot path survives when every row answered with one
        geometry and nothing failed (byte parity with the owner's own
        stacked reply — the bench gate); otherwise the legacy per-key
        list shape carries partial failure exactly as PR-12 defined
        it."""
        n = lanes.shape[0]
        if fwd:
            return self._serve_forwarded("GET", lanes, None, dl)
        local_rows, remote = self.routes.split_lanes(lanes)
        if local_rows is None:
            return self.gateway._handle_get_fast(lanes, None, dl)
        rows_out: List[Any] = [None] * n
        ok_out = np.zeros(n, dtype=bool)
        rings = np.empty(n, dtype=object)
        rings[:] = ""
        ring_errors: Dict[str, str] = {}
        if local_rows.size:
            out = self.gateway._handle_get_fast(lanes[local_rows],
                                                None, dl)
            lsegs, lok = out["SEGMENTS"], np.asarray(out["OK"], bool)
            for i, j in enumerate(local_rows):
                rows_out[int(j)] = lsegs[i]
                ok_out[int(j)] = bool(lok[i])
                rings[int(j)] = out["RINGS"][i]
            for rid, msg in (out.get("RING_ERRORS") or {}).items():
                ring_errors[rid] = msg
        for addr, rrows in remote:
            _, _, segs, ok, failed, err = self._forward_read(
                "GET", addr, lanes[rrows], None, dl)
            rings[rrows] = f"mesh:{addr_str(addr)}"
            if err is not None:
                ring_errors[f"mesh:{addr_str(addr)}"] = err
            for i, j in enumerate(rrows):
                if ok is not None and not failed[i]:
                    rows_out[int(j)] = segs[i]
                    ok_out[int(j)] = bool(ok[i])
        return self._assemble_get(rows_out, ok_out, rings, ring_errors)

    def get_one(self, k: int, dl) -> Tuple[Any, bool]:
        """One REMOTE key's (segments, ok) through the coalescer.
        NEVER cached locally: only the owner's epoch sees the owner's
        writes."""
        own = self.routes.owner(k)
        assert own is not None
        addr = own[1]
        _, _, segs, ok, failed, err = self._forward_read(
            "GET", addr, ints_to_lanes([int(k)]), None, dl)
        if err is not None or ok is None or bool(failed[0]):
            from p2p_dhts_tpu.gateway.router import RingUnavailableError
            raise RingUnavailableError(
                f"mesh forward to {addr_str(addr)} failed: "
                f"{err or 'owner bounced the key'}")
        return segs[0], bool(ok[0])

    @staticmethod
    def _assemble_get(rows_out: List[Any], ok_out: np.ndarray,
                      rings: np.ndarray,
                      ring_errors: Dict[str, str]) -> dict:
        filled = [r for r in rows_out if isinstance(r, np.ndarray)]
        shapes = {r.shape for r in filled}
        if (not ring_errors and len(filled) == len(rows_out)
                and len(shapes) == 1):
            out: dict = {"SEGMENTS": np.stack(filled).astype(np.int32),
                         "OK": ok_out, "RINGS": rings.tolist()}
        else:
            out = {"SEGMENTS": [r if r is not None else []
                                for r in rows_out],
                   "OK": ok_out, "RINGS": rings.tolist()}
        if ring_errors:
            out["RING_ERRORS"] = ring_errors
        return out

    # -- PUT ------------------------------------------------------------------
    def put_is_remote(self, k: int, fwd: bool) -> Optional[Addr]:
        """The single-key PUT split: the owner's addr for a remote key
        on a non-forwarded request, else None (serve locally). A
        forwarded PUT for a key we don't own errors — the one-hop
        rule; writes get no silent re-resolution."""
        if self.owns_local(k):
            return None
        if fwd:
            raise self.not_owner_error(k)
        own = self.routes.owner(k)
        return own[1] if own is not None else None

    def forward_put_one(self, addr: Addr, key_int: int, segments,
                        length: int, start: int, dl) -> bool:
        """Direct (uncoalesced) single-key PUT forward: writes are
        rarer and order-sensitive, so they ride their own RPC. The
        caller's deadline rides the frame (and clamps the transport
        wait) — the gateway deadline-propagation chain crosses the
        process boundary intact."""
        req = {"COMMAND": "PUT", "KEY": format(int(key_int), "x"),
               "SEGMENTS": np.asarray(segments), "LENGTH": int(length),
               "START": int(start), "FWD": 1}
        rem = dl.remaining()
        if rem is not None:
            req["DEADLINE_MS"] = max(rem * 1e3, 1.0)
        return bool(self._forward_direct(addr, req,
                                         deadline=dl).get("OK"))

    def put_entries(self, entries: Sequence[dict], dl, fwd: bool,
                    key_of) -> Optional[dict]:
        """The ENTRIES vector-PUT split. Returns None when every entry
        is local (the caller keeps its existing path); otherwise the
        merged response. Forwarded requests answer local entries and
        bounce the rest as NOT_OWNED + OK:false."""
        keys = [key_of(e) for e in entries]
        lanes = ints_to_lanes(keys)
        local_rows, remote = self.routes.split_lanes(lanes)
        if local_rows is None:
            if not fwd:
                return None
            local_rows = np.arange(len(entries))
            remote = []
        n = len(entries)
        ok_out = [False] * n
        rings_out = [""] * n
        ring_errors: Dict[str, str] = {}
        not_owned: List[int] = []
        if local_rows.size:
            sub = [entries[int(i)] for i in local_rows]
            out = self.gateway._handle_put_entries(sub, None, dl)
            for i, j in enumerate(local_rows):
                ok_out[int(j)] = bool(out["OK"][i])
                rings_out[int(j)] = out["RINGS"][i]
            for rid, msg in (out.get("RING_ERRORS") or {}).items():
                ring_errors[rid] = msg
        for addr, rrows in remote:
            a = f"mesh:{addr_str(addr)}"
            if fwd:
                # One-hop rule: a forwarded write is never re-routed.
                not_owned.extend(int(j) for j in rrows)
                for j in rrows:
                    rings_out[int(j)] = a
                continue
            req = {"COMMAND": "PUT", "FWD": 1,
                   "ENTRIES": [entries[int(j)] for j in rrows]}
            rem = dl.remaining()
            if rem is not None:
                req["DEADLINE_MS"] = max(rem * 1e3, 1.0)
            self.metrics.inc("gateway.forward.puts", len(rrows))
            try:
                resp = self._forward_direct(addr, req, deadline=dl)
            except ForwardError as exc:
                ring_errors[a] = str(exc)
                for j in rrows:
                    rings_out[int(j)] = a
                continue
            for i, j in zip(range(len(rrows)), rrows):
                ok_out[int(j)] = bool(resp["OK"][i])
                rings_out[int(j)] = a
            bounced = resp.get("NOT_OWNED")
            if bounced:
                # Route churn mid-write: the bounced entries' OK:false
                # must read as CHURN, not a store failure, and the
                # owner's fresher table installs so the NEXT write
                # resolves correctly (writes themselves are never
                # silently re-routed — the one-hop rule).
                self.metrics.inc("gateway.forward.not_owner",
                                 len(bounced))
                ring_errors[a] = (
                    f"{len(bounced)} entr{'y' if len(bounced) == 1 else 'ies'} "
                    f"bounced NOT_OWNED by {addr_str(addr)} (route "
                    f"epoch {resp.get('EPOCH')}); re-issue after the "
                    f"route refresh")
                if resp.get("ROUTES_DOC") is not None:
                    self.apply_routes_doc(resp["ROUTES_DOC"])
        out = {"OK": ok_out, "RINGS": rings_out}
        if ring_errors:
            out["RING_ERRORS"] = ring_errors
        if fwd and not_owned:
            out["NOT_OWNED"] = not_owned
            out["EPOCH"] = self.routes.epoch
            out["ROUTES_DOC"] = self.routes_doc()
        return out

    def _forward_direct(self, addr: Addr, req: dict,
                        deadline=None) -> dict:
        timeout = self.peer_verb_timeout_s * 4
        if deadline is not None:
            timeout = deadline.clamp(timeout)
        try:
            resp = Client.make_request(addr[0], addr[1], req,
                                       timeout=max(timeout or 0.0,
                                                   0.001),
                                       retries=1)
        # chordax-lint: disable=bare-except -- a peer failure becomes the caller's per-destination error row, never a handler crash
        except Exception as exc:
            self.metrics.inc("gateway.forward.errors")
            raise ForwardError(
                f"forward to {addr_str(addr)} failed: {exc}") from exc
        if not resp.get("SUCCESS"):
            self.metrics.inc("gateway.forward.errors")
            raise ForwardError(
                f"owner {addr_str(addr)} errored: {resp.get('ERRORS')}")
        return resp

    # -- the owner side of a forward ------------------------------------------
    def _serve_forwarded(self, verb: str, lanes: np.ndarray,
                         starts: Optional[np.ndarray], dl) -> dict:
        """Answer a forwarded run from LOCAL ownership only (the
        one-hop rule): owned rows serve through the gateway's fast
        lane, the rest come back NOT_OWNED with our fresher route
        table piggybacked so the origin can re-resolve once."""
        n = lanes.shape[0]
        local_rows, remote = self.routes.split_lanes(lanes)
        self.metrics.inc("mesh.fwd_served", n - sum(
            r.size for _, r in remote) if remote else n)
        if local_rows is None:
            if verb == "FIND_SUCCESSOR":
                return self.gateway._handle_find_successor_fast(
                    {"STARTS": starts}, lanes, None, dl)
            return self.gateway._handle_get_fast(lanes, None, dl)
        owned = local_rows
        bounced = sorted(int(j) for _, rr in remote for j in rr)
        if verb == "FIND_SUCCESSOR":
            owners = np.full(n, -1, np.int64)
            hops = np.full(n, -1, np.int32)
            rings = [""] * n
            if owned.size:
                sub_starts = starts[owned] if starts is not None \
                    else None
                out = self.gateway._handle_find_successor_fast(
                    {"STARTS": sub_starts}, lanes[owned], None, dl)
                owners[owned] = np.asarray(out["OWNERS"], np.int64)
                hops[owned] = np.asarray(out["HOPS"], np.int32)
                for i, j in enumerate(owned):
                    rings[int(j)] = out["RINGS"][i]
            resp: dict = {"OWNERS": owners, "HOPS": hops,
                          "RINGS": rings}
        else:
            rows_out: List[Any] = [[]] * n
            ok_out = np.zeros(n, dtype=bool)
            rings = [""] * n
            if owned.size:
                out = self.gateway._handle_get_fast(lanes[owned],
                                                    None, dl)
                lsegs = out["SEGMENTS"]
                lok = np.asarray(out["OK"], bool)
                for i, j in enumerate(owned):
                    rows_out[int(j)] = lsegs[i]
                    ok_out[int(j)] = bool(lok[i])
                    rings[int(j)] = out["RINGS"][i]
            resp = self._assemble_get(
                [r if isinstance(r, np.ndarray) else None
                 for r in rows_out], ok_out,
                np.asarray(rings, dtype=object), {})
            resp["RINGS"] = rings
        if bounced:
            resp["NOT_OWNED"] = bounced
            resp["EPOCH"] = self.routes.epoch
            resp["ROUTES_DOC"] = self.routes_doc()
        return resp

    # -- forward + one refresh-retry ------------------------------------------
    def _forward_read(self, verb: str, addr: Addr, lanes: np.ndarray,
                      starts: Optional[np.ndarray], dl
                      ) -> Tuple[Optional[np.ndarray],
                                 Optional[np.ndarray],
                                 Optional[list],
                                 Optional[np.ndarray],
                                 np.ndarray, Optional[str]]:
        """One coalesced forward plus at most ONE refresh-and-retry of
        the rows the owner bounced (the origin's half of the one-hop
        rule). Returns (owners, hops, segments_rows, ok, failed_mask,
        error): arrays are row-aligned with `lanes`; failed rows carry
        no answer."""
        n = lanes.shape[0]
        failed = np.zeros(n, dtype=bool)
        try:
            res = self.coalescer.forward(addr, verb, lanes, starts,
                                         dl.at)
        # chordax-lint: disable=bare-except -- a dead owner fails only its rows; the caller folds the error into per-destination RING_ERRORS
        except Exception as exc:
            failed[:] = True
            return None, None, None, None, failed, str(exc)
        owners = res.owners
        hops = res.hops
        ok = res.ok
        segments = (list(res.segments)
                    if res.segments is not None else None)
        if not res.not_owned:
            return owners, hops, segments, ok, failed, None
        # Retrying mutates per-row answers in place — and wire-decoded
        # arrays are READ-ONLY frombuffer views, so copy first.
        owners = np.array(owners) if owners is not None else None
        hops = np.array(hops) if hops is not None else None
        ok = np.array(ok) if ok is not None else None
        # The owner's table is fresher than ours: install it, then
        # re-resolve the bounced rows ONCE (local or one new owner).
        if res.routes_doc is not None:
            self.apply_routes_doc(res.routes_doc)
        self.metrics.inc("gateway.forward.retries")
        bounced = np.asarray(sorted(res.not_owned), np.int64)
        failed[bounced] = True
        sub_lanes = lanes[bounced]
        sub_starts = starts[bounced] if starts is not None else None
        local_rows, remote = self.routes.split_lanes(sub_lanes)
        if local_rows is None:
            local_rows = np.arange(sub_lanes.shape[0])
            remote = []
        err: Optional[str] = None
        if local_rows.size:
            j = bounced[local_rows]
            if verb == "FIND_SUCCESSOR":
                out = self.gateway._handle_find_successor_fast(
                    {"STARTS": (sub_starts[local_rows]
                                if sub_starts is not None else None)},
                    sub_lanes[local_rows], None, dl)
                owners[j] = np.asarray(out["OWNERS"], np.int64)
                hops[j] = np.asarray(out["HOPS"], np.int32)
            else:
                out = self.gateway._handle_get_fast(
                    sub_lanes[local_rows], None, dl)
                ok[j] = np.asarray(out["OK"], bool)
                for i, jj in enumerate(j):
                    segments[int(jj)] = out["SEGMENTS"][i]
            failed[j] = False
        for new_addr, rrows in remote:
            j = bounced[rrows]
            if new_addr == addr:
                err = (f"owner {addr_str(addr)} bounced "
                       f"{len(rrows)} key(s) it still maps to itself")
                continue
            try:
                res2 = self.coalescer.forward(
                    new_addr, verb, sub_lanes[rrows],
                    sub_starts[rrows] if sub_starts is not None
                    else None, dl.at)
            # chordax-lint: disable=bare-except -- the single retry's failure stays a per-row verdict, never a handler crash
            except Exception as exc:
                err = str(exc)
                continue
            live = np.asarray(
                [i for i in range(len(rrows))
                 if i not in set(res2.not_owned)], np.int64)
            if verb == "FIND_SUCCESSOR":
                owners[j[live]] = res2.owners[live]
                hops[j[live]] = res2.hops[live]
            else:
                ok[j[live]] = res2.ok[live]
                for i in live:
                    segments[int(j[i])] = res2.segments[int(i)]
            failed[j[live]] = False
            if res2.not_owned:
                err = (f"{len(res2.not_owned)} key(s) still unowned "
                       f"after one re-resolution (route churn)")
        return owners, hops, segments, ok, failed, err

    # -- mesh-wide verb merging ------------------------------------------------
    def collect_peer_rows(self, command: str, req: dict
                          ) -> Dict[str, dict]:
        """Every live route peer's own answer to `command` (bounded
        timeout each; an unreachable peer's row is the TYPED stale
        marker — ``STALE: true`` + ``ERROR`` + age-stamped
        ``LAST_GOOD`` when we have one — so a consuming policy tick
        never parses an error string and a brief partition never
        reads as zero capacity) — the proxy/merge half of the
        mesh-wide CAPACITY/HEALTH/PULSE verbs. Peers are polled
        CONCURRENTLY, so the verb costs max(peer latency), never
        sum — N-1 partitioned peers must not park a serving worker
        for N-1 timeouts back to back."""
        base = {k: v for k, v in req.items()
                if k not in ("MESH", trace_mod.WIRE_KEY)}
        base["COMMAND"] = command
        peers = [a for a in self.routes.addresses()
                 if a != self.routes.self_addr]
        if not peers:
            return {}

        def one(addr: Addr) -> dict:
            a = addr_str(addr)
            try:
                resp = Client.make_request(
                    addr[0], addr[1], dict(base),
                    timeout=self.peer_verb_timeout_s)
                resp.pop("SUCCESS", None)
                with self._lock:
                    self._last_good[a] = (time.monotonic(), resp)
                return resp
            # chordax-lint: disable=bare-except -- an unreachable peer's row is its typed stale marker; the merge must answer regardless
            except Exception as exc:
                self.metrics.inc("mesh.peer_rows_stale")
                marker = {"STALE": True, "ERROR": str(exc)}
                with self._lock:
                    good = self._last_good.get(a)
                if good is not None:
                    marker["AGE_S"] = round(
                        max(time.monotonic() - good[0], 0.0), 3)
                    marker["LAST_GOOD"] = good[1]
                return marker

        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(
                max_workers=min(len(peers), 16),
                thread_name_prefix="mesh-verb") as pool:
            answers = list(pool.map(one, peers))
        return {addr_str(a): r for a, r in zip(peers, answers)}

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        self.gateway.router.remove_topology_listener(self._topo_cb)
        self.coalescer.close()

"""The shared fold core: per-(destination, verb) micro-batching of
KEYS-vector RPCs (chordax-edge, ISSUE 17 — one coalescing engine).

ISSUE 15 built the forward coalescer inside the mesh gateway; ISSUE 17
lifts the SAME discipline to the client rim. Rather than fork the
machinery, this module holds the whole fold/flush engine and the two
users subclass it:

  * `mesh.coalescer.ForwardCoalescer` — the gateway's cross-shard
    forward path (`gateway.forward.*` metrics, `mesh.forward` span);
  * `edge.client` — the zero-hop client SDK's rim coalescer
    (`edge.*` metrics, `edge.flush` span, hedged transport).

The shared rules (what "ONE implementation" means here):

  * every fold (a single-key miss OR a whole vector run) enqueues on
    its (destination, verb) lane and waits on its own waiter;
  * one worker per lane drains everything queued — while one RPC is in
    flight, new arrivals pile up and ride the NEXT flush, so load
    coalesces naturally with ZERO added latency when idle;
  * the batch rides the pooled/pipelined binary transport as packed
    little-endian u128 runs (`wire.U128Keys.from_lanes`);
  * DEADLINE_MS is the MINIMUM remaining budget across the folded
    entries (already-expired entries are failed before the flush);
  * the chordax-scope trace context of the FIRST folded entry rides
    the batch (one RPC carries one root);
  * the request carries ``FWD: 1`` — the one-hop rule: the owner
    answers from local ownership only and bounces stale rows back in
    ``NOT_OWNED`` with its fresher route table piggybacked. The core
    reports those rows per entry; the CALLER owns the single
    refresh-and-retry (mesh plane or edge client).

Subclass hooks: `_record_*` methods keep the metric keys LITERAL at
each concrete site (the pass-4 doc-drift gate scans recorder call
literals), and `_transport` owns the actual RPC so the edge can hedge.

LOCK ORDER: `_Lane._lock` and `FoldCore._lock` are LEAVES — held only
for queue/table bookkeeping, never across the RPC, an encode, or a
waiter wait. The flush runs entirely lock-free.
This module never imports jax.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from p2p_dhts_tpu import trace as trace_mod
from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.net import wire
from p2p_dhts_tpu.net.rpc import Client, RpcError

#: Verbs the fold core knows how to batch (KEYS-vector read forms).
FOLD_VERBS = ("FIND_SUCCESSOR", "GET")

#: Flush wait bound when the caller set no deadline (the gateway's
#: DEFAULT_WAIT_S rule: a fold must never park a worker forever).
DEFAULT_FOLD_WAIT_S = 60.0


class FoldError(RuntimeError):
    """The folded batch failed at the transport or the owner."""


class FoldResult:
    """One entry's slice of a flushed batch: the per-row result arrays
    plus the owner's not-owned verdicts and piggybacked routes."""

    __slots__ = ("owners", "hops", "segments", "ok", "not_owned",
                 "routes_doc", "routes_epoch")

    def __init__(self) -> None:
        self.owners: Optional[np.ndarray] = None
        self.hops: Optional[np.ndarray] = None
        self.segments = None          # stacked array or per-row list
        self.ok: Optional[np.ndarray] = None
        self.not_owned: List[int] = []    # row indices WITHIN the entry
        self.routes_doc: Optional[dict] = None
        self.routes_epoch: Optional[int] = None


class _Entry:
    __slots__ = ("lanes", "starts", "deadline_at", "ctx", "ev",
                 "result", "error", "t0")

    def __init__(self, lanes: np.ndarray, starts: Optional[np.ndarray],
                 deadline_at: Optional[float], ctx) -> None:
        self.lanes = lanes
        self.starts = starts
        self.deadline_at = deadline_at
        self.ctx = ctx
        self.ev = threading.Event()
        self.result: Optional[FoldResult] = None
        self.error: Optional[BaseException] = None
        self.t0 = time.perf_counter()


class _Lane:
    """One (destination, verb) queue + its drain worker."""

    def __init__(self, owner: "FoldCore",
                 dest: Tuple[str, int], verb: str):
        self.owner = owner
        self.dest = dest
        self.verb = verb
        self._lock = threading.Lock()
        self._queue: List[_Entry] = []
        self._event = threading.Event()
        self._closed = False
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"{owner.thread_prefix}-{dest[0]}:{dest[1]}-{verb}")
        self.thread.start()

    def enqueue(self, entry: _Entry) -> None:
        with self._lock:
            if self._closed:
                entry.error = self.owner.error_cls(self.owner.closed_msg)
                entry.ev.set()
                return
            self._queue.append(entry)
        self._event.set()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            drained = list(self._queue)
            self._queue.clear()
        for e in drained:
            e.error = self.owner.error_cls(self.owner.closed_msg)
            e.ev.set()
        self._event.set()

    def _run(self) -> None:
        while True:
            self._event.wait(timeout=0.5)
            with self._lock:
                if self._closed and not self._queue:
                    return
                batch = self._queue[:self.owner.max_batch]
                del self._queue[:len(batch)]
                if not self._queue:
                    self._event.clear()
            if batch:
                if self.owner.max_batch == 1:
                    # The PER-KEY baseline (coalescing off): one RPC
                    # per ROW — what a naive proxy loop does, and what
                    # the bench gates the coalescer against.
                    for e in batch:
                        self.owner._flush_per_key(self.dest,
                                                  self.verb, e)
                else:
                    self.owner._flush(self.dest, self.verb, batch)


class FoldCore:
    """Per-destination micro-batching engine; subclasses pin the
    metric keys, the span identity, and the transport."""

    #: Subclass identity knobs — see module docstring.
    error_cls = FoldError
    closed_msg = "fold core closed"
    span_name = "fold.flush"
    span_cat = "fold"
    thread_prefix = "fold"
    verbs = FOLD_VERBS
    default_wait_s = DEFAULT_FOLD_WAIT_S

    #: Extra request fields stamped on EVERY flushed RPC (instance-
    #: overridable). chordax-tower (ISSUE 20): the canary's dedicated
    #: edge client sets {"NOCACHE": 1} here so its probes bypass the
    #: owner's hot-key cache — a per-client identity, never mixed
    #: into another client's folds (each Client owns its own core).
    extra_fields: Dict[str, object] = {}

    def __init__(self, metrics: Optional[Metrics] = None,
                 max_batch: int = 4096, retries: int = 1):
        self.metrics = metrics if metrics is not None else METRICS
        #: Rows per flushed RPC. 1 is the PER-KEY baseline the bench
        #: measures the coalescer against (set_max_batch).
        self.max_batch = int(max_batch)
        self._configured_max_batch = self.max_batch
        self.retries = int(retries)
        self._lock = threading.Lock()
        self._lanes: Dict[Tuple[Tuple[str, int], str], _Lane] = {}
        self._closed = False

    def set_max_batch(self, n: int) -> int:
        """Runtime knob (the bench's coalesced-vs-per-key A/B): 1 =
        one RPC per folded entry, the baseline. Returns the previous
        value. The new value also becomes what set_coalesce(True)
        restores — an operator's tuning survives a SET_COALESCE
        A/B cycle."""
        prev, self.max_batch = self.max_batch, max(int(n), 1)
        self._configured_max_batch = self.max_batch
        return prev

    def set_coalesce(self, on: bool) -> None:
        """Toggle between the configured batching and the per-key
        baseline (the MESH_ROUTES SET_COALESCE wire knob)."""
        self.max_batch = self._configured_max_batch if on else 1

    # -- public folds --------------------------------------------------------
    def forward(self, dest: Tuple[str, int], verb: str,
                lanes: np.ndarray, starts: Optional[np.ndarray],
                deadline_at: Optional[float]) -> FoldResult:
        """Fold one run of keys (1..N rows) toward `dest`, folded with
        whatever else is queued there; blocks for this entry's slice."""
        if verb not in self.verbs:
            raise ValueError(f"unforwardable verb {verb!r}")
        entry = _Entry(np.ascontiguousarray(lanes, dtype=np.uint32),
                       None if starts is None
                       else np.ascontiguousarray(starts, dtype=np.int32),
                       deadline_at, trace_mod.current_raw())
        lane = self._lane(dest, verb)
        lane.enqueue(entry)
        wait_s = self.default_wait_s
        if deadline_at is not None:
            wait_s = max(min(wait_s, deadline_at - time.perf_counter()),
                         0.0)
        # The flush worker always completes every entry it popped (the
        # RPC itself is deadline-bounded), so a small grace on top of
        # the caller budget keeps the error attribution exact.
        if not entry.ev.wait(wait_s + 5.0):
            raise self.error_cls(
                f"forward to {dest[0]}:{dest[1]} timed out")
        if entry.error is not None:
            raise entry.error
        assert entry.result is not None
        return entry.result

    def _lane(self, dest: Tuple[str, int], verb: str) -> _Lane:
        key = ((str(dest[0]), int(dest[1])), verb)
        with self._lock:
            if self._closed:
                raise self.error_cls(self.closed_msg)
            lane = self._lanes.get(key)
            if lane is None:
                lane = self._lanes[key] = _Lane(self, key[0], verb)
        return lane

    def close(self) -> None:
        with self._lock:
            self._closed = True
            lanes = list(self._lanes.values())
            self._lanes.clear()
        for lane in lanes:
            lane.close()

    # -- subclass hooks ------------------------------------------------------
    def _record_flush(self, n_keys: int, folded: int) -> None:
        """One batch left for the wire: count it with LITERAL metric
        keys at the concrete site (doc-drift gate rule)."""

    def _record_error(self) -> None:
        """The flush failed (transport error or owner-side ERRORS)."""

    def _record_latency(self, dt: float) -> None:
        """One successful flush round-trip took `dt` seconds."""

    def _record_not_owner(self, k: int) -> None:
        """`k` rows bounced back NOT_OWNED (stale route)."""

    def _transport(self, dest: Tuple[str, int], verb: str, req: dict,
                   timeout: float,
                   deadline_at: Optional[float]) -> dict:
        """The actual RPC. Runs inside the batch's activated trace
        context and flush span; the edge overrides this with the
        hedged/breaker-guarded send."""
        return Client.make_request(
            dest[0], dest[1], req, timeout=timeout,
            retries=self.retries, deadline=deadline_at)

    def _flush_per_key(self, dest: Tuple[str, int], verb: str,
                       entry: _Entry) -> None:
        """Baseline mode: fold one entry's rows as ONE RPC EACH,
        sequentially — the per-RPC overhead the fold core exists to
        amortize, kept runnable so the bench's A/B stays honest. The
        first transport failure fails the whole entry."""
        rows = entry.lanes.shape[0]
        owners = np.full(rows, -1, np.int64)
        hops = np.full(rows, -1, np.int32)
        ok = np.zeros(rows, dtype=bool)
        segments: List = [None] * rows
        not_owned: List[int] = []
        routes_doc = None
        routes_epoch = None
        for j in range(rows):
            sub = _Entry(entry.lanes[j:j + 1],
                         None if entry.starts is None
                         else entry.starts[j:j + 1],
                         entry.deadline_at, entry.ctx)
            self._flush(dest, verb, [sub])
            if sub.error is not None:
                entry.error = sub.error
                entry.ev.set()
                return
            res = sub.result
            if res.routes_epoch is not None:
                routes_epoch = res.routes_epoch
            if res.not_owned:
                not_owned.append(j)
                routes_doc = res.routes_doc or routes_doc
                continue
            if verb == "FIND_SUCCESSOR":
                owners[j] = res.owners[0]
                hops[j] = res.hops[0]
            else:
                ok[j] = res.ok[0]
                segments[j] = res.segments[0]
        out = FoldResult()
        out.owners, out.hops = owners, hops
        out.ok, out.segments = ok, segments
        out.not_owned = not_owned
        out.routes_doc = routes_doc
        out.routes_epoch = routes_epoch
        entry.result = out
        entry.ev.set()

    # -- the flush -----------------------------------------------------------
    def _flush(self, dest: Tuple[str, int], verb: str,
               batch: List[_Entry]) -> None:
        now = time.perf_counter()
        live: List[_Entry] = []
        for e in batch:
            if e.deadline_at is not None and now >= e.deadline_at:
                from p2p_dhts_tpu.serve import DeadlineExpiredError
                e.error = DeadlineExpiredError(
                    "forward deadline passed before the flush")
                e.ev.set()
            else:
                live.append(e)
        if not live:
            return
        lanes = (live[0].lanes if len(live) == 1
                 else np.vstack([e.lanes for e in live]))
        n = lanes.shape[0]
        starts = None
        if verb == "FIND_SUCCESSOR":
            starts = np.concatenate(
                [e.starts if e.starts is not None
                 else np.zeros(e.lanes.shape[0], np.int32)
                 for e in live])
        deadlines = [e.deadline_at for e in live
                     if e.deadline_at is not None]
        deadline_at = min(deadlines) if deadlines else None
        timeout = self.default_wait_s
        if deadline_at is not None:
            timeout = max(min(timeout, deadline_at - now), 0.001)
        req: dict = {"COMMAND": verb,
                     "KEYS": wire.U128Keys.from_lanes(lanes),
                     "FWD": 1}
        if self.extra_fields:
            req.update(self.extra_fields)
        if starts is not None:
            req["STARTS"] = starts
        if deadline_at is not None:
            req["DEADLINE_MS"] = max(
                (deadline_at - time.perf_counter()) * 1e3, 1.0)
        self._record_flush(n, len(live))
        t0 = time.perf_counter()
        try:
            # The first folded entry's trace context roots the batch
            # (one RPC carries one context): a solo fold keeps its
            # unbroken cross-process chain; a shared frame records the
            # fold size on the flush span.
            with trace_mod.activate(live[0].ctx):
                with trace_mod.span(self.span_name, cat=self.span_cat,
                                    dest=f"{dest[0]}:{dest[1]}",
                                    verb=verb, n=n, folded=len(live)):
                    resp = self._transport(dest, verb, req, timeout,
                                           deadline_at)
        # chordax-lint: disable=bare-except -- the flush is every folded waiter's failure funnel: any error must fan out, never kill the lane thread
        except Exception as exc:
            self._record_error()
            err = exc if isinstance(exc, (RpcError, FoldError)) \
                else self.error_cls(f"{type(exc).__name__}: {exc}")
            for e in live:
                e.error = err
                e.ev.set()
            return
        self._record_latency(time.perf_counter() - t0)
        if not resp.get("SUCCESS"):
            self._record_error()
            err = self.error_cls(
                f"owner {dest[0]}:{dest[1]} errored: "
                f"{resp.get('ERRORS')}")
            for e in live:
                e.error = err
                e.ev.set()
            return
        self._fan_out(verb, live, resp, n)

    def _fan_out(self, verb: str, live: List[_Entry], resp: dict,
                 n: int) -> None:
        not_owned = set(int(i) for i in resp.get("NOT_OWNED", ()))
        if not_owned:
            self._record_not_owner(len(not_owned))
        routes_doc = resp.get("ROUTES_DOC")
        routes_epoch = resp.get("ROUTES_EPOCH")
        if routes_epoch is not None:
            routes_epoch = int(routes_epoch)
        owners = hops = ok = segs = None
        if verb == "FIND_SUCCESSOR":
            owners = np.asarray(resp.get("OWNERS", []), np.int64)
            hops = np.asarray(resp.get("HOPS", []), np.int32)
        else:
            ok = np.asarray(resp.get("OK", []), bool)
            segs = resp.get("SEGMENTS", [])
        off = 0
        for e in live:
            rows = e.lanes.shape[0]
            res = FoldResult()
            res.routes_doc = routes_doc
            res.routes_epoch = routes_epoch
            res.not_owned = [i - off for i in not_owned
                             if off <= i < off + rows]
            try:
                if verb == "FIND_SUCCESSOR":
                    if owners.shape[0] != n or hops.shape[0] != n:
                        raise self.error_cls(
                            f"owner answered {owners.shape[0]} rows "
                            f"for a {n}-row forward")
                    res.owners = owners[off:off + rows]
                    res.hops = hops[off:off + rows]
                else:
                    if ok.shape[0] != n:
                        raise self.error_cls(
                            f"owner answered {ok.shape[0]} rows for "
                            f"a {n}-row forward")
                    res.ok = ok[off:off + rows]
                    # stacked [n,S,m] array and per-row list slice the
                    # same way; rows stay whichever form the owner sent
                    res.segments = segs[off:off + rows]
                e.result = res
            except BaseException as exc:  # noqa: BLE001 — fanned to the waiter
                e.error = exc if isinstance(exc, FoldError) \
                    else self.error_cls(f"{type(exc).__name__}: {exc}")
            e.ev.set()
            off += rows

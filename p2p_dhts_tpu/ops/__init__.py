"""Device kernels: 128-bit lane math, IDA Vandermonde matmuls, hash compare."""

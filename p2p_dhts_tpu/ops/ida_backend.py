"""Selectable IDA decode backends (chordax-fuse, ISSUE 13).

The per-block IDA decode has THREE implementations with wildly
different hardware profiles, and since round 5 the choice has been a
trace-time platform split buried inside ``ida.decode_kernel``:

  * ``dot``    — inverse-Vandermonde then ``modp.mod_matmul``
                 (dot_general). Fastest on XLA:CPU; on TPU the batched
                 tiny [m, m] @ [m, S] pads every batch element to full
                 MXU systolic tiles (the measured 93.3 MB/s cliff,
                 BENCH_ATTEMPT_r04 / BENCH_NOTES_r12.md).
  * ``mac``    — ``modp.mod_matmul_batched_tiny``, the unrolled VPU
                 multiply-accumulate. Dodges the MXU cliff on TPU;
                 ~250x slower than dot on CPU (BENCH_NOTES_r05).
  * ``pallas`` — ``ops.modp_pallas.decode_kernel_pallas``, the whole
                 per-block pipeline (Lagrange synthetic division,
                 Fermat inverse, scale, matmul) fused in VMEM. Written
                 in round 5 but never first-class selectable; compiled
                 Mosaic needs a TPU — on CPU it runs interpret-mode
                 (parity yes, speed no).

This registry makes the choice FIRST-CLASS: resolution order is an
explicit per-call ``backend=`` argument, then the process-wide
``set_backend()`` override, then the ``CHORDAX_IDA_BACKEND`` env var,
then the measured platform default (``dot`` on CPU, ``mac``
otherwise — exactly the round-5 split, so an unconfigured process
behaves byte-for-byte as before). ``"auto"`` names the platform
default explicitly. ``ida.decode_kernel`` resolves through here AT
TRACE TIME (the same moment the old platform split fired), so set the
backend before the first decode traces; ``decode()`` below keys its
jit cache on the backend name and honors a flip at any time — the
parity-gated microbench (``bench.py --config fuse``) measures all
three side by side through it.

All three backends are exact under the same bound the kernels enforce
(m * (p-1)^2 < 2^24 for the f32 paths); byte-identical fragments are
pinned by tests/test_fuse.py and the fuse bench's parity gate.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from p2p_dhts_tpu.ops import modp

#: Environment knob: CHORDAX_IDA_BACKEND=dot|mac|pallas|auto.
ENV_VAR = "CHORDAX_IDA_BACKEND"

#: The selectable concrete backends ("auto" resolves to one of these).
IDA_BACKENDS = ("dot", "mac", "pallas")

_lock = threading.Lock()
_configured: Optional[str] = None


def set_backend(name: Optional[str]) -> None:
    """Install a process-wide backend override (None clears it back to
    env-var/platform resolution). Validates eagerly — a typo must fail
    here, not as a KeyError inside a trace."""
    global _configured
    if name is not None and name != "auto" and name not in IDA_BACKENDS:
        raise ValueError(
            f"unknown IDA backend {name!r}; choose one of "
            f"{IDA_BACKENDS + ('auto',)}")
    with _lock:
        _configured = name


def configured() -> Optional[str]:
    with _lock:
        return _configured


def platform_default() -> str:
    """The measured round-5 platform split: dot rides XLA:CPU's fast
    batched tiny dot; everything else dodges the MXU padding cliff on
    the VPU MAC path (ida.decode_kernel's historical behavior)."""
    return "dot" if jax.default_backend() == "cpu" else "mac"


def resolve(name: Optional[str] = None) -> str:
    """Concrete backend name for this call: explicit arg > set_backend
    > CHORDAX_IDA_BACKEND > platform default. "auto" (at any level)
    short-circuits to the platform default."""
    for cand in (name, configured(), os.environ.get(ENV_VAR)):
        if cand:
            if cand == "auto":
                return platform_default()
            if cand not in IDA_BACKENDS:
                raise ValueError(
                    f"unknown IDA backend {cand!r}; choose one of "
                    f"{IDA_BACKENDS + ('auto',)}")
            return cand
    return platform_default()


def availability(name: str) -> Tuple[bool, str]:
    """(usable, reason). Every backend is *callable* everywhere; the
    reason string says at what cost — the fuse bench surfaces it when
    it skips timing a backend (pallas on CPU runs interpret-mode:
    parity holds but the numbers would measure the interpreter, not
    the kernel)."""
    if name in ("dot", "mac"):
        return True, "pure XLA (portable)"
    if name == "pallas":
        if jax.default_backend() == "cpu":
            return True, ("interpret-mode only on CPU (compiled Mosaic "
                          "needs a TPU): parity holds, timing would "
                          "measure the interpreter")
        return True, "compiled Mosaic kernel (VMEM-fused)"
    raise ValueError(f"unknown IDA backend {name!r}")


# ---------------------------------------------------------------------------
# the three decode bodies — plain traceable functions, shared by the
# jitted public entry point below AND by ida.decode_kernel's trace
# ---------------------------------------------------------------------------

def _decode_dot(rows: jax.Array, indices: jax.Array, p: int) -> jax.Array:
    inv = modp.vandermonde_inverse(indices, p)           # [..., m, m]
    return jnp.swapaxes(modp.mod_matmul(inv, rows, p), -1, -2)


def _decode_mac(rows: jax.Array, indices: jax.Array, p: int) -> jax.Array:
    inv = modp.vandermonde_inverse(indices, p)
    return jnp.swapaxes(modp.mod_matmul_batched_tiny(inv, rows, p),
                        -1, -2)


def _decode_pallas(rows: jax.Array, indices: jax.Array,
                   p: int) -> jax.Array:
    # Deferred import: pallas pulls jax.experimental machinery no
    # dot/mac caller should pay for. Interpret mode on CPU — the
    # kernel body runs as composed jax ops, so it nests fine inside
    # an outer jit (tests/test_ida.py's existing parity discipline).
    from p2p_dhts_tpu.ops.modp_pallas import decode_kernel_pallas
    return decode_kernel_pallas(
        rows, indices, p, interpret=jax.default_backend() == "cpu")


_IMPLS = {"dot": _decode_dot, "mac": _decode_mac,
          "pallas": _decode_pallas}


def decode_body(rows: jax.Array, indices: jax.Array, p: int,
                backend: str) -> jax.Array:
    """The traceable dispatch (backend already concrete): [B, m, S]
    int32 fragment rows + [B, m] 1-based indices -> [B, S, m] decoded
    segments. dot/mac accept arbitrary leading batch dims; pallas is
    3-D (its tile grid is rank-fixed)."""
    return _IMPLS[backend](rows, indices, p)


@functools.partial(jax.jit, static_argnames=("p", "backend"))
def _decode_jit(rows, indices, p, backend):
    return decode_body(rows, indices, p, backend)


def decode(rows, indices, p: int, backend: Optional[str] = None):
    """Public selectable decode: resolve the backend (per-call arg >
    set_backend > env > platform default), then dispatch through a
    jit keyed on the concrete name — flipping the backend mid-process
    re-routes the NEXT call (unlike ida.decode_kernel, whose choice is
    baked at trace time)."""
    return _decode_jit(rows, indices, p, backend=resolve(backend))

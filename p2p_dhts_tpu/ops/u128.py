"""Jittable 128-bit ring arithmetic on [..., 4] uint32 lane vectors.

TPUs have no 128-bit (or even 64-bit, without x64 mode) integer lanes, so ring
ids travel as four little-endian uint32 lanes and every comparison/add/sub
hand-rolls its carry/borrow chain. This module is the device twin of the
reference's `GenericKey` (src/data_structures/key.h): `in_between` reproduces
the clockwise-range quirks of key.h:103-131 exactly (see keyspace.py for the
quirk catalog), `sub_mod` is the modular clockwise distance, and `bit_length`
yields the finger-table index in O(1) — the closed form of the reference's
128-entry linear scan (finger_table.h:115-130): key k lies in finger i of peer
p  iff  2^i <= (k - id_p) mod 2^128 < 2^(i+1), i.e. i = bit_length(d) - 1.

All functions broadcast over leading batch dims and are jit/vmap/shard_map
safe (pure, static shapes, no python branching on traced values).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LANES = 4
_U32 = jnp.uint32


def _u32(x):
    return jnp.asarray(x, dtype=_U32)


# ---------------------------------------------------------------------------
# comparisons — lexicographic over lanes, most-significant (index 3) first
# ---------------------------------------------------------------------------

def eq(a: jax.Array, b: jax.Array) -> jax.Array:
    """a == b elementwise over the trailing lane dim -> bool[...]."""
    return jnp.all(a == b, axis=-1)


def lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """a < b (unsigned 128-bit) -> bool[...]."""
    res = jnp.zeros(a.shape[:-1], dtype=bool)
    tied = jnp.ones(a.shape[:-1], dtype=bool)
    for lane in range(LANES - 1, -1, -1):
        res = res | (tied & (a[..., lane] < b[..., lane]))
        tied = tied & (a[..., lane] == b[..., lane])
    return res


def le(a: jax.Array, b: jax.Array) -> jax.Array:
    return ~lt(b, a)


def gt(a: jax.Array, b: jax.Array) -> jax.Array:
    return lt(b, a)


def ge(a: jax.Array, b: jax.Array) -> jax.Array:
    return ~lt(a, b)


# ---------------------------------------------------------------------------
# modular add / sub (mod 2^128 — the ring size, key.h:279-280)
# ---------------------------------------------------------------------------

def add(a: jax.Array, b: jax.Array) -> jax.Array:
    """(a + b) mod 2^128, lanewise carry chain."""
    out = []
    carry = jnp.zeros(a.shape[:-1], dtype=_U32)
    for lane in range(LANES):
        t = a[..., lane] + b[..., lane]
        c1 = (t < a[..., lane]).astype(_U32)
        s = t + carry
        c2 = (s < t).astype(_U32)
        out.append(s)
        carry = c1 | c2
    return jnp.stack(out, axis=-1)


def sub(a: jax.Array, b: jax.Array) -> jax.Array:
    """(a - b) mod 2^128 — the clockwise ring distance from b to a."""
    out = []
    borrow = jnp.zeros(a.shape[:-1], dtype=_U32)
    for lane in range(LANES):
        t = a[..., lane] - b[..., lane]
        b1 = (a[..., lane] < b[..., lane]).astype(_U32)
        s = t - borrow
        b2 = (t < borrow).astype(_U32)
        out.append(s)
        borrow = b1 | b2
    return jnp.stack(out, axis=-1)


def add_scalar(a: jax.Array, v: int) -> jax.Array:
    """(a + small-python-int) mod 2^128. v must be a static 0 <= v < 2^32."""
    b = jnp.zeros_like(a).at[..., 0].set(_u32(v))
    return add(a, b)


def pow2(k: jax.Array) -> jax.Array:
    """2^k as a lane vector; k is a traced int32 in [0, 128)."""
    k = jnp.asarray(k, dtype=jnp.int32)
    lane_idx = k // 32
    bit = (_u32(1) << (k % 32).astype(_U32))
    lanes = jnp.arange(LANES, dtype=jnp.int32)
    shape = k.shape + (LANES,)
    return jnp.where(
        lanes == lane_idx[..., None],
        jnp.broadcast_to(bit[..., None], shape),
        jnp.zeros(shape, dtype=_U32),
    )


def add_pow2(a: jax.Array, k: jax.Array) -> jax.Array:
    """(a + 2^k) mod 2^128 — finger-range starts (finger_table.h:177-188)."""
    return add(a, pow2(k))


# ---------------------------------------------------------------------------
# bit length — the O(1) finger index
# ---------------------------------------------------------------------------

def _bit_length32(x: jax.Array) -> jax.Array:
    """Branchless bit-length of a uint32 -> int32 in [0, 32]."""
    x = _u32(x)
    r = jnp.zeros(x.shape, dtype=jnp.int32)
    for shift in (16, 8, 4, 2, 1):
        m = x >= (_u32(1) << shift)
        r = r + jnp.where(m, shift, 0)
        x = jnp.where(m, x >> shift, x)
    return r + (x > 0).astype(jnp.int32)


def bit_length(a: jax.Array) -> jax.Array:
    """Bit-length of a u128 -> int32 in [0, 128].

    finger index of clockwise distance d is bit_length(d) - 1: the closed
    form of the reference's linear range scan, since finger i of peer p
    covers distances [2^i, 2^(i+1)-1] (finger_table.h:177-188).
    """
    lanes_bl = _bit_length32(a)  # [..., LANES] int32
    lane_off = jnp.arange(LANES, dtype=jnp.int32) * 32
    per_lane = jnp.where(a > 0, lanes_bl + lane_off, 0)
    return jnp.max(per_lane, axis=-1)


# ---------------------------------------------------------------------------
# clockwise range membership — quirk parity with key.h:103-131
# ---------------------------------------------------------------------------

def in_between(v: jax.Array, lb: jax.Array, ub: jax.Array, inclusive: bool = True) -> jax.Array:
    """Clockwise `v in [lb, ub]` with the reference's exact branch structure.

    bool[...] over broadcast batch dims. `inclusive` is a static python bool
    (the protocol always knows it at trace time).
    """
    bounds_equal = eq(lb, ub)
    on_bound = eq(v, ub)

    lb_lt_ub = lt(lb, ub)
    if inclusive:
        plain = le(lb, v) & le(v, ub)
        wrapped = ~(lt(ub, v) & lt(v, lb))
    else:
        plain = lt(lb, v) & lt(v, ub)
        wrapped = ~(le(ub, v) & le(v, lb))

    return jnp.where(bounds_equal, on_bound, jnp.where(lb_lt_ub, plain, wrapped))


# ---------------------------------------------------------------------------
# sorted search — successor resolution over a sorted id table
# ---------------------------------------------------------------------------

def _bisect_step(sorted_ids: jax.Array, q: jax.Array, lo: jax.Array,
                 hi: jax.Array):
    """One halving of every query's [lo, hi) window: the shared body of
    searchsorted and searchsorted_bucketed (gather mid row, lex compare,
    shrink the active windows)."""
    active = lo < hi
    mid = (lo + hi) // 2
    mid_ids = sorted_ids[mid]
    go_right = active & lt(mid_ids, q)
    lo = jnp.where(go_right, mid + 1, lo)
    hi = jnp.where(active & ~go_right, mid, hi)
    return lo, hi


def searchsorted(sorted_ids: jax.Array, q: jax.Array, n_valid=None) -> jax.Array:
    """Index of the first entry >= q in a lexicographically sorted [N, 4] table.

    Returns int32 in [0, N] (N meaning "past the end", i.e. the caller wraps
    to 0 for ring semantics). Vectorized binary search: log2(N) gather+compare
    steps over the whole query batch — this is the "fingers-as-computed"
    successor primitive for rings too large to materialize [N,128] fingers.

    n_valid: optional traced int32 — number of leading valid rows (for
    capacity-padded tables).
    """
    n = sorted_ids.shape[0]
    hi0 = jnp.int32(n if n_valid is None else n_valid)
    lo = jnp.zeros(q.shape[:-1], dtype=jnp.int32)
    hi = jnp.broadcast_to(hi0, q.shape[:-1]).astype(jnp.int32)
    steps = max(1, (n - 1).bit_length() + 1) if n > 0 else 1

    def body(_, carry):
        return _bisect_step(sorted_ids, q, *carry)

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def ring_successor(sorted_ids: jax.Array, q: jax.Array, n_valid=None) -> jax.Array:
    """Index of the clockwise successor of q in a sorted ring table (wraps)."""
    n = sorted_ids.shape[0]
    idx = searchsorted(sorted_ids, q, n_valid)
    limit = jnp.int32(n if n_valid is None else n_valid)
    return jnp.where(idx >= limit, 0, idx)


# ---------------------------------------------------------------------------
# bucketed sorted search — fewer gathers per query on big tables
# ---------------------------------------------------------------------------

#: Default top-bits width for bucket tables; callers gate bucketing on
#: table size >= 2**DEFAULT_BUCKET_BITS (below that a plain binary
#: search is already as cheap as the table build).
DEFAULT_BUCKET_BITS = 16

#: Cap for size-scaled tables: 2^20 buckets = 4 MiB of i32 starts.
MAX_BUCKET_BITS = 20


def bucket_bits_for(n: int) -> int:
    """Table bits sized to the id count: expected bucket occupancy ~2^3
    ids (so each bucketed search converges in ~3-4 bisect steps instead
    of log2(n)). n is a static shape, so this is trace-time arithmetic.
    At 10M ids: 20 bits -> occupancy ~10 vs 152 at the flat default.
    Sharded callers pass the GLOBAL id count: a shard's contiguous slice
    occupies ~1/d of the (globally-keyed) buckets, so ids per occupied
    bucket is n_global/2^bits independent of the shard count."""
    return min(MAX_BUCKET_BITS, max(DEFAULT_BUCKET_BITS,
                                    (max(n, 2) - 1).bit_length() - 3))

def bucket_starts(sorted_ids: jax.Array, bits: int) -> jax.Array:
    """[2^bits + 1] i32 bucket table over the top `bits` id bits.

    starts[b] = index of the first row whose top bits are >= b, so rows
    with top bits exactly b live in [starts[b], starts[b+1]). Computed as
    one batched binary search for the 2^bits bucket boundary keys (NOT a
    scatter-add histogram: a 10M-update scatter is exactly the op class
    that sends the TPU compiler into multi-minute lowering, while this
    searchsorted pattern is the kernel's own proven-fast primitive).
    Amortized over the hop loop the table cuts every query's binary
    search from log2(N) gather steps to log2(bucket occupancy) — ~24 vs
    ~6 B-sized gathers per search at N = 10M, and HBM gathers are the
    whole cost of computed-finger mode.
    """
    nb = 2 ** bits
    n = sorted_ids.shape[0]
    bvals = (jnp.arange(nb, dtype=jnp.uint32) << _u32(32 - bits))
    q = jnp.zeros((nb, LANES), _U32).at[:, 3].set(bvals)
    starts = searchsorted(sorted_ids, q).astype(jnp.int32)
    return jnp.concatenate([starts, jnp.full((1,), n, jnp.int32)])


def ring_successor_bucketed(sorted_ids: jax.Array, q: jax.Array,
                            starts: jax.Array, bits: int,
                            n_valid=None) -> jax.Array:
    """ring_successor() via a bucket_starts table — identical result.

    Capacity-padded tables work unchanged: padding rows are all-0xFF
    lanes, which sort after every real id and land in the last bucket,
    so the first index >= q is never a padding row unless q exceeds all
    real ids — exactly the wrap-to-0 case.
    """
    n = sorted_ids.shape[0]
    idx = searchsorted_bucketed(sorted_ids, q, starts, bits)
    limit = jnp.int32(n if n_valid is None else n_valid)
    return jnp.where(idx >= limit, 0, idx)


def searchsorted_bucketed(sorted_ids: jax.Array, q: jax.Array,
                          starts: jax.Array, bits: int) -> jax.Array:
    """searchsorted() with per-query bounds from a bucket_starts table.

    Exact for any id distribution (the binary search runs to
    convergence via while_loop); the bucket table only narrows the
    initial [lo, hi) window.
    """
    b = (q[..., 3] >> _u32(32 - bits)).astype(jnp.int32)
    lo = starts[b]
    hi = starts[b + 1]

    def cond(carry):
        lo, hi = carry
        return jnp.any(lo < hi)

    def body(carry):
        return _bisect_step(sorted_ids, q, *carry)

    lo, _ = jax.lax.while_loop(cond, body, (lo, hi))
    return lo


def sort_dedup_keys(keys: jax.Array):
    """Sort [K, 4] u32 keys lexicographically (lanes ride the sort as
    values — no index gather) and mask repeats + all-0xFFFFFFFF
    sentinels. Returns (sorted_keys [K, 4], ok [K] bool), ok marking the
    first instance of each real key. Shared by anti-entropy reconcile
    and the sharded local-maintenance candidate dedup (identical inline
    copies drifted before this helper existed)."""
    k3, k2, k1, k0 = jax.lax.sort(
        (keys[:, 3], keys[:, 2], keys[:, 1], keys[:, 0]), num_keys=4)
    s = jnp.stack([k0, k1, k2, k3], axis=1)
    dup = jnp.concatenate([jnp.zeros((1,), bool), eq(s[1:], s[:-1])])
    sentinel = jnp.all(s == jnp.uint32(0xFFFFFFFF), axis=1)
    return s, ~dup & ~sentinel

"""Pallas TPU kernel for batched IDA decode — inverse + matmul fused in VMEM.

The decode hot path (ida.py decode_kernel; ref ida.cpp:120-141 +
matrix_math.cpp:103-168) computes, per block, a mod-p inverse Vandermonde
from that block's fragment indices and applies it to the fragment rows.
Through XLA this is several kernels (the unrolled Lagrange chain, then a
broadcast-multiply-reduce) with [B, m, S]-sized intermediates round-tripping
HBM. Here the whole per-block pipeline — Lagrange synthetic division,
Fermat inverse of the denominators, coefficient scaling, and the m x m
matmul — runs fused in one Pallas program per batch tile, entirely in VMEM.

Kernel-shape choices (see /opt/skills/guides/pallas_guide.md):
  * every tensor op is >= 2-D with the segment axis (S, a multiple of 128
    in practice) last, so the VPU lanes stay full; the m-sized axes are
    tiny and ride the sublane dim;
  * the m-degree recurrences unroll at trace time (m is static), operating
    on [TB, 1] / [TB, m] tiles — no minor-dim transpose, stack, or gather;
  * the matmul is m^2 unrolled outer-product accumulations onto [TB, S]
    f32 tiles (exact: m * (p-1)^2 < 2^24, the same bound ops/modp.py
    enforces for its MXU path).

Parity with ops/modp.py's vandermonde_inverse + mod_matmul_batched_tiny is
pinned by tests/test_ida.py (interpret mode on CPU, compiled on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# f32 sublane height — one tile of blocks per program.
_TILE_B = 8


def _decode_tile_kernel(idx_ref, rows_ref, out_ref, *, m: int, p: int):
    """One batch tile: idx [TB, m] int32, rows [TB, m, S] int32 ->
    out [TB, m, S] int32 (segments transposed back by the caller)."""
    basis = idx_ref[:] % p                                   # [TB, m]

    # Master polynomial P(x) = prod_t (x - b_t), coefficients ascending,
    # kept as m+1 separate [TB, 1] columns so the recurrence never needs a
    # lane-axis shift/concat.
    tb = basis.shape[0]
    zero = jnp.zeros((tb, 1), jnp.int32)
    coeffs = [zero] * (m + 1)
    coeffs[0] = jnp.ones((tb, 1), jnp.int32)
    for t in range(m):
        b_t = basis[:, t:t + 1]                              # [TB, 1]
        new = [zero] * (m + 1)
        for j in range(m + 1):
            shifted = coeffs[j - 1] if j > 0 else zero
            new[j] = (shifted - b_t * coeffs[j]) % p
        coeffs = new

    # Synthetic division of P by (x - b_i) for all i at once, descending:
    # qs[k][b, i] = coeff of x^(m-1-k) in l_i's numerator.
    qs = [jnp.ones((tb, m), jnp.int32)]
    for k in range(1, m):
        qs.append((coeffs[m - k] + basis * qs[-1]) % p)

    # Denominators d_i = prod_{t != i} (b_i - b_t), then Fermat inverse.
    col = jax.lax.broadcasted_iota(jnp.int32, (tb, m), 1)
    denom = jnp.ones((tb, m), jnp.int32)
    for t in range(m):
        d = (basis - basis[:, t:t + 1]) % p
        d = jnp.where(col == t, 1, d)
        denom = (denom * d) % p
    inv_denom = jnp.ones((tb, m), jnp.int32)
    sq = denom
    e = p - 2
    while e > 0:
        if e & 1:
            inv_denom = (inv_denom * sq) % p
        sq = (sq * sq) % p
        e >>= 1

    # out[b, r, s] = sum_i inv[b, r, i] * rows[b, i, s] mod p, with
    # inv[b, r, i] = (qs[m-1-r][b, i] * inv_denom[b, i]) mod p. Unrolled
    # m^2 outer products accumulating f32 [TB, S] tiles.
    for r in range(m):
        acc = None
        for i in range(m):
            c = (qs[m - 1 - r][:, i:i + 1] * inv_denom[:, i:i + 1]) % p
            term = c.astype(jnp.float32) * rows_ref[:, i, :].astype(
                jnp.float32)
            acc = term if acc is None else acc + term
        out_ref[:, r, :] = acc.astype(jnp.int32) % p


@functools.partial(jax.jit, static_argnames=("p", "interpret"))
# chordax-lint: disable=gspmd-kernel-untraced -- single-core Pallas primitive (no GSPMD partitioning decisions in its body); traced in interpret mode and pinned against ida.decode_kernel by tests/test_ida.py
def decode_kernel_pallas(rows: jax.Array, indices: jax.Array, p: int,
                         interpret: bool = False) -> jax.Array:
    """Pallas twin of ida.decode_kernel: [B, m, S] rows + [B, m] 1-based
    indices -> [B, S, m] segments. `interpret=True` runs the kernel in the
    Pallas interpreter (CPU tests)."""
    b, m, s = rows.shape
    # Same exactness bound mod_matmul enforces: the kernel accumulates in
    # f32 and squares int32 residues, both of which overflow silently for
    # large p. The practical IDA modulus is 257.
    if m * (p - 1) * (p - 1) >= (1 << 24) or (p - 1) * (p - 1) > 2**31 - 1:
        raise ValueError(
            f"decode_kernel_pallas requires m*(p-1)^2 < 2^24 (exact f32 "
            f"accumulation), got m={m} p={p}; use ida.decode_kernel")
    if b == 0:
        return jnp.zeros((0, s, m), jnp.int32)
    pad = (-b) % _TILE_B
    if pad:
        rows = jnp.concatenate(
            [rows, jnp.zeros((pad, m, s), rows.dtype)], axis=0)
        # Padding rows still need DISTINCT indices: a singular Vandermonde
        # would divide by zero mod p. 1..m is always valid.
        indices = jnp.concatenate(
            [indices,
             jnp.broadcast_to(jnp.arange(1, m + 1, dtype=jnp.int32),
                              (pad, m))], axis=0)
    bp = rows.shape[0]

    out = pl.pallas_call(
        functools.partial(_decode_tile_kernel, m=m, p=p),
        grid=(bp // _TILE_B,),
        in_specs=[
            pl.BlockSpec((_TILE_B, m), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, m, s), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE_B, m, s), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, m, s), jnp.int32),
        interpret=interpret,
    )(indices.astype(jnp.int32), rows.astype(jnp.int32))

    return jnp.swapaxes(out[:b], -1, -2)

"""Jittable mod-p linear algebra — the compute kernel under the IDA.

The reference does scalar mod-p arithmetic on ``vector<int>`` one inner
product at a time (src/ida/matrix_math.cpp:26-55). On TPU the same math is a
batched integer matmul: fragment encode is ``[n, m] @ [m, S] mod p`` and
decode is an inverse-Vandermonde matmul, both over large block batches.

dtype strategy: values live in int32. When ``k * (p-1)^2 < 2^24`` the matmul
is lowered through float32 (exact — every intermediate fits the f32 mantissa)
so it rides the MXU; otherwise an int32 einsum with per-k modular reduction
is used. For the reference's defaults (m=10, p=257) the float path is exact:
10 * 256^2 = 655,360 << 2^24.

All functions are pure, shape-static, and vmap/jit/shard_map safe.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

_F32_EXACT_LIMIT = 1 << 24


def _float_path_exact(k: int, p: int) -> bool:
    """Is an f32 matmul with contraction length k over values < p exact?"""
    return k * (p - 1) * (p - 1) < _F32_EXACT_LIMIT


def mod_matmul(a: jax.Array, b: jax.Array, p: int) -> jax.Array:
    """``(a @ b) mod p`` over the trailing two dims; leading dims broadcast.

    a: [..., r, k] int32 with entries in [0, p)
    b: [..., k, c] int32 with entries in [0, p)
    returns [..., r, c] int32 in [0, p)

    Reference semantics: MatrixProduct (matrix_math.cpp:35-55) reduces mod p
    per multiply-add; since inputs are canonical (in [0, p)) the result is
    identical to reducing once at the end, which is what the MXU path does.
    """
    k = a.shape[-1]
    if (p - 1) * (p - 1) > 2**31 - 1:
        # The int32 fallback path forms individual a*b products; they must
        # fit int32 (p <= 46341). Same bound mod_pow documents.
        raise ValueError(f"mod_matmul requires (p-1)^2 < 2^31, got p={p}")
    if _float_path_exact(k, p):
        prod = jnp.matmul(
            a.astype(jnp.float32), b.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        )
        return (prod.astype(jnp.int32)) % p
    # Wide path: reduce in chunks small enough that int32 never overflows.
    chunk = max(1, (2**31 - 1) // max(1, (p - 1) * (p - 1)))
    out = jnp.zeros(a.shape[:-1] + (b.shape[-1],), dtype=jnp.int32)
    for start in range(0, k, chunk):
        end = min(k, start + chunk)
        part = jnp.einsum(
            "...rk,...kc->...rc",
            a[..., start:end].astype(jnp.int32),
            b[..., start:end, :].astype(jnp.int32),
        )
        out = (out + part % p) % p
    return out


def mod_matmul_batched_tiny(a: jax.Array, b: jax.Array, p: int) -> jax.Array:
    """``(a @ b) mod p`` for PER-BATCH tiny matrices — the decode shape.

    a: [..., r, k], b: [..., k, c], r and k tiny (IDA m=10), with a REAL
    batch dim on both sides. Lowering this through dot_general gives XLA a
    batched 10x10 MXU matmul: every batch element pads its operands to full
    systolic tiles, so ~99% of the array does padding work and throughput
    collapses (measured: decode at 93 MB/s vs encode at 22 GB/s on v5e —
    encode escapes because its broadcast LHS flattens into one dense
    matmul). A broadcast-multiply-reduce keeps the same exact f32 math on
    the VPU, where tiny contractions cost what they should.

    Exactness bound is mod_matmul's: k * (p-1)^2 < 2^24.

    The reduction is an unrolled k-step multiply-accumulate rather than a
    materialized [..., r, c, k] broadcast product: at the bench decode
    shape (B=8192, m=10, S=128) the broadcast intermediate would be a
    ~420 MB HBM tensor, a k-times blowup over the output (ADVICE r4);
    per-step peak here is one [..., r, c] f32 buffer, which XLA fuses.
    """
    if not _float_path_exact(a.shape[-1], p):
        return mod_matmul(a, b, p)  # wide path already chunks on the VPU
    a_f = a.astype(jnp.float32)
    b_f = b.astype(jnp.float32)
    acc = jnp.zeros(a.shape[:-1] + (b.shape[-1],), jnp.float32)
    for kk in range(a.shape[-1]):  # k is tiny (IDA m=10) and static
        acc = acc + (a_f[..., :, kk][..., None] *
                     b_f[..., kk, :][..., None, :])
    return acc.astype(jnp.int32) % p


def mod_pow(x: jax.Array, e: int, p: int) -> jax.Array:
    """x**e mod p elementwise; e, p static python ints (binary exponentiation).

    Requires (p-1)^2 < 2^31 so int32 products never overflow (p < 46341 —
    far above any practical IDA modulus; the reference uses 257).
    """
    x = jnp.asarray(x, dtype=jnp.int32) % p
    result = jnp.ones_like(x)
    while e > 0:
        if e & 1:
            result = (result * x) % p
        x = (x * x) % p
        e >>= 1
    return result


def mod_inverse(x: jax.Array, p: int) -> jax.Array:
    """Multiplicative inverse mod prime p via Fermat: x^(p-2) mod p.

    The reference uses extended Euclid (matrix_math.cpp:66-86); Fermat is the
    branch-free jittable equivalent for prime p (an IDA invariant,
    ida.cpp:54-56 requires it implicitly — non-prime p breaks decode).
    """
    return mod_pow(x, p - 2, p)


def vandermonde_matrix(n: int, m: int, p: int) -> np.ndarray:
    """Encoding matrix: row a-1 = [a^0, a^1, ..., a^(m-1)] mod p for a=1..n.

    Reference: ConstructEncodingMatrix (matrix_math.cpp:88-101). Host-side —
    it depends only on static params and is baked into the jitted encode.
    """
    rows = np.arange(1, n + 1, dtype=np.int64)
    out = np.ones((n, m), dtype=np.int64)
    for j in range(1, m):
        out[:, j] = (out[:, j - 1] * rows) % p
    return out.astype(np.int32)


def vandermonde_inverse(basis: jax.Array, p: int) -> jax.Array:
    """Inverse of the square Vandermonde V[i, j] = basis[i]^j, mod prime p.

    basis: [..., m] int32 of distinct values in [1, p) (fragment indices).
    returns [..., m, m] int32 with (V @ inv) == I mod p.

    Method (distinct from the reference's elementary-symmetric-polynomial
    construction at matrix_math.cpp:103-168, same unique result): Lagrange
    interpolation. inv[j, i] = coeff of x^j in l_i(x), where
    l_i(x) = prod_{t != i} (x - b_t) / prod_{t != i} (b_i - b_t).
    The numerator polynomials are all synthetic divisions of the master
    polynomial P(x) = prod_t (x - b_t) by (x - b_i) — O(m^2) total, fully
    vectorized over both the basis dim and any leading batch dims.
    """
    basis = jnp.asarray(basis, dtype=jnp.int32) % p
    m = basis.shape[-1]

    # Master polynomial coefficients c[0..m]: P(x) = prod (x - b_t).
    batch = basis.shape[:-1]
    coeffs = jnp.zeros(batch + (m + 1,), dtype=jnp.int32).at[..., 0].set(1)
    # Multiply (poly) by (x - b_t) iteratively; static m, unrolled.
    for t in range(m):
        b_t = basis[..., t : t + 1]
        # Shift-by-one via update-slice, NOT concatenate([zeros, slice]):
        # jax 0.4.x's SPMD partitioner miscompiles concat-of-slices on
        # sharded operands under GSPMD auto-sharding (the
        # two_phase_hop_loop merge rule; chordax-lint gspmd pass).
        shifted = jnp.zeros_like(coeffs).at[..., 1:].set(coeffs[..., :-1])
        coeffs = (shifted - b_t * coeffs) % p
    # coeffs[k] = coeff of x^k (ascending); coeffs[m] = 1 is the leading term.

    # Synthetic division of P by (x - b_i) for every i at once, descending:
    # q_i has degree m-1; q_i[0] = 1; q_i[k] = coeff_desc[k] + b_i * q_i[k-1],
    # where coeff_desc[k] = coeffs[m - k].
    qs = [jnp.broadcast_to(jnp.ones(batch + (m,), jnp.int32), batch + (m,))]
    for k in range(1, m):
        prev = qs[-1]
        qs.append((coeffs[..., m - k, None] + basis * prev) % p)
    q = jnp.stack(qs, axis=-1)  # [..., i, k], q[..., i, k] = coeff of x^(m-1-k)

    # Denominators d_i = prod_{t != i} (b_i - b_t) mod p, vectorized.
    diff = (basis[..., :, None] - basis[..., None, :]) % p  # [..., i, t]
    diff = jnp.where(jnp.eye(m, dtype=bool), 1, diff)
    denom = jnp.ones(batch + (m,), dtype=jnp.int32)
    for t in range(m):
        denom = (denom * diff[..., t]) % p
    inv_denom = mod_inverse(denom, p)  # [..., i]

    scaled = (q * inv_denom[..., None]) % p  # [..., i, k] coeff of x^(m-1-k)
    # inv[j, i] = coeff of x^j in l_i = scaled[i, m-1-j]  -> flip then transpose.
    return jnp.swapaxes(jnp.flip(scaled, axis=-1), -1, -2)

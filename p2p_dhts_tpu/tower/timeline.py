"""chordax-tower: the merged incident timeline (ISSUE 20).

Every signal an incident is made of already lands in per-process
rings: HAVOC plan installs/uninstalls, gateway ring-health
transitions, breaker opens and loop round-failures in the flight
recorder; SLO warn/breach/recovered crossings from the pulse engine
(also flight events); split/grow/shrink actions in the elastic
decision ledger. This module merges the COLLECTED tails of all of
them into one causally-ordered document: "19:02:01.213 gw-b havoc
plan_installed ... 19:02:01.940 gw-a pulse slo_breach ...
19:02:04.102 gw-b pulse slo_recovered" — the first page of any
postmortem, generated instead of reconstructed.

Ordering: events sort on (aligned wall time, peer, source, seq) —
peer walls are shifted by the collector's clock offsets first, and
the per-peer monotonic `seq` breaks same-millisecond ties in true
record order. The render is DETERMINISTIC (regression-tested): the
same event set in any arrival order produces byte-identical markdown.

Pure functions over plain dicts; stdlib only; never imports jax.
"""

from __future__ import annotations

import json
import time
from typing import List, Mapping, Optional, Sequence

__all__ = ["build_timeline", "render_markdown"]

#: Flight-event keys lifted into the timeline row proper; everything
#: else becomes sorted `detail` pairs.
_CORE_KEYS = ("t", "seq", "subsystem", "event")


def _detail(fields: Mapping, skip: Sequence[str]) -> str:
    """Deterministic one-line rendering of an event's extra fields:
    sorted key=value pairs, values via canonical JSON (repr-stable
    across runs, unlike str() of nested dicts)."""
    parts = []
    for k in sorted(fields):
        if k in skip:
            continue
        parts.append(f"{k}={json.dumps(fields[k], sort_keys=True, separators=(',', ':'), default=str)}")
    return " ".join(parts)


def build_timeline(events_by_peer: Mapping[str, Sequence[Mapping]],
                   ledger_by_peer: Optional[
                       Mapping[str, Sequence[Mapping]]] = None,
                   offsets: Optional[Mapping[str, float]] = None
                   ) -> List[dict]:
    """Normalize + merge + order every collected signal.

    `events_by_peer` holds flight-recorder events (`t`, `seq`,
    `subsystem`, `event`, fields) — which already includes HAVOC
    installs, ring transitions, SLO crossings and loop failures;
    `ledger_by_peer` holds elastic decision-ledger rows (rendered as
    subsystem "elastic", event = the row's action or "tick").
    `offsets` aligns peer walls onto the collector clock.

    Returns ordered rows: {"t" (aligned), "peer", "source", "seq",
    "subsystem", "event", "detail"}."""
    offsets = offsets or {}
    rows: List[dict] = []
    for peer in sorted(events_by_peer):
        off = float(offsets.get(peer, 0.0))
        for e in events_by_peer[peer]:
            rows.append({
                "t": float(e.get("t", 0.0)) + off,
                "peer": peer,
                "source": "flight",
                "seq": int(e.get("seq", -1)),
                "subsystem": str(e.get("subsystem", "?")),
                "event": str(e.get("event", "?")),
                "detail": _detail(e, _CORE_KEYS),
            })
    for peer in sorted(ledger_by_peer or {}):
        off = float(offsets.get(peer, 0.0))
        for e in (ledger_by_peer or {})[peer]:
            action = e.get("action") or e.get("decision") or "tick"
            rows.append({
                "t": float(e.get("t", 0.0)) + off,
                "peer": peer,
                "source": "ledger",
                "seq": int(e.get("seq", -1)),
                "subsystem": "elastic",
                "event": str(action),
                "detail": _detail(
                    e, ("t", "seq", "action", "decision")),
            })
    rows.sort(key=lambda r: (r["t"], r["peer"], r["source"],
                             r["seq"]))
    return rows


def render_markdown(rows: Sequence[Mapping],
                    title: str = "chordax incident timeline") -> str:
    """The timeline document: one markdown table, times both absolute
    (UTC, for cross-artifact correlation) and relative to the first
    event (for reading the incident's shape). Byte-identical for the
    same row set."""
    lines = [f"# {title}", ""]
    if not rows:
        lines.append("(no events)")
        return "\n".join(lines) + "\n"
    t0 = rows[0]["t"]
    lines.append("| time (UTC) | +s | peer | subsystem | event "
                 "| detail |")
    lines.append("|---|---|---|---|---|---|")
    for r in rows:
        stamp = time.strftime("%H:%M:%S",
                              time.gmtime(r["t"])) + \
            f".{int((r['t'] % 1.0) * 1000):03d}"
        rel = f"+{r['t'] - t0:.3f}"
        detail = r.get("detail", "").replace("|", "\\|")
        lines.append(f"| {stamp} | {rel} | {r['peer']} "
                     f"| {r['subsystem']} | {r['event']} "
                     f"| {detail} |")
    return "\n".join(lines) + "\n"

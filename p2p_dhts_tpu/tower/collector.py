"""chordax-tower: the fleet collector (ISSUE 20 — the tentpole).

One `health.PacedLoop` that turns N processes' private observability
rings into one queryable pool:

  * DISCOVERY — peers come from the epoch-stamped route table (any
    object with `.addresses()`: a mesh `RouteTable` or an edge
    `RouteCache`), so the collector follows joins, splits and
    retirements without its own membership protocol.
  * INCREMENTAL PULLS — per peer, per round: the span tail
    (TRACE_PULL SINCE/LIMIT), the flight-recorder tail + elastic
    ledger rows (HEALTH SINCE / LEDGER_SINCE), and pulse series
    deltas (PULSE SERIES, deduped client-side by last-seen point
    time). Every pull resumes a monotonic sequence cursor —
    duplicate-free across polls, eviction-visible (GAP counts are
    surfaced as `tower.collector.*_gap` counters, never swallowed).
  * CLOCK OFFSET — each TRACE_PULL reply carries the peer's wall
    clock; `offset = peer_wall - (t_send + rtt/2)` is the NTP-style
    RTT-midpoint sample, and the estimate keeps the sample with the
    SMALLEST rtt over a sliding window (the tightest bound wins).
    `stitch`/`timeline` shift each peer's walls by this estimate.
  * EXEMPLAR RETRACE — metrics exemplars (value, trace_id) pulled
    per round; `slow_traces(k)` stitches the top-k slowest exemplars'
    traces from the pool. A trace whose spans the incremental pulls
    already delivered costs NOTHING extra; only a pool miss falls
    back to a by-trace fetch (TRACE_STATUS TRACE_ID), counted in
    `tower.collector.retraces` — zero in steady state (bench-gated).
  * RETIREMENT — a peer leaving the route table retires its
    `tower.peer.*.<addr>` metric keys AND its cursor/pool state
    (the PR-8 rule: keys for departed instances never go stale,
    they go away), counted in `tower.peers_retired`.

LOCK ORDER: `Collector._lock` is a LEAF — held around pool/cursor
mutation only, never across an RPC. Pulls run on the loop thread;
accessors copy under the lock. This module never imports jax.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from p2p_dhts_tpu.health import PacedLoop
from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.net.rpc import Client as RpcClient
from p2p_dhts_tpu.tower import stitch as stitch_mod
from p2p_dhts_tpu.tower import timeline as timeline_mod

__all__ = ["Collector"]

#: Sliding window of (rtt, offset) samples per peer; the estimate is
#: the offset of the window's minimum-RTT sample.
OFFSET_WINDOW = 16

#: Per-peer retained pool bounds (spans / flight events / ledger rows
#: / pulse points per series).
SPAN_POOL = 16384
EVENT_POOL = 4096
LEDGER_POOL = 4096
PULSE_POOL = 512

#: Per-peer metric families the collector owns — retired (exact key,
#: addresses contain dots) when the peer leaves the route table.
_PEER_KEYS = ("tower.peer.offset_ms", "tower.peer.rtt_ms",
              "tower.peer.span_cursor")


class _PeerState:
    """One peer's cursors + clock-offset window (loop-thread only)."""

    __slots__ = ("span_cursor", "flight_cursor", "ledger_cursor",
                 "samples", "offset_s", "rtt_s", "pulse_last")

    def __init__(self) -> None:
        self.span_cursor = 0
        self.flight_cursor = 0
        self.ledger_cursor = 0
        self.samples: deque = deque(maxlen=OFFSET_WINDOW)
        self.offset_s = 0.0
        self.rtt_s: Optional[float] = None
        #: series id -> last ingested point time (the dedupe cursor —
        #: PULSE has no seq, but point times are strictly increasing
        #: per ring).
        self.pulse_last: Dict[str, float] = {}


class Collector(PacedLoop):
    """The fleet collector loop. `routes` is any object with
    `.addresses() -> [(ip, port), ...]`; the collector polls exactly
    that set each round."""

    def __init__(self, routes, *, metrics: Optional[Metrics] = None,
                 interval_s: float = 1.0,
                 span_limit: int = 2048, flight_tail: int = 512,
                 pulse_prefix: Optional[str] = None,
                 pulse_tail: int = 64,
                 timeout_s: float = 5.0,
                 pull_exemplars: bool = True,
                 registry=None):
        super().__init__(
            name="tower-collector", kind="tower",
            interval_s=interval_s, interval_idle_s=interval_s * 4,
            backoff_base_s=max(interval_s, 0.25), backoff_cap_s=30.0,
            metrics=metrics, failure_metric="tower.collector.failures",
            thread_name="tower-collector", registry=registry)
        self.routes = routes
        self.span_limit = int(span_limit)
        self.flight_tail = int(flight_tail)
        self.pulse_prefix = pulse_prefix
        self.pulse_tail = int(pulse_tail)
        self.timeout_s = float(timeout_s)
        self.pull_exemplars = bool(pull_exemplars)
        self._lock = threading.Lock()   # LEAF: pools + peer state
        self._peers: Dict[str, _PeerState] = {}
        self._spans: Dict[str, deque] = {}
        self._events: Dict[str, deque] = {}
        self._ledger: Dict[str, deque] = {}
        self._pulse: Dict[str, Dict[str, deque]] = {}
        #: peer -> hist name -> newest exemplar rows (value, trace_id).
        self._exemplars: Dict[str, Dict[str, List[dict]]] = {}

    # -- the round -----------------------------------------------------------
    def _addresses(self) -> List[Tuple[str, int]]:
        return [(str(ip), int(port))
                for ip, port in self.routes.addresses()]

    def _round(self) -> None:
        addrs = self._addresses()
        live = {f"{ip}:{port}" for ip, port in addrs}
        with self._lock:
            gone = [p for p in self._peers if p not in live]
        for peer in gone:
            self._retire(peer)
        for ip, port in addrs:
            peer = f"{ip}:{port}"
            try:
                self._pull_peer(peer, ip, port)
            # chordax-lint: disable=bare-except -- one unreachable peer must not stall the whole fleet's collection round
            except Exception:
                self.metrics.inc("tower.collector.pull_failures")
        self.rounds += 1

    def _rpc(self, ip: str, port: int, req: dict) -> dict:
        resp = RpcClient.make_request(ip, port, req,
                                      timeout=self.timeout_s)
        if resp.get("SUCCESS") is False:
            raise RuntimeError(
                f"{req.get('COMMAND')} failed: {resp.get('ERRORS')}")
        return resp

    def _pull_peer(self, peer: str, ip: str, port: int) -> None:
        with self._lock:
            st = self._peers.setdefault(peer, _PeerState())
        self._pull_spans(peer, st, ip, port)
        self._pull_health(peer, st, ip, port)
        if self.pulse_prefix is not None:
            self._pull_pulse(peer, st, ip, port)
        if self.pull_exemplars:
            self._pull_exemplars(peer, ip, port)

    def _pull_spans(self, peer: str, st: _PeerState, ip: str,
                    port: int) -> None:
        t_send = time.time()
        p0 = time.perf_counter()
        resp = self._rpc(ip, port, {"COMMAND": "TRACE_PULL",
                                    "SINCE": st.span_cursor,
                                    "LIMIT": self.span_limit})
        rtt = time.perf_counter() - p0
        # NTP-style midpoint sample: the peer stamped WALL somewhere
        # inside our [send, recv] window; assuming the midpoint bounds
        # the error by rtt/2. Keep the window's min-RTT sample — the
        # tightest bound, robust to one slow pull.
        wall = resp.get("WALL")
        if wall is not None:
            st.samples.append((rtt, float(wall) - (t_send + rtt / 2)))
            best = min(st.samples, key=lambda s: s[0])
            st.rtt_s, st.offset_s = best
        spans = resp.get("SPANS") or []
        gap = int(resp.get("GAP", 0) or 0)
        with self._lock:
            pool = self._spans.setdefault(peer,
                                          deque(maxlen=SPAN_POOL))
            pool.extend(spans)
            st.span_cursor = int(resp.get("NEXT", st.span_cursor))
        if spans:
            self.metrics.inc("tower.collector.spans_pulled",
                             len(spans))
        if gap:
            self.metrics.inc("tower.collector.span_gap", gap)
        self.metrics.gauge(f"tower.peer.span_cursor.{peer}",
                           st.span_cursor)
        self.metrics.gauge(f"tower.peer.offset_ms.{peer}",
                           round(st.offset_s * 1e3, 3))
        if st.rtt_s is not None:
            self.metrics.gauge(f"tower.peer.rtt_ms.{peer}",
                               round(st.rtt_s * 1e3, 3))

    def _pull_health(self, peer: str, st: _PeerState, ip: str,
                     port: int) -> None:
        resp = self._rpc(ip, port,
                         {"COMMAND": "HEALTH",
                          "SINCE": st.flight_cursor,
                          "TAIL": self.flight_tail,
                          "LEDGER_SINCE": st.ledger_cursor})
        health = resp.get("HEALTH") or {}
        flight = health.get("FLIGHT") or {}
        events = flight.get("tail") or []
        with self._lock:
            pool = self._events.setdefault(peer,
                                           deque(maxlen=EVENT_POOL))
            pool.extend(events)
            st.flight_cursor = int(flight.get("next_seq",
                                              st.flight_cursor))
        if events:
            self.metrics.inc("tower.collector.events_pulled",
                             len(events))
        gap = int(flight.get("gap", 0) or 0)
        if gap:
            self.metrics.inc("tower.collector.event_gap", gap)
        ledger = health.get("LEDGER")
        if ledger is not None:
            rows = ledger.get("rows") or []
            with self._lock:
                pool = self._ledger.setdefault(
                    peer, deque(maxlen=LEDGER_POOL))
                pool.extend(rows)
                st.ledger_cursor = int(ledger.get("next_seq",
                                                  st.ledger_cursor))
            if rows:
                self.metrics.inc("tower.collector.ledger_pulled",
                                 len(rows))
            lgap = int(ledger.get("gap", 0) or 0)
            if lgap:
                self.metrics.inc("tower.collector.ledger_gap", lgap)

    def _pull_pulse(self, peer: str, st: _PeerState, ip: str,
                    port: int) -> None:
        sel = self.pulse_prefix if self.pulse_prefix else True
        resp = self._rpc(ip, port, {"COMMAND": "PULSE", "SERIES": sel,
                                    "TAIL": self.pulse_tail})
        series = resp.get("SERIES") or {}
        fresh = 0
        with self._lock:
            rings = self._pulse.setdefault(peer, {})
            for sid, pts in series.items():
                last = st.pulse_last.get(sid, float("-inf"))
                ring = rings.setdefault(sid, deque(maxlen=PULSE_POOL))
                for t, v in pts:
                    # Dedupe on point time: PULSE tails overlap across
                    # polls by design; only strictly-newer points land.
                    if t > last:
                        ring.append((t, v))
                        last = t
                        fresh += 1
                st.pulse_last[sid] = last
        if fresh:
            self.metrics.inc("tower.collector.pulse_points", fresh)

    def _pull_exemplars(self, peer: str, ip: str, port: int) -> None:
        resp = self._rpc(ip, port, {"COMMAND": "METRICS",
                                    "EXEMPLARS": 1})
        ex = resp.get("EXEMPLARS") or {}
        if ex:
            with self._lock:
                self._exemplars[peer] = {
                    str(h): [dict(r) for r in rows]
                    for h, rows in ex.items()}

    # -- retirement (the PR-8 rule) ------------------------------------------
    def _retire(self, peer: str) -> None:
        """Drop a departed peer's cursors, pools and per-peer metric
        keys — addresses contain dots, so remove_prefix matches the
        exact assembled key (the mesh plane's retirement idiom)."""
        with self._lock:
            self._peers.pop(peer, None)
            self._spans.pop(peer, None)
            self._events.pop(peer, None)
            self._ledger.pop(peer, None)
            self._pulse.pop(peer, None)
            self._exemplars.pop(peer, None)
        for fam in _PEER_KEYS:
            self.metrics.remove_prefix(f"{fam}.{peer}")
        self.metrics.inc("tower.peers_retired")

    # -- accessors (copy under the leaf lock) --------------------------------
    def peers(self) -> List[str]:
        with self._lock:
            return sorted(self._peers)

    def offsets(self) -> Dict[str, float]:
        """peer -> seconds to ADD to that peer's wall stamps to land
        on the collector's clock (the stitch/timeline alignment
        input). The estimate's sign convention: a peer whose clock
        runs AHEAD has a positive raw offset, so alignment SUBTRACTS
        it — hence the negation here."""
        with self._lock:
            return {p: -st.offset_s for p, st in self._peers.items()}

    def spans_by_peer(self) -> Dict[str, List[dict]]:
        with self._lock:
            return {p: list(d) for p, d in self._spans.items()}

    def events_by_peer(self) -> Dict[str, List[dict]]:
        with self._lock:
            return {p: list(d) for p, d in self._events.items()}

    def ledger_by_peer(self) -> Dict[str, List[dict]]:
        with self._lock:
            return {p: list(d) for p, d in self._ledger.items()}

    def pulse_series(self, peer: str) -> Dict[str, List[Tuple]]:
        with self._lock:
            return {sid: list(ring) for sid, ring
                    in self._pulse.get(peer, {}).items()}

    def exemplars_by_peer(self) -> Dict[str, Dict[str, List[dict]]]:
        with self._lock:
            return {p: {h: list(rows) for h, rows in fams.items()}
                    for p, fams in self._exemplars.items()}

    # -- the stitched artifacts ----------------------------------------------
    def stitch(self, trace_id: str) -> str:
        """One trace's cross-process Chrome export from the pool."""
        return stitch_mod.stitch_trace(self.spans_by_peer(),
                                       trace_id, self.offsets())

    def timeline(self, title: str = "chordax incident timeline"
                 ) -> str:
        """The merged incident timeline over everything collected."""
        rows = timeline_mod.build_timeline(self.events_by_peer(),
                                           self.ledger_by_peer(),
                                           self.offsets())
        return timeline_mod.render_markdown(rows, title=title)

    def slow_traces(self, k: int = 3,
                    hist: Optional[str] = None) -> List[dict]:
        """The top-k slowest exemplars across the fleet (optionally
        one histogram family), each with its stitched cross-process
        export. Steady state is FREE: the incremental span pulls
        already delivered the trace's spans, so stitching is a pool
        read. Only a pool miss (the trace raced eviction, or landed
        after the last pull) falls back to a by-trace TRACE_STATUS
        fetch from every peer — counted in `tower.collector.retraces`
        and asserted ZERO by the bench's steady-state gate."""
        rows = []
        for peer, fams in self.exemplars_by_peer().items():
            for h, exes in fams.items():
                if hist is not None and h != hist:
                    continue
                for e in exes:
                    if e.get("trace_id"):
                        rows.append({"peer": peer, "hist": h,
                                     "value": float(e["value"]),
                                     "trace_id": str(e["trace_id"])})
        rows.sort(key=lambda r: (-r["value"], r["trace_id"]))
        top: List[dict] = []
        seen = set()
        for r in rows:
            if r["trace_id"] in seen:
                continue
            seen.add(r["trace_id"])
            top.append(r)
            if len(top) >= int(k):
                break
        pool = self.spans_by_peer()
        offsets = self.offsets()
        for r in top:
            tid = r["trace_id"]
            if not any(s.get("trace_id") == tid
                       for spans in pool.values() for s in spans):
                self._retrace(tid, pool)
            r["chrome"] = stitch_mod.stitch_trace(pool, tid, offsets)
        return top

    def _retrace(self, trace_id: str,
                 pool: Dict[str, List[dict]]) -> None:
        """Pool-miss fallback: fetch one trace's spans by id from
        every live peer (TRACE_STATUS TRACE_ID). Counted — the bench
        asserts this stays zero in steady state."""
        self.metrics.inc("tower.collector.retraces")
        for ip, port in self._addresses():
            peer = f"{ip}:{port}"
            try:
                resp = self._rpc(ip, port,
                                 {"COMMAND": "TRACE_STATUS",
                                  "TRACE_ID": trace_id})
            # chordax-lint: disable=bare-except -- a retrace is best-effort enrichment; a dead peer's spans are simply absent
            except Exception:
                continue
            spans = resp.get("SPANS") or []
            if spans:
                pool.setdefault(peer, []).extend(spans)

    def status(self) -> dict:
        with self._lock:
            return {
                "peers": sorted(self._peers),
                "spans": {p: len(d) for p, d in self._spans.items()},
                "events": {p: len(d)
                           for p, d in self._events.items()},
                "ledger": {p: len(d)
                           for p, d in self._ledger.items()},
                "offsets_ms": {p: round(-st.offset_s * 1e3, 3)
                               for p, st in self._peers.items()},
            }

"""chordax-tower: fleet observability (ISSUE 20).

One process's chordax-scope planes (spans, flight recorder, pulse
series, elastic ledger) already answer "what happened HERE"; tower
answers "what happened to the FLEET, in one artifact". Four pieces:

  * `Collector` (collector.py) — a PacedLoop that discovers peers from
    the epoch-stamped route table and incrementally pulls each
    process's span tail (TRACE_PULL), flight/ledger tails (HEALTH
    SINCE / LEDGER_SINCE) and pulse deltas over the wire — duplicate-
    free monotonic cursors, eviction-visible gaps, and a per-peer
    clock offset estimated from pull RTT midpoints.
  * `stitch` (stitch.py) — assembles every pulled span sharing a
    trace_id into ONE Chrome/Perfetto export with one pid-lane per
    process, wall-clock aligned by the per-peer offsets.
  * `timeline` (timeline.py) — merges flight events, HAVOC plan
    installs, elastic ledger actions, membership/ring transitions and
    SLO burn-rate crossings into one causally-ordered markdown
    incident timeline.
  * `Canary` (canary.py) — a black-box prober driving synthetic
    per-shard GET/PUT/lookup probes through a dedicated `edge.Client`
    (counted, rate-capped, NOCACHE so probes never warm the hot-key
    cache), feeding `tower.canary.availability/p99.<shard>` gauges and
    an availability SLO the pulse engine burns against.

Everything here is stdlib + numpy; no module imports jax.
"""

from p2p_dhts_tpu.tower.canary import Canary
from p2p_dhts_tpu.tower.collector import Collector
from p2p_dhts_tpu.tower.stitch import (stitch_chrome, stitch_trace,
                                       wall_start)
from p2p_dhts_tpu.tower.timeline import (build_timeline,
                                         render_markdown)

__all__ = [
    "Canary",
    "Collector",
    "build_timeline",
    "render_markdown",
    "stitch_chrome",
    "stitch_trace",
    "wall_start",
]

"""chordax-tower: cross-process trace stitching (ISSUE 20).

`SpanStore.export_chrome` renders ONE process's spans on its private
perf_counter timeline; a hedged cross-shard request leaves spans in
two, three, four processes and those timelines are incomparable on the
wire. The stitcher fixes both halves:

  * TIME — every span carries a wall-clock completion stamp (`wall`,
    trace.record_span); `wall - (t1 - t0)` is its wall START, and
    shifting each peer's walls by the collector's estimated clock
    offset (RTT-midpoint, NTP-style) puts every process on one shared
    timeline. Sub-millisecond skew is not the goal — causal ordering
    of multi-millisecond RPC hops is, and the offset bound is the
    pull's RTT/2.
  * LANES — one Chrome `pid` lane per process, assigned in sorted
    peer-name order with `process_name` metadata events, so the
    Perfetto view reads "gateway A called gateway B" top to bottom.

DETERMINISM CONTRACT (regression-tested): the export is a pure
function of the span SET — any arrival order, any per-peer
interleaving, produces byte-identical JSON. Events sort on the
canonical key (ts, pid, seq, span_id); JSON renders with sorted keys
and fixed separators.

Pure functions over plain dicts; stdlib only; never imports jax.
"""

from __future__ import annotations

import json
from typing import Dict, List, Mapping, Optional, Sequence

__all__ = ["stitch_chrome", "stitch_trace", "wall_start"]


def wall_start(span: Mapping) -> float:
    """A span's wall-clock START instant. Spans are stamped with wall
    time at COMPLETION (they land in the store when they finish), so
    the start is `wall - duration`. Spans from a pre-tower peer
    (no `wall`) fall back to t0 — unaligned but never dropped."""
    if "wall" in span:
        return float(span["wall"]) - max(
            float(span["t1"]) - float(span["t0"]), 0.0)
    return float(span["t0"])


def _canonical_event(span: Mapping, pid: int, base: float,
                     offset: float) -> dict:
    """One Chrome `ph: "X"` complete event on the stitched timeline.
    ts is microseconds from the stitched epoch `base` after shifting
    this peer's walls by `offset` (peer clock -> collector clock)."""
    args = dict(span.get("args") or {})
    args["trace_id"] = span["trace_id"]
    args["span_id"] = span["span_id"]
    if span.get("parent_id"):
        args["parent_id"] = span["parent_id"]
    if span.get("links"):
        args["links"] = list(span["links"])
    if "seq" in span:
        args["seq"] = int(span["seq"])
    return {
        "name": span["name"],
        "cat": span.get("cat") or "chordax",
        "ph": "X",
        "ts": round((wall_start(span) + offset - base) * 1e6, 1),
        "dur": round(max(float(span["t1"]) - float(span["t0"]), 0.0)
                     * 1e6, 1),
        "pid": pid,
        "tid": int(span.get("tid", 0)),
        "args": args,
    }


def stitch_chrome(spans_by_peer: Mapping[str, Sequence[Mapping]],
                  offsets: Optional[Mapping[str, float]] = None
                  ) -> str:
    """Stitch every peer's spans into one Chrome trace-event JSON
    document: one pid lane per peer (sorted peer order, pid 1..N, with
    `process_name` metadata), wall-aligned via `offsets` (peer ->
    seconds to ADD to that peer's wall clocks; absent peers shift 0).

    Byte-identical for any arrival order of the same span set: lanes
    come from sorted names, events from a canonical sort, and the JSON
    from sorted keys + fixed separators."""
    offsets = offsets or {}
    peers = sorted(spans_by_peer)
    # Stitched epoch: the earliest ALIGNED wall start anywhere, so
    # every ts is >= 0 regardless of which peer's span began first.
    base = 0.0
    starts = [wall_start(s) + float(offsets.get(p, 0.0))
              for p in peers for s in spans_by_peer[p]]
    if starts:
        base = min(starts)
    events: List[dict] = []
    for pid, peer in enumerate(peers, start=1):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": peer},
        })
    rows: List[dict] = []
    for pid, peer in enumerate(peers, start=1):
        off = float(offsets.get(peer, 0.0))
        for s in spans_by_peer[peer]:
            rows.append(_canonical_event(s, pid, base, off))
    rows.sort(key=lambda e: (e["ts"], e["pid"],
                             e["args"].get("seq", -1),
                             e["args"]["span_id"]))
    events.extend(rows)
    return json.dumps({"traceEvents": events,
                       "displayTimeUnit": "ms"},
                      sort_keys=True, separators=(",", ":"))


def stitch_trace(spans_by_peer: Mapping[str, Sequence[Mapping]],
                 trace_id: str,
                 offsets: Optional[Mapping[str, float]] = None
                 ) -> str:
    """One trace's stitched export: filter every peer's pool to
    `trace_id`, keep only peers that contributed a span (lane count ==
    process count in the trace — the bench's >= 2-process gate reads
    it straight off the metadata events), then stitch."""
    subset: Dict[str, List[Mapping]] = {}
    for peer, spans in spans_by_peer.items():
        mine = [s for s in spans if s.get("trace_id") == trace_id]
        if mine:
            subset[peer] = mine
    return stitch_chrome(subset, offsets)

"""chordax-tower: black-box canary probing (ISSUE 20).

Every other signal in the fleet is WHITE-box — the process reporting
on itself. The canary is the outside view: a PacedLoop driving
synthetic GET / PUT / lookup probes at every shard through a
DEDICATED `edge.Client`, measuring what a real client would see
(routing, folding, breakers — everything but hedging, which is
disabled so one probe measures ONE gateway's honest latency).

Probe discipline:

  * PER-SHARD — one probe key per shard: the shard's LOWEST owned key
    (`RouteTable.shard_of`), stable across rounds, guaranteed to
    route to that member. Storage cost is bounded at one canary value
    per shard, reused forever.
  * COUNTED — every probe increments `tower.canary.probes`; failures
    increment `tower.canary.failures` — the availability SLO's
    numerator/denominator (`slo_spec()` wires them to the pulse
    engine). A GET that cleanly answers "not found" is AVAILABLE:
    the canary measures the serving path, not data presence.
  * RATE-CAPPED — a token bucket (`rate_cap_per_s`) clips the probe
    budget per round; clipped probes count `tower.canary.rate_capped`
    and are skipped, never queued (a slow fleet must not accumulate
    probe debt).
  * CACHE-EXCLUDED — the probe client stamps `NOCACHE: 1` on every
    request, so the same probe key hitting every round can never warm
    the hot-key cache and fake availability from memory.

Gauges `tower.canary.availability.<shard>` (percent, windowed) and
`tower.canary.p99.<shard>` (ms) publish the outside view per shard; a
shard leaving the route table retires both keys and its window (the
PR-8 rule), counted in `tower.canary.shards_retired`.

LOCK ORDER: no new locks — windows are loop-thread-only state; the
edge client's own leaf lock is internal. Never imports jax.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from p2p_dhts_tpu.edge.client import Client as EdgeClient
from p2p_dhts_tpu.health import PacedLoop
from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.net.rpc import Client as RpcClient

__all__ = ["Canary"]

#: Default sliding probe window per shard (availability/p99 horizon).
DEFAULT_WINDOW = 64

#: Per-shard gauge families the canary owns — retired with the shard.
_SHARD_KEYS = ("tower.canary.availability", "tower.canary.p99")


class Canary(PacedLoop):
    """The black-box prober. `gateways` seeds the probe client's route
    cache; the probed shard set then follows the live table."""

    def __init__(self, gateways, *, metrics: Optional[Metrics] = None,
                 interval_s: float = 1.0, window: int = DEFAULT_WINDOW,
                 rate_cap_per_s: float = 50.0,
                 deadline_ms: float = 1000.0,
                 put_payload: Optional[Tuple[np.ndarray, int]] = None,
                 client: Optional[EdgeClient] = None,
                 registry=None):
        super().__init__(
            name="tower-canary", kind="tower",
            interval_s=interval_s, interval_idle_s=interval_s,
            backoff_base_s=max(interval_s, 0.25), backoff_cap_s=30.0,
            metrics=metrics, failure_metric="tower.canary.round_failures",
            thread_name="tower-canary", registry=registry)
        # The DEDICATED probe client: folds never mix across Client
        # instances, so NOCACHE stamps probes only; hedging is off so
        # a probe's latency is one gateway's honest answer, not the
        # min of two.
        self.client = client if client is not None else EdgeClient(
            gateways, metrics=self.metrics, hedge_enabled=False,
            request_fields={"NOCACHE": 1})
        self._owns_client = client is None
        self.deadline_ms = float(deadline_ms)
        self.window = int(window)
        self.rate_cap_per_s = float(rate_cap_per_s)
        self.put_payload = put_payload
        #: shard label ("ip:port") -> deque[(ok, seconds)].
        self._windows: Dict[str, deque] = {}
        self._tokens = float(rate_cap_per_s)
        self._last_refill = time.monotonic()

    # -- the round -----------------------------------------------------------
    def _shards(self) -> List[Tuple[str, int]]:
        """[(shard label, probe key)] from the live table: the probe
        key is the shard's lowest owned key — stable, member-owned."""
        self.client.routes.ensure()
        table = self.client.routes.table
        out = []
        for member, addr in sorted(table.peers().items()):
            rng = table.shard_of(member)
            if rng is None:
                continue
            out.append((f"{addr[0]}:{addr[1]}", int(rng[0])))
        return out

    def _admit(self, n: int) -> int:
        """Token-bucket clip: how many of `n` wanted probes run this
        round. Clipped probes are counted and DROPPED (no debt)."""
        now = time.monotonic()
        self._tokens = min(
            self.rate_cap_per_s,
            self._tokens + (now - self._last_refill)
            * self.rate_cap_per_s)
        self._last_refill = now
        grant = int(min(n, self._tokens))
        self._tokens -= grant
        if grant < n:
            self.metrics.inc("tower.canary.rate_capped", n - grant)
        return grant

    def _round(self) -> None:
        shards = self._shards()
        live = {label for label, _ in shards}
        for label in [s for s in self._windows if s not in live]:
            self._retire_shard(label)
        per_shard = 3 if self.put_payload is not None else 2
        budget = self._admit(len(shards) * per_shard)
        for label, key in shards:
            if budget < per_shard:
                break
            budget -= per_shard
            self._probe_shard(label, key)
        self.rounds += 1

    def _probe_shard(self, label: str, key: int) -> None:
        probes = [("lookup", lambda: self._lookup(key)),
                  ("get", lambda: self._get(key))]
        if self.put_payload is not None:
            probes.append(("put", lambda: self._put(key)))
        win = self._windows.setdefault(label,
                                       deque(maxlen=self.window))
        for kind, fn in probes:
            t0 = time.perf_counter()
            try:
                ok = bool(fn())
            # chordax-lint: disable=bare-except -- a probe failure IS the measurement; it lands in the window, never kills the loop
            except Exception:
                ok = False
            dt = time.perf_counter() - t0
            win.append((ok, dt))
            self.metrics.inc("tower.canary.probes")
            if not ok:
                self.metrics.inc("tower.canary.failures")
                self.metrics.inc(f"tower.canary.failed.{kind}")
        self._publish(label, win)

    def _lookup(self, key: int) -> bool:
        res = self.client.find_successor([key],
                                         deadline_ms=self.deadline_ms)
        return not res.failed.any()

    def _get(self, key: int) -> bool:
        # A clean miss (ok=False, failed=False) is AVAILABLE: the path
        # answered; the canary does not require its key to exist.
        res = self.client.get([key], deadline_ms=self.deadline_ms)
        return not res.failed.any()

    def _put(self, key: int) -> bool:
        segments, length = self.put_payload
        owner = self.client.routes.table.owner(key)
        if owner is None:
            return False
        ip, port = owner[1]
        resp = RpcClient.make_request(
            str(ip), int(port),
            {"COMMAND": "PUT", "KEY": format(int(key), "x"),
             "SEGMENTS": np.ascontiguousarray(segments, np.int32),
             "LENGTH": int(length), "NOCACHE": 1,
             "DEADLINE_MS": self.deadline_ms},
            timeout=self.deadline_ms / 1e3 + 1.0)
        return bool(resp.get("SUCCESS"))

    # -- publication + retirement --------------------------------------------
    def _publish(self, label: str, win: deque) -> None:
        oks = [1.0 if ok else 0.0 for ok, _ in win]
        lats = sorted(dt for ok, dt in win if ok)
        pct = 100.0 * sum(oks) / len(oks) if oks else 0.0
        self.metrics.gauge(f"tower.canary.availability.{label}",
                           round(pct, 3))
        if lats:
            p99 = lats[min(len(lats) - 1,
                           int(0.99 * (len(lats) - 1) + 0.5))]
            self.metrics.gauge(f"tower.canary.p99.{label}",
                               round(p99 * 1e3, 3))

    def _retire_shard(self, label: str) -> None:
        """A shard left the table: its windows and gauge keys go AWAY
        (exact-key remove_prefix — labels contain dots), never stale."""
        self._windows.pop(label, None)
        for fam in _SHARD_KEYS:
            self.metrics.remove_prefix(f"{fam}.{label}")
        self.metrics.inc("tower.canary.shards_retired")

    # -- introspection -------------------------------------------------------
    def availability(self) -> Optional[float]:
        """Fleet-wide windowed availability percent (None before any
        probe) — what the bench compares against its own measured
        success rate."""
        total = ok = 0
        for win in self._windows.values():
            total += len(win)
            ok += sum(1 for o, _ in win if o)
        return 100.0 * ok / total if total else None

    def shard_labels(self) -> List[str]:
        return sorted(self._windows)

    def slo_spec(self, *, target_pct: float = 99.0,
                 window_s: float = 60.0,
                 long_window_s: float = 300.0) -> dict:
        """The availability Slo over the probe counters — hand to
        `pulse.SloEngine` so canary failures burn an error budget like
        any first-class objective."""
        return {"name": "tower.canary", "kind": "availability",
                "total": "tower.canary.probes",
                "errors": "tower.canary.failures",
                "target_pct": float(target_pct),
                "window_s": float(window_s),
                "long_window_s": float(long_window_s)}

    def close(self, timeout: float = 30.0) -> None:
        super().close(timeout)
        if self._owns_client:
            self.client.close()

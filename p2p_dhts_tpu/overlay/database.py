"""Thread-safe per-peer database over the Merkle index.

ref src/data_structures/database.h: GenericDB<V> = MerkleTree index +
size counter behind read/write locks; aliases FragmentDb =
GenericDB<DataFragment> and TextDb = GenericDB<std::string>
(database.h:200-201).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from p2p_dhts_tpu.overlay.merkle_tree import MerkleTree


class GenericDB:
    """ref GenericDB<ValueType> (database.h:28-201)."""

    def __init__(self):
        self._index = MerkleTree()
        self._size = 0
        self._lock = threading.RLock()

    def insert(self, key: int, val: object) -> None:
        with self._lock:
            existed = self._index.contains(key)
            self._index.insert(int(key), val)
            if not existed:
                self._size += 1

    def lookup(self, key: int) -> object:
        with self._lock:
            return self._index.lookup(int(key))

    def update(self, key: int, val: object) -> None:
        with self._lock:
            self._index.update(int(key), val)

    def delete(self, key: int) -> None:
        with self._lock:
            self._index.delete(int(key))
            self._size -= 1

    def contains(self, key: int) -> bool:
        with self._lock:
            return self._index.contains(int(key))

    def read_range(self, lb: int, ub: int) -> Dict[int, object]:
        with self._lock:
            return self._index.read_range(lb, ub)

    def next(self, key: int) -> Optional[Tuple[int, object]]:
        with self._lock:
            return self._index.next(key)

    def get_entries(self) -> List[Tuple[int, object]]:
        with self._lock:
            return self._index.get_entries()

    def get_index(self) -> MerkleTree:
        return self._index

    @property
    def size(self) -> int:
        with self._lock:
            return self._size

    def __len__(self) -> int:
        return self.size


# ref database.h:200-201
TextDb = GenericDB
FragmentDb = GenericDB

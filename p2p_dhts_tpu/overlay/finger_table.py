"""128-entry finger table with a ``backend="jax"`` batched lookup path.

ref src/data_structures/finger_table.h: entry i covers
[start + 2^i, start + 2^(i+1) - 1] (GetNthRange, finger_table.h:177-188);
Lookup returns the successor of the range containing the key via a linear
scan (finger_table.h:115-130); AdjustFingers rewrites entries covered by
a new peer's range (finger_table.h:148-157); ReplaceDeadPeer swaps every
entry naming a dead peer (finger_table.h:159-168).

The jax backend is the BASELINE.json north star hook: the table's ranges
are fixed, so "which entry contains key k" is bit_length((k - start) mod
2^128) - 1 — the O(1) closed form of the linear scan. Batched lookup
lives in the device core (core/ring.find_successor), not here: the host
overlay is the per-request wire-parity layer and resolves one key per
RPC exactly like the reference.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Tuple

from p2p_dhts_tpu.keyspace import KEYS_IN_RING, Key
from p2p_dhts_tpu.overlay.remote_peer import RemotePeer

logger = logging.getLogger(__name__)


class Finger:
    """ref struct Finger (finger_table.h:20-28)."""

    __slots__ = ("lower_bound", "upper_bound", "successor")

    def __init__(self, lower_bound: Key, upper_bound: Key,
                 successor: RemotePeer):
        self.lower_bound = Key(lower_bound)
        self.upper_bound = Key(upper_bound)
        self.successor = successor


class FingerTable:
    """ref FingerTable<PeerType> (finger_table.h:30-288)."""

    NUM_ENTRIES = 128  # binary key length (finger_table.h:44, key.h:152-155)

    #: After a device-resolve failure the table serves the host closed
    #: form for this long, then RETRIES the device path — a recovered
    #: TPU tunnel puts the device back in service without a restart
    #: (round-5 advisor #3: the old bare except degraded forever).
    DEGRADED_RETRY_S = 30.0

    def __init__(self, starting_key: Key, backend: str = "python"):
        if backend not in ("python", "jax"):
            raise ValueError(f"unknown backend {backend!r}")
        self.starting_key = Key(starting_key)
        self.backend = backend
        self._table: List[Finger] = []
        self._lock = threading.RLock()
        self._resolver = None  # engine-backed resolver, built on first use
        #: Visible degradation state: True while device resolves are
        #: failing and lookups fall back to the host closed form.
        self.degraded = False
        self._degraded_logged = False
        self._retry_at = 0.0
        # Dedicated lock for the degradation state: lookup() runs the
        # device resolve with the TABLE lock released (so worker
        # threads can share batches), so these transitions need their
        # own serialization — it is never held across the device call.
        self._degrade_lock = threading.Lock()
        self._probe_inflight = False

    def _device_resolver(self):
        """Lazy batching bridge, built through the gateway so the
        overlay's lookups and the RPC front door share ONE finger
        engine (cross-table AND cross-path batching); falls back to a
        bare EngineFingerResolver if the gateway layer cannot be
        built, then to the legacy per-table DeviceFingerResolver if
        the engine layer itself cannot be."""
        with self._lock:
            if self._resolver is None:
                try:
                    from p2p_dhts_tpu.gateway import global_gateway
                    self._resolver = global_gateway().finger_resolver(
                        int(self.starting_key))
                # chordax-lint: disable=bare-except -- any gateway/engine construction failure must fall back down the chain
                except Exception:
                    try:
                        from p2p_dhts_tpu.serve import EngineFingerResolver
                        self._resolver = EngineFingerResolver(
                            int(self.starting_key))
                    # chordax-lint: disable=bare-except -- any engine-layer construction failure must fall back to the legacy bridge
                    except Exception:
                        from p2p_dhts_tpu.overlay.jax_bridge import (
                            DeviceFingerResolver)
                        self._resolver = DeviceFingerResolver(
                            int(self.starting_key))
            return self._resolver

    def _device_lookup_index(self, key: Key) -> int:
        """Device-path entry resolve with visible, recoverable
        degradation: a failure logs ONCE (with traceback), flips
        `degraded`, and starts serving the semantics-identical host
        closed form; the device path is retried every
        DEGRADED_RETRY_S by ONE prober at a time (concurrent workers
        keep serving host-side — no exception storm against a dead
        backend), and a successful retry clears the flag."""
        probing = False
        with self._degrade_lock:
            if self.degraded:
                if (time.monotonic() < self._retry_at
                        or self._probe_inflight):
                    return self._host_closed_form_index(key)
                self._probe_inflight = True
                probing = True
        try:
            idx = self._device_resolver().lookup_index(int(key))
        # chordax-lint: disable=bare-except -- device backend raises arbitrary init errors; visible degradation + retry handles them
        except Exception:
            # jax missing OR its backend unusable (dead TPU tunnel
            # raises RuntimeError at init — a state this host regularly
            # sees): the wire path must keep serving.
            with self._degrade_lock:
                if probing:
                    self._probe_inflight = False
                self._retry_at = time.monotonic() + self.DEGRADED_RETRY_S
                if not self._degraded_logged:
                    logger.warning(
                        "device finger resolve failed; serving host "
                        "closed form (retry in %.0fs)",
                        self.DEGRADED_RETRY_S, exc_info=True)
                    self._degraded_logged = True
                self.degraded = True
            return self._host_closed_form_index(key)
        with self._degrade_lock:
            if probing:
                self._probe_inflight = False
            if self.degraded:
                logger.warning("device finger resolve recovered; leaving "
                               "degraded mode")
                self.degraded = False
                self._degraded_logged = False
        return idx

    def _host_closed_form_index(self, key: Key) -> int:
        dist = (int(key) - int(self.starting_key)) % KEYS_IN_RING
        return dist.bit_length() - 1 if dist else -1

    # -- structure ---------------------------------------------------------
    def add_finger(self, finger: Finger) -> None:
        with self._lock:
            self._table.append(finger)

    def get_nth_entry(self, n: int) -> RemotePeer:
        with self._lock:
            self._check_index(n)
            return self._table[n].successor

    def edit_nth_finger(self, n: int, succ: RemotePeer) -> None:
        with self._lock:
            self._check_index(n)
            self._table[n].successor = succ

    def _check_index(self, n: int) -> None:
        """Out-of-range access raises RuntimeError, NOT IndexError: the
        reference's table_.at(n) throws std::out_of_range here (e.g.
        PopulateFingerTable(false) on a never-initialized table — a lone
        StartChord'd peer's first stabilize) and its StabilizeLoop
        catches-and-continues (chord_peer.cpp:225-238). Every recovery
        path in this package catches RuntimeError, so the error class
        must match or a survivable state crashes the maintenance
        caller."""
        if not 0 <= n < len(self._table):
            raise RuntimeError(
                f"finger table has {len(self._table)} entries, "
                f"index {n} out of range")

    def get_nth_range(self, n: int) -> Tuple[Key, Key]:
        """[start + 2^n, start + 2^(n+1) - 1] mod ring
        (finger_table.h:177-188)."""
        lb = (int(self.starting_key) + (1 << n)) % KEYS_IN_RING
        ub = ((int(self.starting_key) + (1 << (n + 1))) % KEYS_IN_RING - 1) \
            % KEYS_IN_RING
        return Key(lb), Key(ub)

    def empty(self) -> bool:
        with self._lock:
            return not self._table

    def size(self) -> int:
        with self._lock:
            return len(self._table)

    # -- lookup ------------------------------------------------------------
    def lookup(self, key: Key) -> RemotePeer:
        """Successor of the range containing key (finger_table.h:115-130).

        python backend: the reference's linear scan, verbatim.
        jax backend: the DEVICE kernel, via the batching bridge —
        concurrent per-RPC lookups coalesce into one ``u128`` batch
        (entry index = bit_length((key - start) mod 2^128) - 1, the
        closed form of the scan). The device resolve runs with the
        table lock RELEASED so the server's worker threads can share a
        batch; the entry read re-takes it. A failing device path
        degrades VISIBLY (logged once, `degraded` flag, periodic
        retry) to the semantics-identical host closed form.
        """
        if self.backend == "jax":
            with self._lock:
                full = len(self._table) == self.NUM_ENTRIES
            if full:
                idx = self._device_lookup_index(key)
                if idx < 0:
                    raise LookupError("ChordKey not found")
                with self._lock:
                    if len(self._table) == self.NUM_ENTRIES:
                        return self._table[idx].successor
                # table shrank mid-flight: fall through to the scan
        with self._lock:
            for finger in self._table:
                if Key(key).in_between(finger.lower_bound,
                                       finger.upper_bound, True):
                    return finger.successor
            raise LookupError("ChordKey not found")

    # -- repairs -----------------------------------------------------------
    def adjust_fingers(self, new_peer: RemotePeer) -> None:
        """Point entries whose range start lies in [new.min_key, new.id]
        at the new peer (finger_table.h:148-157)."""
        with self._lock:
            for finger in self._table:
                if finger.lower_bound.in_between(new_peer.min_key,
                                                 new_peer.id, True):
                    finger.successor = new_peer

    def replace_dead_peer(self, dead: RemotePeer,
                          replacement: RemotePeer) -> None:
        """finger_table.h:159-168."""
        with self._lock:
            for finger in self._table:
                if finger.successor.id == dead.id:
                    finger.successor = replacement

    # -- wire form (finger_table.h:249-265) ---------------------------------
    def to_json(self) -> dict:
        with self._lock:
            return {
                "STARTING_KEY": str(self.starting_key),
                "FINGERS": [
                    {"LOWER_BOUND": str(f.lower_bound),
                     "UPPER_BOUND": str(f.upper_bound),
                     "SUCCESSOR": f.successor.to_json()}
                    for f in self._table
                ],
            }

    def get_entries(self) -> List[Finger]:
        with self._lock:
            return list(self._table)

    def __str__(self) -> str:
        """Condensed table pretty-print (the reference's string cast,
        finger_table.h:194-241): consecutive ranges with the same
        successor collate into one display row."""
        with self._lock:
            rows: List[List[str]] = []
            for f in self._table:
                succ = f.successor
                if rows and rows[-1][2] == str(succ.id):
                    rows[-1][1] = str(f.upper_bound)
                else:
                    rows.append([str(f.lower_bound), str(f.upper_bound),
                                 str(succ.id),
                                 f"{succ.ip_addr}:{succ.port}"])
        header = ["LOWER BOUND", "UPPER BOUND", "SUCC ID", "SUCC IP:PORT"]
        widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
                  for i, h in enumerate(header)]
        border = "-" * (sum(widths) + 3 * len(widths) + 1)
        out = [border,
               "| " + " | ".join(h.ljust(w) for h, w in zip(header, widths))
               + " |", border]
        for r in rows:
            out.append("| " + " | ".join(c.ljust(w)
                                         for c, w in zip(r, widths)) + " |")
        out.append(border)
        return "\n".join(out)

"""Host Merkle tree, hash-compatible with the reference's MerkleTree.

Missing-key/position errors raise RuntimeError (not KeyError): the
reference throws std::runtime_error from Lookup/Update/Delete
(merkle_tree.h:153,231,255) and every overlay recovery path catches
RuntimeError — a KeyError would sail through them (found replaying
ReadKeyTest.json NON_EXISTENT_KEY). LookupByPosition differs upstream:
the reference returns std::nullopt and its caller dies on .value()
(bad_optional_access, dhash_peer.cpp:469-477); here the same condition
raises RuntimeError so the RPC envelope reports it instead of crashing.

Mirrors src/data_structures/merkle_tree.h: an 8-ary tree partitioning the
whole 2^128 keyspace; leaves split at more than 8 kv-pairs
(merkle_tree.h:126-128); node hashes are SHA-1 (the same UUIDv5 derivation
as ids) of concatenated KEY hex strings at leaves — values are NOT hashed
(merkle_tree.h:724-749, a deliberate reference property: value updates are
invisible to sync) — and of concatenated child hashes at internal nodes;
empty nodes hash to 0. Keys route to children by depth-scaled 3-bit shifts
(ChildNum, merkle_tree.h:704-722). Ranges are ring-aware (wrapped
ReadRange splits, merkle_tree.h:168-219; wrap-around Next,
merkle_tree.h:280-321). NonRecursiveSerialize sends one node plus its
children with keys-only leaves for the XCHNG_NODE sync protocol
(merkle_tree.h:592-620).

This host tree backs the per-peer databases of the wire-parity overlay;
the batched device analog is p2p_dhts_tpu.dhash.merkle.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from p2p_dhts_tpu.keyspace import KEYS_IN_RING, sha1_id

NUM_CHILDREN = 8          # merkle_tree.h:790-791
CHILD_BITS = 3            # log2(8)
MAX_LEAF_SIZE = 8         # leaf splits at > 8 entries (merkle_tree.h:126-128)
KEY_BITS = 128


def _hex(v: int) -> str:
    """Hex without leading zeros (IntToHexStr, key.h:41-47); 0 -> '0'."""
    return format(v, "x")


class MerkleNode:
    """One node: covers [min_key, max_key); leaf iff no children."""

    __slots__ = ("min_key", "max_key", "hash", "position", "children", "data")

    def __init__(self, min_key: int, max_key: int,
                 position: Optional[List[int]] = None):
        self.min_key = min_key
        self.max_key = max_key
        self.hash = 0
        self.position: List[int] = list(position or [])
        self.children: List["MerkleNode"] = []
        self.data: Dict[int, object] = {}

    # -- structure ---------------------------------------------------------
    def is_leaf(self) -> bool:
        return not self.children

    def depth(self) -> int:
        return len(self.position)

    def child_num(self, key: int) -> int:
        """Route a key to a child slot (ref ChildNum,
        merkle_tree.h:704-722)."""
        if key >= self.max_key:
            return NUM_CHILDREN - 1
        if key < self.min_key:
            return 0
        shift = KEY_BITS - CHILD_BITS * (self.depth() + 1)
        return (key >> shift) & (NUM_CHILDREN - 1)

    def _create_children(self) -> None:
        """Split this leaf's range into 8 equal slices and distribute its
        data (ref CreateChildren, merkle_tree.h:755-779)."""
        key_range = self.max_key - self.min_key
        last = self.min_key
        items = sorted(self.data.items())
        self.data = {}
        it = 0
        for i in range(NUM_CHILDREN):
            ub = last + key_range // NUM_CHILDREN
            child = MerkleNode(last, ub, self.position + [i])
            while it < len(items) and last <= items[it][0] <= ub - 1:
                child.data[items[it][0]] = items[it][1]
                it += 1
            child.rehash()
            self.children.append(child)
            last = ub

    def rehash(self) -> None:
        """ref Rehash (merkle_tree.h:724-749): leaf hash covers KEYS only;
        internal = hash of concatenated child hex hashes; empty -> 0."""
        if self.is_leaf():
            if not self.data:
                self.hash = 0
                return
            concat = "".join(_hex(k) for k in sorted(self.data))
        else:
            concat = "".join(_hex(c.hash) for c in self.children)
            if concat == "0" * NUM_CHILDREN:
                self.hash = 0
                return
        self.hash = sha1_id(concat)

    # -- ops ---------------------------------------------------------------
    def insert(self, key: int, val: object) -> None:
        if self.is_leaf():
            self.data[key] = val
            if len(self.data) > MAX_LEAF_SIZE:
                self._create_children()
        else:
            self.children[self.child_num(key)].insert(key, val)
        self.rehash()

    def lookup(self, key: int) -> object:
        if self.is_leaf():
            if key not in self.data:
                raise RuntimeError("Key nonexistent.")
            return self.data[key]
        return self.children[self.child_num(key)].lookup(key)

    def contains(self, key: int) -> bool:
        if self.is_leaf():
            return key in self.data
        return self.children[self.child_num(key)].contains(key)

    def update(self, key: int, val: object) -> None:
        if self.is_leaf():
            if key not in self.data:
                raise RuntimeError("Key nonexistent.")
            self.data[key] = val
        else:
            self.children[self.child_num(key)].update(key, val)
        self.rehash()

    def delete(self, key: int) -> None:
        if self.is_leaf():
            if key not in self.data:
                raise RuntimeError("Key nonexistent.")
            del self.data[key]
        else:
            self.children[self.child_num(key)].delete(key)
        self.rehash()

    def entries(self) -> Iterator[Tuple[int, object]]:
        if self.is_leaf():
            yield from sorted(self.data.items())
        else:
            for child in self.children:
                yield from child.entries()

    def read_simple_range(self, lb: int, ub: int) -> Dict[int, object]:
        """Keys in [lb, ub] inclusive, non-wrapped."""
        if ub < self.min_key or lb >= self.max_key:
            return {}
        if self.is_leaf():
            return {k: v for k, v in sorted(self.data.items())
                    if lb <= k <= ub}
        out: Dict[int, object] = {}
        for child in self.children:
            out.update(child.read_simple_range(lb, ub))
        return out


class MerkleTree:
    """Public tree API over the root node (ref MerkleTree<ValType>,
    merkle_tree.h:28-788)."""

    def __init__(self):
        self.root = MerkleNode(0, KEYS_IN_RING)

    # -- CRUD --------------------------------------------------------------
    def insert(self, key: int, val: object) -> None:
        self.root.insert(int(key), val)

    def lookup(self, key: int) -> object:
        return self.root.lookup(int(key))

    def contains(self, key: int) -> bool:
        return self.root.contains(int(key))

    def update(self, key: int, val: object) -> None:
        self.root.update(int(key), val)

    def delete(self, key: int) -> None:
        self.root.delete(int(key))

    def get_entries(self) -> List[Tuple[int, object]]:
        return list(self.root.entries())

    def __len__(self) -> int:
        return sum(1 for _ in self.root.entries())

    @property
    def hash(self) -> int:
        return self.root.hash

    # -- ring-aware reads (merkle_tree.h:168-219, 280-321) ------------------
    def read_range(self, lb: int, ub: int) -> Dict[int, object]:
        """Clockwise [lb, ub] inclusive; wrapped ranges split in two."""
        lb, ub = int(lb) % KEYS_IN_RING, int(ub) % KEYS_IN_RING
        if lb <= ub:
            return self.root.read_simple_range(lb, ub)
        out = self.root.read_simple_range(lb, KEYS_IN_RING - 1)
        out.update(self.root.read_simple_range(0, ub))
        return out

    def next(self, key: int) -> Optional[Tuple[int, object]]:
        """First stored kv strictly after key, wrapping; None if empty."""
        key = int(key) % KEYS_IN_RING
        after = self.root.read_simple_range(key + 1, KEYS_IN_RING - 1)
        if after:
            k = min(after)
            return k, after[k]
        rest = self.root.read_simple_range(0, key)
        if rest:
            k = min(rest)
            return k, rest[k]
        return None

    # -- sync protocol support ---------------------------------------------
    def lookup_by_position(self, position: Sequence[int]) -> MerkleNode:
        """Follow a child-index path from the root (ref LookupByPosition,
        merkle_tree.h:330-349)."""
        node = self.root
        for step in position:
            if node.is_leaf():
                raise RuntimeError("Position beyond leaf.")
            node = node.children[step]
        return node

    @staticmethod
    def serialize_node(node: MerkleNode, children: bool = True) -> dict:
        """ref NonRecursiveSerialize (merkle_tree.h:592-620): HASH +
        range + keys-only KV_PAIRS at leaves + one level of CHILDREN."""
        out = {
            "HASH": _hex(node.hash),
            "MIN_KEY": _hex(node.min_key),
            "KEY": _hex(node.max_key),
            "POSITION": list(node.position),
        }
        if node.is_leaf():
            out["KV_PAIRS"] = {_hex(k): "" for k in sorted(node.data)}
        elif children:
            out["CHILDREN"] = [
                MerkleTree.serialize_node(c, children=False)
                for c in node.children
            ]
        return out

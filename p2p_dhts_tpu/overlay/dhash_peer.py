"""Host DHash peer: erasure-coded storage over the Chord overlay.

Wire-parity re-implementation of src/dhash/dhash_peer.{h,cpp}: values are
IDA-encoded DataBlocks whose n fragments stripe across the key's n
successors; reads collect m distinct fragments; maintenance = Stabilize +
global re-placement + Merkle-synchronized local repair every cycle, with
the XCHNG_NODE node-exchange protocol and base-64 fragment wire forms.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from p2p_dhts_tpu.ida import DataBlock, DataFragment
from p2p_dhts_tpu.keyspace import KEYS_IN_RING, Key
from p2p_dhts_tpu.net.rpc import JsonObj
from p2p_dhts_tpu.overlay.chord_peer import AbstractChordPeer
from p2p_dhts_tpu.overlay.database import FragmentDb
from p2p_dhts_tpu.overlay.merkle_tree import MerkleNode, MerkleTree
from p2p_dhts_tpu.overlay.remote_peer import RemotePeer

KeyRange = Tuple[Key, Key]


class _RemoteNodeView:
    """A serialized Merkle node received over XCHNG_NODE
    (NonRecursiveSerialize form, merkle_tree.h:592-620)."""

    def __init__(self, obj: JsonObj):
        self.hash = int(obj["HASH"], 16)
        self.min_key = int(obj["MIN_KEY"], 16)
        self.max_key = int(obj["KEY"], 16)
        self.position: List[int] = list(obj.get("POSITION") or [])
        self._leaf = "KV_PAIRS" in obj
        self.kv_keys: List[int] = [
            int(k, 16) for k in (obj.get("KV_PAIRS") or {})
        ]
        self.children: List[JsonObj] = list(obj.get("CHILDREN") or [])

    def is_leaf(self) -> bool:
        return self._leaf

    def child_hash(self, i: int) -> int:
        return int(self.children[i]["HASH"], 16)


class DHashPeer(AbstractChordPeer):
    """ref DHashPeer (dhash_peer.h:20-81): num_succs doubles as the
    replication factor n; IDA params default n=14 m=10 p=257
    (dhash_peer.cpp:14-16)."""

    def __init__(self, ip_addr: str, port: int, num_replicas: int,
                 backend: str = "python",
                 maintenance_interval: Optional[float] = 5.0,
                 num_server_threads: int = 3,
                 server_backend: str = "python",
                 device_store_ring: Optional[str] = None):
        self.db = FragmentDb()
        self.n, self.m, self.p = 14, 10, 257
        # Host-overlay/device-store hybrid (the ROADMAP's gateway
        # follow-through): when set, create/read route block STORAGE
        # through a gateway-registered device ring while the host
        # overlay keeps doing membership/routing. A ring id names one
        # explicitly; "auto" uses the default ring if it carries a
        # store whose IDA m matches this peer's; None (the default)
        # keeps the pure host path.
        self.device_store_ring = device_store_ring
        self._device_ring_warned = False
        # Re-index census memo: key -> successor-id tuple last verified
        # duplicate-free (run_local_maintenance's heal pass).
        self._reindex_ok: Dict[int, tuple] = {}
        super().__init__(ip_addr, port, num_replicas, backend,
                         maintenance_interval, num_server_threads,
                         server_backend)
        # Gateway wiring: device rings registered in this process after
        # a DHash peer exists default to ITS replication params, so
        # gateway PUT/GET validation (segments [S, m]) matches the
        # overlay's erasure-coding config instead of a hardcoded one.
        try:
            from p2p_dhts_tpu.gateway import global_gateway
            global_gateway().set_default_ida(self.n, self.m, self.p)
        # chordax-lint: disable=bare-except -- gateway layer is additive; DHash protocol comes up regardless
        except Exception:
            pass

    def handlers(self):
        return {
            "JOIN": self.join_handler,
            "NOTIFY": self.notify_handler,
            "LEAVE": self.leave_handler,
            "GET_SUCC": self.get_succ_handler,
            "GET_PRED": self.get_pred_handler,
            "CREATE_KEY": self.create_key_handler,
            "READ_KEY": self.read_key_handler,
            "READ_RANGE": self.read_range_handler,
            "XCHNG_NODE": self.exchange_node_handler,
            "RECTIFY": self.rectify_handler,
        }

    # -- IDA params (dhash_peer.cpp:488-498) ---------------------------------
    def get_ida_params(self) -> Tuple[int, int, int]:
        return self.n, self.m, self.p

    def set_ida_params(self, n: int, m: int, p: int) -> None:
        self.n, self.m, self.p = n, m, p

    # -- device-store hybrid (chordax-repair satellite) ----------------------
    def _device_backend(self):
        """(gateway, ring_id) serving this peer's block storage, or
        None for the host path. Resolution is per-call so rings
        registered after the peer came up are picked up, and any
        gateway-layer surprise degrades to the host path (logged once)
        — the DHash protocol must come up regardless."""
        if self.device_store_ring is None:
            return None
        try:
            from p2p_dhts_tpu.gateway import global_gateway
            gw = global_gateway()
            if self.device_store_ring != "auto":
                backend = gw.router.get(self.device_store_ring)
            else:
                _, backend = gw.router.snapshot()
            if backend is None or not getattr(backend.engine,
                                              "has_store", False):
                return None
            # The device ring's erasure coding must match this peer's
            # (segments are [S, m]); a mismatched ring cannot serve it.
            if backend.engine.ida_params[1] != self.m:
                return None
            return gw, backend.ring_id
        # chordax-lint: disable=bare-except -- hybrid resolution is additive; any failure routes to the host path
        except Exception:
            return None

    def _device_fallback(self, op: str, exc: Exception) -> None:
        if not self._device_ring_warned:
            self._device_ring_warned = True
            self.log(f"device-store {op} failed "
                     f"({type(exc).__name__}: {exc}); falling back to "
                     f"the host store path (logged once)")

    # -- create (dhash_peer.cpp:89-154) --------------------------------------
    def create(self, key, val: str) -> None:
        key = key if isinstance(key, Key) else Key.from_plaintext(key)
        hybrid = self._device_backend()
        if hybrid is not None:
            gw, ring_id = hybrid
            from p2p_dhts_tpu.ida import split_to_segments
            seg = split_to_segments(val.encode(), self.m)
            try:
                ok = gw.dhash_put(int(key), seg, seg.shape[0], 0,
                                  ring_id=ring_id)
            except (RuntimeError, ValueError) as exc:
                # Gateway-layer failure (degraded ring, busy, deadline)
                # OR a value the device store cannot hold (segments
                # beyond the ring's max_segments raise ValueError at
                # engine validation): visible fallback, the host path
                # still serves the write.
                self._device_fallback("create", exc)
            else:
                if not ok:
                    # The ring answered: placement quorum failed — the
                    # reference's error, not a fallback case.
                    raise RuntimeError("Too few succs responded to "
                                       "requests.")
                return
        block = DataBlock(val, self.n, self.m, self.p)
        self.create_block(key, block)

    def create_block(self, key: Key, block: DataBlock) -> None:
        succ_list = self.get_n_successors(key, self.n)
        if len(succ_list) < self.m:
            raise RuntimeError(
                "Insufficient succs in list to complete request.")
        num_replicas = 0
        for i, succ in enumerate(succ_list):
            frag = block.fragments[i]
            if succ.id == self.id:
                self.db.insert(int(key), frag)
                num_replicas += 1
            elif succ.is_alive():
                try:
                    if self.create_key(key, frag, succ):
                        num_replicas += 1
                except RuntimeError:
                    pass
        if num_replicas < self.m:
            raise RuntimeError("Too few succs responded to requests.")

    def create_key(self, key: Key, frag: DataFragment,
                   peer: RemotePeer) -> bool:
        resp = peer.send_request({"COMMAND": "CREATE_KEY",
                                  "KEY": str(key),
                                  "VALUE": frag.to_json()})
        return bool(resp.get("SUCCESS"))

    def create_key_handler(self, req: JsonObj) -> JsonObj:
        key = Key.from_hex(req["KEY"])
        if self.db.contains(int(key)):
            raise RuntimeError("Key already exists in db.")
        self.db.insert(int(key), DataFragment.from_json(req["VALUE"]))
        return {}

    # -- read (dhash_peer.cpp:156-217) ---------------------------------------
    def read(self, key) -> str:
        key = key if isinstance(key, Key) else Key.from_plaintext(key)
        hybrid = self._device_backend()
        if hybrid is not None:
            gw, ring_id = hybrid
            try:
                segments, ok = gw.dhash_get(int(key), ring_id=ring_id)
            except (RuntimeError, ValueError) as exc:
                self._device_fallback("read", exc)
            else:
                if ok:
                    from p2p_dhts_tpu.ida import strip_decoded
                    return strip_decoded(segments).decode()
                # Device miss (key may predate the device ring, or its
                # block is device-unreadable): the host overlay is the
                # durable fallback, exactly like a degraded lookup.
        return self.read_block(key).decode()

    def read_block(self, key: Key) -> DataBlock:
        succ_list = self.get_n_successors(key, self.num_succs)
        fragments: Dict[int, DataFragment] = {}
        for succ in succ_list:
            if len(fragments) == self.m:
                break
            if succ.id == self.id and self.db.contains(int(key)):
                frag = self.db.lookup(int(key))
                fragments[frag.index] = frag
            else:
                try:
                    frag = self.read_key(key, succ)
                    fragments[frag.index] = frag
                except RuntimeError:
                    continue
        if len(fragments) < self.m:
            raise RuntimeError(f"Less than {self.m} distinct frags.")
        return DataBlock(fragments=list(fragments.values()),
                         n=self.n, m=self.m, p=self.p)

    def read_key(self, key: Key, peer: RemotePeer) -> DataFragment:
        resp = peer.send_request({"COMMAND": "READ_KEY", "KEY": str(key)})
        return DataFragment.from_json(resp["VALUE"])

    def read_key_handler(self, req: JsonObj) -> JsonObj:
        key = Key.from_hex(req["KEY"])
        return {"VALUE": self.db.lookup(int(key)).to_json()}

    # -- range transfer (dhash_peer.cpp:219-253) -----------------------------
    def read_range_rpc(self, succ: RemotePeer,
                       key_range: KeyRange) -> Dict[int, DataFragment]:
        resp = succ.send_request({
            "COMMAND": "READ_RANGE",
            "LOWER_BOUND": str(key_range[0]),
            "UPPER_BOUND": str(key_range[1]),
        })
        return {
            int(kv["KEY"], 16): DataFragment.from_json(kv["VAL"])
            for kv in (resp.get("KV_PAIRS") or [])
        }

    def read_range_handler(self, req: JsonObj) -> JsonObj:
        lb = Key.from_hex(req["LOWER_BOUND"])
        ub = Key.from_hex(req["UPPER_BOUND"])
        pairs = [
            {"KEY": format(k, "x"), "VAL": frag.to_json()}
            for k, frag in self.db.read_range(int(lb), int(ub)).items()
        ]
        self.log(f"Received read range {lb}-{ub}")
        return {"KV_PAIRS": pairs}

    # -- maintenance (dhash_peer.cpp:265-365) --------------------------------
    def start_maintenance(self) -> None:
        def body():
            self.stabilize()
            self.run_global_maintenance()
            self.run_local_maintenance()
        self._start_maintenance_thread(body)

    def run_global_maintenance(self) -> None:
        """Walk own DB ring-wise; push misplaced keys to their true
        successors and delete locally (dhash_peer.cpp:298-348).

        Documented fix of a reference-shaped livelock: a live
        ``db.next``-driven walk that breaks when it re-enters
        ``[id, first_stored_key]`` never terminates if that anchor key is
        itself pushed-and-deleted mid-walk (exactly what a just-joined
        successor causes). The walk here runs over a ring-ordered SNAPSHOT
        of the stored keys with a clockwise watermark, performing the same
        per-range actions, with guaranteed termination."""
        self.log("running global maintenance")
        ring_pos = lambda k: (int(k) - int(self.id) - 1) % KEYS_IN_RING
        snapshot = sorted((k for k, _ in self.db.get_entries()),
                          key=ring_pos)
        watermark = -1  # ring_pos of the last range already covered
        for k in snapshot:
            if ring_pos(k) <= watermark:
                continue  # absorbed by a processed successor range
            next_key = Key(k)
            succs = self.get_n_successors(next_key, self.n)
            misplaced = all(s.id != self.id for s in succs)
            if misplaced and succs:
                for succ in succs:
                    try:
                        have_remote = self.read_range_rpc(
                            succ, (next_key, succs[0].id))
                    except RuntimeError:
                        continue
                    local = self.db.read_range(int(next_key),
                                               int(succs[0].id))
                    for key_int, frag in local.items():
                        if key_int not in have_remote:
                            try:
                                self.create_key(Key(key_int), frag, succ)
                                self.db.delete(key_int)
                            except RuntimeError:
                                pass
            watermark = max(watermark,
                            ring_pos(succs[0].id) if succs else ring_pos(k))
        self.log("Global maintenance over")

    def run_local_maintenance(self) -> None:
        """Merkle-sync own range with every successor
        (dhash_peer.cpp:350-365), then re-index held fragments to the
        Create placement invariant.

        The re-index pass is a DOCUMENTED DEVIATION (round 5), the
        second half of the retrieve_missing fix: joins shift holders'
        positions in a key's successor list while stored fragments keep
        their old indices, so index collisions accumulate (each new
        position-0 successor regenerates idx 1) until the successor set
        serves fewer than m DISTINCT indices and reads fail permanently
        even though distinct fragments survive on misplaced holders.
        The heal is DUPLICATE-ONLY: a peer rewrites its fragment only
        when its index is duplicated within the successor set AND some
        index is missing from it — each rewrite strictly increases the
        set's distinct count, and the common post-churn state (indices
        all distinct, merely position-shifted) is left untouched (an
        unconditional position re-index transiently broke distinctness
        at n=14/m=10 — the 18-peer fixtures caught it). A successful
        whole-block read is required before rewriting, so the last
        reachable copy is never destroyed.

        Convergence under CONCURRENT maintenance (production timer
        loops, not the tests' sequential cycles): within a duplicate
        group, only the LOWEST MISMATCHED position rewrites this cycle
        — a deterministic leader computed from the same census — so two
        holders of one index can't lockstep-rewrite onto the same
        missing index forever. A per-key memo (successor-id tuple ->
        verified distinct) skips the (n-1)-RPC census in the permanent
        shifted-but-distinct steady state; churn changes the successor
        list and invalidates it."""
        self.log("Running local maintenance")
        if self.db.size == 0:
            return
        for i in range(self.successors.size()):
            succ = self.successors.get_nth_entry(i)
            if succ.id != self.id:
                try:
                    self.synchronize(succ, (self.min_key, Key(self.id)))
                except RuntimeError:
                    continue
        for key_int, frag in list(self.db.get_entries()):
            try:
                succs = self.get_n_successors(Key(key_int), self.n)
                pos = next((j for j, s in enumerate(succs)
                            if s.id == self.id), None)
                if pos is None or frag.index == pos + 1:
                    continue  # absent or already canonical: no census
                succ_ids = tuple(int(s.id) for s in succs)
                if self._reindex_ok.get(key_int) == succ_ids:
                    continue  # memo: verified distinct on this topology
                by_pos = {pos: frag.index}
                census_complete = True
                for j, s in enumerate(succs):
                    if s.id == self.id:
                        continue
                    try:
                        by_pos[j] = self.read_key(Key(key_int), s).index
                    except RuntimeError:
                        census_complete = False  # no memo from a
                        # partial view: an unreachable duplicate holder
                        # would otherwise wedge the heal permanently
                        # (the leader defers to us, we memo-skip).
                held = list(by_pos.values())
                missing = [i for i in range(1, len(succs) + 1)
                           if i not in held]
                if held.count(frag.index) < 2 or not missing:
                    if held.count(frag.index) < 2 and census_complete:
                        self._reindex_ok[key_int] = succ_ids
                    continue
                # Leader election within the duplicate group: only the
                # lowest MISMATCHED position rewrites this cycle.
                group = [j for j, ix in by_pos.items()
                         if ix == frag.index and ix != j + 1]
                if not group or pos != min(group):
                    continue
                target = pos + 1 if (pos + 1) in missing else missing[0]
                block = self.read_block(Key(key_int))
                if target - 1 < len(block.fragments):
                    self.db.update(key_int, block.fragments[target - 1])
            except RuntimeError:
                continue  # unreadable/mid-churn: keep the old fragment
        # Prune memo entries for keys no longer held (global maintenance
        # pushes-and-deletes) so the memo stays bounded by db size and a
        # re-acquired key re-censuses.
        self._reindex_ok = {k: v for k, v in self._reindex_ok.items()
                            if self.db.contains(k)}
        self.log("Local maintenance over")

    def retrieve_missing(self, key: Key) -> None:
        """Read the whole block, regenerate all n fragments, store the
        one whose 1-based index matches this peer's POSITION in the
        key's successor list — the placement invariant Create itself
        establishes (fragment i on the i-th successor,
        dhash_peer.cpp:106-123).

        DOCUMENTED DEVIATION (round 5): the reference stores one RANDOM
        fragment here (dhash_peer.cpp:367-379). Random picks collide,
        and a successor set whose regenerated fragments share an index
        serves fewer than m DISTINCT fragments — reads then fail
        PERMANENTLY even though distinct fragments survive elsewhere in
        the ring (reproduced by the mixed-impl churn soak: the key's
        three successors all held idx1 while idx2/idx3 sat stranded on
        misplaced old holders global maintenance skips by key). Falls
        back to the reference's random pick only when this peer cannot
        locate itself in the key's successor list (mid-churn
        transient)."""
        block = self.read_block(key)
        succs = self.get_n_successors(key, self.n)
        pos = next((i for i, s in enumerate(succs) if s.id == self.id),
                   None)
        if pos is not None and pos < len(block.fragments):
            frag = block.fragments[pos]  # fragments[i] bears index i+1
        else:
            frag = random.choice(block.fragments)
        self.db.insert(int(key), frag)

    # -- Merkle sync protocol (dhash_peer.cpp:381-481) -----------------------
    def synchronize(self, succ: RemotePeer, key_range: KeyRange) -> None:
        self._synchronize_helper(succ, key_range, self.db.get_index().root)

    def _synchronize_helper(self, succ: RemotePeer, key_range: KeyRange,
                            local_node: MerkleNode) -> None:
        remote_node = self.exchange_node(succ, local_node, key_range)
        self.compare_nodes(remote_node, local_node, succ, key_range)
        if not remote_node.is_leaf() and not local_node.is_leaf():
            for i, child in enumerate(local_node.children):
                if remote_node.child_hash(i) != child.hash:
                    self._synchronize_helper(succ, key_range, child)

    def compare_nodes(self, remote_node: _RemoteNodeView,
                      local_node: MerkleNode, succ: RemotePeer,
                      key_range: KeyRange) -> None:
        """ref CompareNodes (dhash_peer.cpp:416-441)."""
        if remote_node.is_leaf():
            for k in remote_node.kv_keys:
                if self.is_missing(Key(k), key_range):
                    self.retrieve_missing(Key(k))
        elif local_node.is_leaf():
            # Shape mismatch: pull everything the remote has in this range.
            succ_kvs = self.read_range_rpc(
                succ, (Key(local_node.min_key),
                       Key(local_node.max_key - 1)))
            for k in succ_kvs:
                if self.is_missing(Key(k), key_range):
                    self.retrieve_missing(Key(k))

    def is_missing(self, k: Key, key_range: KeyRange) -> bool:
        return k.in_between(key_range[0], key_range[1], True) \
            and not self.db.contains(int(k))

    def exchange_node(self, succ: RemotePeer, node: MerkleNode,
                      key_range: KeyRange) -> _RemoteNodeView:
        resp = succ.send_request({
            "COMMAND": "XCHNG_NODE",
            "NODE": MerkleTree.serialize_node(node, children=True),
            "REQUESTER": self.peer_as_json(),
            "LOWER_BOUND": str(key_range[0]),
            "UPPER_BOUND": str(key_range[1]),
        })
        return _RemoteNodeView(resp)

    def exchange_node_handler(self, req: JsonObj) -> JsonObj:
        remote_node = _RemoteNodeView(req["NODE"])
        local_node = self.db.get_index().lookup_by_position(
            remote_node.position)
        requester = RemotePeer.from_json(req["REQUESTER"])
        key_range = (Key.from_hex(req["LOWER_BOUND"]),
                     Key.from_hex(req["UPPER_BOUND"]))
        self.compare_nodes(remote_node, local_node, requester, key_range)
        return MerkleTree.serialize_node(local_node, children=True)

    # -- routing: LookupLiving fallback variant (dhash_peer.cpp:500-529) -----
    def forward_request(self, key: Key, request: JsonObj) -> JsonObj:
        key_succ = self.finger_table.lookup(key)
        if key_succ.id == self.id and self.predecessor is not None \
                and self.predecessor.is_alive():
            key_succ = self.predecessor
        elif not key_succ.is_alive():
            succ_lookup = self.successors.lookup_living(key)
            if succ_lookup is not None:
                key_succ = succ_lookup
            elif self.successors.size() > 0 \
                    and self.successors.get_nth_entry(0).is_alive():
                key_succ = self.successors.get_nth_entry(0)
            else:
                raise RuntimeError("Lookup failed")
        return key_succ.send_request(request)

    # -- joins don't move keys in DHash (dhash_peer.cpp:556-570) -------------
    def absorb_keys(self, kv_pairs: JsonObj) -> None:
        pass

    def keys_as_json(self) -> JsonObj:
        return {}

    def handle_notify_from_pred(self, new_pred: RemotePeer) -> JsonObj:
        """ref dhash_peer.cpp:531-545 — no key transfer, just links."""
        self.finger_table.adjust_fingers(new_pred)
        self.predecessor = new_pred
        self.min_key = new_pred.id + 1
        if self.successors.size() == 0:
            self.successors.populate(
                self.get_n_successors(self.id + 1, self.num_succs))
        return {}

    def handle_pred_failure(self, old_pred: RemotePeer) -> None:
        self.finger_table.adjust_fingers(self.to_remote_peer())
        self.rectify(old_pred)

    def fail(self) -> None:
        self.log("Stopping server/stabilize loop now")
        if self.server.is_alive():
            self.server.kill()
        self._stop_maintenance()

"""Host overlay: real TCP peers speaking the reference wire protocol.

This is the capability-parity layer (SURVEY.md §7 stage 6): ChordPeer /
DHashPeer classes a user of the reference can switch to — same RPC
commands, same JSON wire forms, same protocol behavior — with the
batched device kernels behind a ``backend="jax"`` flag on the lookup
path (BASELINE.json north star).
"""

from p2p_dhts_tpu.overlay.merkle_tree import MerkleTree  # noqa: F401
from p2p_dhts_tpu.overlay.database import (  # noqa: F401
    FragmentDb,
    GenericDB,
    TextDb,
)
from p2p_dhts_tpu.overlay.remote_peer import (  # noqa: F401
    RemotePeer,
    RemotePeerList,
)
from p2p_dhts_tpu.overlay.finger_table import Finger, FingerTable  # noqa: F401
from p2p_dhts_tpu.overlay.chord_peer import ChordPeer  # noqa: F401
from p2p_dhts_tpu.overlay.dhash_peer import DHashPeer  # noqa: F401

"""ctypes facade over the native C++ Chord peer (net/native/chord_peer.cc).

The reference's peers are native C++ objects; `NativeChordPeer` is the
rebuild's. All protocol logic — join, notify, leave, stabilize, rectify,
finger-table routing, key transfer, create/read — runs in native code on the
native engine's sockets; this class only marshals calls and mirrors enough
of the Python `ChordPeer` surface (`id`, `min_key`, `predecessor`, `create`,
`read`, `stabilize`, `join`, `leave`, `fail`) that mixed-implementation
rings can be built and asserted on by one test harness
(tests/test_native_rpc.py).

Native and Python peers interoperate in a single ring — the protocol-level
cross-implementation proof, one level above the transport-level byte
matrix.
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import Optional

from p2p_dhts_tpu.keyspace import Key
from p2p_dhts_tpu.net.native_rpc import (_take_cbytes, _take_cstr,
                                         load_library)
from p2p_dhts_tpu.overlay.remote_peer import RemotePeer


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    if getattr(lib, "_nc_bound", False):
        return lib
    lib.nc_dhash_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_double,
                                    ctypes.c_int]
    lib.nc_dhash_create.restype = ctypes.c_void_p
    lib.nc_dhash_set_ida.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_int, ctypes.c_longlong]
    lib.nc_dhash_set_ida.restype = ctypes.c_int
    lib.nc_dhash_maintain.argtypes = [ctypes.c_void_p]
    lib.nc_dhash_maintain.restype = ctypes.c_int
    lib.nc_merkle_probe.argtypes = [ctypes.c_char_p]
    lib.nc_merkle_probe.restype = ctypes.c_void_p
    lib.nc_peer_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_int, ctypes.c_double,
                                   ctypes.c_int]
    lib.nc_peer_create.restype = ctypes.c_void_p
    lib.nc_last_error.restype = ctypes.c_char_p
    lib.nc_peer_port.argtypes = [ctypes.c_void_p]
    lib.nc_peer_port.restype = ctypes.c_int
    for fn in (lib.nc_peer_id_hex, lib.nc_peer_min_key_hex,
               lib.nc_peer_pred_json):
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = ctypes.c_void_p
    lib.nc_peer_db_size.argtypes = [ctypes.c_void_p]
    lib.nc_peer_db_size.restype = ctypes.c_longlong
    lib.nc_peer_start_chord.argtypes = [ctypes.c_void_p]
    lib.nc_peer_start_chord.restype = ctypes.c_int
    lib.nc_peer_join.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int]
    lib.nc_peer_join.restype = ctypes.c_int
    for fn in (lib.nc_peer_stabilize, lib.nc_peer_leave):
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = ctypes.c_int
    lib.nc_peer_fail.argtypes = [ctypes.c_void_p]
    lib.nc_peer_create_key.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_char_p, ctypes.c_longlong]
    lib.nc_peer_create_key.restype = ctypes.c_int
    lib.nc_peer_read_key.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.POINTER(ctypes.c_longlong)]
    lib.nc_peer_read_key.restype = ctypes.c_int
    lib.nc_peer_get_successor.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.POINTER(ctypes.c_void_p)]
    lib.nc_peer_get_successor.restype = ctypes.c_int
    for fn in (lib.nc_peer_upload_file, lib.nc_peer_download_file):
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]
        fn.restype = ctypes.c_int
    lib.nc_peer_destroy.argtypes = [ctypes.c_void_p]
    lib._nc_bound = True
    return lib


class NativeChordPeer:
    """A Chord peer whose protocol logic runs in C++ (chord_peer.cc)."""

    # Subclasses override to construct a different native peer kind with
    # the same lifecycle (NativeDHashPeer -> nc_dhash_create).
    _CREATE_FN = "nc_peer_create"

    def __init__(self, ip_addr: str, port: int, num_succs: int,
                 maintenance_interval: Optional[float] = 5.0,
                 num_server_threads: int = 3):
        self._lib = _bind(load_library())
        interval = -1.0 if maintenance_interval is None \
            else float(maintenance_interval)
        self._h = getattr(self._lib, self._CREATE_FN)(
            ip_addr.encode(), port, num_succs, interval, num_server_threads)
        if not self._h:
            raise OSError(self._lib.nc_last_error().decode())
        self.ip_addr = ip_addr
        self.port = self._lib.nc_peer_port(self._h)
        self.num_succs = num_succs
        self._destroyed = False

    # -- state mirrors (for ring-invariant assertions) ----------------------
    @property
    def id(self) -> Key:
        return Key.from_hex(_take_cstr(self._lib,
                                       self._lib.nc_peer_id_hex(self._h)))

    @property
    def min_key(self) -> Key:
        return Key.from_hex(
            _take_cstr(self._lib, self._lib.nc_peer_min_key_hex(self._h)))

    @property
    def predecessor(self) -> Optional[RemotePeer]:
        obj = json.loads(
            _take_cstr(self._lib, self._lib.nc_peer_pred_json(self._h)))
        return None if obj is None else RemotePeer.from_json(obj)

    @property
    def db_size(self) -> int:
        return int(self._lib.nc_peer_db_size(self._h))

    # -- protocol ----------------------------------------------------------
    def _check(self, rc: int) -> None:
        if rc != 0:
            raise RuntimeError(self._lib.nc_last_error().decode())

    def start_chord(self) -> None:
        self._check(self._lib.nc_peer_start_chord(self._h))

    def join(self, gateway_ip: str, gateway_port: int) -> None:
        self._check(self._lib.nc_peer_join(self._h, gateway_ip.encode(),
                                           gateway_port))

    def stabilize(self) -> None:
        self._check(self._lib.nc_peer_stabilize(self._h))

    def leave(self) -> None:
        self._check(self._lib.nc_peer_leave(self._h))

    def fail(self) -> None:
        self._lib.nc_peer_fail(self._h)

    def create(self, key, val: str) -> None:
        k = key if isinstance(key, Key) else Key.from_plaintext(key)
        # Value strings may carry binary bytes as lone surrogates in the
        # U+DC80..U+DCFF surrogateescape range (PEP 383); the C side holds
        # them as WTF-8. Surrogates OUTSIDE that range are rejected loudly
        # — exactly like the Python twin's encode("utf-8",
        # "surrogateescape") — instead of being silently mangled.
        try:
            raw = val.encode("utf-8")
        except UnicodeEncodeError:
            val.encode("utf-8", "surrogateescape")  # the PEP 383 validator:
            # accepts exactly U+DC80..DCFF, raises (like the Python twin)
            # on any other lone surrogate.
            raw = val.encode("utf-8", "surrogatepass")
        # Length-carrying call: embedded NULs are legal and a C string
        # would clip them.
        self._check(self._lib.nc_peer_create_key(
            self._h, str(k).encode(), raw, len(raw)))

    def read(self, key) -> str:
        k = key if isinstance(key, Key) else Key.from_plaintext(key)
        out = ctypes.c_void_p()
        out_len = ctypes.c_longlong()
        rc = self._lib.nc_peer_read_key(self._h, str(k).encode(),
                                        ctypes.byref(out),
                                        ctypes.byref(out_len))
        text = _take_cbytes(self._lib, out.value, out_len.value) \
            if out.value else ""
        if rc != 0:
            raise RuntimeError(self._lib.nc_last_error().decode())
        return text

    def upload_file(self, file_path: str) -> None:
        """Store a whole file under its path (UploadFile,
        abstract_chord_peer.cpp:268-283); IO runs natively."""
        k = Key.from_plaintext(file_path)
        self._check(self._lib.nc_peer_upload_file(
            self._h, str(k).encode(), os.fsencode(file_path)))

    def download_file(self, file_name: str, output_path: str) -> None:
        """Fetch a stored file to output_path (DownloadFile,
        abstract_chord_peer.cpp:285-304)."""
        k = Key.from_plaintext(file_name)
        self._check(self._lib.nc_peer_download_file(
            self._h, str(k).encode(), os.fsencode(output_path)))

    def get_successor(self, key) -> RemotePeer:
        """Resolve a key's successor through the live ring (the public
        GetSuccessor surface, abstract_chord_peer.cpp:313-330)."""
        k = key if isinstance(key, Key) else Key.from_plaintext(key)
        out = ctypes.c_void_p()
        rc = self._lib.nc_peer_get_successor(self._h, str(k).encode(),
                                             ctypes.byref(out))
        text = _take_cstr(self._lib, out.value) if out.value else ""
        if rc != 0:
            raise RuntimeError(self._lib.nc_last_error().decode())
        return RemotePeer.from_json(json.loads(text))

    def close(self) -> None:
        if not self._destroyed:
            self._destroyed = True
            self._lib.nc_peer_destroy(self._h)

    def __del__(self):
        try:
            self.close()
        # chordax-lint: disable=bare-except -- best-effort finalizer; close() is the real teardown path
        except Exception:
            pass


class NativeDHashPeer(NativeChordPeer):
    """A DHash peer whose protocol logic — IDA fragment striping, Merkle
    anti-entropy, global placement maintenance — runs in C++
    (chord_peer.cc DHashPeerN). Wire- and hash-compatible with the Python
    DHashPeer, so the two sync against each other."""

    _CREATE_FN = "nc_dhash_create"

    def set_ida_params(self, n: int, m: int, p: int) -> None:
        if self._lib.nc_dhash_set_ida(self._h, n, m, p) != 0:
            raise RuntimeError(self._lib.nc_last_error().decode())

    def maintain(self) -> None:
        """One stabilize + global + local maintenance round
        (dhash_peer.cpp:271-296, stepped)."""
        if self._lib.nc_dhash_maintain(self._h) != 0:
            raise RuntimeError(self._lib.nc_last_error().decode())


def native_merkle_probe(keys) -> dict:
    """Build a native Merkle tree over int keys and return its root
    serialization — the hash-parity pin against overlay.MerkleTree."""
    lib = _bind(load_library())
    csv = ",".join(format(int(k), "x") for k in keys).encode()
    ptr = lib.nc_merkle_probe(csv)
    if not ptr:
        raise RuntimeError(lib.nc_last_error().decode())
    return json.loads(_take_cstr(lib, ptr))

"""RemotePeer stub + ring-sorted successor list.

ref src/chord/remote_peer.{h,cpp} and remote_peer_list.{h,cpp}: a remote
peer is {id, min_key, ip, port}; every send is gated on a TCP liveness
probe and raises on a SUCCESS=false envelope (remote_peer.cpp:28-41);
the successor list is a bounded vector kept in clockwise order relative
to its owner's id with a hand-rolled insert (std::set can't express the
ring order — remote_peer_list.cpp:31-84).
"""

from __future__ import annotations

import threading
from typing import List, Optional

from p2p_dhts_tpu.keyspace import Key
from p2p_dhts_tpu.net.rpc import Client, JsonObj


class RemotePeer:
    """ref class RemotePeer (remote_peer.h)."""

    def __init__(self, id: Key, min_key: Key, ip_addr: str, port: int):
        self.id = Key(id)
        self.min_key = Key(min_key)
        self.ip_addr = ip_addr
        self.port = int(port)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_json(cls, obj: JsonObj) -> "RemotePeer":
        """ref RemotePeer(const Json::Value&) (remote_peer.cpp:21-26)."""
        if not obj.get("PORT"):
            raise ValueError("Corrupted JSON")
        return cls(Key.from_hex(obj["ID"]), Key.from_hex(obj["MIN_KEY"]),
                   obj["IP_ADDR"], int(obj["PORT"]))

    def to_json(self) -> JsonObj:
        """ref operator Json::Value (remote_peer.cpp:85-93)."""
        return {"IP_ADDR": self.ip_addr, "PORT": self.port,
                "ID": str(self.id), "MIN_KEY": str(self.min_key)}

    # -- RPC ---------------------------------------------------------------
    def is_alive(self) -> bool:
        return Client.is_alive(self.ip_addr, self.port)

    def send_request(self, request: JsonObj) -> JsonObj:
        """ref SendRequest (remote_peer.cpp:28-41): liveness gate, raise
        on SUCCESS=false."""
        if not self.is_alive():
            raise RuntimeError("Peer is down.")
        resp = Client.make_request(self.ip_addr, self.port, request)
        if resp.get("SUCCESS"):
            return resp
        raise RuntimeError(f"Failed request: {resp}")

    def get_succ(self) -> "RemotePeer":
        """GET_SUCC(id + 1) (remote_peer.cpp:48-57)."""
        resp = self.send_request({"COMMAND": "GET_SUCC",
                                  "KEY": str(self.id + 1)})
        return RemotePeer.from_json(resp)

    def get_pred(self) -> "RemotePeer":
        """GET_PRED(id) (remote_peer.cpp:59-68)."""
        resp = self.send_request({"COMMAND": "GET_PRED",
                                  "KEY": str(self.id)})
        return RemotePeer.from_json(resp)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, RemotePeer):
            return NotImplemented
        return (self.ip_addr == other.ip_addr and self.id == other.id
                and self.min_key == other.min_key and self.port == other.port)

    def __lt__(self, other: "RemotePeer") -> bool:
        return self.id < other.id

    def __repr__(self) -> str:
        return f"RemotePeer({self.id}@{self.ip_addr}:{self.port})"


class RemotePeerList:
    """Bounded clockwise-sorted peer list (ref RemotePeerList,
    remote_peer_list.{h,cpp})."""

    def __init__(self, max_entries: int, starting_key: Key):
        self.max_entries = max_entries
        self.starting_key = Key(starting_key)
        self._peers: List[RemotePeer] = []
        self._lock = threading.RLock()

    def populate(self, peers: List[RemotePeer]) -> None:
        with self._lock:
            self._peers = list(peers)

    def insert(self, new_peer: RemotePeer) -> bool:
        """Clockwise insert relative to starting_key
        (remote_peer_list.cpp:31-84); dedup by id; evict the tail when
        over capacity."""
        with self._lock:
            if new_peer.port == 0:
                raise RuntimeError("Corrupted JSON")
            if not self._peers:
                self._peers.append(new_peer)
                return True
            prev = self.starting_key
            for i, entry in enumerate(self._peers):
                if new_peer.id == entry.id:
                    return False
                if new_peer.id.in_between(prev, entry.id, True):
                    self._peers.insert(i, new_peer)
                    if len(self._peers) > self.max_entries:
                        self._peers.pop()
                    return True
                prev = entry.id
            if len(self._peers) < self.max_entries:
                self._peers.append(new_peer)
                return True
            return False

    def lookup(self, key: Key, succ: bool = True) -> Optional[RemotePeer]:
        """Owning entry of key (or its predecessor entry when succ=False)
        (remote_peer_list.cpp:86-110)."""
        with self._lock:
            prev = self.starting_key
            for i, entry in enumerate(self._peers):
                if Key(key).in_between(prev, entry.id, True):
                    if succ:
                        return entry
                    return self._peers[i - 1] if i != 0 else None
                prev = entry.id
            return None

    def lookup_living(self, key: Key) -> Optional[RemotePeer]:
        """First alive entry at-or-after the owning one
        (remote_peer_list.cpp:112-132 — NOTE: the reference's fallback
        loop condition `i % size < succ_ind` is false on its first
        iteration, so its scan never runs; here the scan actually works,
        a documented fix of that defect)."""
        with self._lock:
            succ = self.lookup(key, True)
            if succ is None:
                return None
            if succ.is_alive():
                return succ
            start = self.get_index(succ)
            for off in range(1, len(self._peers)):
                peer = self._peers[(start + off) % len(self._peers)]
                if peer.is_alive():
                    return peer
            return None

    def delete(self, id_or_peer) -> None:
        with self._lock:
            target = id_or_peer.id if isinstance(id_or_peer, RemotePeer) \
                else Key(id_or_peer)
            for i, entry in enumerate(self._peers):
                if entry.id == target:
                    del self._peers[i]
                    return

    def erase(self) -> None:
        with self._lock:
            self._peers = []

    def contains(self, peer: RemotePeer) -> bool:
        with self._lock:
            return any(p.id == peer.id for p in self._peers)

    def get_nth_entry(self, n: int) -> RemotePeer:
        with self._lock:
            return self._peers[n]

    def first_living(self) -> RemotePeer:
        with self._lock:
            peers = list(self._peers)
        for p in peers:
            if p.is_alive():
                return p
        raise RuntimeError("No living peers")

    def get_index(self, peer: RemotePeer) -> int:
        with self._lock:
            for i, p in enumerate(self._peers):
                if p.id == peer.id:
                    return i
            return -1

    def size(self) -> int:
        with self._lock:
            return len(self._peers)

    def get_entries(self) -> List[RemotePeer]:
        with self._lock:
            return list(self._peers)

    def to_json(self) -> JsonObj:
        with self._lock:
            return {
                "MAX_ENTRIES": self.max_entries,
                "STARTING_KEY": str(self.starting_key),
                "PEERS": [p.to_json() for p in self._peers],
            }

"""Host Chord peer: the reference's AbstractChordPeer + ChordPeer.

Wire-parity re-implementation of src/chord/abstract_chord_peer.{h,cpp}
and chord_peer.{h,cpp}: a real TCP JSON-RPC peer with the same 8 commands
{JOIN, NOTIFY, LEAVE, GET_SUCC, GET_PRED, CREATE_KEY, READ_KEY, RECTIFY},
the same JSON forms, and the same protocol behavior (including the
non-textbook lookup semantics the device kernels pin — ForwardRequest's
self-hit -> predecessor correction, succ-list fallback, linear-scan
range-successor finger lookup).

Differences from the reference, all deliberate:
  * the server binds BEFORE the id is derived so port=0 (ephemeral) works
    in tests; with a fixed port the id is byte-identical to the
    reference's SHA1("ip:port") (abstract_chord_peer.cpp:13-28).
  * maintenance_interval is a constructor argument (the reference
    hardcodes 5 s, chord_peer.cpp:219); interval=None disables the
    thread so tests can step Stabilize deterministically instead of
    sleeping (SURVEY.md §4 implications).
  * backend="jax" routes finger lookups through the O(1)/batched device
    path (BASELINE.json north-star flag); backend="python" is the
    reference's linear scan.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from p2p_dhts_tpu.keyspace import Key
from p2p_dhts_tpu.metrics import METRICS
from p2p_dhts_tpu.net.rpc import Client, JsonObj, Server
from p2p_dhts_tpu.overlay.database import TextDb
from p2p_dhts_tpu.overlay.finger_table import Finger, FingerTable
from p2p_dhts_tpu.overlay.remote_peer import RemotePeer, RemotePeerList

logger = logging.getLogger("p2p_dhts_tpu.overlay")

KEY_BITS = 128  # ChordKey::BinaryLen()


class AbstractChordPeer:
    """Protocol core (ref AbstractChordPeer, abstract_chord_peer.h:62-415).

    Subclasses register their command handlers by overriding handlers()
    and implement the pure virtuals: create/read/start_maintenance/
    keys_as_json/fail/handle_notify_from_pred/absorb_keys/
    handle_pred_failure/forward_request.
    """

    def __init__(self, ip_addr: str, port: int, num_succs: int,
                 backend: str = "python",
                 maintenance_interval: Optional[float] = 5.0,
                 num_server_threads: int = 3,
                 server_backend: str = "python"):
        # num_server_threads defaults to the reference's 3 io workers
        # (chord_peer.cpp:42). Deep recursive handler chains right after
        # mass churn can exhaust 3 workers and wedge until the client
        # timeout (the reference sleeps these stalls out); harnesses may
        # raise it to trade threads for wall-clock.
        self.ip_addr = ip_addr
        self.num_succs = num_succs
        self.backend = backend
        self.maintenance_interval = maintenance_interval

        # server_backend="native" serves this peer's RPCs from the C++
        # engine (net/native/rpc_engine.cc) — the rebuild's counterpart of
        # the reference's native asio runtime; "python" is net/rpc.py.
        # Both speak the same wire bytes (tests/test_native_rpc.py).
        if server_backend == "native":
            from p2p_dhts_tpu.net.native_rpc import NativeServer
            self.server = NativeServer(port, {},
                                       num_threads=num_server_threads)
        elif server_backend == "python":
            self.server = Server(port, {}, num_threads=num_server_threads)
        else:
            raise ValueError(f"unknown server_backend {server_backend!r}")
        self.port = self.server.port
        self.server.update_handlers(self.handlers())
        # Gateway front door (ISSUE 4): every peer's server also speaks
        # the device-serving commands (FIND_SUCCESSOR / GET / PUT /
        # FINGER_INDEX), routed through the process-global gateway into
        # the batched ServeEngine path — concurrent wire lookups from
        # ANY peer's port coalesce into shared device batches. Install
        # is a handler-map swap (no jax, no backend init); a gateway
        # build failure must not take the reference protocol down.
        try:
            from p2p_dhts_tpu.gateway import install_gateway_handlers
            install_gateway_handlers(self.server)
        # chordax-lint: disable=bare-except -- the gateway surface is additive; the 8 reference commands must come up regardless
        except Exception:
            logger.warning("gateway handlers unavailable on peer %s:%s",
                           ip_addr, self.port, exc_info=True)

        # id = SHA1("ip:port") (abstract_chord_peer.cpp:13-28)
        self.id = Key.from_plaintext(f"{self.ip_addr}:{self.port}")
        self.min_key = Key(self.id)
        self.predecessor: Optional[RemotePeer] = None
        self._pred_lock = threading.RLock()
        self.finger_table = FingerTable(self.id, backend=backend)
        self.successors = RemotePeerList(num_succs, self.id)

        self._maint_stop = threading.Event()
        self._maint_thread: Optional[threading.Thread] = None
        self.server.run_in_background()
        self.log("Created peer.")

    # -- virtuals ----------------------------------------------------------
    def handlers(self) -> Dict[str, object]:
        raise NotImplementedError

    def create(self, key, val):
        raise NotImplementedError

    def read(self, key):
        raise NotImplementedError

    def keys_as_json(self) -> JsonObj:
        raise NotImplementedError

    def absorb_keys(self, kv_pairs: JsonObj) -> None:
        raise NotImplementedError

    def handle_notify_from_pred(self, new_pred: RemotePeer) -> JsonObj:
        raise NotImplementedError

    def handle_pred_failure(self, old_pred: RemotePeer) -> None:
        raise NotImplementedError

    def forward_request(self, key: Key, request: JsonObj) -> JsonObj:
        raise NotImplementedError

    # -- lifecycle ---------------------------------------------------------
    def start_chord(self) -> None:
        """First node owns everything (abstract_chord_peer.cpp:66-71)."""
        self.min_key = self.id + 1
        self.start_maintenance()

    def join(self, gateway_ip: str, gateway_port: int) -> None:
        """ref Join (abstract_chord_peer.cpp:83-117)."""
        self.log("Joining chord")
        resp = Client.make_request(gateway_ip, gateway_port,
                                   {"COMMAND": "JOIN",
                                    "NEW_PEER": self.peer_as_json()})
        self.predecessor = RemotePeer.from_json(resp["PREDECESSOR"])
        self.min_key = self.predecessor.id + 1

        self.populate_finger_table(initialize=True)
        self.notify(self.finger_table.get_nth_entry(0))

        # Arbitrary cutoff kept for parity (abstract_chord_peer.cpp:103-110).
        if self.num_succs > 10:
            for pred in self.get_n_predecessors(self.id, self.num_succs):
                self.notify(pred)
            self.successors.populate(
                self.get_n_successors(self.id + 1, self.num_succs))

        self.fix_other_fingers(self.id)
        self.start_maintenance()

    def join_handler(self, req: JsonObj):
        """ref JoinHandler (abstract_chord_peer.cpp:119-136).

        Mass-churn wedge fix (ISSUE 7): the handler's recursive
        pred-resolution (get_predecessor -> GET_PRED/GET_SUCC chains)
        used to run ON a server worker — with the reference's 3-worker
        pool, >3 simultaneous joiners occupied every worker while each
        join's nested RPCs to this same server starved behind them,
        wedging until the client timeout. The join work now hands off
        to the membership join pool (net.rpc.DeferredResponse): the
        worker frees immediately and the nested lookups land on it.
        Servers without deferred support (the native C++ engine) keep
        the reference-faithful inline path."""
        if getattr(self.server, "supports_deferred", False):
            from p2p_dhts_tpu.membership.manager import \
                overlay_join_executor
            from p2p_dhts_tpu.net.rpc import DeferredResponse
            return DeferredResponse(self._join_handler_impl,
                                    overlay_join_executor())
        return self._join_handler_impl(req)

    def _join_handler_impl(self, req: JsonObj) -> JsonObj:
        new_peer = RemotePeer.from_json(req["NEW_PEER"])
        new_peer_pred = self.get_predecessor(new_peer.id)
        self.finger_table.adjust_fingers(new_peer)
        self.successors.insert(new_peer)
        return {"PREDECESSOR": new_peer_pred.to_json()}

    def leave(self) -> None:
        """ref Leave (abstract_chord_peer.cpp:192-226)."""
        self.log("Leaving chord.")
        notification = {
            "COMMAND": "LEAVE",
            "LEAVING_ID": str(self.id),
            "NEW_PRED": self.predecessor.to_json(),
            "NEW_MIN": str(self.min_key),
            "KEYS_TO_ABSORB": self.keys_as_json(),
        }
        for pred in self.get_n_predecessors(self.id, self.num_succs):
            try:
                pred.send_request(notification)
            except RuntimeError:
                pass
        succ = self.finger_table.get_nth_entry(0)
        succ_condones = True
        if succ.is_alive():
            succ_resp = succ.send_request(notification)
            succ_condones = bool(succ_resp.get("SUCCESS"))
        if succ_condones:
            self.log("Leaving now.")
            self.fail()
        else:
            raise RuntimeError("Not ready to leave")

    def leave_handler(self, req: JsonObj) -> JsonObj:
        """ref LeaveHandler (abstract_chord_peer.cpp:228-260).

        Reference quirk mirrored: the final AdjustFingers(NEW_SUCC) is a
        no-op because Leave() never sets NEW_SUCC (SURVEY.md §7 quirks);
        here it is simply skipped.
        """
        leaving_id = Key.from_hex(req["LEAVING_ID"])
        if self.predecessor is not None \
                and leaving_id == self.predecessor.id:
            old_pred_id = self.predecessor.id
            self.predecessor = RemotePeer.from_json(req["NEW_PRED"])
            self.min_key = Key.from_hex(req["NEW_MIN"])
            self.fix_other_fingers(old_pred_id)
            self.absorb_keys(req.get("KEYS_TO_ABSORB") or {})
        self.successors.delete(leaving_id)
        if self.successors.size() == 0:
            self.successors.populate(
                self.get_n_successors(self.id + 1, self.num_succs))
        return {}

    def fail(self) -> None:
        raise NotImplementedError

    def start_maintenance(self) -> None:
        raise NotImplementedError

    # -- notify ------------------------------------------------------------
    def notify(self, peer_to_notify: RemotePeer) -> None:
        """ref Notify (abstract_chord_peer.cpp:138-148)."""
        resp = peer_to_notify.send_request(
            {"COMMAND": "NOTIFY", "NEW_PEER": self.peer_as_json()})
        self.absorb_keys(resp.get("KEYS_TO_ABSORB") or {})

    def notify_handler(self, req: JsonObj) -> JsonObj:
        """ref NotifyHandler (abstract_chord_peer.cpp:150-190)."""
        new_peer = RemotePeer.from_json(req["NEW_PEER"])
        self.log(f"Received notify from {new_peer.port}")

        if self.predecessor is not None and not self.predecessor.is_alive():
            old_pred = self.predecessor
            resp = self.handle_notify_from_pred(new_peer)
            self.handle_pred_failure(old_pred)
            return resp

        self.finger_table.adjust_fingers(new_peer)
        self.successors.insert(new_peer)

        peer_is_pred = self.predecessor is None or \
            new_peer.id.in_between(self.predecessor.id, self.id, False)
        if peer_is_pred:
            return self.handle_notify_from_pred(new_peer)

        if self.finger_table.empty():
            self.populate_finger_table(initialize=True)
        return {}

    # -- files (abstract_chord_peer.cpp:268-304) ----------------------------
    def upload_file(self, file_path: str) -> None:
        with open(file_path, "rb") as fh:
            contents = fh.read()
        self.create(file_path, contents.decode("utf-8",
                                               errors="surrogateescape"))

    def download_file(self, file_name: str, output_path: str) -> None:
        contents = self.read(file_name)
        with open(output_path, "wb") as fh:
            fh.write(contents.encode("utf-8", errors="surrogateescape"))

    # -- succ/pred resolution ----------------------------------------------
    def get_successor(self, key) -> RemotePeer:
        """ref GetSuccessor (abstract_chord_peer.cpp:313-330)."""
        key = key if isinstance(key, Key) else Key.from_plaintext(key)
        if self.stored_locally(key):
            return self.to_remote_peer()
        resp = self.forward_request(
            key, {"COMMAND": "GET_SUCC", "KEY": str(key)})
        return RemotePeer.from_json(resp)

    def get_succ_handler(self, req: JsonObj) -> JsonObj:
        return self.get_successor(Key.from_hex(req["KEY"])).to_json()

    def get_n_successors(self, key, n: int) -> List[RemotePeer]:
        """ref GetNSuccessors with repeat-break
        (abstract_chord_peer.cpp:345-373)."""
        key = key if isinstance(key, Key) else Key(key)
        out: List[RemotePeer] = []
        seen = set()
        prev = key - 1
        for _ in range(n):
            ith = self.get_successor(prev + 1)
            if ith.id.value in seen:
                break
            out.append(ith)
            seen.add(ith.id.value)
            prev = ith.id
        return out

    def get_predecessor(self, key) -> RemotePeer:
        """ref GetPredecessor with the succ-list shortcut
        (abstract_chord_peer.cpp:380-416)."""
        key = key if isinstance(key, Key) else Key(key)
        if self.predecessor is None:
            return self.to_remote_peer()
        if self.stored_locally(key):
            return self.predecessor
        succ_of_key = self.successors.lookup(key)
        if succ_of_key is not None:
            try:
                pred_of_succ = succ_of_key.get_pred()
                if key.in_between(pred_of_succ.id, succ_of_key.id, True):
                    return pred_of_succ
            except RuntimeError:
                pass
        resp = self.forward_request(
            key, {"COMMAND": "GET_PRED", "KEY": str(key)})
        if resp.get("SUCCESS"):
            return RemotePeer.from_json(resp)
        raise RuntimeError(f"Lookup failed w/ error: {resp.get('ERRORS')}")

    def get_pred_handler(self, req: JsonObj) -> JsonObj:
        return self.get_predecessor(Key.from_hex(req["KEY"])).to_json()

    def get_n_predecessors(self, key, n: int) -> List[RemotePeer]:
        """ref GetNPredecessors (abstract_chord_peer.cpp:431-449)."""
        key = key if isinstance(key, Key) else Key(key)
        out: List[RemotePeer] = []
        prev = key
        for i in range(n):
            ith = self.get_predecessor(prev - 1)
            out.append(ith)
            if prev == key and i != 0:
                break
            prev = ith.id
        return out

    # -- maintenance -------------------------------------------------------
    def stabilize(self) -> None:
        """ref Stabilize (abstract_chord_peer.cpp:460-505)."""
        METRICS.inc("overlay.stabilize_rounds")
        self.log("Running stabilize.")
        if self.predecessor is not None \
                and not self.predecessor.is_alive():
            self.handle_pred_failure(self.predecessor)

        if self.successors.size() == 0:
            self.successors.populate(
                self.get_n_successors(self.id + 1, self.num_succs))
            self.populate_finger_table(initialize=False)
            return

        immediate_succ = self.successors.get_nth_entry(0)
        while not immediate_succ.is_alive():
            self.successors.delete(immediate_succ)
            if self.successors.size() == 0:
                # Every listed successor was dead: rebuild from scratch as
                # the empty-list branch above does, instead of indexing
                # into the drained list.
                self.successors.populate(
                    self.get_n_successors(self.id + 1, self.num_succs))
                self.populate_finger_table(initialize=False)
                return
            immediate_succ = self.successors.get_nth_entry(0)

        pred_of_succ = immediate_succ.get_pred()
        incorrect_succ = self.id.in_between(pred_of_succ.id,
                                            immediate_succ.id, True)
        if incorrect_succ or not pred_of_succ.is_alive():
            self.log(f"Notifying {immediate_succ.port}")
            self.notify(immediate_succ)

        self.update_succ_list()
        self.populate_finger_table(initialize=False)

    def update_succ_list(self) -> None:
        """ref UpdateSuccList pred-walk gap filling
        (abstract_chord_peer.cpp:507-562)."""
        old_peer_list = self.successors.get_entries()
        previous_succ_id = self.id
        for nth_entry in old_peer_list:
            last_entry = nth_entry
            while True:
                try:
                    pred_of_last = last_entry.get_pred()
                except RuntimeError:
                    break
                if pred_of_last.id == previous_succ_id \
                        or pred_of_last.id == self.id:
                    break
                if pred_of_last.is_alive():
                    self.successors.insert(pred_of_last)
                last_entry = pred_of_last
            previous_succ_id = nth_entry.id

        if self.successors.size() < self.num_succs:
            size = self.successors.size()
            discrepancy = self.num_succs - size
            last_succ = self.successors.get_nth_entry(size - 1)
            for peer in self.get_n_successors(last_succ.id + 1, discrepancy):
                if peer.id != self.id:
                    self.successors.insert(peer)

    def populate_finger_table(self, initialize: bool) -> None:
        """ref PopulateFingerTable (abstract_chord_peer.cpp:564-613):
        128 sequential GET_SUCCs, each asking the previous entry as the
        closest known preceding peer."""
        for i in range(FingerTable.NUM_ENTRIES):
            lb, ub = self.finger_table.get_nth_range(i)
            succ_req = {"COMMAND": "GET_SUCC", "KEY": str(lb)}
            if initialize:
                if self.stored_locally(lb):
                    self.finger_table.add_finger(
                        Finger(lb, ub, self.to_remote_peer()))
                else:
                    peer_to_query = self.predecessor if i == 0 \
                        else self.finger_table.get_nth_entry(i - 1)
                    resp = peer_to_query.send_request(succ_req)
                    self.finger_table.add_finger(
                        Finger(lb, ub, RemotePeer.from_json(resp)))
            else:
                if i == 0:
                    self.finger_table.edit_nth_finger(
                        0, self.get_successor(lb))
                else:
                    peer_to_query = self.finger_table.get_nth_entry(i - 1)
                    resp = peer_to_query.send_request(succ_req)
                    self.finger_table.edit_nth_finger(
                        i, RemotePeer.from_json(resp))

    def fix_other_fingers(self, starting_key: Key) -> None:
        """ref FixOtherFingers (abstract_chord_peer.cpp:615-645)."""
        former: Optional[RemotePeer] = None
        for i in range(1, KEY_BITS + 1):
            p = self.get_predecessor(Key(starting_key) - (1 << (i - 1)))
            if former is not None and former == p:
                continue
            former = p
            if p.id == self.id:
                break
            if p.is_alive():
                self.notify(p)

    def rectify(self, failed_peer: RemotePeer) -> None:
        """ref Rectify — Zave's repair broadcast
        (abstract_chord_peer.cpp:647-682)."""
        if failed_peer.is_alive():
            return
        self.log(f"Rectifying failure of {failed_peer.port}")
        req = {"COMMAND": "RECTIFY",
               "FAILED_NODE": failed_peer.to_json(),
               "ORIGINATOR": self.peer_as_json()}
        former: Optional[RemotePeer] = None
        for i in range(1, KEY_BITS + 1):
            p = self.get_predecessor(failed_peer.id - (1 << (i - 1)))
            if former is not None and former == p:
                continue
            former = p
            if p.id == self.id:
                break
            if p.is_alive():
                p.send_request(req)

    def rectify_handler(self, req: JsonObj) -> JsonObj:
        """ref RectifyHandler (abstract_chord_peer.cpp:684-698)."""
        originator = RemotePeer.from_json(req["ORIGINATOR"])
        if originator.id == self.id:
            return {}
        failed_node = RemotePeer.from_json(req["FAILED_NODE"])
        self.successors.delete(failed_node)
        self.finger_table.replace_dead_peer(failed_node, originator)
        self.notify(originator)
        return {}

    # -- misc --------------------------------------------------------------
    def to_remote_peer(self) -> RemotePeer:
        return RemotePeer(self.id, self.min_key, self.ip_addr, self.port)

    def peer_as_json(self) -> JsonObj:
        return self.to_remote_peer().to_json()

    def stored_locally(self, key: Key) -> bool:
        """key in [min_key, id] (abstract_chord_peer.cpp:720-725)."""
        return Key(key).in_between(self.min_key, self.id, True)

    def log(self, msg: str) -> None:
        logger.debug("[%s@%s:%s] %s", self.id, self.ip_addr, self.port, msg)

    # -- maintenance thread plumbing ---------------------------------------
    def _start_maintenance_thread(self, body) -> None:
        if self.maintenance_interval is None:
            return
        self._maint_stop.clear()

        def loop():
            last = time.monotonic()
            while not self._maint_stop.is_set():
                if time.monotonic() - last < self.maintenance_interval:
                    time.sleep(0.01)
                    continue
                try:
                    body()
                # chordax-lint: disable=bare-except -- reference catch-and-continue parity (StabilizeLoop, chord_peer.cpp:225-238)
                except Exception as exc:  # catch-and-continue
                    self.log(f"CAUGHT {exc} - CONTINUING")
                last = time.monotonic()

        self._maint_thread = threading.Thread(target=loop, daemon=True)
        self._maint_thread.start()

    def _stop_maintenance(self) -> None:
        self._maint_stop.set()


class ChordPeer(AbstractChordPeer):
    """Plain Chord storage peer (ref ChordPeer, chord_peer.{h,cpp}):
    unreplicated create/read against the key's successor; TextDb."""

    def __init__(self, ip_addr: str, port: int, num_succs: int,
                 backend: str = "python",
                 maintenance_interval: Optional[float] = 5.0,
                 num_server_threads: int = 3,
                 server_backend: str = "python"):
        self.db = TextDb()
        super().__init__(ip_addr, port, num_succs, backend,
                         maintenance_interval, num_server_threads,
                         server_backend)

    def handlers(self):
        return {
            "JOIN": self.join_handler,
            "NOTIFY": self.notify_handler,
            "LEAVE": self.leave_handler,
            "GET_SUCC": self.get_succ_handler,
            "GET_PRED": self.get_pred_handler,
            "CREATE_KEY": self.create_key_handler,
            "READ_KEY": self.read_key_handler,
            "RECTIFY": self.rectify_handler,
        }

    # -- create/read (chord_peer.cpp:77-177) --------------------------------
    def create(self, key, val: str) -> None:
        key = key if isinstance(key, Key) else Key.from_plaintext(key)
        if self.stored_locally(key):
            self.db.insert(int(key), val)
            return
        succ = self.get_successor(key)
        if not self.create_key(key, val, succ):
            raise RuntimeError("Remote creation failed")

    def create_key(self, key: Key, val: str, peer: RemotePeer) -> bool:
        resp = peer.send_request({"COMMAND": "CREATE_KEY",
                                  "KEY": str(key), "VALUE": val})
        return bool(resp.get("SUCCESS"))

    def create_key_handler(self, req: JsonObj) -> JsonObj:
        key = Key.from_hex(req["KEY"])
        if not self.stored_locally(key):
            raise RuntimeError("Key not in range.")
        self.db.insert(int(key), req["VALUE"])
        return {}

    def read(self, key) -> str:
        key = key if isinstance(key, Key) else Key.from_plaintext(key)
        if self.stored_locally(key):
            return self.db.lookup(int(key))
        succ = self.get_successor(key)
        return self.read_key(key, succ)

    def read_key(self, key: Key, peer: RemotePeer) -> str:
        resp = peer.send_request({"COMMAND": "READ_KEY", "KEY": str(key)})
        if resp.get("SUCCESS"):
            return resp["VALUE"]
        raise RuntimeError("Key not stored on peer.")

    def read_key_handler(self, req: JsonObj) -> JsonObj:
        key = Key.from_hex(req["KEY"])
        if not self.stored_locally(key):
            raise RuntimeError("Key not stored locally.")
        return {"VALUE": self.db.lookup(int(key))}

    # -- routing (chord_peer.cpp:185-211) -----------------------------------
    def forward_request(self, key: Key, request: JsonObj) -> JsonObj:
        key_succ = self.finger_table.lookup(key)
        if key_succ.id == self.id and self.predecessor is not None \
                and self.predecessor.is_alive():
            key_succ = self.predecessor
        elif not key_succ.is_alive():
            succ_lookup = self.successors.lookup(key)
            if succ_lookup is not None and succ_lookup.is_alive():
                key_succ = succ_lookup
            else:
                raise RuntimeError("Lookup failed")
        return key_succ.send_request(request)

    # -- key transfer (chord_peer.cpp:242-310) -------------------------------
    def absorb_keys(self, kv_pairs: JsonObj) -> None:
        for hex_key, val in (kv_pairs or {}).items():
            self.db.insert(int(hex_key, 16), val)

    def handle_notify_from_pred(self, new_pred: RemotePeer) -> JsonObj:
        to_transfer = self.db.read_range(int(self.min_key), int(new_pred.id))
        data = {format(k, "x"): v for k, v in to_transfer.items()}
        for k in to_transfer:
            self.db.delete(k)
        self.finger_table.adjust_fingers(new_pred)
        self.predecessor = new_pred
        self.min_key = new_pred.id + 1
        return {"KEYS_TO_ABSORB": data}

    def handle_pred_failure(self, old_pred: RemotePeer) -> None:
        self.finger_table.adjust_fingers(self.to_remote_peer())
        self.rectify(old_pred)

    def keys_as_json(self) -> JsonObj:
        return {format(k, "x"): v for k, v in self.db.get_entries()}

    def fail(self) -> None:
        """Silent exit for fault injection (chord_peer.cpp:293-300)."""
        self.log("Stopping server/stabilize loop now")
        if self.server.is_alive():
            self.server.kill()
        self._stop_maintenance()

    def start_maintenance(self) -> None:
        self._start_maintenance_thread(self.stabilize)

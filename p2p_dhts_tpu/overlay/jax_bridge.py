"""Batching bridge: per-RPC finger lookups -> device ``u128`` kernel.

BASELINE.json's north star puts ``backend="jax"`` on ChordPeer's lookup
path (the reference resolves one key per FIND_SUCCESSOR RPC through
FingerTable::Lookup's 128-entry linear scan, finger_table.h:115-130,
called from chord_peer.cpp:185-211). A TPU executes that scan as a
batched kernel — but the wire layer receives keys ONE per RPC, so the
bridge's job is aggregation: concurrent lookups from the server's worker
threads coalesce into one device batch per dispatch window, pay one
kernel launch, and fan the results back out.

This module is the LEGACY bridge: the serving path for backend="jax"
finger tables now routes through ``p2p_dhts_tpu.serve`` (ServeEngine —
adaptive window, cross-table batching, pipelined dispatch), and this
class remains as the dependency-free fallback plus the reference
implementation its tests pin. It stays importable and correct.

Design:
  * no dedicated dispatcher thread — the first caller into an idle
    bridge becomes the batch leader, sleeps one window to let
    concurrent callers pile in, then serves everything pending in a
    single jitted call (``u128.sub`` + ``u128.bit_length``: entry
    index = bit_length((key - start) mod 2^128) - 1, the closed form
    of the reference's scan). A SOLO leader (nobody else pending after
    a short grace re-check) skips the window: the uncontended lookup
    no longer pays the full coalescing sleep (round-5 advisor #1).
  * static shapes: batches pad to power-of-two buckets so each bucket
    size compiles once per process.
  * jax imports lazily on first use — the overlay layer stays
    importable (and fast) for pure-wire deployments, and constructing
    peers never touches the TPU claim (verify-skill tunnel etiquette).

The bulk path for key-dense workloads remains ``DeviceDHT`` /
``core.ring.find_successor``; this bridge is the honest device wiring
for the per-request wire protocol.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from p2p_dhts_tpu.keyspace import KEYS_IN_RING

_kernel_lock = threading.Lock()
_kernel = None  # populated on first use; holds (jitted_fn, np, keyspace)


def _load_kernel():
    """Build the jitted finger-index kernel (once per process)."""
    global _kernel
    with _kernel_lock:
        if _kernel is None:
            import numpy as np

            import jax

            from p2p_dhts_tpu import keyspace
            from p2p_dhts_tpu.ops import u128

            @jax.jit
            # chordax-lint: disable=gspmd-kernel-untraced -- thin bridge over the same closed form the registry traces as serve.finger_index (ring.finger_index_batch); only host-side glue differs
            def finger_index(keys, start):
                # dist==0 -> bit_length 0 -> index -1: the "key is the
                # table's own starting key" LookupError case.
                dist = u128.sub(keys, start[None, :])
                return u128.bit_length(dist) - 1

            _kernel = (finger_index, np, keyspace)
    return _kernel


class DeviceFingerResolver:
    """Coalesces concurrent single-key lookups into device batches.

    ``lookup_index(key_int)`` blocks until the containing batch is
    served and returns the finger-table entry index (or -1 for the
    zero-distance LookupError case). Thread-safe; callers MUST NOT hold
    the finger table's lock while blocked here, or batching degrades to
    sequential singles.
    """

    MAX_BATCH = 1024
    #: Solo-leader grace: a leader that finds only its own slot pending
    #: sleeps this FRACTION of the window, re-checks, and if still
    #: alone serves immediately — the uncontended path pays window/4,
    #: not the full window (round-5 advisor #1). A fraction (not a
    #: fixed few-microsecond pause) so concurrent callers on a slow or
    #: oversubscribed host still get a real chance to enqueue before
    #: the solo verdict. 1.0 reproduces the pre-fix fixed window
    #: (bench.py uses that as the honest legacy baseline).
    SOLO_GRACE_FRACTION = 0.25

    def __init__(self, starting_key: int, window_s: float = 0.001):
        self._start_int = int(starting_key) % KEYS_IN_RING
        self._window_s = float(window_s)
        self._lock = threading.Lock()
        self._pending: List[Tuple[int, dict]] = []
        self._leader_active = False
        self._start_lanes = None  # device-resident [4] u32, built lazily
        # Telemetry for tests/metrics: sizes of recent device batches
        # (bounded — this sits on the per-RPC hot path) + running totals.
        from collections import deque
        self.batch_sizes = deque(maxlen=1024)
        self.batches_served = 0
        self.keys_served = 0

    # -- public ------------------------------------------------------------
    def lookup_index(self, key_int: int,
                     timeout: Optional[float] = None) -> int:
        """Resolve one key's finger-table entry index. `timeout` bounds
        the wait for the containing batch (None = wait forever, the
        historical behavior) — the same bounded-wait contract the
        engine path's slot.wait offers, so a caller propagating a
        deadline can hold it on whichever resolver layer it lands on.
        A timed-out follower leaves its slot in place — the leader
        still serves it (results nobody reads are dropped), so timing
        out never corrupts a batch."""
        slot: dict = {"ev": threading.Event()}
        with self._lock:
            self._pending.append((int(key_int) % KEYS_IN_RING, slot))
            lead = not self._leader_active
            if lead:
                self._leader_active = True
        if lead:
            # Exception-safe leadership: whatever happens from the
            # coalescing sleep through serving (KeyboardInterrupt, a
            # SIGALRM-injected timeout landing between the swap and
            # _serve's own handler), leadership is released and every
            # unserved slot is failed out — a wedged leader would
            # deadlock every later lookup.
            batch: List[Tuple[int, dict]] = []
            try:
                try:
                    self._coalescing_wait()
                finally:
                    with self._lock:
                        batch, self._pending = self._pending, []
                        self._leader_active = False
                self._serve(batch)
            except BaseException as exc:  # noqa: BLE001
                for _, s in batch:
                    if "index" not in s and "error" not in s:
                        s["error"] = exc
                        s["ev"].set()
                raise
        if not slot["ev"].wait(timeout):
            raise TimeoutError(
                f"legacy bridge lookup not served within {timeout}s")
        if "error" in slot:
            raise slot["error"]
        return slot["index"]

    # -- internals ----------------------------------------------------------
    def _coalescing_wait(self) -> None:
        """The leader's window sleep — skipped when the pending queue
        holds only the leader's own slot after a short grace re-check,
        so uncontended lookups dispatch immediately while concurrent
        callers still get the full coalescing window."""
        if self._window_s <= 0:
            return
        with self._lock:
            solo = len(self._pending) <= 1
        if not solo:
            time.sleep(self._window_s)
            return
        grace = self._window_s * self.SOLO_GRACE_FRACTION
        time.sleep(grace)
        with self._lock:
            solo = len(self._pending) <= 1
        if solo:
            return
        time.sleep(max(self._window_s - grace, 0.0))

    def _serve(self, batch: List[Tuple[int, dict]]) -> None:
        try:
            fn, np, keyspace = _load_kernel()
            if self._start_lanes is None:
                import jax.numpy as jnp
                self._start_lanes = jnp.asarray(
                    keyspace.ints_to_lanes([self._start_int])[0])
            for off in range(0, len(batch), self.MAX_BATCH):
                chunk = batch[off:off + self.MAX_BATCH]
                bucket = 1
                while bucket < len(chunk):
                    bucket *= 2
                ints = [k for k, _ in chunk]
                ints += [self._start_int] * (bucket - len(chunk))  # pad
                lanes = keyspace.ints_to_lanes(ints)
                idx = np.asarray(fn(lanes, self._start_lanes))
                self.batch_sizes.append(len(chunk))
                self.batches_served += 1
                self.keys_served += len(chunk)
                for j, (_, slot) in enumerate(chunk):
                    slot["index"] = int(idx[j])
                    slot["ev"].set()
        except BaseException as exc:  # noqa: BLE001 — fanned out to callers
            delivered = 0
            for _, slot in batch:
                if "index" not in slot and "error" not in slot:
                    slot["error"] = exc
                    slot["ev"].set()
                    delivered += 1
            if delivered == 0:
                # Nobody was left to receive the failure (empty batch,
                # or it struck after every slot was served): re-raise to
                # the leader instead of dropping it (round-5 advisor #2).
                raise

"""Compact sparse Merkle tree — port of the reference's DEPRECATED
CSMerkleNode (src/data_structures/merkle_node.h:1-945).

The reference carries two Merkle indexes: the active keyspace-partitioned
MerkleTree (merkle_tree.h, ours in overlay/merkle_tree.py) and this
earlier compact-sparse design, deprecated by its own header
(merkle_node.h:2) yet still unit-tested upstream
(test/merkle_tree_test.cc:5-23).  It is ported here for inventory
completeness: a binary Merkle tree where a new key's position is chosen
by XOR distance — floor(log2(key1 ^ key2)) (Distance,
merkle_node.h:57-61) — per the compact sparse Merkle tree construction
(eprint 2018/955) Cates' thesis approximates.

Semantics mirrored from the reference:
  * Leaf hash = SHA-1 of the VALUE string (ctor 1, merkle_node.h:90-96 —
    unlike the active MerkleTree, whose leaf hashes cover keys only).
  * Interior node: key = max(left.key, right.key), hash =
    SHA-1(hex(left.hash) + hex(right.hash)) (ConcatHash,
    merkle_node.h:70-73,101-110).
  * Insert descends toward the child at smaller XOR distance
    (merkle_node.h:547-590); equal distances append the new leaf beside
    the current subtree, ordered by key (merkle_node.h:570-580).
  * Lookup/Contains retrace the insertion path; equal distances mean
    "not present" (merkle_node.h:628-655, 847-870).
  * Delete promotes the sibling (merkle_node.h:768-802); Update rebuilds
    the spine (merkle_node.h:725-758).
  * ReadRange prunes on the left-max-key order and is ring-aware through
    Key.in_between (merkle_node.h:665-717).
  * Positions (left=False/right=True paths from the root) are reassigned
    after every mutation (FixPositions, merkle_node.h:884-901) and drive
    LookupPosition / NonRecursiveSerialize — the node-addressing scheme
    the XCHNG_NODE sync protocol of this generation used.

Documented fixes (not bugs ported): the reference's recursive
Insert/Update/Delete/ReadRange helpers sometimes read the OUTER object's
`left_`/`right_`/`root_` members instead of the `root` parameter
(merkle_node.h:573-574, 731, 742, 771, 785) — harmless only on the paths
its one test exercises; this port consistently uses the current subtree.
Missing-key LOOKUPS and any mutation of an empty tree raise RuntimeError
to match the overlay's error taxonomy (see overlay/merkle_tree.py module
doc); update/delete of a key absent from a NON-empty tree silently no-op,
as the reference's recursions do.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from p2p_dhts_tpu.keyspace import KEYS_IN_RING, Key, sha1_id


def _hex(v: int) -> str:
    """Hex without leading zeros (IntToHexStr, key.h:41-47); 0 -> '0'."""
    return format(v, "x")


def distance(key1: int, key2: int) -> int:
    """floor(log2(key1 ^ key2)) (merkle_node.h:57-61); -1 when equal
    (the reference's log2(0) = -inf: strictly below every real
    distance, so an exact-key match always wins the descent)."""
    return (int(key1) ^ int(key2)).bit_length() - 1


def concat_hash(hash1: int, hash2: int) -> int:
    """SHA-1 of the concatenated hex forms (ConcatHash,
    merkle_node.h:70-73)."""
    return sha1_id(_hex(hash1) + _hex(hash2))


class CSNode:
    """One node. Leaf: (key, value, hash=SHA1(value)). Interior:
    key = max child key, hash = concat_hash of child hashes."""

    __slots__ = ("key", "hash", "value", "left", "right", "position")

    def __init__(self, key: int, hash_: int, value: Optional[object],
                 left: Optional["CSNode"], right: Optional["CSNode"]):
        self.key = key
        self.hash = hash_
        self.value = value
        self.left = left
        self.right = right
        self.position: List[bool] = []

    @classmethod
    def leaf(cls, key: int, value: object) -> "CSNode":
        # hash_(val, false): SHA-1 of the value's string form
        # (merkle_node.h:90-96).
        return cls(int(key), sha1_id(str(value)), value, None, None)

    @classmethod
    def interior(cls, left: "CSNode", right: "CSNode") -> "CSNode":
        return cls(max(left.key, right.key),
                   concat_hash(left.hash, right.hash), None, left, right)

    @property
    def is_leaf(self) -> bool:
        return self.left is None and self.right is None

    def min_key(self) -> int:
        """Leftmost key in the subtree (GetMinKey, merkle_node.h:395-401)."""
        node = self
        while node.left is not None:
            node = node.left
        return node.key

    def fix_positions(self, dirs: List[bool]) -> None:
        """Reassign root-to-node direction paths (FixPositions,
        merkle_node.h:884-901)."""
        self.position = list(dirs)
        if self.left is not None:
            self.left.fix_positions(dirs + [False])
        if self.right is not None:
            self.right.fix_positions(dirs + [True])

    def leaves(self) -> Iterator["CSNode"]:
        if self.is_leaf:
            yield self
            return
        yield from self.left.leaves()
        yield from self.right.leaves()


class CSMerkleNode:
    """Tree facade over CSNode, the port of the reference class (which
    doubles as its own root handle, merkle_node.h:79-208)."""

    def __init__(self) -> None:
        self.root: Optional[CSNode] = None

    # -- mutation ----------------------------------------------------------

    def insert(self, key: int, value: object) -> None:
        """Insert / overwrite (Insert, merkle_node.h:208-226,547-620)."""
        key = int(key)
        if self.root is None:
            self.root = CSNode.leaf(key, value)
        else:
            self.root = self._insert(self.root, key, value)
        self.root.fix_positions([])

    def _insert(self, root: CSNode, key: int, value: object) -> CSNode:
        if root.is_leaf:
            # InsertLeaf (merkle_node.h:602-620): same key overwrites,
            # otherwise the leaf gains a key-ordered sibling.
            if root.key == key:
                return CSNode.leaf(key, value)
            new_leaf = CSNode.leaf(key, value)
            return (CSNode.interior(new_leaf, root) if key < root.key
                    else CSNode.interior(root, new_leaf))

        if root.left.is_leaf and root.left.key == key:
            return CSNode.interior(CSNode.leaf(key, value), root.right)
        if root.right.is_leaf and root.right.key == key:
            return CSNode.interior(root.left, CSNode.leaf(key, value))

        l_dist = distance(key, root.left.key)
        r_dist = distance(key, root.right.key)
        if l_dist == r_dist:
            # Equidistant: the new leaf becomes the subtree's sibling,
            # ordered against its smallest key (merkle_node.h:570-580;
            # outer-member read fixed, see module doc).
            new_leaf = CSNode.leaf(key, value)
            min_key = min(root.left.key, root.right.key)
            return (CSNode.interior(new_leaf, root) if key < min_key
                    else CSNode.interior(root, new_leaf))
        if l_dist < r_dist:
            return CSNode.interior(self._insert(root.left, key, value),
                                   root.right)
        return CSNode.interior(root.left,
                               self._insert(root.right, key, value))

    def update(self, key: int, new_value: object) -> None:
        """Rewrite a key's value (Update, merkle_node.h:265-276,725-758).

        Error contract mirrors the reference exactly: an EMPTY tree
        raises (the `!root_` branch throws, merkle_node.h:271-275); a
        non-empty tree missing the key is a silent no-op (the recursion
        returns the subtree unchanged on the equidistant and
        leaf-mismatch paths, merkle_node.h:730-753)."""
        if self.root is None:
            raise RuntimeError("key does not exist in tree")
        self.root = self._update(self.root, int(key), new_value)
        self.root.fix_positions([])

    def _update(self, root: CSNode, key: int, new_value: object) -> CSNode:
        if root.is_leaf:
            return CSNode.leaf(key, new_value) if root.key == key else root
        if root.left.is_leaf and root.left.key == key:
            return CSNode.interior(CSNode.leaf(key, new_value), root.right)
        if root.right.is_leaf and root.right.key == key:
            return CSNode.interior(root.left, CSNode.leaf(key, new_value))
        l_dist = distance(key, root.left.key)
        r_dist = distance(key, root.right.key)
        if l_dist == r_dist:
            return root
        if l_dist < r_dist:
            return CSNode.interior(self._update(root.left, key, new_value),
                                   root.right)
        return CSNode.interior(root.left,
                               self._update(root.right, key, new_value))

    def delete(self, key: int) -> None:
        """Remove a key; the sibling replaces the parent (Delete,
        merkle_node.h:283-300,768-802). Same error contract as update:
        empty tree raises, non-empty tree missing the key no-ops."""
        if self.root is None:
            raise RuntimeError("key does not exist in tree")
        self.root = self._delete(self.root, int(key))
        if self.root is not None:
            self.root.fix_positions([])

    def _delete(self, root: CSNode, key: int) -> Optional[CSNode]:
        if root.is_leaf:
            return None if root.key == key else root
        if root.left.is_leaf and root.left.key == key:
            return root.right
        if root.right.is_leaf and root.right.key == key:
            return root.left
        l_dist = distance(key, root.left.key)
        r_dist = distance(key, root.right.key)
        if l_dist == r_dist:
            return root  # not present (merkle_node.h:792-795)
        if l_dist < r_dist:
            return CSNode.interior(self._delete(root.left, key), root.right)
        return CSNode.interior(root.left, self._delete(root.right, key))

    # -- queries -----------------------------------------------------------

    def lookup(self, key: int) -> object:
        """Value for key, RuntimeError if absent (Lookup,
        merkle_node.h:235-243,628-655)."""
        if self.root is None:
            raise RuntimeError("key does not exist in tree")
        return self._lookup(self.root, int(key))

    def _lookup(self, root: CSNode, key: int) -> object:
        if root.is_leaf:
            if root.key == key:
                return root.value
            raise RuntimeError("Value not in tree")
        if root.left.is_leaf and root.left.key == key:
            return root.left.value
        if root.right.is_leaf and root.right.key == key:
            return root.right.value
        l_dist = distance(key, root.left.key)
        r_dist = distance(key, root.right.key)
        if l_dist < r_dist:
            return self._lookup(root.left, key)
        if r_dist < l_dist:
            return self._lookup(root.right, key)
        raise RuntimeError("Value not in tree")

    def contains(self, key: int) -> bool:
        """Contains (merkle_node.h:332-344,847-870)."""
        if self.root is None:
            return False
        return self._contains(self.root, int(key))

    def _contains(self, root: CSNode, key: int) -> bool:
        if root.is_leaf:
            return root.key == key
        if (root.left.is_leaf and root.left.key == key) or \
           (root.right.is_leaf and root.right.key == key):
            return True
        l_dist = distance(key, root.left.key)
        r_dist = distance(key, root.right.key)
        if l_dist < r_dist:
            return self._contains(root.left, key)
        if r_dist < l_dist:
            return self._contains(root.right, key)
        return False

    def read_range(self, lower_bound: int, upper_bound: int) -> Dict[int, object]:
        """kv pairs with key in the (ring-aware, inclusive) range
        (ReadRange, merkle_node.h:251-258,665-717).

        Documented fix: the reference prunes subtrees with LINEAR key
        comparisons (merkle_node.h:679,699) while testing leaves with the
        ring-aware InBetween, so a wrapped range (ub < lb) under-returns
        there; here a wrapped range is split at the ring origin into two
        linear ranges first (the same split the active MerkleTree does,
        merkle_tree.h:168-219)."""
        if self.root is None:
            return {}
        lb, ub = int(lower_bound), int(upper_bound)
        if lb <= ub:
            return self._read_range(self.root, lb, ub)
        out = self._read_range(self.root, lb, KEYS_IN_RING - 1)
        out.update(self._read_range(self.root, 0, ub))
        return out

    def _read_range(self, root: CSNode, lb: int, ub: int) -> Dict[int, object]:
        results: Dict[int, object] = {}
        if root.is_leaf:
            if Key(root.key).in_between(lb, ub, True):
                results[root.key] = root.value
            return results
        # Left subtree holds every key <= left.key (its max): prune when
        # even that max is below the lower bound (merkle_node.h:679-696).
        # Right subtree only matters once the left max enters the range
        # (merkle_node.h:699-714). Documented fix: the reference recurses
        # right with the LEFT child's key as the new lower bound
        # (merkle_node.h:707-710), which loosens the range whenever the
        # left prune fired (left.key < lb) and returns keys in
        # (left.key, lb); the original bound is kept here.
        if lb <= root.left.key:
            results.update(self._read_range(root.left, lb, ub))
        if root.left.key <= ub:
            results.update(self._read_range(root.right, lb, ub))
        return results

    def next(self, key: int) -> Optional[Tuple[int, object]]:
        """Next-greatest kv pair after key, None at the end (Next,
        merkle_node.h:304-327,812-835) — no ring wraparound, unlike the
        active MerkleTree.

        Documented fixes: the reference's recursion returns nullptr
        whenever it bottoms out at a leaf (merkle_node.h:814-816), losing
        the successor for any key that is a left-subtree maximum at depth
        >= 3, and the left-leaf-match case returns the raw right node
        (merkle_node.h:820-823), which its public wrapper dereferences as
        a leaf (bad_optional_access on interior nodes,
        merkle_node.h:319-325). Here the successor search descends on the
        left-max-key order (the same order the reference prunes by) and
        always resolves to a leaf."""
        if self.root is None:
            return None
        node = self._next(self.root, int(key))
        if node is None:
            return None
        return (node.key, node.value)

    def _next(self, root: CSNode, key: int) -> Optional[CSNode]:
        if root.is_leaf:
            return root if root.key > key else None
        # The left subtree holds every key <= left.key (its max): the
        # successor lives there iff that max exceeds key.
        if root.left.key > key:
            found = self._next(root.left, key)
            if found is not None:
                return found
        return self._next(root.right, key)

    def lookup_position(self, directions: Sequence[bool]) -> Optional[CSNode]:
        """Walk left(False)/right(True) from the root (LookupPosition,
        merkle_node.h:350-371)."""
        node = self.root
        for go_right in directions:
            if node is None:
                return None
            node = node.right if go_right else node.left
        return node

    def overlaps(self, lower_bound: int, upper_bound: int) -> bool:
        """Does the tree hold any key in the ring range? (Overlaps,
        merkle_node.h:379-391)."""
        if self.root is None:
            return False
        if self.root.is_leaf:
            return Key(self.root.key).in_between(lower_bound, upper_bound,
                                                 True)
        min_key = self.root.min_key()
        return (Key(lower_bound).in_between(min_key, self.root.key, True) or
                Key(upper_bound).in_between(min_key, self.root.key, True))

    # -- accessors / wire forms --------------------------------------------

    @property
    def hash(self) -> int:
        return 0 if self.root is None else self.root.hash

    @property
    def key(self) -> Optional[int]:
        return None if self.root is None else self.root.key

    @property
    def size(self) -> int:
        return 0 if self.root is None else sum(1 for _ in self.root.leaves())

    def items(self) -> Dict[int, object]:
        if self.root is None:
            return {}
        return {n.key: n.value for n in self.root.leaves()}

    def copy(self) -> "CSMerkleNode":
        """Value-semantics copy (the reference's copy ctor / assignment,
        merkle_node.h:142-190, exercised by merkle_tree_test.cc:5-23)."""
        out = CSMerkleNode()
        if self.root is not None:
            out.root = self._copy_node(self.root)
            out.root.fix_positions([])
        return out

    @staticmethod
    def _copy_node(node: CSNode) -> CSNode:
        if node.is_leaf:
            return CSNode.leaf(node.key, node.value)
        return CSNode.interior(CSMerkleNode._copy_node(node.left),
                               CSMerkleNode._copy_node(node.right))

    def non_recursive_serialize(self, node: Optional[CSNode] = None,
                                children: bool = True) -> dict:
        """One node (+ optionally its children, themselves child-free) for
        node exchange (NonRecursiveSerialize, merkle_node.h:470-496)."""
        if node is None:
            node = self.root
        if node is None:
            return {}
        out = {"HASH": _hex(node.hash), "KEY": _hex(node.key),
               "POSITION": [bool(b) for b in node.position]}
        if node.value is not None:
            out["VALUE"] = str(node.value)
        if children and node.left is not None:
            out["LEFT"] = self.non_recursive_serialize(node.left, False)
        if children and node.right is not None:
            out["RIGHT"] = self.non_recursive_serialize(node.right, False)
        return out

    def to_json(self) -> dict:
        """Full recursive JSON (operator Json::Value,
        merkle_node.h:498-524)."""
        return self._node_json(self.root) if self.root is not None else {}

    def _node_json(self, node: CSNode) -> dict:
        out = {"HASH": _hex(node.hash), "KEY": _hex(node.key),
               "POSITION": [bool(b) for b in node.position]}
        if node.value is not None:
            out["VALUE"] = str(node.value)
        if node.left is not None:
            out["LEFT"] = self._node_json(node.left)
        if node.right is not None:
            out["RIGHT"] = self._node_json(node.right)
        return out

    @classmethod
    def from_json(cls, obj: dict) -> "CSMerkleNode":
        """Rebuild from to_json output (ctor 3, merkle_node.h:115-136)."""
        out = cls()
        if obj:
            out.root = cls._node_from_json(obj)
            out.root.fix_positions([])
        return out

    @classmethod
    def _node_from_json(cls, obj: dict) -> CSNode:
        if "LEFT" in obj or "RIGHT" in obj:
            return CSNode.interior(cls._node_from_json(obj["LEFT"]),
                                   cls._node_from_json(obj["RIGHT"]))
        node = CSNode.leaf(int(obj["KEY"], 16), obj.get("VALUE"))
        # A keys-only wire form has no VALUE; keep the transmitted hash.
        node.hash = int(obj["HASH"], 16)
        return node

    def to_string(self) -> str:
        """Debug pretty-print (ToString, merkle_node.h:913-945)."""
        if self.root is None:
            return "<empty>"
        return self._to_string(self.root, 0)

    def _to_string(self, node: CSNode, level: int) -> str:
        tabs = "\t" * level
        res = f"{tabs}HASH: {_hex(node.hash)}\n{tabs}KEY: {_hex(node.key)}"
        if node.value is not None:
            res += f"\n{tabs}VALUE: {node.value}"
        if node.position:
            res += f"\n{tabs}POSITION:" + "".join(
                f" {int(b)}" for b in node.position)
        if node.left is not None:
            res += (f"\n{tabs}LEFT: {{\n{self._to_string(node.left, level + 1)}"
                    f"\n{tabs}}}")
        if node.right is not None:
            res += (f"\n{tabs}RIGHT: {{\n"
                    f"{self._to_string(node.right, level + 1)}\n{tabs}}}")
        return res

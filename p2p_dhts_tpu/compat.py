"""Version compatibility shims for the jax API surface.

The sharded kernels target the modern `jax.shard_map` entry point
(top-level since jax 0.6, `check_vma=` replication-checking kwarg).
Older runtimes — including the 0.4.x line this container bakes in —
ship the same machinery as `jax.experimental.shard_map.shard_map` with
the kwarg spelled `check_rep=`. One import point here keeps every call
site written against the modern spelling while degrading transparently:
without this gate, merely importing `p2p_dhts_tpu.dhash` (whose
__init__ re-exports the sharded layer) died with ImportError on 0.4.x,
taking bench.py and seven test modules down with it.
"""

from __future__ import annotations

import functools

try:  # jax >= 0.6: the public, stable entry point
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x/0.5.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f=None, /, **kwargs):
        """Modern-signature adapter over the experimental shard_map:
        accepts (and translates) `check_vma=` and supports the
        functools.partial(shard_map, ...) decorator idiom the kernels
        use."""
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return functools.partial(shard_map, **kwargs)
        return _shard_map_legacy(f, **kwargs)

__all__ = ["shard_map"]

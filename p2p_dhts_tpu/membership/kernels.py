"""chordax-membership device kernels: batched mixed-op churn + the
paced stabilize round, as single XLA programs over a capacity-padded
RingState.

The reference mutates membership one RPC at a time (Join / Leave /
Fail + the per-peer 5 s StabilizeLoop); chordax already batched each
op (core/churn.py) but nothing APPLIED them behind live traffic. These
two kernels are the device half of that control plane:

  * `churn_apply` — one [B]-lane batch of heterogeneous membership ops
    (op code + 128-bit member id per lane) applied in a fixed
    fail -> leave -> join order. Leave/fail lanes resolve their id to a
    table row by searchsorted (never a capacity-sized gather — the TPU
    compile-cliff rule from churn.leave); lanes whose id is unknown,
    dead, duplicated, or beyond the table's padding capacity come back
    applied=False with ZERO state mutation. Shape-stable by
    construction: the ring's capacity is fixed (power-of-two >= N,
    `padded_capacity`), so every batch bucket hits one cached program
    and the serve loop's zero-retrace contract extends to churn.
  * `stabilize_round` — one whole-ring stabilize/rectify sweep
    (core.churn.stabilize_sweep) plus the placement_converged verdict,
    so the MembershipManager can pace sweeps and stop when the ring
    has re-tiled its custody boundaries.

Padding discipline (the serve engine replicates a batch's first
request into pad lanes): a replicated JOIN is an intra-batch duplicate
(rejected), a replicated FAIL/LEAVE is an idempotent re-kill whose
scatters agree with the original lane — padding can never introduce a
new membership action, the same obligation serve.py's module doc pins
for puts.

Trace accounting mirrors repair/kernels.py: TRACE_COUNTS bumps at
trace time; the standalone jitted forms exist for tests and the GSPMD
registry, while serve.ServeEngine wraps the `_impl` bodies with its
own per-kind counters.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from p2p_dhts_tpu.core import churn
from p2p_dhts_tpu.core.ring import RingState, placement_converged
from p2p_dhts_tpu.membership import OP_FAIL, OP_JOIN, OP_LEAVE, OP_NOOP
from p2p_dhts_tpu.ops import u128

#: Traces per kernel since process start (repair/kernels.py pattern).
TRACE_COUNTS: Dict[str, int] = {"churn_apply": 0, "stabilize_round": 0}


def _count(kernel: str) -> None:
    TRACE_COUNTS[kernel] += 1


def trace_snapshot() -> Dict[str, int]:
    return dict(TRACE_COUNTS)


def retraces_since(snapshot: Dict[str, int]) -> int:
    return sum(TRACE_COUNTS.values()) - sum(snapshot.values())


def padded_capacity(n: int, minimum: int = 8) -> int:
    """The fixed table capacity an elastic ring is built with: the
    smallest power of two >= max(n, minimum). Every churn op on a
    capacity-padded ring is shape-stable (the alive mask absorbs
    membership change; array shapes never move), which is what keeps
    the serve loop's pre-traced buckets valid across a churn storm."""
    cap = int(minimum)
    n = max(int(n), 1)
    while cap < n:
        cap *= 2
    return cap


def _sorted_to_lane_order(values: jax.Array, perm: jax.Array
                          ) -> jax.Array:
    """Scatter sorted-batch-aligned values back to original lane order
    (sorted slot s holds original lane perm[s])."""
    out = jnp.zeros_like(values)
    return out.at[perm].set(values)


def churn_apply_impl(state: RingState, ops: jax.Array,
                     lanes: jax.Array, store=None):
    """Apply one mixed membership batch; returns (new state, applied)
    — or (new state, new store, applied) when a FragmentStore rides
    along. `applied` is [B] bool aligned to the INPUT lane order.

    ops:   [B] i32 of OP_NOOP / OP_JOIN / OP_LEAVE / OP_FAIL
    lanes: [B, 4] u32 member ids

    Order within the batch is fixed and documented: fails first, then
    leaves, then joins — so a fail+join of the same id in one batch is
    a restart (the id's row dies, then resurrects), matching the
    reference's kill-then-rejoin lifecycle. Leave/fail rows are
    resolved against the PRE-batch table (row indices are stable under
    fail/leave; join runs last precisely because it remaps rows).

    With a store, churn is STORE-MUTATING in the same program — the
    two row-indirection fixups that keep the serving store coherent
    with the new table happen atomically with the membership change:
      * graceful leavers hand their fragments to the alive ring
        successor (dhash.maintenance.leave_handover — the reference's
        LeaveHandler key transfer; a FAILED peer's fragments die with
        it, a LEAVING peer's do not), and
      * every holder row index is re-resolved through its peer id
        after the join shifted the table layout
        (dhash.maintenance.remap_holders) — without this, reads would
        consult the WRONG row's alive bit the moment a join inserts
        below a holder.
    Dead-held purging/regeneration is deliberately NOT here: it is
    unbounded decode work, paced separately (the "dhash_maintain"
    engine kind).
    """
    n = state.ids.shape[0]
    old_ids = state.ids  # pre-join table, for the holder remap

    # Resolve leave/fail ids -> rows (searchsorted + one B-sized
    # gather; the table-sized-gather compile cliff rule).
    pos = u128.searchsorted(state.ids, lanes, state.n_valid)
    pos_c = jnp.minimum(pos, n - 1)
    found = (pos < state.n_valid) & u128.eq(state.ids[pos_c], lanes) \
        & state.alive[pos_c]
    fail_rows = jnp.where((ops == OP_FAIL) & found, pos_c, n)
    leave_rows = jnp.where((ops == OP_LEAVE) & found, pos_c, n)
    state = churn.fail(state, fail_rows)
    state = churn.leave(state, leave_rows)
    if store is not None:
        # Handover BEFORE join: leaver rows are pre-join coordinates.
        from p2p_dhts_tpu.dhash.maintenance import (_handover_holders,
                                                    _remapped_holders)
        from p2p_dhts_tpu.core.ring import next_alive_map
        new_holder = _handover_holders(store.holder, store.used,
                                       next_alive_map(state),
                                       jnp.sort(leave_rows), n)
        store = store._replace(holder=new_holder)

    join_mask = ops == OP_JOIN
    state, jrows = churn.join(state, lanes, mask=join_mask)
    if store is not None:
        store = store._replace(
            holder=_remapped_holders(store.holder, old_ids, state))

    # join's rows are aligned to its SORTED batch (public contract kept
    # for existing callers); replay the identical deterministic sort —
    # the masked form's 5-key (ids, ~mask, lane) sort — to route the
    # admitted flags back to input lane order.
    k = lanes.shape[0]
    sort_ops = [lanes[:, 3], lanes[:, 2], lanes[:, 1], lanes[:, 0],
                (~join_mask).astype(jnp.int32),
                jnp.arange(k, dtype=jnp.int32)]
    *_, perm = jax.lax.sort(sort_ops, num_keys=5)
    join_applied = _sorted_to_lane_order(jrows >= 0, perm)

    applied = jnp.where(join_mask, join_applied,
                        ((ops == OP_LEAVE) | (ops == OP_FAIL)) & found)
    if store is not None:
        return state, store, applied
    return state, applied


@jax.jit
def churn_apply(state: RingState, ops: jax.Array, lanes: jax.Array
                ) -> Tuple[RingState, jax.Array]:
    """Jitted standalone form (tests, the GSPMD registry); the serve
    engine's "churn_apply" kind wraps the impl with the engine's own
    per-kind trace counter instead."""
    _count("churn_apply")
    return churn_apply_impl(state, ops, lanes)


@jax.jit
def churn_apply_store(state: RingState, ops: jax.Array,
                      lanes: jax.Array, store):
    """Standalone jitted form of the store-carrying churn batch."""
    _count("churn_apply")
    return churn_apply_impl(state, ops, lanes, store)


def stabilize_round_impl(state: RingState
                         ) -> Tuple[RingState, jax.Array]:
    """One whole-ring maintenance sweep + convergence verdict:
    (swept state, placement_converged(swept state))."""
    swept = churn.stabilize_sweep(state)
    return swept, placement_converged(swept)


@jax.jit
def stabilize_round(state: RingState) -> Tuple[RingState, jax.Array]:
    """Jitted standalone form of stabilize_round_impl."""
    _count("stabilize_round")
    return stabilize_round_impl(state)


__all__ = [
    "OP_FAIL", "OP_JOIN", "OP_LEAVE", "OP_NOOP", "TRACE_COUNTS",
    "churn_apply", "churn_apply_impl", "padded_capacity",
    "retraces_since", "stabilize_round", "stabilize_round_impl",
    "trace_snapshot",
]

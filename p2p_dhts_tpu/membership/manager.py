"""MembershipManager: the per-ring churn control loop.

The reference keeps rings alive with one maintenance thread per peer
(StabilizeLoop, chord_peer.cpp:213-240) and detects death by TCP
connect probes. Here ONE background loop per device ring drives the
whole lifecycle against the batched kernels:

  heartbeats -> failure detection -> churn batch -> stabilize rounds
                                   (engine "churn_apply")  ("stabilize_sweep")

  * FAILURE DETECTION — phi-accrual style (Hayashibara et al. 2004,
    simplified to a normalized-staleness score): each member's
    heartbeat inter-arrival time is EWMA-tracked, and
    phi = elapsed / max(ewma_interval, heartbeat_interval_s). A member
    is SUSPECTED at phi >= phi_threshold / 2 and FAILED (an OP_FAIL
    row enqueued) at phi >= phi_threshold — but never before
    `min_heartbeats` samples exist, so a slow-but-alive peer whose
    cadence the EWMA has adapted to is not failed early (the
    false-positive obligation tests pin). A heartbeat from a suspect
    clears the suspicion. PARTITION-AWARE (ISSUE 10): the FAIL verdict
    additionally needs `confirm_rounds` consecutive over-threshold
    scans, an optional reachability `probe` can VETO it (an asymmetric
    partition that blocks only the heartbeat path must not flap a
    reachable peer dead/alive — vetoed candidates stay SUSPECT,
    counted), and a heartbeat arriving while the OP_FAIL row still
    pends CANCELS the row (flap suppression). Post-heal, a re-JOIN of
    a dead row resurrects it and schedules the maintenance pass +
    repair-pair nudge, so the transferred-back custody reconciles
    rectify-style.
  * ADMISSION — joins are bounded per ring (`max_pending_joins`); an
    over-budget JOIN_RING is rejected visibly (counted), never queued
    without limit — the RingAdmission philosophy applied to
    membership.
  * PACING — the PR-6 scheduler discipline: a token bucket bounds
    churn rows/second (take / refund, non-blocking), each batch runs
    under a round deadline that the gateway threads into the engine
    (expired churn work is shed BEFORE device dispatch), failed rounds
    requeue their rows and back off exponentially WITH JITTER, and two
    consecutive rounds that apply nothing while work pends flip a
    visible `stalled` flag (counted) and drop to idle pacing.
  * OWNERSHIP HANDOFF — while a batch is in flight the backend is
    marked in-handoff: gateway fallback lookups serve from this
    manager's HOST MIRROR (closed form over the mirrored table —
    counted, never wrong) instead of the stale device snapshot; after
    the batch applies, the mirror, the backend's fallback RingState,
    and the transfer log all update before the window closes. Lost
    rows (fail/leave) nudge the attached repair scheduler so the
    transferred ranges heal from replicas at the repair cadence.

The host mirror is the exact twin of the device table (ids sorted
ascending including dead rows, parallel alive flags): it is updated
ONLY from the per-lane applied flags the churn kernel returns, so
mirror row i IS device row i — the oracle-parity property
tests/test_membership.py pins against a downloaded RingState.

Detection scope, deliberate: the phi detector covers REGISTERED
members — peers that came through request_join/JOIN_RING and
heartbeat. Rows seeded from the ring's initial table have no cadence
to model (failing them for never heartbeating would mass-fail a
healthy seed ring at startup), so they stay undetected until they
register (JOIN_RING on an alive id is an idempotent accept that
starts tracking) or an operator calls fail_member.

LOCK ORDER: `MembershipManager._lock` is a LEAF — never held across a
gateway/engine call, a device sync, or a sleep; the loop sleeps on an
Event holding nothing (the repair scheduler's rule).

This module never imports jax.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from p2p_dhts_tpu import havoc as havoc_mod
from p2p_dhts_tpu import trace as trace_mod
from p2p_dhts_tpu.health import PacedLoop
from p2p_dhts_tpu.keyspace import KEYS_IN_RING
from p2p_dhts_tpu.membership import OP_FAIL, OP_JOIN, OP_LEAVE
from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.repair.scheduler import TokenBucket

#: Member lifecycle states.
JOINING = "joining"
ALIVE = "alive"
SUSPECT = "suspect"
FAILED = "failed"
LEFT = "left"


class _Member:
    __slots__ = ("member_id", "state", "last_heard", "mean_interval_s",
                 "n_heartbeats", "over_phi_rounds")

    def __init__(self, member_id: int, state: str, now: float):
        self.member_id = member_id
        self.state = state
        self.last_heard = now
        self.mean_interval_s: Optional[float] = None
        self.n_heartbeats = 0
        #: Consecutive detector scans at/above the FAIL threshold —
        #: the partition-aware confirmation counter (a single late
        #: scan after a scheduling hiccup must not fail a peer).
        self.over_phi_rounds = 0


class MembershipManager(PacedLoop):
    """Live churn/elasticity control plane for one registered ring.

    A PacedLoop (ISSUE 8's consolidation): the background thread,
    jittered start, failure backoff and stall-aware pacing live in
    health.PacedLoop; this class owns the membership round itself
    (`step()`) and overrides `_busy()` with the membership rule — a
    round that batched rows or left the ring unconverged keeps active
    pacing unless stalled."""

    def __init__(self, gateway, ring_id: str, *,
                 heartbeat_interval_s: float = 1.0,
                 phi_threshold: float = 4.0,
                 min_heartbeats: int = 3,
                 confirm_rounds: int = 2,
                 probe=None,
                 interval_s: float = 0.05,
                 interval_idle_s: float = 1.0,
                 max_batch: int = 256,
                 max_pending_joins: int = 1024,
                 rate_rows_s: float = 4096.0,
                 burst_rows: float = 8192.0,
                 round_timeout_s: Optional[float] = 30.0,
                 backoff_base_s: float = 0.25,
                 backoff_cap_s: float = 10.0,
                 sweep_max_rounds: int = 8,
                 metrics: Optional[Metrics] = None):
        import numpy as np

        from p2p_dhts_tpu.keyspace import lanes_to_ints

        self.gateway = gateway
        self.ring_id = str(ring_id)
        self.backend = gateway.router.get(self.ring_id)
        self.engine = self.backend.engine
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.phi_threshold = float(phi_threshold)
        self.min_heartbeats = int(min_heartbeats)
        #: Partition-aware detection (ISSUE 10): a member must sit at/
        #: above the FAIL threshold for this many CONSECUTIVE detector
        #: scans before OP_FAIL is even considered...
        self.confirm_rounds = max(int(confirm_rounds), 1)
        #: ...and when a reachability `probe(member_id) -> bool` is
        #: provided, a confirmed candidate that still answers it is
        #: VETOED (kept SUSPECT, counted) instead of failed — an
        #: asymmetric partition that only blocks the heartbeat path
        #: must not flap a slow-but-reachable peer dead/alive. The
        #: probe runs OUTSIDE the manager lock (it may do an RPC).
        self.probe = probe
        self.max_batch = int(max_batch)
        self.max_pending_joins = int(max_pending_joins)
        self.round_timeout_s = round_timeout_s
        self.sweep_max_rounds = int(sweep_max_rounds)
        if metrics is None:
            # Default to the gateway's registry so membership.* counters
            # land next to the gateway.*/repair.* families it reports.
            metrics = getattr(getattr(gateway, "metrics", None),
                              "base", None)
        # PacedLoop owns interval_s/interval_idle_s/backoff_*/metrics,
        # the stop event, the thread, and the failure/backoff/stall
        # bookkeeping (the PR-6 discipline, now the one shared base).
        PacedLoop.__init__(
            self, name=f"membership:{self.ring_id}", kind="membership",
            interval_s=interval_s, interval_idle_s=interval_idle_s,
            backoff_base_s=backoff_base_s, backoff_cap_s=backoff_cap_s,
            metrics=metrics if metrics is not None else METRICS,
            failure_metric=f"membership.round_failures.{self.ring_id}",
            bucket=TokenBucket(rate_rows_s, burst_rows),
            thread_name=f"membership-{self.ring_id}")

        self._lock = threading.Lock()
        self._pending: Deque[Tuple[int, int]] = collections.deque()
        self._pending_joins = 0
        self._members: Dict[int, _Member] = {}
        self._recent_transfers: Deque[Tuple[int, int]] = \
            collections.deque(maxlen=64)
        # Applied-batch listeners (chordax-mesh, ISSUE 15): fired
        # AFTER a churn batch lands on the device AND the mirror, with
        # the applied [(op, member_id)] rows — the mesh coordinator's
        # re-split trigger. Fired outside every lock; callbacks must
        # be cheap and never call back into step().
        self._applied_listeners: List = []

        # Host mirror of the device table: ALL table ids (sorted
        # ascending, dead rows included) + parallel alive flags, seeded
        # from one download of the engine's current chained state.
        state = self.engine.ring_snapshot()
        if state is None:
            raise ValueError(f"ring {ring_id!r} has no RingState; a "
                             f"membership manager needs a device ring")
        ids_np = np.asarray(state.ids)
        alive_np = np.asarray(state.alive)
        nv = int(state.n_valid)
        self._mirror_ids: List[int] = lanes_to_ints(ids_np[:nv])
        self._mirror_alive: List[bool] = [bool(a) for a in alive_np[:nv]]
        self.capacity = int(ids_np.shape[0])

        # Loop state (written by step()/the loop thread); rounds /
        # failures / backoff_s / stalled / last_error live on the
        # PacedLoop base. A fresh ring starts converged.
        self.batches_applied = 0
        self.rows_applied = 0
        self.sweep_rounds = 0
        self.rows_regenerated = 0
        self.converged = True
        self._noop_rounds = 0
        self._maintain_due = False
        self._loop_busy = False

        # Attach: the gateway's handoff-failover path and the wire
        # verbs (JOIN_RING / HEARTBEAT / MEMBER_STATUS) find us here.
        self.backend.membership = self
        gateway.attach_membership(self)

    def add_applied_listener(self, cb) -> None:
        """Register cb(applied_rows) to fire after every churn batch
        that applied at least one row (applied_rows =
        [(op_code, member_id)] of the rows whose per-lane flag was
        True). The mesh coordinator subscribes here so a join/fail
        landing on the control ring re-splits the shard map without a
        polling loop."""
        with self._lock:
            self._applied_listeners.append(cb)

    def _fire_applied(self, applied_rows) -> None:
        with self._lock:
            listeners = list(self._applied_listeners)
        for cb in listeners:
            try:
                cb(applied_rows)
            # chordax-lint: disable=bare-except -- a listener error must never fail the membership round that already applied
            except Exception:
                self.metrics.inc(
                    f"membership.listener_errors.{self.ring_id}")

    # -- wire-facing membership API ------------------------------------------
    def request_join(self, member_id: int) -> bool:
        """Admit one join (JOIN_RING): bounded per-ring admission —
        an over-budget request is refused visibly, never queued
        without limit. Returns acceptance; the id enters the ring at
        the next applied churn batch."""
        member_id = int(member_id) % KEYS_IN_RING
        now = time.monotonic()
        with self._lock:
            if self._pending_joins >= self.max_pending_joins:
                self.metrics.inc(
                    f"membership.join_rejected.{self.ring_id}")
                return False
            i = bisect.bisect_left(self._mirror_ids, member_id)
            already = (i < len(self._mirror_ids)
                       and self._mirror_ids[i] == member_id
                       and self._mirror_alive[i])
            m = self._members.get(member_id)
            if already and (m is None or m.state in (ALIVE, SUSPECT)):
                # Already a live member: idempotent accept, nothing to
                # enqueue (the reference's rejoin-under-same-id mode
                # only matters for DEAD rows). This is also how a
                # member SEEDED from the ring's initial table opts into
                # failure detection: registering here creates its
                # tracking entry, and its heartbeats take over.
                self._members.setdefault(
                    member_id, _Member(member_id, ALIVE, now))
                return True
            if m is not None and m.state == JOINING:
                # A retry racing the still-pending first row: one
                # OP_JOIN lane is enough — a duplicate would be
                # device-rejected and miscounted as an admission
                # refusal, and would burn token budget in a storm.
                return True
            self._members[member_id] = _Member(member_id, JOINING, now)
            self._pending.append((OP_JOIN, member_id))
            self._pending_joins += 1
        self.metrics.inc(f"membership.join_requests.{self.ring_id}")
        return True

    def request_join_many(self, member_ids) -> int:
        """Policy-initiated churn entry point (chordax-elastic): admit
        a whole batch of joins through the SAME bounded, idempotent
        per-id gate as request_join — an elastic grow never bypasses
        admission, it just amortizes the call. Returns the accepted
        count; refusals are the usual visible
        `membership.join_rejected.<ring>` rows."""
        return sum(1 for m in member_ids if self.request_join(m))

    def heartbeat(self, member_id: int) -> bool:
        """Record one heartbeat; returns False for unknown members
        (they must JOIN_RING first — counted, not an error).

        FLAP SUPPRESSION (ISSUE 10): a heartbeat from a member the
        detector marked FAILED whose OP_FAIL row is still PENDING
        cancels the row and restores the member — a late-but-delivered
        heartbeat after a transient one-way cut costs nothing. Once the
        row has been applied the member is gone from the table and must
        JOIN_RING again (the post-heal rejoin path, which resurrects
        the dead device row and nudges the repair pairs)."""
        member_id = int(member_id) % KEYS_IN_RING
        now = time.monotonic()
        if havoc_mod.enabled():
            act = havoc_mod.decide("membership.heartbeat",
                                   key=member_id)
            if act is not None:
                action = act.get("action", "drop")
                if action == "drop":
                    # The partitioned direction: this heartbeat never
                    # arrives. (The peer itself is untouched — the
                    # asymmetric shape.)
                    return False
                if action == "delay":
                    # Arrived LATE: the recorded arrival predates now,
                    # so the inter-arrival model sees the gap a slow
                    # path would have produced.
                    now -= float(act.get("delay_s", 0.0))
        with self._lock:
            m = self._members.get(member_id)
            if m is not None and m.state == FAILED:
                try:
                    self._pending.remove((OP_FAIL, member_id))
                    cancelled = True
                except ValueError:
                    cancelled = False  # already popped/applied
                if cancelled:
                    m.state = ALIVE
                    m.over_phi_rounds = 0
                    self.metrics.inc(
                        f"membership.flap_suppressed.{self.ring_id}")
                else:
                    m = None  # fall through to the unknown path
            if m is None or m.state == LEFT:
                self.metrics.inc(
                    f"membership.heartbeat_unknown.{self.ring_id}")
                return False
            # An injected delay can place `now` before the last record;
            # the model never learns a negative interval.
            dt = max(now - m.last_heard, 0.0)
            if m.n_heartbeats > 0:
                m.mean_interval_s = (dt if m.mean_interval_s is None
                                     else 0.8 * m.mean_interval_s
                                     + 0.2 * dt)
            m.n_heartbeats += 1
            m.last_heard = max(now, m.last_heard)
            m.over_phi_rounds = 0
            if m.state == SUSPECT:
                m.state = ALIVE
                self.metrics.inc(
                    f"membership.suspicion_cleared.{self.ring_id}")
        self.metrics.inc(f"membership.heartbeats.{self.ring_id}")
        return True

    def request_leave(self, member_id: int) -> bool:
        """Graceful leave: custody hands to the successor at the next
        applied batch (core.churn.leave semantics)."""
        return self._enqueue_departure(member_id, OP_LEAVE)

    def fail_member(self, member_id: int) -> bool:
        """Explicit failure injection (the detector's path, exposed for
        tests/benches and operator kill)."""
        return self._enqueue_departure(member_id, OP_FAIL)

    def _enqueue_departure(self, member_id: int, op: int) -> bool:
        member_id = int(member_id) % KEYS_IN_RING
        now = time.monotonic()
        with self._lock:
            i = bisect.bisect_left(self._mirror_ids, member_id)
            known = (i < len(self._mirror_ids)
                     and self._mirror_ids[i] == member_id
                     and self._mirror_alive[i])
            if not known:
                return False
            m = self._members.get(member_id)
            if m is not None and m.state in (FAILED, LEFT):
                # Already departing (e.g. the detector's OP_FAIL racing
                # an operator kill): one row is enough — duplicates
                # would double-count lost_rows and burn tokens.
                return True
            m = self._members.setdefault(
                member_id, _Member(member_id, ALIVE, now))
            m.state = LEFT if op == OP_LEAVE else FAILED
            self._pending.append((op, member_id))
        return True

    # -- failure detection ----------------------------------------------------
    def _phi(self, m: _Member, now: float) -> float:
        scale = max(m.mean_interval_s or 0.0, self.heartbeat_interval_s)
        return (now - m.last_heard) / scale

    def _detect_failures_locked(self, now: float) -> List[int]:
        """Scan members; returns the ids whose phi sat at/above the
        FAIL threshold for `confirm_rounds` consecutive scans — the
        CANDIDATES. Nothing is failed here: the caller confirms them
        outside the lock (reachability probe — it may do an RPC).
        Caller holds the lock."""
        candidates: List[int] = []
        for m in self._members.values():
            if m.state not in (ALIVE, SUSPECT):
                continue
            if m.n_heartbeats < self.min_heartbeats:
                # Not enough evidence to model this member's cadence —
                # the no-premature-verdict rule.
                continue
            skew = 0.0
            if havoc_mod.enabled():
                act = havoc_mod.decide("membership.clock",
                                       key=m.member_id)
                if act is not None:
                    # Injected clock skew: the detector sees this
                    # member's silence stretched/compressed.
                    skew = float(act.get("skew_s", 0.0))
            phi = self._phi(m, now + skew)
            if phi >= self.phi_threshold:
                m.over_phi_rounds += 1
                if m.state == ALIVE:
                    m.state = SUSPECT
                    self.metrics.inc(
                        f"membership.suspects.{self.ring_id}")
                if m.over_phi_rounds >= self.confirm_rounds:
                    candidates.append(m.member_id)
            elif phi >= self.phi_threshold / 2:
                m.over_phi_rounds = 0
                if m.state == ALIVE:
                    m.state = SUSPECT
                    self.metrics.inc(
                        f"membership.suspects.{self.ring_id}")
            else:
                m.over_phi_rounds = 0
        return candidates

    def _confirm_failures(self, candidates: Sequence[int]) -> int:
        """The un-locked half of detection: probe each confirmed
        candidate (when a probe is configured) and enqueue OP_FAIL for
        the unreachable ones. A candidate that still answers the probe
        is an ASYMMETRIC-PARTITION suspect — heartbeats blocked, peer
        alive — and is vetoed (counted), not failed: no dead/alive
        flapping on a one-way network cut."""
        enqueued = 0
        for member_id in candidates:
            reachable = False
            if self.probe is not None:
                try:
                    reachable = bool(self.probe(member_id))
                # chordax-lint: disable=bare-except -- a probe error is "unreachable", never a detector crash
                except Exception:
                    reachable = False
            with self._lock:
                m = self._members.get(member_id)
                if m is None or m.state not in (ALIVE, SUSPECT):
                    continue  # a heartbeat/departure raced the probe
                if reachable:
                    m.over_phi_rounds = 0
                    self.metrics.inc(
                        f"membership.fail_vetoed.{self.ring_id}")
                    continue
                m.state = FAILED
                self._pending.append((OP_FAIL, m.member_id))
                self.metrics.inc(
                    f"membership.failures_detected.{self.ring_id}")
                enqueued += 1
        return enqueued

    # -- the control round ----------------------------------------------------
    def step(self) -> dict:
        """One foreground control round (the deterministic form tests,
        the bench, and the dryrun drive; the background loop calls the
        same thing). Detect -> batch -> apply -> sweep.

        chordax-pulse (ISSUE 11): with tracing enabled the whole round
        is ONE linked span tree — `membership.round` at the root, the
        scan -> churn_apply -> stabilize -> maintain phases as
        children, the gateway/engine spans of the device batches
        nesting underneath — so a membership round reads as a single
        trace in the Chrome export (the PR-8 open thread). span() is
        one flag read when tracing is off."""
        with trace_mod.span("membership.round", cat="membership",
                            ring=self.ring_id):
            return self._step_impl()

    def _step_impl(self) -> dict:
        from p2p_dhts_tpu.gateway.admission import Deadline

        now = time.monotonic()
        with trace_mod.span("membership.scan", cat="membership"):
            with self._lock:
                candidates = self._detect_failures_locked(now)
            if candidates:
                self._confirm_failures(candidates)
        granted = self.bucket.take(self.max_batch)
        batch: List[Tuple[int, int]] = []
        with self._lock:
            while self._pending and len(batch) < granted:
                batch.append(self._pending.popleft())
            for op, _ in batch:
                if op == OP_JOIN:
                    self._pending_joins -= 1
        self.bucket.refund(granted - len(batch))

        applied_n = 0
        lost_rows = 0
        resurrected = 0
        if batch:
            dl = Deadline.from_timeout(self.round_timeout_s)
            self.backend.begin_handoff()
            try:
                with trace_mod.span("membership.churn_apply",
                                    cat="membership",
                                    rows=len(batch)):
                    flags = self.gateway.churn_apply_many(
                        batch, ring_id=self.ring_id, deadline=dl)
                with self._lock:
                    applied_n, lost_rows, resurrected = \
                        self._apply_to_mirror_locked(
                            batch, flags, time.monotonic())
                # Fallback-path snapshot: the engine's chained state
                # already includes this batch (FIFO), so the swap and
                # the mirror update close the handoff window together.
                self.backend.set_ring_state(self.engine.ring_snapshot())
            except BaseException:
                # Nothing applied: the rows go back to the FRONT of
                # the queue (order preserved) and their tokens return.
                with self._lock:
                    self._pending.extendleft(reversed(batch))
                    self._pending_joins += sum(
                        1 for op, _ in batch if op == OP_JOIN)
                self.bucket.refund(len(batch))
                raise
            finally:
                self.backend.end_handoff()
            self.metrics.inc(f"membership.batches.{self.ring_id}")
            self.metrics.inc(f"membership.rows_applied.{self.ring_id}",
                             applied_n)
            self.batches_applied += 1
            self.rows_applied += applied_n
            self.converged = False
            if applied_n:
                self._fire_applied(
                    [row for row, ok in zip(batch, flags) if ok])
            # Lost rows AND post-heal resurrections re-transfer
            # custody: both schedule the maintenance pass + repair
            # nudge (the rectify-style post-heal reconcile).
            self._maintain_due = (self._maintain_due or lost_rows > 0
                                  or resurrected > 0)

        # Stabilize pacing: one sweep per round while unconverged,
        # bounded per step so a wedged ring cannot monopolize the loop.
        sweeps = 0
        if not self.converged:
            with trace_mod.span("membership.stabilize",
                                cat="membership"):
                while not self.converged and \
                        sweeps < self.sweep_max_rounds:
                    dl = Deadline.from_timeout(self.round_timeout_s)
                    self.converged = bool(self.gateway.stabilize_ring(
                        self.ring_id, deadline=dl))
                    self.sweep_rounds += 1
                    sweeps += 1
                    if not batch and sweeps >= 1:
                        break  # idle rounds sweep at most once

        # Targeted heals for the transferred ranges, once the sweep has
        # re-tiled custody: one paced local-maintenance pass purges the
        # dead-held rows and regenerates every >= m-survivor block
        # in-ring; the purge makes the loss digest-visible, and the
        # nudged repair pairs heal the rest from replicas.
        regenerated = 0
        if self._maintain_due and self.converged:
            with trace_mod.span("membership.maintain",
                                cat="membership"):
                dl = Deadline.from_timeout(self.round_timeout_s)
                if getattr(self.engine, "has_store", False):
                    regenerated = self.gateway.dhash_maintain(
                        self.ring_id, deadline=dl)
                    self.rows_regenerated += regenerated
                    if regenerated:
                        self.metrics.inc(
                            f"membership.rows_regenerated."
                            f"{self.ring_id}",
                            regenerated)
                self._maintain_due = False
                nudged = self.gateway.nudge_repair(self.ring_id)
                if nudged:
                    self.metrics.inc(
                        f"membership.heal_enqueued.{self.ring_id}",
                        nudged)

        # Stall detection (the PR-6 rule): work pends but two
        # consecutive rounds applied nothing — flip visible, idle-pace.
        if batch and applied_n == 0:
            self._noop_rounds += 1
            self.metrics.inc(
                f"membership.stalled_rounds.{self.ring_id}")
        elif batch:
            self._noop_rounds = 0
        self.stalled = self._noop_rounds >= 2

        self.rounds += 1
        self.mark_round()
        with self._lock:
            pending = len(self._pending)
            alive = sum(1 for a in self._mirror_alive if a)
        self.metrics.gauge(f"membership.pending.{self.ring_id}", pending)
        self.metrics.gauge(f"membership.members_alive.{self.ring_id}",
                           alive)
        self.metrics.gauge(f"membership.converged.{self.ring_id}",
                           1.0 if self.converged else 0.0)
        return {"batched": len(batch), "applied": applied_n,
                "lost_rows": lost_rows, "pending": pending,
                "converged": self.converged, "sweeps": sweeps,
                "regenerated": regenerated,
                "maintain_due": self._maintain_due,
                "alive": alive, "stalled": self.stalled}

    def _apply_to_mirror_locked(self, batch: Sequence[Tuple[int, int]],
                                flags: Sequence[bool], now: float
                                ) -> Tuple[int, int, int]:
        """Mirror the kernel's per-lane outcomes onto the host table.
        Returns (applied rows, lost rows i.e. applied fails+leaves,
        resurrected rows i.e. joins that revived a dead row — the
        post-heal rejoin shape, which re-transfers custody and so
        wants the same maintain/repair nudge a loss does).
        Caller holds the lock."""
        applied = 0
        lost = 0
        resurrected = 0
        for (op, member_id), ok in zip(batch, flags):
            m = self._members.get(member_id)
            if not ok:
                if op == OP_JOIN:
                    # Rejected by the device (duplicate / capacity):
                    # visible, and the member entry does not linger as
                    # a zombie the detector would later "fail".
                    self.metrics.inc(
                        f"membership.join_rejected.{self.ring_id}")
                    if m is not None and m.state == JOINING:
                        del self._members[member_id]
                continue
            applied += 1
            i = bisect.bisect_left(self._mirror_ids, member_id)
            present = (i < len(self._mirror_ids)
                       and self._mirror_ids[i] == member_id)
            if op == OP_JOIN:
                if present:
                    if not self._mirror_alive[i]:
                        # Post-heal rejoin: the dead row revives and
                        # custody moves BACK — digests changed, so the
                        # maintain/repair nudge must follow.
                        resurrected += 1
                        self.metrics.inc(
                            f"membership.rejoins.{self.ring_id}")
                    self._mirror_alive[i] = True   # rejoin/resurrect
                else:
                    self._mirror_ids.insert(i, member_id)
                    self._mirror_alive.insert(i, True)
                if m is not None:
                    m.state = ALIVE
                    m.last_heard = now  # grace until first heartbeat
                self._recent_transfers.append(
                    self._owned_range_locked(member_id))
            else:
                if present:
                    self._mirror_alive[i] = False
                # Departed entries leave the member table once applied:
                # the detector never re-scans them, heartbeats answer
                # KNOWN:false (rejoin), and the table stays bounded by
                # the ACTIVE membership under unbounded churn of
                # unique ids.
                self._members.pop(member_id, None)
                self._recent_transfers.append(
                    self._owned_range_locked(member_id))
                lost += 1
        if applied:
            self.metrics.inc(
                f"membership.ranges_transferred.{self.ring_id}", applied)
        return applied, lost, resurrected

    def _owned_range_locked(self, member_id: int) -> Tuple[int, int]:
        """[pred_alive_id + 1, member_id]: the key range whose custody
        the op transferred (to the member on join, to its successor on
        fail/leave)."""
        n = len(self._mirror_ids)
        i = bisect.bisect_left(self._mirror_ids, member_id)
        j = (i - 1) % n if n else 0
        for _ in range(max(n - 1, 0)):
            if self._mirror_alive[j] and self._mirror_ids[j] != member_id:
                break
            j = (j - 1) % n
        lo = (self._mirror_ids[j] + 1) % KEYS_IN_RING if n else 0
        return (lo, member_id)

    # -- host-mirror resolution (the handoff closed form) ---------------------
    def owner_row(self, key_int: int) -> int:
        """Device row of the alive ring successor of `key_int`,
        resolved on the HOST mirror (bisect + alive scan). Mirror row
        indices ARE device rows (same sorted table, dead rows kept), so
        this is the closed-form twin of core.ring.owner_of — the
        never-wrong answer the gateway serves during a handoff window.
        -1 when no member is alive."""
        key_int = int(key_int) % KEYS_IN_RING
        with self._lock:
            n = len(self._mirror_ids)
            if n == 0:
                return -1
            i = bisect.bisect_left(self._mirror_ids, key_int)
            for k in range(n):
                j = (i + k) % n
                if self._mirror_alive[j]:
                    return j
        return -1

    def alive_ids(self) -> List[int]:
        with self._lock:
            return [pid for pid, a in zip(self._mirror_ids,
                                          self._mirror_alive) if a]

    def mirror_snapshot(self) -> Tuple[List[int], List[bool]]:
        with self._lock:
            return list(self._mirror_ids), list(self._mirror_alive)

    @property
    def pending_ops(self) -> int:
        with self._lock:
            return len(self._pending)

    def recent_transfers(self) -> List[Tuple[int, int]]:
        with self._lock:
            return list(self._recent_transfers)

    # -- foreground driving ---------------------------------------------------
    def quiesce(self, max_rounds: int = 64) -> dict:
        """Drive step() until nothing pends and the ring converged —
        the bounded post-storm convergence the bench smoke asserts.
        Raises on stall or budget exhaustion."""
        last: dict = {}
        for _ in range(int(max_rounds)):
            last = self.step()
            if self.stalled:
                raise RuntimeError(
                    f"membership ring {self.ring_id!r} STALLED: "
                    f"{last['pending']} ops pend but rounds apply "
                    f"nothing (capacity full? duplicate storm?)")
            if last["pending"] == 0 and last["batched"] == 0 \
                    and last["converged"] and not last["maintain_due"]:
                return last
        raise RuntimeError(
            f"membership ring {self.ring_id!r} did not quiesce within "
            f"{max_rounds} rounds ({last})")

    # -- lifecycle ------------------------------------------------------------
    # start()/close() and the background thread come from PacedLoop;
    # the two hooks below are the membership-specific pacing policy.

    def _round(self) -> None:
        summary = self.step()
        self._loop_busy = (summary["batched"] > 0
                           or not summary["converged"])

    def _busy(self) -> bool:
        # A round that batched rows or left the ring unconverged keeps
        # the active interval — unless the loop stalled (work pends but
        # rounds apply nothing), which idles it visibly.
        return self._loop_busy and not self.stalled

    def __enter__(self) -> "MembershipManager":
        self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- observability --------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            by_state: Dict[str, int] = {}
            for m in self._members.values():
                by_state[m.state] = by_state.get(m.state, 0) + 1
            pending = len(self._pending)
            alive = sum(1 for a in self._mirror_alive if a)
            table = len(self._mirror_ids)
        return {
            "ring": self.ring_id,
            "capacity": self.capacity,
            "table_rows": table,
            "alive": alive,
            "members": by_state,
            "pending_ops": pending,
            "rounds": self.rounds,
            "batches_applied": self.batches_applied,
            "rows_applied": self.rows_applied,
            "sweep_rounds": self.sweep_rounds,
            "rows_regenerated": self.rows_regenerated,
            "converged": self.converged,
            "stalled": self.stalled,
            "failures": self.failures,
            "backoff_s": round(self.backoff_s, 3),
            "last_error": self.last_error,
            "tokens": round(self.bucket.tokens, 1),
        }


# ---------------------------------------------------------------------------
# host-overlay join pool (the chord_peer mass-churn wedge fix)
# ---------------------------------------------------------------------------

_JOIN_POOL_LOCK = threading.Lock()
_JOIN_POOL: Optional[ThreadPoolExecutor] = None


def overlay_join_executor() -> ThreadPoolExecutor:
    """The process-wide pool JOIN handlers defer their recursive
    pred-resolution onto (net.rpc.DeferredResponse): a storm of
    simultaneous joiners occupies THIS pool while the server's 3
    reference workers stay free to answer the nested GET_PRED/GET_SUCC
    requests the join work itself issues — the mass-churn wedge
    (overlay/chord_peer.py) dissolves instead of timing out."""
    global _JOIN_POOL
    with _JOIN_POOL_LOCK:
        if _JOIN_POOL is None:
            _JOIN_POOL = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="membership-join")
        return _JOIN_POOL

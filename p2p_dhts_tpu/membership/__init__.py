"""chordax-membership: the live churn/elasticity control plane
(ISSUE 7).

The reference's defining runtime behavior — peers join, crash, and
stabilize continuously (Stoica et al. 2001; Zave's rectify) — as a
first-class subsystem over the PR-4 gateway and the PR-2 ServeEngine:

  mutable rings  capacity-padded RingStates (power-of-two capacity,
                 alive mask) churn through the engine's store-chaining
                 "churn_apply" / "stabilize_sweep" kinds — FIFO-ordered
                 with in-flight lookups/puts, epoch-rolled-back on
                 failure, zero steady-state retraces
                 (membership/kernels.py + serve.py).
  manager        a per-ring background loop: heartbeat-driven
                 phi-accrual-style failure detection, bounded join
                 admission, token-bucket-paced churn batches and
                 stabilize rounds with jittered backoff, pre-dispatch
                 deadline shedding and stall detection — the PR-6
                 scheduler discipline (membership/manager.py).
  integration    JOIN_RING / HEARTBEAT / MEMBER_STATUS wire verbs on
                 every gateway server; ownership handoff windows whose
                 fallback lookups serve from the manager's host mirror
                 (counted, never wrong); lost ranges nudge the repair
                 scheduler; router hot add/remove auto-enrolls and
                 retires repair pairs (gateway/frontend.py).

Importing this package pulls the gateway/serve stack but never
initializes a jax backend; device work happens only once churn flows.
"""

#: Membership op codes (the churn_apply lane vocabulary). Plain ints,
#: defined BEFORE the manager import so membership/kernels.py and
#: membership/manager.py can both import them from here without a
#: cycle.
OP_NOOP = 0
OP_JOIN = 1
OP_LEAVE = 2
OP_FAIL = 3

#: The ops serve.ServeEngine accepts in a churn_apply payload (OP_NOOP
#: lanes are legal no-ops so callers can pad their own batches).
VALID_OPS = frozenset({OP_NOOP, OP_JOIN, OP_LEAVE, OP_FAIL})

from p2p_dhts_tpu.membership.manager import (  # noqa: E402,F401
    MembershipManager,
    overlay_join_executor,
)

__all__ = [
    "MembershipManager", "OP_FAIL", "OP_JOIN", "OP_LEAVE", "OP_NOOP",
    "VALID_OPS", "overlay_join_executor",
]

"""Replicated gateway writes: one PUT fanned to n rings, quorum return.

The reference gets durability from striping one block's n IDA fragments
over n PEERS of one ring (DHashPeer::Create, dhash_peer.cpp:89-129);
the gateway generalizes the same >= quorum-acks contract one level up:
a PUT fans to `n_replicas` registered RINGS through each ring's own
bounded admission, the caller returns as soon as `w` rings acked, and
the remaining replicas complete ASYNCHRONOUSLY on a small fan-out pool
with their lag recorded per ring (`repair.replication.lag_ms.<ring>`).

Semantics pinned by tests/test_gateway.py's quorum oracle checks:

  * w-of-n success — the caller's PUT succeeds iff >= w target rings
    ack within its deadline; a slow ring cannot delay a satisfied
    quorum (it finishes in the background, lag-accounted).
  * no cross-ring store forks on failure — a replica that fails keeps
    its engine-applied store EXACTLY as the engine left it: there is
    no side-path retry, no fallback write (the gateway's store ops
    never fall back), and no rollback of the rings that DID ack — the
    under-replicated key is the anti-entropy scheduler's job, which is
    how the reference treats a Create that reached only m..n-1 peers.
  * per-replica deadlines — the quorum WAIT honors the caller's
    deadline; the replica PUTs themselves run under
    max(caller deadline, now + async_grace_s) so a tight caller budget
    returns fast without shedding the background replication work.

LOCK ORDER: `_QuorumState` waits only on its own condition (the
lockcheck-exempt pattern) and the writer's lock guards pool
construction only; no lock is ever held across a gateway/engine call.
This module never imports jax.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional, Sequence, Tuple

from p2p_dhts_tpu.metrics import METRICS, Metrics
from p2p_dhts_tpu.serve import DeadlineExpiredError

logger = logging.getLogger(__name__)


class QuorumWriteError(RuntimeError):
    """Fewer than w target rings could ack the PUT."""


class ReplicationPolicy:
    """PUT fan-out policy: n_replicas target rings, quorum w."""

    def __init__(self, n_replicas: int = 2, w: int = 1,
                 async_grace_s: float = 30.0):
        n_replicas, w = int(n_replicas), int(w)
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if not 1 <= w <= n_replicas:
            raise ValueError(f"quorum w must be in [1, n_replicas], got "
                             f"w={w} n_replicas={n_replicas}")
        self.n_replicas = n_replicas
        self.w = w
        self.async_grace_s = float(async_grace_s)

    def as_dict(self) -> dict:
        return {"n_replicas": self.n_replicas, "w": self.w,
                "async_grace_s": self.async_grace_s}

    def __repr__(self) -> str:
        return (f"ReplicationPolicy(n_replicas={self.n_replicas}, "
                f"w={self.w})")


class PutOutcome:
    """What a replicated PUT looked like at quorum-return time."""

    __slots__ = ("ok", "per_entry_ok", "targets", "acked_rings",
                 "failed_rings", "quorum_s")

    def __init__(self, ok: bool, per_entry_ok: List[bool],
                 targets: List[str], acked_rings: List[str],
                 failed_rings: List[str], quorum_s: float):
        self.ok = ok
        self.per_entry_ok = per_entry_ok
        self.targets = targets
        self.acked_rings = acked_rings
        self.failed_rings = failed_rings
        self.quorum_s = quorum_s


class _QuorumState:
    """Per-call ack bookkeeping: ring completions arrive on pool
    threads; the caller waits on the condition until every entry has w
    acks, a quorum becomes impossible, or its deadline lapses."""

    def __init__(self, n_entries: int, n_targets: int, w: int):
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.acks = [0] * n_entries          # rings acking each entry
        self.rings_done = 0
        self.rings_failed = 0
        self.n_targets = n_targets
        self.w = w
        self.acked_rings: List[str] = []
        self.failed_rings: List[str] = []
        self.t_quorum: Optional[float] = None

    def _quorum_met_locked(self) -> bool:
        return all(a >= self.w for a in self.acks)

    def _quorum_impossible_locked(self) -> bool:
        remaining = self.n_targets - self.rings_done
        return any(a + remaining < self.w for a in self.acks)

    def record(self, ring_id: str, oks: Optional[Sequence[bool]]) -> None:
        """One ring finished: oks per entry, or None for a ring-level
        failure. Returns after waking any quorum waiter."""
        with self.cond:
            self.rings_done += 1
            if oks is None:
                self.rings_failed += 1
                self.failed_rings.append(ring_id)
            else:
                ring_ok = True
                for i, ok in enumerate(oks):
                    if ok:
                        self.acks[i] += 1
                    else:
                        ring_ok = False
                (self.acked_rings if ring_ok
                 else self.failed_rings).append(ring_id)
            if self.t_quorum is None and self._quorum_met_locked():
                self.t_quorum = time.perf_counter()
            self.cond.notify_all()

    def wait_quorum(self, deadline) -> bool:
        """True iff the quorum was met; False when it became impossible
        or the deadline lapsed first (the caller maps each to its
        error). Never blocks past the deadline."""
        with self.cond:
            while True:
                if self._quorum_met_locked():
                    return True
                if self._quorum_impossible_locked() \
                        or self.rings_done >= self.n_targets:
                    return False
                rem = deadline.remaining()
                if rem is not None and rem <= 0:
                    return False
                self.cond.wait(rem if rem is not None else 0.5)


class ReplicatedWriter:
    """The gateway's PUT fan-out engine (one per Gateway, built when a
    ReplicationPolicy is set)."""

    #: Fan-out pool bound: replicas of concurrent PUTs share it; the
    #: per-ring admission budgets are the real backpressure.
    POOL_WORKERS = 8

    def __init__(self, gateway, policy: ReplicationPolicy,
                 metrics: Optional[Metrics] = None):
        self.gateway = gateway
        self.policy = policy
        self.metrics = metrics if metrics is not None else METRICS
        self._pool_lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None

    def _get_pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.POOL_WORKERS,
                    thread_name_prefix=f"repl-{self.gateway.name}")
            return self._pool

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    # -- target selection ----------------------------------------------------
    def targets_for(self, key_int: Optional[int]) -> List[Any]:
        """The n_replicas target backends: the routed primary first
        (key-range owner / default ring), then the other registered
        rings in registration order. Fewer registered rings than
        n_replicas is allowed (best effort — the policy's w still
        gates success); fewer than w raises up front."""
        ring_list, default = self.gateway.router.snapshot()
        primary = None
        if key_int is not None:
            primary = next(
                (b for b in ring_list if b.owns_key(int(key_int))), None)
        if primary is None:
            primary = default if default is not None else (
                ring_list[0] if ring_list else None)
        if primary is None:
            from p2p_dhts_tpu.gateway.router import UnknownRingError
            raise UnknownRingError("replicated PUT: no rings registered")
        targets = [primary] + [b for b in ring_list if b is not primary]
        targets = targets[: self.policy.n_replicas]
        if len(targets) < self.policy.w:
            raise QuorumWriteError(
                f"quorum w={self.policy.w} impossible: only "
                f"{len(targets)} ring(s) registered")
        return targets

    # -- the fan-out ---------------------------------------------------------
    def put_many(self, payloads: Sequence[tuple], deadline) -> PutOutcome:
        """Fan the (key_int, segments, length, start_row) payload list
        to every target ring; return at quorum. `deadline` bounds the
        QUORUM WAIT; each replica's engine work runs under
        max(deadline, now + async_grace_s) so post-quorum stragglers
        finish in the background instead of being shed."""
        from p2p_dhts_tpu.gateway.admission import Deadline
        policy = self.policy
        targets = self.targets_for(payloads[0][0] if payloads else None)
        state = _QuorumState(len(payloads), len(targets), policy.w)
        t0 = time.perf_counter()
        grace_at = t0 + policy.async_grace_s
        replica_dl = Deadline(
            max(deadline.at, grace_at) if deadline.at is not None
            else grace_at)
        self.metrics.inc("repair.replication.requests")
        self.metrics.inc("repair.replication.replica_puts", len(targets))

        pool = self._get_pool()
        for backend in targets:
            pool.submit(self._replica_put, backend, list(payloads),
                        replica_dl, state, t0)

        met = state.wait_quorum(deadline)
        with state.lock:
            per_entry = [a >= policy.w for a in state.acks]
            outcome = PutOutcome(
                ok=met and all(per_entry),
                per_entry_ok=per_entry,
                targets=[b.ring_id for b in targets],
                acked_rings=list(state.acked_rings),
                failed_rings=list(state.failed_rings),
                quorum_s=(state.t_quorum - t0) if state.t_quorum else
                time.perf_counter() - t0)
        if outcome.ok:
            self.metrics.inc("repair.replication.quorum_ok")
            self.metrics.observe_hist("repair.replication.quorum_ms",
                                      outcome.quorum_s * 1e3)
        else:
            self.metrics.inc("repair.replication.quorum_failed")
            if deadline.expired():
                raise DeadlineExpiredError(
                    f"replicated PUT: deadline lapsed with "
                    f"{min(state.acks) if state.acks else 0}/{policy.w} "
                    f"acks (replicas continue in the background)")
        return outcome

    def put(self, key_int: int, segments, length: int, start_row: int,
            deadline) -> bool:
        return self.put_many(
            [(key_int, segments, int(length), int(start_row))],
            deadline).ok

    def _replica_put(self, backend, payloads, replica_dl, state,
                     t0: float) -> None:
        """One ring's replica write, on a pool thread. Routes through
        the gateway's full admission/health path (RingBusy and
        fail-fast semantics included) and reports to the quorum state;
        post-quorum completions record their lag."""
        rid = backend.ring_id
        oks: Optional[List[bool]] = None
        try:
            oks = [bool(v) for v in self.gateway._serve_many(
                backend, "dhash_put", payloads, replica_dl)]
        # chordax-lint: disable=bare-except -- a replica failure is DATA for the quorum state, never a pool-thread crash
        except Exception as exc:  # noqa: BLE001 — fanned into quorum state
            self.metrics.inc(f"repair.replication.replica_failed.{rid}")
            logger.warning("replicated PUT: ring %r replica failed "
                           "(%s: %s)", rid, type(exc).__name__, exc)
        else:
            if all(oks):
                self.metrics.inc(f"repair.replication.replica_ok.{rid}")
            else:
                self.metrics.inc(
                    f"repair.replication.replica_failed.{rid}")
            # chordax-fastlane: a STRAGGLER completing after the
            # quorum return must epoch-bump the read cache itself —
            # the caller's bump happened at quorum, and a read that
            # cached this replica's pre-write value in the window
            # would otherwise serve it forever (the cache invariant
            # is "no cached answer survives a write", not "…survives
            # the quorum ack").
            self.gateway._invalidate_reads("replica_straggler")
        state.record(rid, oks)
        with state.lock:
            t_q = state.t_quorum
        now = time.perf_counter()
        lag_s = max(now - t_q, 0.0) if t_q is not None else 0.0
        self.metrics.observe_hist(f"repair.replication.lag_ms.{rid}",
                                  lag_s * 1e3)
        if t_q is not None and now > t_q:
            self.metrics.inc("repair.replication.async_completed")

"""RepairScheduler: device-batched anti-entropy rounds between rings.

The reference runs maintenance per peer every 5 s — Merkle-sync with
each successor, one XCHNG_NODE RPC per differing tree node
(dhash_peer.cpp:271-296, 381-481). Here one background loop PER RING
PAIR drives the whole reconciliation as a handful of engine-batched
device ops per round:

  round =  digest(A) + digest(B)      # ServeEngine "sync_digest" kind:
                                      # FIFO-ordered with in-flight puts
        -> merkle_diff                # one vectorized equality/level
        -> reindex(A) + reindex(B)    # "repair_reindex" kind — the r05
                                      # duplicate-index re-pair pass
        -> delta_scan(A) + delta_scan(B)  # keys in differing buckets
        -> heal batch                 # batched GET on the readable
                                      # side, batched PUT on the other
                                      # (both sides re-put when both
                                      # read, canonicalizing layout)

Every GET/PUT/digest/reindex goes through the gateway's
route->health->admission->engine path, so repair traffic obeys the same
per-ring budgets and deadline shedding as client traffic (a repair
batch whose round deadline lapsed is dropped BEFORE device dispatch,
the PR-4 rule) and can never starve it.

Pacing: a token bucket bounds healed keys/second per pair (a huge
divergence heals over many rounds instead of one store-sized burst);
failed rounds back off exponentially WITH JITTER (the net/rpc.py retry
rule — N pair loops that saw the same failure must not re-converge in
lockstep); a converged pair idles at `interval_idle_s`.

Convergence: digests equal => the pair's stored (key, frag_idx)
multisets are equal (dhash/merkle.py's contract) => every key readable
on one ring is readable on both. Keys readable on NEITHER ring are
data loss (the reference's Read throws) — counted `unhealable` and
excluded from the convergence wait so a lost block cannot wedge the
loop forever.

Observability (metrics.py, `repair.*`): rounds / deltas_found /
keys_healed.<ring> / canonicalized / reindexed.<ring> / bytes_moved /
unhealable / round_failures counters, backlog + converged + tokens
gauges per pair, round_ms + convergence_ms histograms.

LOCK ORDER: `TokenBucket._lock` and the scheduler's `_lock` are
LEAVES — neither is ever held across a gateway call, a device op, or a
sleep; the pair loops sleep on `threading.Event.wait` (interruptible
close) holding nothing.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from p2p_dhts_tpu import trace as trace_mod
from p2p_dhts_tpu.health import PacedLoop
from p2p_dhts_tpu.metrics import METRICS, Metrics


class TokenBucket:
    """Non-blocking token bucket: `take(n)` grants what is available
    (never waits — an under-granted heal batch defers the remainder to
    the next round)."""

    def __init__(self, rate_per_s: float, burst: float):
        if rate_per_s <= 0 or burst <= 0:
            raise ValueError("rate_per_s and burst must be positive")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._lock = threading.Lock()
        self._tokens = float(burst)
        self._t_last = time.monotonic()

    def _refill_locked(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t_last) * self.rate)
        self._t_last = now

    def take(self, n: int) -> int:
        with self._lock:
            self._refill_locked(time.monotonic())
            granted = int(min(n, self._tokens))
            self._tokens -= granted
            return granted

    def refund(self, n: int) -> None:
        """Return unused tokens (capped at burst) — a round that took a
        full grant but found few candidates must not drain the bucket
        for the round that finally needs the burst."""
        if n <= 0:
            return
        with self._lock:
            self._tokens = min(self.burst, self._tokens + n)

    @property
    def tokens(self) -> float:
        with self._lock:
            self._refill_locked(time.monotonic())
            return self._tokens


class RoundResult(NamedTuple):
    pair: Tuple[str, str]
    converged: bool
    leaf_diffs: int
    nodes_exchanged: int
    candidates: int          # delta keys found (pre token limit)
    examined: int            # delta keys actually healed this round
    healed: Dict[str, int]   # ring_id -> keys written there
    canonicalized: int       # both-readable keys re-put on both sides
    reindexed: Dict[str, int]  # ring_id -> duplicate rows rewritten
    unhealable: int          # readable on neither side (data loss)
    deferred: int            # token-shed candidates (next round's work)


def _derived_length(segments) -> int:
    """Real segment count of a decoded block: trailing all-zero rows
    are padding (ida.strip_decoded's rule). A true data block whose
    tail rows are all zero shrinks its stored `length` metadata — reads
    return the full padded [S, m] either way, so readability and
    payload bytes are unaffected (documented deviation)."""
    import numpy as np
    seg = np.asarray(segments)
    nz = np.nonzero(seg.any(axis=1))[0]
    return int(nz[-1]) + 1 if nz.size else 1


def run_sync_round(gateway, ring_a: str, ring_b: str, *,
                   max_keys: int = 256,
                   max_heal: Optional[int] = None,
                   deadline=None,
                   reindex: bool = True,
                   metrics: Optional[Metrics] = None) -> RoundResult:
    """One anti-entropy round between two registered store rings.
    Standalone (the SYNC_RANGE RPC verb calls this directly); the
    scheduler adds pacing/backoff around it.

    chordax-pulse (ISSUE 11): with tracing enabled the whole round is
    ONE linked span tree — `repair.round` at the root, the
    digest -> diff -> reindex -> scan -> heal phases as children, and
    the gateway/engine spans each phase's device ops open nesting
    underneath — so a repair round reads as a single trace in the
    Chrome export instead of an unparented span soup (the PR-8 open
    thread). span() is a no-op after one flag read when tracing is
    off (the serve hot-path discipline)."""
    with trace_mod.span("repair.round", cat="repair",
                        pair=f"{ring_a}-{ring_b}"):
        return _sync_round_impl(
            gateway, ring_a, ring_b, max_keys=max_keys,
            max_heal=max_heal, deadline=deadline, reindex=reindex,
            metrics=metrics)


def _sync_round_impl(gateway, ring_a: str, ring_b: str, *,
                     max_keys: int, max_heal: Optional[int],
                     deadline, reindex: bool,
                     metrics: Optional[Metrics]) -> RoundResult:
    import numpy as np

    import jax.numpy as jnp

    from p2p_dhts_tpu.dhash.merkle import MerkleIndex
    from p2p_dhts_tpu.gateway.admission import NO_DEADLINE
    from p2p_dhts_tpu.keyspace import ints_to_lanes, lanes_to_ints
    from p2p_dhts_tpu.repair import kernels

    mets = metrics if metrics is not None else METRICS
    dl = deadline if deadline is not None else NO_DEADLINE
    pair = (str(ring_a), str(ring_b))
    backends = {rid: gateway.router.get(rid) for rid in pair}
    depths = {rid: getattr(b.engine, "merkle_shape", (4, 3))
              for rid, b in backends.items()}
    if depths[pair[0]] != depths[pair[1]]:
        raise ValueError(
            f"rings {pair} have mismatched merkle shapes {depths} — "
            f"their digests cannot be compared")
    depth, fanout_bits = depths[pair[0]]

    # 1. digests, engine-ordered with in-flight puts.
    with trace_mod.span("repair.digest", cat="repair"):
        dig = {rid: gateway.sync_digest(rid, deadline=dl)
               for rid in pair}
    with trace_mod.span("repair.diff", cat="repair"):
        ia = MerkleIndex(
            levels=tuple(jnp.asarray(l) for l in dig[pair[0]].levels),
            counts=jnp.asarray(dig[pair[0]].counts))
        ib = MerkleIndex(
            levels=tuple(jnp.asarray(l) for l in dig[pair[1]].levels),
            counts=jnp.asarray(dig[pair[1]].counts))
        leaf_diff, nodes = kernels.merkle_diff(ia, ib)
        leaf_diffs = int(jnp.sum(leaf_diff))
    mets.inc("repair.rounds")
    if leaf_diffs == 0:
        return RoundResult(pair, True, 0, int(nodes), 0, 0,
                           {rid: 0 for rid in pair}, 0,
                           {rid: 0 for rid in pair}, 0, 0)
    mets.inc("repair.deltas_found", leaf_diffs)

    # 2. the duplicate-index re-pair pass (engine-ordered store rewrite).
    rw = {rid: 0 for rid in pair}
    if reindex:
        with trace_mod.span("repair.reindex", cat="repair"):
            for rid in pair:
                rw[rid] = int(gateway.repair_reindex(rid, deadline=dl))
                if rw[rid]:
                    mets.inc(f"repair.reindexed.{rid}", rw[rid])

    # 3. delta key extraction from each ring's store snapshot.
    cand_ints: List[int] = []
    seen = set()
    with trace_mod.span("repair.scan", cat="repair"):
        for rid in pair:
            snap = backends[rid].engine.store_snapshot()
            cand, ok = kernels.delta_scan(snap, leaf_diff, depth,
                                          fanout_bits, max_keys)
            ok_np = np.asarray(ok)
            for j, k in enumerate(lanes_to_ints(np.asarray(cand))):
                if ok_np[j] and k not in seen:
                    seen.add(k)
                    cand_ints.append(k)
    candidates = len(cand_ints)
    heal_n = candidates if max_heal is None else min(candidates,
                                                    int(max_heal))
    deferred = candidates - heal_n
    heal_keys = cand_ints[:heal_n]
    healed = {rid: 0 for rid in pair}
    canonicalized = 0
    unhealable = 0
    if heal_keys:
        with trace_mod.span("repair.heal", cat="repair",
                            candidates=len(heal_keys)):
            # 4. batched reads from BOTH sides, one engine batch each.
            reads = {rid: gateway.dhash_get_many(heal_keys,
                                                 ring_id=rid,
                                                 deadline=dl)
                     for rid in pair}
            # Entries are (payload, is_canon): canonicalize re-puts of
            # already-readable keys are layout repair, NOT heals —
            # keeping them out of `healed` is what lets the
            # scheduler's stall detector see a round that changed
            # nothing.
            puts: Dict[str, List[tuple]] = {rid: [] for rid in pair}
            bytes_moved = 0
            for j, k in enumerate(heal_keys):
                res = {rid: reads[rid][j] for rid in pair}
                ok_by = {rid: bool(res[rid][1]) for rid in pair}
                if not any(ok_by.values()):
                    unhealable += 1
                    continue
                if all(ok_by.values()):
                    # Both readable yet the pair still differs
                    # somewhere in this bucket: re-put each side from
                    # ITS OWN read — canonical (key, 1..n) layout,
                    # per-ring values preserved (value divergence is
                    # invisible to a keys-only tree, exactly as in
                    # the reference).
                    canonicalized += 1
                    for rid in pair:
                        seg = np.asarray(res[rid][0])
                        puts[rid].append(
                            ((k, seg, _derived_length(seg), 0), True))
                    continue
                src = pair[0] if ok_by[pair[0]] else pair[1]
                dst = pair[1] if src == pair[0] else pair[0]
                seg = np.asarray(res[src][0])
                puts[dst].append(
                    ((k, seg, _derived_length(seg), 0), False))
                bytes_moved += int(seg.size) * 4
            for rid, entries in puts.items():
                if not entries:
                    continue
                oks = gateway.dhash_put_many([e for e, _ in entries],
                                             ring_id=rid, deadline=dl)
                n_ok = sum(1 for (_, canon), v in zip(entries, oks)
                           if v and not canon)
                healed[rid] += n_ok
                if n_ok:
                    mets.inc(f"repair.keys_healed.{rid}", n_ok)
            if bytes_moved:
                mets.inc("repair.bytes_moved", bytes_moved)
            if canonicalized:
                mets.inc("repair.canonicalized", canonicalized)
            if unhealable:
                mets.inc("repair.unhealable", unhealable)
    # Converged means NOTHING healable remained this round: no
    # candidates beyond data loss, nothing deferred, nothing rewritten.
    converged = (deferred == 0 and canonicalized == 0
                 and sum(healed.values()) == 0 and sum(rw.values()) == 0
                 and candidates == unhealable)
    return RoundResult(pair, converged, leaf_diffs, int(nodes),
                       candidates, heal_n, healed, canonicalized, rw,
                       unhealable, deferred)


class DriftRoundResult(NamedTuple):
    ring: str
    converged: bool          # nothing left to restore this round
    leaf_diffs: int          # differing buckets vs the baseline index
    candidates: int          # baseline keys in differing buckets
    healed: int              # keys re-put onto the live ring
    unhealable: int          # unreadable in the baseline too
    deferred: int            # token/bound-shed candidates


def run_drift_round(gateway, ring_id: str, baseline_store, *,
                    max_keys: int = 256,
                    max_heal: Optional[int] = None,
                    deadline=None,
                    metrics: Optional[Metrics] = None
                    ) -> DriftRoundResult:
    """One INTRA-ring anti-entropy round: the live store against a
    reference FragmentStore (typically a checkpoint restore,
    checkpoint.py) — the scheduler-driven form of
    dhash.antientropy.reconcile's drift-repair use case. Keys the
    baseline holds in differing leaf buckets that the live ring can no
    longer read are decoded FROM THE BASELINE (content-level,
    liveness-forced like store_index's contract) and re-put through
    the gateway, so checkpoint drift heals on the same engine-ordered
    path — and, under RepairScheduler.add_drift, the same token-bucket
    cadence — as cross-ring repair. One-directional on purpose: keys
    created since the checkpoint differ too but need no restore, so
    convergence means "nothing left to heal", not "digests equal".
    Traced as one `repair.drift_round` root span (ISSUE 11) so a
    drift heal reads as a single trace like a pair round."""
    with trace_mod.span("repair.drift_round", cat="repair",
                        ring=str(ring_id)):
        return _drift_round_impl(
            gateway, ring_id, baseline_store, max_keys=max_keys,
            max_heal=max_heal, deadline=deadline, metrics=metrics)


def _drift_round_impl(gateway, ring_id: str, baseline_store, *,
                      max_keys: int, max_heal: Optional[int],
                      deadline, metrics: Optional[Metrics]
                      ) -> DriftRoundResult:
    import numpy as np

    import jax.numpy as jnp

    from p2p_dhts_tpu.dhash.antientropy import store_index
    from p2p_dhts_tpu.dhash.merkle import MerkleIndex
    from p2p_dhts_tpu.dhash.store import read_batch
    from p2p_dhts_tpu.gateway.admission import NO_DEADLINE
    from p2p_dhts_tpu.keyspace import ints_to_lanes, lanes_to_ints
    from p2p_dhts_tpu.repair import kernels

    mets = metrics if metrics is not None else METRICS
    dl = deadline if deadline is not None else NO_DEADLINE
    backend = gateway.router.get(ring_id)
    depth, fanout_bits = getattr(backend.engine, "merkle_shape", (4, 3))
    mets.inc("repair.drift_rounds")

    live = gateway.sync_digest(ring_id, deadline=dl)  # engine-ordered
    ia = MerkleIndex(levels=tuple(jnp.asarray(l) for l in live.levels),
                     counts=jnp.asarray(live.counts))
    ib = store_index(baseline_store, depth, fanout_bits)
    leaf_diff, _nodes = kernels.merkle_diff(ia, ib)
    leaf_diffs = int(jnp.sum(leaf_diff))
    if leaf_diffs == 0:
        return DriftRoundResult(ring_id, True, 0, 0, 0, 0, 0)

    cand, ok = kernels.delta_scan(baseline_store, leaf_diff, depth,
                                  fanout_bits, max_keys)
    ok_np = np.asarray(ok)
    cand_ints = [k for j, k in enumerate(lanes_to_ints(np.asarray(cand)))
                 if ok_np[j]]
    candidates = len(cand_ints)
    heal_n = candidates if max_heal is None else min(candidates,
                                                    int(max_heal))
    deferred = candidates - heal_n
    probe = cand_ints[:heal_n]
    healed = unhealable = 0
    if probe:
        reads = gateway.dhash_get_many(probe, ring_id=ring_id,
                                       deadline=dl)
        missing = [k for k, (_, live_ok) in zip(probe, reads)
                   if not bool(live_ok)]
        if missing:
            # Decode the missing blocks from the BASELINE store. The
            # batch pads to max_keys (one traced program per drift
            # config) and the ring view forces every valid row alive:
            # a checkpoint's holders may have died since, but the
            # content is exactly what the restore is for
            # (antientropy.store_index's liveness-agnostic rule).
            state = backend.engine.ring_snapshot()
            if state is None:
                state = backend.ring_state
            if state is None:
                raise RuntimeError(
                    f"ring {ring_id!r} has no RingState for a drift "
                    f"decode")
            rows = jnp.arange(state.ids.shape[0], dtype=jnp.int32)
            all_alive = state._replace(alive=rows < state.n_valid)
            n, m, p = backend.engine.ida_params
            padded = missing + [missing[0]] * (max_keys - len(missing))
            segs, ok_b = read_batch(all_alive, baseline_store,
                                    jnp.asarray(ints_to_lanes(padded)),
                                    n, m, p)
            segs, ok_b = np.asarray(segs), np.asarray(ok_b)
            entries = []
            for j, k in enumerate(missing):
                if not ok_b[j]:
                    unhealable += 1
                    continue
                seg = segs[j]  # [S, m] decoded block
                entries.append((k, seg, _derived_length(seg), 0))
            if entries:
                oks = gateway.dhash_put_many(entries, ring_id=ring_id,
                                             deadline=dl)
                healed = sum(1 for v in oks if v)
                if healed:
                    mets.inc(f"repair.drift_healed.{ring_id}", healed)
            if unhealable:
                mets.inc("repair.drift_unhealable", unhealable)
    converged = healed == 0 and deferred == 0
    return DriftRoundResult(ring_id, converged, leaf_diffs, candidates,
                            healed, unhealable, deferred)


class _PairLoop(PacedLoop):
    """One ring pair's background loop + pacing state.

    The run/backoff/stall body lives in health.PacedLoop (ISSUE 8's
    consolidation of the three paced-loop bodies): jittered start, one
    `run_once()` per wake, jittered exponential backoff on failure,
    idle pacing while converged OR stalled (the base's default `_busy`
    predicate), and the scheduler's global `_stop` as the extra stop
    event. `_stop_ev` stays per-loop: hot remove_ring retires ONE pair
    while the scheduler (and its other loops) keep running."""

    def __init__(self, sched: "RepairScheduler",
                 pair: Tuple[str, str]) -> None:
        self.sched = sched
        self.pair = pair
        super().__init__(
            name=f"repair:{pair[0]}-{pair[1]}", kind="repair",
            interval_s=sched.interval_s,
            interval_idle_s=sched.interval_idle_s,
            backoff_base_s=sched.backoff_base_s,
            backoff_cap_s=sched.backoff_cap_s,
            metrics=sched.metrics,
            failure_metric=f"repair.round_failures."
                           f"{pair[0]}-{pair[1]}",
            extra_stop=sched._stop,
            bucket=TokenBucket(sched.rate_keys_s, sched.burst_keys),
            thread_name=f"repair-{pair[0]}-{pair[1]}")
        #: stalled (from PacedLoop): True when consecutive rounds make
        #: NO progress on a residual diff (e.g. one ring structurally
        #: cannot hold a key's full fragment multiset — fewer than n
        #: alive peers): the loop drops to the idle interval instead of
        #: re-putting the same keys at full rate forever. Any progress
        #: clears it.
        self._stall_rounds = 0
        self.last: Optional[RoundResult] = None
        self._diverged_at: Optional[float] = None

    def _round(self) -> None:
        self.run_once()

    def nudge(self) -> None:
        """Drop converged/stalled so the next round runs at active
        cadence — an applied churn batch's transferred ranges become
        this loop's work without waiting out the idle interval."""
        self.converged = False
        self.stalled = False
        self._stall_rounds = 0

    def run_once(self) -> RoundResult:
        """One paced round (also the deterministic entry tests and the
        dryrun call directly — no background thread needed)."""
        sched = self.sched
        granted = self.bucket.take(sched.max_keys_round)
        t0 = time.perf_counter()
        try:
            res = run_sync_round(
                sched.gateway, self.pair[0], self.pair[1],
                max_keys=sched.max_keys_round, max_heal=granted,
                deadline=sched._round_deadline(), reindex=sched.reindex,
                metrics=sched.metrics)
        except BaseException:
            self.bucket.refund(granted)  # nothing was healed
            raise
        self.bucket.refund(granted - res.examined)
        self.rounds += 1
        self.mark_round()
        prev = self.last
        self.last = res
        # Stall detection: an unconverged round whose only action was
        # re-putting already-readable keys, with the SAME residual diff
        # as last round, made no progress — two in a row and the loop
        # idles (counted) instead of burning its rate on a diff it
        # cannot close (e.g. a ring below n alive peers).
        no_progress = (not res.converged and res.deferred == 0
                       and sum(res.healed.values()) == 0
                       and sum(res.reindexed.values()) == 0
                       and prev is not None
                       and res.leaf_diffs == prev.leaf_diffs)
        if no_progress:
            self._stall_rounds += 1
            sched.metrics.inc(
                f"repair.stalled_rounds.{self.pair[0]}-{self.pair[1]}")
        else:
            self._stall_rounds = 0
        self.stalled = self._stall_rounds >= 2
        if res.deferred:
            sched.metrics.inc("repair.token_deferred", res.deferred)
        pair_key = f"{self.pair[0]}-{self.pair[1]}"
        sched.metrics.observe_hist(f"repair.round_ms.{pair_key}",
                                   (time.perf_counter() - t0) * 1e3)
        sched.metrics.gauge(f"repair.backlog.{pair_key}", res.deferred)
        sched.metrics.gauge(f"repair.tokens.{pair_key}",
                            self.bucket.tokens)
        now = time.perf_counter()
        if res.converged:
            if not self.converged and self._diverged_at is not None:
                sched.metrics.observe_hist(
                    "repair.convergence_ms",
                    (now - self._diverged_at) * 1e3)
            self._diverged_at = None
        elif self._diverged_at is None:
            self._diverged_at = now
        self.converged = res.converged
        sched.metrics.gauge(f"repair.converged.{pair_key}",
                            1.0 if res.converged else 0.0)
        return res

    def status(self) -> dict:
        last = self.last
        return {
            "pair": list(self.pair),
            "rounds": self.rounds,
            "converged": self.converged,
            "stalled": self.stalled,
            "failures": self.failures,
            "backoff_s": round(self.backoff_s, 3),
            "tokens": round(self.bucket.tokens, 1),
            "last_error": self.last_error,
            "last_round": None if last is None else {
                "leaf_diffs": last.leaf_diffs,
                "candidates": last.candidates,
                "healed": dict(last.healed),
                "canonicalized": last.canonicalized,
                "reindexed": dict(last.reindexed),
                "unhealable": last.unhealable,
                "deferred": last.deferred,
            },
        }


class _DriftLoop(PacedLoop):
    """One ring's intra-ring drift loop (live store vs a baseline
    FragmentStore): the same PacedLoop pacing discipline — token
    bucket, jittered backoff, converged idling — around
    run_drift_round. Duck-types _PairLoop where the scheduler's
    lifecycle and run_until_converged need it (stalled stays False, so
    the base's converged-or-stalled idle predicate reduces to the
    drift loop's converged-only rule)."""

    def __init__(self, sched: "RepairScheduler", ring_id: str,
                 baseline) -> None:
        self.sched = sched
        self.ring_id = str(ring_id)
        self.pair = (self.ring_id, "__baseline__")
        self._baseline = baseline  # FragmentStore or () -> FragmentStore
        super().__init__(
            name=f"repair-drift:{ring_id}", kind="repair-drift",
            interval_s=sched.interval_s,
            interval_idle_s=sched.interval_idle_s,
            backoff_base_s=sched.backoff_base_s,
            backoff_cap_s=sched.backoff_cap_s,
            metrics=sched.metrics,
            failure_metric=f"repair.round_failures.{self.ring_id}-drift",
            extra_stop=sched._stop,
            bucket=TokenBucket(sched.rate_keys_s, sched.burst_keys),
            thread_name=f"repair-drift-{ring_id}")
        self.last: Optional[DriftRoundResult] = None

    def _baseline_store(self):
        return self._baseline() if callable(self._baseline) \
            else self._baseline

    def _round(self) -> None:
        self.run_once()

    def run_once(self) -> DriftRoundResult:
        sched = self.sched
        granted = self.bucket.take(sched.max_keys_round)
        try:
            res = run_drift_round(
                sched.gateway, self.ring_id, self._baseline_store(),
                max_keys=sched.max_keys_round, max_heal=granted,
                deadline=sched._round_deadline(), metrics=sched.metrics)
        except BaseException:
            self.bucket.refund(granted)
            raise
        self.bucket.refund(granted - res.healed)
        self.rounds += 1
        self.mark_round()
        self.last = res
        self.converged = res.converged
        sched.metrics.gauge(f"repair.converged.{self.ring_id}-drift",
                            1.0 if res.converged else 0.0)
        return res

    def nudge(self) -> None:
        self.converged = False
        self.stalled = False

    def status(self) -> dict:
        last = self.last
        return {
            "pair": list(self.pair),
            "rounds": self.rounds,
            "converged": self.converged,
            "stalled": self.stalled,
            "failures": self.failures,
            "backoff_s": round(self.backoff_s, 3),
            "tokens": round(self.bucket.tokens, 1),
            "last_error": self.last_error,
            "last_round": None if last is None else {
                "leaf_diffs": last.leaf_diffs,
                "candidates": last.candidates,
                "healed": last.healed,
                "unhealable": last.unhealable,
                "deferred": last.deferred,
            },
        }


class RepairScheduler:
    """Background anti-entropy over a set of ring pairs.

    `start()` spawns one loop per pair; `run_until_converged()` is the
    deterministic foreground form (tests, the dryrun, bench --config
    repair). Construct, then `gateway.attach_repair(sched)` so the
    REPAIR_STATUS verb can see it."""

    def __init__(self, gateway, pairs: Sequence[Tuple[str, str]], *,
                 interval_s: float = 1.0,
                 interval_idle_s: float = 10.0,
                 rate_keys_s: float = 2048.0,
                 burst_keys: float = 4096.0,
                 max_keys_round: int = 256,
                 round_timeout_s: Optional[float] = 30.0,
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 30.0,
                 reindex: bool = True,
                 dynamic: bool = False,
                 metrics: Optional[Metrics] = None):
        if not pairs and not dynamic:
            raise ValueError("RepairScheduler needs at least one ring "
                             "pair (or dynamic=True for hot-enrolled "
                             "pairs)")
        self.dynamic = bool(dynamic)
        self.gateway = gateway
        self.interval_s = float(interval_s)
        self.interval_idle_s = float(interval_idle_s)
        self.rate_keys_s = float(rate_keys_s)
        self.burst_keys = float(burst_keys)
        self.max_keys_round = int(max_keys_round)
        self.round_timeout_s = round_timeout_s
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.reindex = bool(reindex)
        self.metrics = metrics if metrics is not None else METRICS
        self._stop = threading.Event()
        self._started = False
        self._lock = threading.Lock()
        self.loops = [_PairLoop(self, (str(a), str(b))) for a, b in pairs]

    def _round_deadline(self):
        from p2p_dhts_tpu.gateway.admission import Deadline
        return Deadline.from_timeout(self.round_timeout_s)

    # -- hot pair management (router add/remove auto-enrollment) -------------
    def add_pair(self, pair: Tuple[str, str]) -> bool:
        """Enroll one ring pair while the scheduler runs (idempotent,
        unordered: (a, b) == (b, a)). Started schedulers spawn the new
        loop's thread immediately. Returns whether a loop was added."""
        a, b = str(pair[0]), str(pair[1])
        if a == b:
            raise ValueError(f"a repair pair needs two distinct rings, "
                             f"got {pair}")
        with self._lock:
            for loop in self.loops:
                if set(loop.pair) == {a, b}:
                    return False
            loop = _PairLoop(self, (a, b))
            self.loops.append(loop)
            started = self._started
        self.metrics.inc("repair.pairs_enrolled")
        if started:
            loop.thread.start()
        return True

    def remove_ring(self, ring_id: str, timeout: float = 30.0) -> int:
        """Retire every loop covering `ring_id` (hot remove_ring): the
        loops stop, join, and leave the set. Returns how many retired."""
        ring_id = str(ring_id)
        with self._lock:
            victims = [l for l in self.loops if ring_id in l.pair]
            self.loops = [l for l in self.loops if ring_id not in l.pair]
            started = self._started
        for loop in victims:
            loop.stop()  # signals the loop AND drops it from HEALTH
        if started:
            for loop in victims:
                if loop.thread.is_alive():
                    loop.thread.join(timeout)
        if victims:
            self.metrics.inc("repair.pairs_retired", len(victims))
            # Stale-telemetry hygiene (chordax-scope): a retired
            # pair's last-write-wins gauges and round hists must not
            # haunt dashboards forever.
            for loop in victims:
                if isinstance(loop, _DriftLoop):
                    for fam in ("converged", "round_failures"):
                        self.metrics.remove_prefix(
                            f"repair.{fam}.{loop.ring_id}-drift")
                    continue
                pair_key = f"{loop.pair[0]}-{loop.pair[1]}"
                for fam in ("backlog", "converged", "tokens",
                            "round_ms", "round_failures",
                            "stalled_rounds"):
                    self.metrics.remove_prefix(
                        f"repair.{fam}.{pair_key}")
        return len(victims)

    def nudge(self, ring_id: str) -> int:
        """Wake the loops covering `ring_id` out of converged/stalled
        idling (the membership control plane's targeted-heal enqueue).
        Returns the number of loops nudged."""
        ring_id = str(ring_id)
        with self._lock:
            loops = [l for l in self.loops if ring_id in l.pair]
        for loop in loops:
            loop.nudge()
        return len(loops)

    def add_drift(self, ring_id: str, baseline) -> "_DriftLoop":
        """Enroll one INTRA-ring drift loop: the named ring's live
        store reconciles against `baseline` (a FragmentStore, or a
        zero-arg callable returning one — e.g. a checkpoint restore)
        on the same token-bucket cadence as the cross-ring pairs."""
        loop = _DriftLoop(self, ring_id, baseline)
        with self._lock:
            self.loops.append(loop)
            started = self._started
        self.metrics.inc("repair.drift_enrolled")
        if started:
            loop.thread.start()
        return loop

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RepairScheduler":
        with self._lock:
            if self._started:
                return self
            if self._stop.is_set():
                raise RuntimeError("RepairScheduler is closed")
            self._started = True
            loops = list(self.loops)
        for loop in loops:
            loop.thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        self._stop.set()
        with self._lock:
            started = self._started
            loops = list(self.loops)
        for loop in loops:
            loop.stop()  # signals the loop AND drops it from HEALTH
        if not started:
            return
        for loop in loops:
            if not loop.thread.is_alive() and loop.thread.ident is None:
                continue  # enrolled after close raced start; never ran
            loop.thread.join(timeout)
            if loop.thread.is_alive():
                raise TimeoutError(
                    f"repair pair loop {loop.pair} did not stop within "
                    f"{timeout}s")

    def __enter__(self) -> "RepairScheduler":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- deterministic foreground driving ------------------------------------
    def run_until_converged(self, max_rounds: int = 16
                            ) -> List[RoundResult]:
        """Drive every pair's rounds inline until all converge; raises
        if any pair is still diverged after max_rounds (the bounded-
        convergence contract the bench smoke asserts)."""
        out: List[RoundResult] = []
        for _ in range(int(max_rounds)):
            all_conv = True
            for loop in self.loops:
                res = loop.run_once()
                out.append(res)
                all_conv = all_conv and res.converged
            if all_conv:
                return out
            if all(loop.converged or loop.stalled for loop in self.loops):
                stalled = [loop.pair for loop in self.loops
                           if loop.stalled]
                raise RuntimeError(
                    f"repair STALLED: pairs {stalled} hold a residual "
                    f"diff no round can close (one ring likely cannot "
                    f"store the full fragment multiset — check alive "
                    f"peer counts vs IDA n)")
        still = [loop.pair for loop in self.loops if not loop.converged]
        raise RuntimeError(
            f"repair did not converge within {max_rounds} rounds; "
            f"diverged pairs: {still}")

    def status(self) -> dict:
        return {
            "started": self._started,
            "closed": self._stop.is_set(),
            "interval_s": self.interval_s,
            "rate_keys_s": self.rate_keys_s,
            "max_keys_round": self.max_keys_round,
            "pairs": [loop.status() for loop in self.loops],
        }

"""chordax-repair: replicated writes + device-batched anti-entropy
(ISSUE 6).

The DHash durability promise (Cates 2003) as a first-class subsystem on
top of the PR-4 gateway, driving all repair compute through the PR-2
ServeEngine:

  replication   a gateway PUT fans to n registered rings through each
                ring's own admission, returns at quorum w, stragglers
                complete asynchronously with per-ring lag recorded
                (repair/replication.py).
  anti-entropy  ring pairs reconcile by Merkle digest diff — the
                engine-ordered "sync_digest" kind, one vectorized
                equality per level, a bounded delta key-set, batched
                GET/PUT heals (repair/kernels.py + repair/scheduler.py).
  re-pair       the r05 fragment-stranding fix generalized: duplicate
                fragment indices rewrite onto missing ones via the
                store-chaining "repair_reindex" engine kind; distinct
                count strictly increases, the last copy is never
                destroyed.
  control       SYNC_RANGE / REPAIR_STATUS RPC verbs on every gateway
                server; repair.* metrics; bench.py --config repair.

Importing this package pulls the gateway/serve stack but never
initializes a jax backend (overlay etiquette); device work happens only
once digests/heals flow.
"""

from p2p_dhts_tpu.repair.replication import (  # noqa: F401
    PutOutcome,
    QuorumWriteError,
    ReplicatedWriter,
    ReplicationPolicy,
)
from p2p_dhts_tpu.repair.scheduler import (  # noqa: F401
    DriftRoundResult,
    RepairScheduler,
    RoundResult,
    TokenBucket,
    run_drift_round,
    run_sync_round,
)

__all__ = [
    "DriftRoundResult", "PutOutcome", "QuorumWriteError",
    "RepairScheduler", "ReplicatedWriter", "ReplicationPolicy",
    "RoundResult", "TokenBucket", "run_drift_round", "run_sync_round",
]

"""chordax-repair device kernels: Merkle delta extraction + the
duplicate-index re-pair pass, as batched XLA programs.

Two kernels close the gap between "two rings' trees differ" and "the
stores converge", with work proportional to the DIVERGENCE:

  * `merkle_diff` / `delta_scan` — the comparison half. Two rings'
    keyspace-partitioned Merkle indices (dhash.merkle level arrays,
    built through each ring's ServeEngine "sync_digest" kind so the
    digest is FIFO-ordered with in-flight puts) compare level-by-level
    in one vectorized equality per level, and the keys living in
    DIFFERING leaf buckets come back as a bounded candidate set — the
    whole recursive XCHNG_NODE exchange (dhash_peer.cpp:381-481) as a
    log-depth device op plus one store scan, no per-key host loops.
  * `reindex_duplicates` — the repair half of the r05
    fragment-stranding fix (overlay/dhash_peer.py
    run_local_maintenance's duplicate-only heal), generalized to the
    device store: rows whose fragment index DUPLICATES an earlier
    reachable row of the same key are rewritten to a missing index
    (decode from >= m distinct survivors, re-encode, in-place row
    rewrite). Each rewrite strictly INCREASES the block's
    distinct-fragment count — a duplicate only ever becomes a missing
    index, never another duplicate — and the guard set mirrors the
    host heal's: no rewrite unless the block is decodable (>= m
    distinct reachable fragments, the "successful whole-block read"
    precondition), and only the dedup LOSERS rewrite (the first row
    bearing an index is never touched), so the last copy of any
    fragment is never destroyed.

Trace accounting: every kernel bumps `TRACE_COUNTS` at trace time (the
serve.py recompile-counter pattern) so the repair path can prove zero
steady-state retraces — `trace_snapshot()` / `retraces_since()` are the
scheduler's and the bench's measuring stick.

This module imports jax at module scope (it is pure kernel code, pulled
in lazily by serve/_get_kernels and the repair scheduler) but never
initializes a backend at import.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from p2p_dhts_tpu.dhash.antientropy import _marked_leader_keys, store_index
from p2p_dhts_tpu.dhash.merkle import MerkleIndex, diff_indices
from p2p_dhts_tpu.dhash.store import (FragmentStore, _sort_store,
                                      placement_owners)
from p2p_dhts_tpu.ida import decode_kernel, encode_kernel
from p2p_dhts_tpu.ops import u128

#: Traces per kernel since process start (bumped at TRACE time — python
#: side effects inside jit run once per compilation, exactly the
#: recompile counter the zero-retrace contract needs).
TRACE_COUNTS: Dict[str, int] = {"merkle_diff": 0, "delta_scan": 0,
                                "reindex_duplicates": 0}


def _count(kernel: str) -> None:
    TRACE_COUNTS[kernel] += 1


def trace_snapshot() -> Dict[str, int]:
    return dict(TRACE_COUNTS)


def retraces_since(snapshot: Dict[str, int]) -> int:
    return sum(TRACE_COUNTS.values()) - sum(snapshot.values())


# ---------------------------------------------------------------------------
# comparison: digest diff + delta key extraction
# ---------------------------------------------------------------------------

@jax.jit
def merkle_diff(ia: MerkleIndex, ib: MerkleIndex
                ) -> Tuple[jax.Array, jax.Array]:
    """(leaf_diff [n_leaf] bool, nodes_exchanged i32) for two indices of
    the same (depth, fanout) — dhash.merkle.diff_indices with the repair
    path's trace accounting."""
    _count("merkle_diff")
    return diff_indices(ia, ib)


@functools.partial(jax.jit,
                   static_argnames=("depth", "fanout_bits", "max_keys"))
def delta_scan(store: FragmentStore, leaf_diff: jax.Array,
               depth: int = 4, fanout_bits: int = 3,
               max_keys: int = 256) -> Tuple[jax.Array, jax.Array]:
    """Up to max_keys distinct keys of live rows hashing into DIFFERING
    leaf buckets: (keys [max_keys, 4] u32, ok [max_keys] bool). The
    bounded per-round candidate set a heal batch is built from (call
    again next round while diffs remain — the reference's recursion
    also descends incrementally)."""
    _count("delta_scan")
    cand = _marked_leader_keys(store, leaf_diff, depth, fanout_bits,
                               max_keys)
    sentinel = jnp.full((1, 4), 0xFFFFFFFF, jnp.uint32)
    ok = ~u128.eq(cand, sentinel)
    return cand, ok


# ---------------------------------------------------------------------------
# repair: the duplicate-index re-pair pass
# ---------------------------------------------------------------------------

class ReindexStats(NamedTuple):
    rewritten: jax.Array        # i32 — rows re-pointed to missing indices
    duplicate_rows: jax.Array   # i32 — dup rows observed pre-repair
    blocks_repaired: jax.Array  # i32 — distinct keys that had a rewrite


def reindex_duplicates_impl(ring, store: FragmentStore,
                            n: int = 14, m: int = 10, p: int = 257,
                            max_hops: Optional[int] = None
                            ) -> Tuple[FragmentStore, ReindexStats]:
    """Un-jitted body (serve.py wraps it with its own trace counter;
    `reindex_duplicates` below is the standalone jitted form)."""
    c = store.capacity
    rows = jnp.arange(c, dtype=jnp.int32)
    live = store.used & (rows < store.n_used)
    prev_same = jnp.concatenate([
        jnp.zeros((1,), bool), u128.eq(store.keys[1:], store.keys[:-1])])
    leaders = live & ~prev_same

    # Window of up to n rows after each leader (the store is sorted by
    # (key, frag_idx), so a key's rows are contiguous). Unlike
    # _key_window this keeps RAW validity — the dedup losers are
    # exactly the rows this pass exists to rewrite.
    w = jnp.arange(n, dtype=jnp.int32)[None, :]
    win = rows[:, None] + w
    win_c = jnp.minimum(win, c - 1)
    h = store.holder[win_c]
    valid = (win < store.n_used) \
        & u128.eq(store.keys[win_c], store.keys[:, None, :]) \
        & store.used[win_c] \
        & ring.alive[jnp.maximum(h, 0)] & (h >= 0)
    fidx = store.frag_idx[win_c]

    # A later reachable row bearing an earlier reachable row's index is
    # the dedup LOSER — the rewrite candidate. The first bearer stays.
    dup_pair = (fidx[:, :, None] == fidx[:, None, :]) \
        & valid[:, :, None] & valid[:, None, :]
    earlier = jnp.tril(jnp.ones((n, n), bool), k=-1)[None]
    is_dup = (dup_pair & earlier).any(axis=2)                   # [C, n]
    distinct = valid & ~is_dup

    idx_grid = jnp.arange(1, n + 1, dtype=jnp.int32)
    present = ((fidx[:, :, None] == idx_grid[None, None, :])
               & distinct[:, :, None]).any(axis=1)              # [C, n]
    n_distinct = present.sum(axis=1)

    # Designated holders: fragment i belongs on the key's i-th alive
    # successor — a rewritten row moves to its canonical position.
    start = jnp.zeros((c,), jnp.int32)
    owners = placement_owners(ring, store.keys, start, n, max_hops)
    owner_alive = ring.alive[jnp.maximum(owners, 0)] & (owners >= 0)
    missing = ~present & owner_alive                            # [C, n]

    # The whole-block-read precondition: decodable (>= m distinct
    # reachable fragments) or nothing is touched.
    can = leaders & (n_distinct >= m) & is_dup.any(axis=1) \
        & missing.any(axis=1)

    # Decode from the first m distinct fragments, re-encode all n.
    order = jnp.argsort(~distinct, axis=1, stable=True)[:, :m]
    sel = jnp.take_along_axis(win_c, order, axis=1)
    rows_v = store.values[sel]                                  # [C, m, S]
    idx_v = jnp.where(jnp.take_along_axis(distinct, order, axis=1),
                      store.frag_idx[sel], 0)
    idx_safe = jnp.where(can[:, None], idx_v,
                         jnp.arange(1, m + 1, dtype=jnp.int32)[None, :])
    segments = decode_kernel(rows_v, idx_safe, p)               # [C, S, m]
    all_frags = encode_kernel(segments, n, m, p)                # [C, n, S]

    # k-th duplicate takes the k-th missing index: every rewrite lands
    # on a DISTINCT absent index, so the distinct count strictly grows.
    dup_rank = jnp.cumsum(is_dup.astype(jnp.int32), axis=1) - 1  # [C, n]
    miss_order = jnp.argsort(~missing, axis=1, stable=True)      # [C, n]
    miss_count = missing.sum(axis=1)
    k = jnp.clip(dup_rank, 0, n - 1)
    tgt_pos = jnp.take_along_axis(miss_order, k, axis=1)         # 0-based
    assign = can[:, None] & is_dup & (dup_rank < miss_count[:, None])

    smax = store.max_segments
    flat_rows = jnp.where(assign, win_c, c).reshape(-1)  # OOB -> dropped
    new_vals = jnp.take_along_axis(
        all_frags, tgt_pos[:, :, None], axis=1).reshape(-1, smax)
    new_fidx = (tgt_pos + 1).reshape(-1)
    new_holder = jnp.take_along_axis(owners, tgt_pos, axis=1).reshape(-1)

    out = store._replace(
        frag_idx=store.frag_idx.at[flat_rows].set(new_fidx, mode="drop"),
        values=store.values.at[flat_rows].set(new_vals, mode="drop"),
        holder=store.holder.at[flat_rows].set(new_holder, mode="drop"))
    stats = ReindexStats(
        rewritten=assign.astype(jnp.int32).sum(),
        duplicate_rows=(is_dup & leaders[:, None]).astype(jnp.int32).sum(),
        blocks_repaired=assign.any(axis=1).astype(jnp.int32).sum())
    return _sort_store(out), stats


@functools.partial(jax.jit, static_argnames=("n", "m", "p", "max_hops"))
def reindex_duplicates(ring, store: FragmentStore,
                       n: int = 14, m: int = 10, p: int = 257,
                       max_hops: Optional[int] = None
                       ) -> Tuple[FragmentStore, ReindexStats]:
    """Jitted standalone form (tests, the GSPMD registry); the serve
    engine's "repair_reindex" kind wraps the impl with the engine's own
    per-kind trace counter instead."""
    _count("reindex_duplicates")
    return reindex_duplicates_impl(ring, store, n, m, p, max_hops)


__all__ = [
    "MerkleIndex", "ReindexStats", "TRACE_COUNTS", "delta_scan",
    "merkle_diff", "reindex_duplicates", "reindex_duplicates_impl",
    "retraces_since", "store_index", "trace_snapshot",
]

"""p2p_dhts_tpu — a TPU-native peer-to-peer DHT framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the C++
reference (Patrick-McKeever/P2P-DHTs): the Chord overlay protocol (Stoica et
al. 2001 with Zave's rectify extension) and the DHash erasure-coded storage
layer (Cates 2003, Rabin IDA), plus a keyspace-partitioned Merkle index and a
JSON-RPC wire layer.

Instead of one OS process per peer talking TCP (reference
`src/chord/chord_peer.cpp`), the whole simulated ring lives as device-resident
arrays: ids `[N,4]u32`, finger matrix `[N,128]i32`, successor lists `[N,S]i32`.
Per-peer protocol logic is expressed as pure, batched state-transition
functions (`vmap`/`lax.while_loop`) so millions of peers and lookups resolve
as single XLA programs, sharded over a device mesh for multi-chip.

Layer map (mirrors SURVEY.md §1):
  L1 keyspace   — 128-bit ring ids          (ref: src/data_structures/key.h)
  L2 storage    — Merkle index + DB         (ref: merkle_tree.h, database.h)
  L3 ida        — Rabin IDA erasure coding  (ref: src/ida/*)
  L4 net        — JSON-RPC client/server    (ref: src/networking/*)
  L5 core.ring  — Chord overlay as arrays   (ref: src/chord/*)
  L6 dhash      — replication layer         (ref: src/dhash/*)
"""

__version__ = "0.1.0"

import os as _os

from p2p_dhts_tpu.config import RingConfig, IdaParams  # noqa: F401
from p2p_dhts_tpu.keyspace import Key  # noqa: F401

if _os.environ.get("CHORDAX_LOCK_CHECK") == "1":
    # Opt-in runtime lock-order watchdog (chordax-lint Pass 3's dynamic
    # twin): every threading.Lock/RLock created after this import is
    # wrapped with acquisition-order bookkeeping, and inverted orders
    # accumulate in analysis.lockcheck.WATCHDOG.violations (the serve
    # soak asserts they stay empty). Installed at import so the env var
    # alone instruments a whole run; lockcheck never imports jax, so
    # the package's zero-backend-init rule holds.
    from p2p_dhts_tpu.analysis.lockcheck import WATCHDOG as _WATCHDOG
    _WATCHDOG.install()

# Everything that would pull in jax (or socket machinery) resolves
# lazily (PEP 562): `from p2p_dhts_tpu import build_ring` still works,
# but `import p2p_dhts_tpu` alone imports neither jax nor the overlay.
# (Under the axon sitecustomize jax is already in sys.modules before any
# user import runs, so the jax half only matters in plain environments;
# what ALWAYS matters is that nothing here initializes a backend —
# __graft_entry__ depends on importing with zero device side effects.)
_LAZY = {
    "IDA": ("p2p_dhts_tpu.ida", "IDA"),
    "DataBlock": ("p2p_dhts_tpu.ida", "DataBlock"),
    "DataFragment": ("p2p_dhts_tpu.ida", "DataFragment"),
    "build_ring": ("p2p_dhts_tpu.core.ring", "build_ring"),
    "build_ring_random": ("p2p_dhts_tpu.core.ring", "build_ring_random"),
    "ring_genesis": ("p2p_dhts_tpu.core.ring", "ring_genesis"),
    "RingState": ("p2p_dhts_tpu.core.ring", "RingState"),
    "find_successor": ("p2p_dhts_tpu.core.ring", "find_successor"),
    "get_n_successors": ("p2p_dhts_tpu.core.ring", "get_n_successors"),
    "keys_from_ints": ("p2p_dhts_tpu.core.ring", "keys_from_ints"),
    "materialize_converged_fingers":
        ("p2p_dhts_tpu.core.ring", "materialize_converged_fingers"),
    "owner_of": ("p2p_dhts_tpu.core.ring", "owner_of"),
    "ChordPeer": ("p2p_dhts_tpu.overlay.chord_peer", "ChordPeer"),
    "DHashPeer": ("p2p_dhts_tpu.overlay.dhash_peer", "DHashPeer"),
    "save_checkpoint": ("p2p_dhts_tpu.checkpoint", "save_checkpoint"),
    "load_checkpoint": ("p2p_dhts_tpu.checkpoint", "load_checkpoint"),
    "DeviceDHT": ("p2p_dhts_tpu.simulator", "DeviceDHT"),
    "ServeEngine": ("p2p_dhts_tpu.serve", "ServeEngine"),
    "EngineFingerResolver": ("p2p_dhts_tpu.serve", "EngineFingerResolver"),
}


def __getattr__(name: str):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    return getattr(importlib.import_module(mod_name), attr)


def __dir__():
    return sorted(list(globals()) + list(_LAZY))


# Star-import surface: without __all__, `from p2p_dhts_tpu import *`
# would copy only real globals and never consult __getattr__, silently
# dropping the lazy names that used to be eager exports.
__all__ = ["RingConfig", "IdaParams", "Key"] + sorted(_LAZY)

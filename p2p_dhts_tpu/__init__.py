"""p2p_dhts_tpu — a TPU-native peer-to-peer DHT framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the C++
reference (Patrick-McKeever/P2P-DHTs): the Chord overlay protocol (Stoica et
al. 2001 with Zave's rectify extension) and the DHash erasure-coded storage
layer (Cates 2003, Rabin IDA), plus a keyspace-partitioned Merkle index and a
JSON-RPC wire layer.

Instead of one OS process per peer talking TCP (reference
`src/chord/chord_peer.cpp`), the whole simulated ring lives as device-resident
arrays: ids `[N,4]u32`, finger matrix `[N,128]i32`, successor lists `[N,S]i32`.
Per-peer protocol logic is expressed as pure, batched state-transition
functions (`vmap`/`lax.while_loop`) so millions of peers and lookups resolve
as single XLA programs, sharded over a device mesh for multi-chip.

Layer map (mirrors SURVEY.md §1):
  L1 keyspace   — 128-bit ring ids          (ref: src/data_structures/key.h)
  L2 storage    — Merkle index + DB         (ref: merkle_tree.h, database.h)
  L3 ida        — Rabin IDA erasure coding  (ref: src/ida/*)
  L4 net        — JSON-RPC client/server    (ref: src/networking/*)
  L5 core.ring  — Chord overlay as arrays   (ref: src/chord/*)
  L6 dhash      — replication layer         (ref: src/dhash/*)
"""

__version__ = "0.1.0"

from p2p_dhts_tpu.config import RingConfig, IdaParams  # noqa: F401
from p2p_dhts_tpu.keyspace import Key  # noqa: F401
from p2p_dhts_tpu.ida import IDA, DataBlock, DataFragment  # noqa: F401

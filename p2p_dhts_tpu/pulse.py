"""chordax-pulse: continuous telemetry, SLO tracking, exposition.

Everything chordax-scope (ISSUE 8) records is either a lifetime
counter or a one-shot snapshot — nobody could answer "what was p99
over the last 30 seconds" or "is availability burning its budget",
which is exactly what a capacity policy loop (ROADMAP chordax-elastic)
must consume and what the reference's DHash maintenance cadence
implicitly assumes: decisions driven by RATES OVER WINDOWS, not
totals. Three pieces:

  * `PulseSampler` — a `health.PacedLoop` that snapshots the metrics
    registry each tick (`Metrics.state()`: one lock, no reservoir
    copy) into bounded per-key time-series rings:
      - counters  -> `<key>|rate`   windowed delta / tick dt (per s)
      - gauges    -> `<key>|value`  the raw instantaneous value
      - hists     -> `<key>|p50` / `<key>|p99` / `<key>|n`  INTERVAL
        percentiles over only the samples appended since the previous
        tick (`Metrics.hist_delta`, the snapshot-delta API), so
        `serve.*` / `gateway.*` / `rpc.*` all gain windowed latency
        percentiles with zero per-request instrumentation.
    Rings are bounded (evictions counted, never silent); a series
    whose source key left the registry (ring retirement,
    `remove_prefix`) is retired on the next tick — the PR-8
    stale-telemetry rule applied to pulse itself.
  * `SloEngine` — declarative objectives (`availability` %, `latency`
    bound, `error_rate` bound, each over a window) evaluated every
    tick into OK / WARN / BREACH verdicts with MULTI-WINDOW
    error-budget burn rates (short window reacts, long window
    confirms — the SRE multi-window multi-burn-rate rule, simplified).
    Verdict transitions are counted, gauged, and — for breaches —
    land in the flight recorder as incident events carrying the burn
    rates, so `health.dump_on_error()` replays the SLO story next to
    the fault that caused it.
  * `expose_prometheus()` — Prometheus-style text exposition of the
    live registry (counters / gauges / timer+hist summaries), the
    lingua-franca form the PULSE wire verb serves next to series
    tails and SLO verdicts.

Sampling OFF costs nothing: an un-started sampler never touches the
registry, and every instrumentation site this PR adds to the control
planes is a `trace.span()` (one flag read when tracing is disabled —
the chordax-scope discipline).

LOCK ORDER: `PulseSampler._lock` and `SloEngine._lock` are LEAVES —
never held across a registry call, a flight-recorder append, or a
sleep. `sample()` is driven by ONE thread at a time (the loop thread,
or a foreground driver while the loop is not started). This module
never imports jax.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from p2p_dhts_tpu.health import FLIGHT, PacedLoop
from p2p_dhts_tpu.metrics import METRICS, Metrics, nearest_rank

#: Points retained per series ring (newest win).
DEFAULT_RING_POINTS = 128

#: Metric-key prefixes the sampler tracks by default: the serving
#: families whose rates/percentiles the elastic loop and the watcher
#: consume. Operator-extensible per sampler. "lens." makes the
#: chordax-lens capacity plane (ISSUE 14) — busy fraction, headroom,
#: saturation, queue delay — pulse series (and SLO-selectable) for
#: free; "tower." does the same for the chordax-tower canary gauges
#: (ISSUE 20), so canary availability/p99 are SLO-selectable.
DEFAULT_PREFIXES = ("serve.", "gateway.", "rpc.", "repair.",
                    "membership.", "lens.", "tower.")

#: Verdicts, in escalation order.
OK, WARN, BREACH = "OK", "WARN", "BREACH"
_STATE_CODE = {OK: 0, WARN: 1, BREACH: 2}


class SeriesRing:
    """One bounded time series: (t, value) points, newest win;
    evictions counted (the SpanStore rule)."""

    __slots__ = ("points", "evicted")

    def __init__(self, capacity: int):
        self.points: deque = deque(maxlen=int(capacity))
        self.evicted = 0

    def append(self, t: float, value: float) -> None:
        if len(self.points) == self.points.maxlen:
            self.evicted += 1
        self.points.append((t, value))


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------

class Slo:
    """One parsed objective. Declarative spec (the README's "SLO spec
    format"):

      {"name": "gw-avail", "kind": "availability",
       "target_pct": 99.0,                # error budget = 1%
       "total": "rpc.client.requests",    # counter key, or prefix
       "errors": "rpc.client.errors",     #   ending "." (summed)
       "window_s": 2.0,                   # short (reacting) window
       "long_window_s": 8.0,              # long (confirming) window
       "warn_burn": 0.5, "breach_burn": 1.0}

      {"name": "gw-p99", "kind": "latency",
       "hist": "gateway.latency_ms.dhash_get.r1",  # key or prefix
       "quantile": 0.99, "bound_ms": 50.0,
       "window_s": 5.0, "warn_ratio": 0.8}

      {"name": "gw-errs", "kind": "error_rate",
       "max_ratio": 0.05,                 # error budget = 5%
       "total": "gateway.requests.", "errors": "gateway.errors.",
       "window_s": 2.0, "long_window_s": 8.0}

    Counter kinds (`availability` / `error_rate`) share the machinery:
    the windowed error fraction divided by the budget is the BURN RATE
    (burn 1.0 = spending exactly the whole budget); a verdict goes
    BREACH when BOTH windows burn at/above `breach_burn`, WARN when
    the short window burns at/above `warn_burn`, OK otherwise — and a
    window with no traffic is OK (no evidence is not an incident).
    `latency` compares the WORST interval quantile point inside
    `window_s` against `bound_ms` (burn = worst / bound)."""

    KINDS = ("availability", "latency", "error_rate")

    def __init__(self, spec: dict):
        spec = dict(spec)
        self.name = str(spec.pop("name"))
        self.kind = str(spec.pop("kind"))
        if self.kind not in self.KINDS:
            raise ValueError(f"SLO {self.name!r}: unknown kind "
                             f"{self.kind!r} (want one of {self.KINDS})")
        self.window_s = float(spec.pop("window_s", 5.0))
        self.long_window_s = float(
            spec.pop("long_window_s", self.window_s * 4))
        if self.long_window_s < self.window_s:
            raise ValueError(f"SLO {self.name!r}: long_window_s < "
                             f"window_s")
        self.warn_burn = float(spec.pop("warn_burn", 0.5))
        self.breach_burn = float(spec.pop("breach_burn", 1.0))
        if self.kind == "latency":
            self.hist = str(spec.pop("hist"))
            self.quantile = float(spec.pop("quantile", 0.99))
            self.bound_ms = float(spec.pop("bound_ms"))
            self.warn_ratio = float(spec.pop("warn_ratio", 0.8))
            self.total = self.errors = None
            self.budget = None
        else:
            self.total = str(spec.pop("total"))
            self.errors = str(spec.pop("errors"))
            if self.kind == "availability":
                target = float(spec.pop("target_pct"))
                if not 0.0 < target < 100.0:
                    raise ValueError(f"SLO {self.name!r}: target_pct "
                                     f"must be in (0, 100)")
                self.budget = 1.0 - target / 100.0
            else:
                self.budget = float(spec.pop("max_ratio"))
                if not 0.0 < self.budget <= 1.0:
                    raise ValueError(f"SLO {self.name!r}: max_ratio "
                                     f"must be in (0, 1]")
            self.hist = None
        if spec:
            raise ValueError(f"SLO {self.name!r}: unknown spec fields "
                             f"{sorted(spec)}")


def _counter_sum(counters: Dict[str, int], sel: str) -> int:
    """Exact key, or — when `sel` ends with a dot — the family sum."""
    if sel.endswith("."):
        return sum(v for k, v in counters.items() if k.startswith(sel))
    return counters.get(sel, 0)


class SloEngine:
    """Evaluates a set of Slo objectives each tick against cumulative
    counter snapshots (windowed deltas) and the sampler's interval
    percentile points. Owned/driven by PulseSampler; readable from any
    thread via `verdicts()`."""

    def __init__(self, slos: Sequence, *,
                 metrics: Optional[Metrics] = None, flight=None):
        self.slos: List[Slo] = [s if isinstance(s, Slo) else Slo(s)
                                for s in slos]
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.metrics = metrics if metrics is not None else METRICS
        self.flight = flight if flight is not None else FLIGHT
        self._lock = threading.Lock()
        # Per counter-SLO: deque of (t, total, errors) cumulative
        # snapshots, trimmed to the long window.
        self._track: Dict[str, deque] = {s.name: deque()
                                         for s in self.slos}
        self._verdicts: Dict[str, dict] = {
            s.name: {"verdict": OK, "kind": s.kind, "burn_short": 0.0,
                     "burn_long": 0.0, "since": None}
            for s in self.slos}

    def _burn_counter(self, slo: Slo, track: deque, now: float,
                      window_s: float) -> float:
        """Windowed error fraction / budget over the trailing window.
        The baseline is the OLDEST snapshot still inside the window
        (or the newest one before it, so a window spanning one tick
        still sees that tick's delta)."""
        if not track:
            return 0.0
        t_now, tot_now, err_now = track[-1]
        base = None
        for (t, tot, err) in reversed(track):
            if t_now - t <= window_s + 1e-9:
                base = (t, tot, err)
            else:
                base = (t, tot, err)  # one snapshot beyond the edge
                break
        if base is None or base[0] >= t_now:
            return 0.0
        d_tot = tot_now - base[1]
        d_err = err_now - base[2]
        if d_tot <= 0:
            return 0.0
        return (d_err / d_tot) / slo.budget

    def _burn_latency(self, slo: Slo, points: Sequence[Tuple[float,
                                                             float]],
                      now: float) -> float:
        worst = None
        for t, v in reversed(points):
            if now - t > slo.window_s + 1e-9:
                break
            worst = v if worst is None else max(worst, v)
        if worst is None:
            return 0.0
        return worst / slo.bound_ms

    def evaluate(self, now: float, counters: Dict[str, int],
                 latency_points) -> List[dict]:
        """One tick: update tracks, compute burns, move verdicts.
        `latency_points(hist_key, quantile) -> [(t, v), ...]` is the
        sampler's interval-percentile lookup. Returns the transition
        records (already counted/gauged/flight-fed)."""
        transitions: List[dict] = []
        # Latency points are fetched BEFORE our lock: latency_points
        # takes the sampler's leaf, and two leaves must never stack.
        lat_points = {slo.name: latency_points(slo.hist, slo.quantile)
                      for slo in self.slos if slo.kind == "latency"}
        with self._lock:
            for slo in self.slos:
                row = self._verdicts[slo.name]
                if slo.kind == "latency":
                    burn_short = self._burn_latency(
                        slo, lat_points[slo.name], now)
                    burn_long = burn_short
                    warn_at, breach_at = slo.warn_ratio, 1.0
                else:
                    track = self._track[slo.name]
                    track.append((now,
                                  _counter_sum(counters, slo.total),
                                  _counter_sum(counters, slo.errors)))
                    while len(track) > 2 and \
                            now - track[1][0] > slo.long_window_s:
                        track.popleft()
                    burn_short = self._burn_counter(
                        slo, track, now, slo.window_s)
                    burn_long = self._burn_counter(
                        slo, track, now, slo.long_window_s)
                    warn_at, breach_at = slo.warn_burn, slo.breach_burn
                if burn_short >= breach_at and burn_long >= breach_at:
                    verdict = BREACH
                elif burn_short >= warn_at:
                    verdict = WARN
                else:
                    verdict = OK
                prev = row["verdict"]
                row["burn_short"] = round(burn_short, 4)
                row["burn_long"] = round(burn_long, 4)
                if verdict != prev:
                    row["verdict"] = verdict
                    row["since"] = now
                    transitions.append({
                        "slo": slo.name, "kind": slo.kind,
                        "from": prev, "to": verdict,
                        "burn_short": round(burn_short, 4),
                        "burn_long": round(burn_long, 4)})
        # Recording happens OUTSIDE the leaf lock (flight/metrics own
        # their own leaves; never stack them under ours).
        for tr in transitions:
            name = tr["slo"]
            self.metrics.gauge(f"pulse.slo_state.{name}",
                               _STATE_CODE[tr["to"]])
            if tr["to"] == BREACH:
                self.metrics.inc(f"pulse.slo_breach.{name}")
                self.flight.record(
                    "pulse", "slo_breach", slo=name, kind=tr["kind"],
                    burn_short=tr["burn_short"],
                    burn_long=tr["burn_long"])
            elif tr["to"] == WARN:
                self.metrics.inc(f"pulse.slo_warn.{name}")
                self.flight.record(
                    "pulse", "slo_warn", slo=name, kind=tr["kind"],
                    burn_short=tr["burn_short"])
            else:
                self.metrics.inc(f"pulse.slo_recovered.{name}")
                self.flight.record(
                    "pulse", "slo_recovered", slo=name,
                    kind=tr["kind"], burn_short=tr["burn_short"],
                    burn_long=tr["burn_long"])
        for slo in self.slos:
            with self._lock:
                burn = self._verdicts[slo.name]["burn_short"]
                burn_l = self._verdicts[slo.name]["burn_long"]
            self.metrics.gauge(f"pulse.burn_short.{slo.name}", burn)
            self.metrics.gauge(f"pulse.burn_long.{slo.name}", burn_l)
        return transitions

    def verdicts(self) -> Dict[str, dict]:
        with self._lock:
            return {name: dict(row)
                    for name, row in self._verdicts.items()}


# ---------------------------------------------------------------------------
# the sampler loop
# ---------------------------------------------------------------------------

class PulseSampler(PacedLoop):
    """Fixed-cadence registry sampler + SLO evaluator (one per
    process is typical; tests run private ones over private
    registries). `start()` runs it as a background PacedLoop (it
    self-registers in health.HEALTH like every paced loop); `sample()`
    is the deterministic foreground tick tests and the dryrun drive.
    Attach to a gateway (`gateway.attach_pulse(sampler)`) so the PULSE
    wire verb can serve its series and verdicts."""

    def __init__(self, *, metrics: Optional[Metrics] = None,
                 interval_s: float = 1.0,
                 ring_points: int = DEFAULT_RING_POINTS,
                 prefixes: Sequence[str] = DEFAULT_PREFIXES,
                 slos: Sequence = (),
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 10.0,
                 registry=None):
        mets = metrics if metrics is not None else METRICS
        PacedLoop.__init__(
            self, name="pulse", kind="pulse",
            interval_s=interval_s, interval_idle_s=interval_s,
            backoff_base_s=backoff_base_s, backoff_cap_s=backoff_cap_s,
            metrics=mets, failure_metric="pulse.tick_failures",
            thread_name="pulse-sampler", registry=registry)
        self.ring_points = int(ring_points)
        self.prefixes = tuple(str(p) for p in prefixes)
        self.slo = SloEngine(slos, metrics=mets)
        # A latency SLO reads the sampler's interval-percentile rings;
        # a hist outside our prefixes never grows one, so the
        # objective would sit at OK forever — a misconfiguration only
        # a constructor check can surface (counter SLOs read the raw
        # registry and are prefix-independent).
        for slo in self.slo.slos:
            if slo.kind == "latency" and not self._tracked(slo.hist):
                raise ValueError(
                    f"latency SLO {slo.name!r} watches hist "
                    f"{slo.hist!r}, which is outside the sampler's "
                    f"prefixes {self.prefixes} — no interval series "
                    f"would ever exist and the verdict could never "
                    f"leave OK")
        self._lock = threading.Lock()   # LEAF: rings + cursors only
        self._rings: Dict[str, SeriesRing] = {}
        #: Per-counter (incarnation stamp, value) cursor — same
        #: aliasing rule as the hist cursors below.
        self._prev_counters: Dict[str, Tuple[int, int]] = {}
        #: Per-hist (incarnation stamp, appended-sample total) cursor:
        #: the stamp detects a hist deleted and re-created between
        #: ticks, whose totals alone could alias a valid position.
        self._prev_hist_totals: Dict[str, Tuple[int, int]] = {}
        self._prev_t: Optional[float] = None

    # -- the tick ------------------------------------------------------------
    def _round(self) -> None:
        self.sample()

    def _tracked(self, key: str) -> bool:
        return any(key.startswith(p) for p in self.prefixes)

    def sample(self, now: Optional[float] = None) -> dict:
        """One sampling tick. `now` (monotonic-like seconds) is
        injectable so tests hand-compute rates/windows; production
        ticks use time.monotonic(). Returns a tick summary."""
        t_wall0 = time.perf_counter()
        t = time.monotonic() if now is None else float(now)
        st = self.metrics.state()
        counters = st["counters"]
        gauges = st["gauges"]
        hist_totals = st["hist_totals"]
        hist_epochs = st.get("hist_epochs", {})
        counter_epochs = st.get("counter_epochs", {})
        # Interval hist percentiles FIRST (hist_delta takes the
        # registry lock per key; do it before taking our own leaf).
        # hist_delta's RETURNED total is the cursor to advance to:
        # samples appended between state() and hist_delta are in this
        # tick's delta, and re-reading them next tick would
        # double-count them in the interval series.
        hist_points: Dict[str, Tuple[float, float, int]] = {}
        live_totals: Dict[str, int] = {}
        with self._lock:
            prev_cursors = dict(self._prev_hist_totals)
        for key, total in hist_totals.items():
            if not self._tracked(key):
                continue
            epoch = hist_epochs.get(key, 0)
            prev = prev_cursors.get(key)
            if prev is None or prev[0] != epoch:
                # First sighting, or a re-created hist (fresh
                # incarnation stamp): the old cursor is meaningless
                # regardless of how the totals compare — seed only.
                continue
            if total > prev[1]:
                samples, live_total = self.metrics.hist_delta(
                    key, prev[1])
                live_totals[key] = live_total
                if samples:
                    srt = sorted(samples)
                    hist_points[key] = (nearest_rank(srt, 0.5),
                                        nearest_rank(srt, 0.99),
                                        len(samples))
        evicted = 0
        retired = 0
        n_series = 0
        with self._lock:
            dt = (t - self._prev_t) if self._prev_t is not None else None
            live_ids = set()

            def _append(series_id: str, value: float) -> None:
                nonlocal evicted
                ring = self._rings.get(series_id)
                if ring is None:
                    ring = self._rings[series_id] = SeriesRing(
                        self.ring_points)
                before = ring.evicted
                ring.append(t, float(value))
                evicted += ring.evicted - before
                live_ids.add(series_id)

            for key, val in counters.items():
                if not self._tracked(key):
                    continue
                prev = self._prev_counters.get(key)
                ep = counter_epochs.get(key, 0)
                if prev is not None and prev[0] == ep \
                        and dt is not None and dt > 0 \
                        and val >= prev[1]:
                    _append(f"{key}|rate", (val - prev[1]) / dt)
                else:
                    # First sighting, a re-created counter (fresh
                    # incarnation stamp), or a reset: seed only.
                    live_ids.add(f"{key}|rate")
            for key, val in gauges.items():
                if self._tracked(key):
                    _append(f"{key}|value", val)
            for key, (p50, p99, n) in hist_points.items():
                _append(f"{key}|p50", p50)
                _append(f"{key}|p99", p99)
                _append(f"{key}|n", n)
            # A hist that exists but saw no new samples keeps its ring.
            for key in hist_totals:
                if self._tracked(key):
                    for suffix in ("|p50", "|p99", "|n"):
                        if f"{key}{suffix}" in self._rings:
                            live_ids.add(f"{key}{suffix}")
            # Retire rings whose source key left the registry (ring
            # retirement / remove_prefix): stale series must not haunt
            # the PULSE verb, the PR-8 rule.
            for dead in [sid for sid in self._rings
                         if sid not in live_ids]:
                del self._rings[dead]
                retired += 1
            self._prev_counters = {
                k: (counter_epochs.get(k, 0), v)
                for k, v in counters.items() if self._tracked(k)}
            self._prev_hist_totals = {
                k: (hist_epochs.get(k, 0), live_totals.get(k, v))
                for k, v in hist_totals.items() if self._tracked(k)}
            self._prev_t = t
            n_series = len(self._rings)
        transitions = self.slo.evaluate(
            t, counters, self._latency_points)
        self.rounds += 1
        self.mark_round()
        self.metrics.inc("pulse.ticks")
        if evicted:
            self.metrics.inc("pulse.series_evicted", evicted)
        if retired:
            self.metrics.inc("pulse.series_retired", retired)
        tick_ms = (time.perf_counter() - t_wall0) * 1e3
        self.metrics.observe_hist("pulse.tick_ms", tick_ms)
        return {"t": t, "series": n_series, "evicted": evicted,
                "retired": retired, "transitions": transitions,
                "tick_ms": round(tick_ms, 3)}

    def _latency_points(self, hist_key: str, quantile: float
                        ) -> List[Tuple[float, float]]:
        """The SLO engine's interval-percentile lookup: the `|p50` or
        `|p99` series of `hist_key` (nearest supported quantile; a
        prefix selector takes the worst across matching series)."""
        suffix = "|p50" if quantile <= 0.75 else "|p99"
        with self._lock:
            if hist_key.endswith("."):
                # Dot-bounded family match, the _counter_sum rule:
                # "gateway.read." must not absorb "gateway.readiness".
                merged: List[Tuple[float, float]] = []
                for sid, ring in self._rings.items():
                    if sid.startswith(hist_key) and \
                            sid.endswith(suffix):
                        merged.extend(ring.points)
                merged.sort(key=lambda p: p[0])
                return merged
            ring = self._rings.get(f"{hist_key}{suffix}")
            return list(ring.points) if ring is not None else []

    # -- read side (PULSE verb / tests / artifact) ---------------------------
    def series_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def series_tail(self, selector: Optional[str] = None,
                    n: int = 32) -> Dict[str, List[Tuple[float,
                                                         float]]]:
        """{series id: the newest `n` (t, value) points, oldest
        first} for every series whose id starts with `selector`
        (None = all). `n` <= 0 enumerates the matching ids with
        empty point lists — the cheap what-exists poll."""
        n = int(n)
        with self._lock:
            return {sid: (list(ring.points)[-n:] if n > 0 else [])
                    for sid, ring in sorted(self._rings.items())
                    if selector is None or sid.startswith(selector)}

    def evictions(self) -> int:
        with self._lock:
            return sum(r.evicted for r in self._rings.values())

    def verdicts(self) -> Dict[str, dict]:
        return self.slo.verdicts()

    def status(self) -> dict:
        """The PULSE verb's status payload."""
        with self._lock:
            n_series = len(self._rings)
            n_points = sum(len(r.points) for r in self._rings.values())
        return {
            "ticks": self.rounds,
            "interval_s": self.interval_s,
            "series": n_series,
            "points": n_points,
            "ring_points": self.ring_points,
            "prefixes": list(self.prefixes),
            "slos": [s.name for s in self.slo.slos],
            "running": self.thread.is_alive(),
        }

    def export_series(self) -> dict:
        """The whole series store as one JSON-able dict (the watcher's
        archived artifact: series next to the BENCH records)."""
        with self._lock:
            return {sid: [[round(tt, 3), vv] for tt, vv in ring.points]
                    for sid, ring in sorted(self._rings.items())}


# ---------------------------------------------------------------------------
# Prometheus-style exposition
# ---------------------------------------------------------------------------

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(key: str) -> str:
    return "chordax_" + _NAME_SANITIZE.sub("_", key)


def expose_prometheus(metrics: Optional[Metrics] = None) -> str:
    """Prometheus text exposition of the live registry: counters and
    gauges verbatim, timers and reservoir hists as summaries (count /
    sum, p50/p99 quantile samples). Dotted keys sanitize to
    `chordax_<key_with_underscores>`; dynamic key segments stay in the
    metric name (label-less exposition — the bounded key families make
    that safe). On-demand only: this walks snapshot(), never the
    sampler."""
    m = metrics if metrics is not None else METRICS
    snap = m.snapshot()
    st = m.state()
    lines: List[str] = []
    for key, val in sorted(snap.get("counters", {}).items()):
        name = _prom_name(key)
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {val}")
    for key, val in sorted(snap.get("gauges", {}).items()):
        name = _prom_name(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {val}")
    for key, row in sorted(snap.get("timers", {}).items()):
        name = _prom_name(key)
        lines.append(f"# TYPE {name} summary")
        lines.append(f"{name}_count {row['count']}")
        lines.append(f"{name}_sum {row['total_s']}")
    for key, row in sorted(snap.get("hists", {}).items()):
        name = _prom_name(key)
        lines.append(f"# TYPE {name} summary")
        if row.get("p50") is not None:
            lines.append(f'{name}{{quantile="0.5"}} {row["p50"]}')
        if row.get("p99") is not None:
            lines.append(f'{name}{{quantile="0.99"}} {row["p99"]}')
        # Summary _count/_sum must be CUMULATIVE (Prometheus rate()
        # over them is the whole point): the monotonic appended
        # totals, not the reservoir occupancy (which caps at HIST_CAP
        # and would read as rate 0 under sustained load). Quantiles
        # above remain reservoir-windowed — an operational summary.
        lines.append(
            f"{name}_count {st['hist_totals'].get(key, row['count'])}")
        lines.append(
            f"{name}_sum {st['hist_sums'].get(key, 0.0)}")
    return "\n".join(lines) + "\n"


#: One exposition line: `name value` or `name{labels} value` (the
#: value is validated by float(), not the pattern — nan/inf/exponent
#: forms all pass through).
PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal exposition parser (the round-trip half the tests and
    the PULSE verb's consumers rely on): {sample name [+labels]:
    float value}; comment/TYPE lines skipped; malformed lines raise."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        m = PROM_LINE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        out[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return out

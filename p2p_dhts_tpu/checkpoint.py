"""Checkpoint / resume of device ring + store state (SURVEY.md §5.5).

The reference's peers are memory-only; its nearest persistence analogs
are fragment/file writes (ida.cpp:105-118, data_fragment.cpp:34-47),
which the host layer mirrors in `ida.py`. This module adds what the
reference never had and SURVEY §5.5 directs the rebuild to provide: a
whole-simulation snapshot. A RingState / FragmentStore is a flat pytree
of device arrays plus static metadata, so a checkpoint is one npz file —
device->host gather on save, host->device upload on restore.

Format: a single .npz whose keys are `ring/<field>`, `store/<field>`,
plus `meta/*` scalars (format version, max_hops). `fingers` may be
absent (computed-finger mode). Either section may be omitted.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

import jax.numpy as jnp

from p2p_dhts_tpu.core.ring import RingState
from p2p_dhts_tpu.dhash.store import FragmentStore

FORMAT_VERSION = 1

_RING_FIELDS = ("ids", "alive", "n_valid", "min_key", "preds", "succs")
_STORE_FIELDS = ("keys", "frag_idx", "holder", "values", "length", "used",
                 "n_used")


def save_checkpoint(path: str, ring: Optional[RingState] = None,
                    store: Optional[FragmentStore] = None) -> None:
    """Write ring and/or store state to `path` (.npz, atomic rename)."""
    if ring is None and store is None:
        raise ValueError("nothing to checkpoint")
    payload = {"meta/version": np.int64(FORMAT_VERSION)}
    if ring is not None:
        for f in _RING_FIELDS:
            payload[f"ring/{f}"] = np.asarray(getattr(ring, f))
        if ring.fingers is not None:
            payload["ring/fingers"] = np.asarray(ring.fingers)
        payload["meta/max_hops"] = np.int64(ring.max_hops)
    if store is not None:
        for f in _STORE_FIELDS:
            payload[f"store/{f}"] = np.asarray(getattr(store, f))
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez_compressed(fh, **payload)
    os.replace(tmp, path)


def load_checkpoint(path: str) -> Tuple[Optional[RingState],
                                        Optional[FragmentStore]]:
    """Read a checkpoint; returns (ring or None, store or None)."""
    with np.load(path) as z:
        version = int(z["meta/version"])
        if version != FORMAT_VERSION:
            raise ValueError(f"checkpoint format {version} != "
                             f"{FORMAT_VERSION}")
        ring = None
        if "ring/ids" in z:
            ring = RingState(
                ids=jnp.asarray(z["ring/ids"]),
                alive=jnp.asarray(z["ring/alive"]),
                n_valid=jnp.asarray(z["ring/n_valid"]),
                min_key=jnp.asarray(z["ring/min_key"]),
                preds=jnp.asarray(z["ring/preds"]),
                succs=jnp.asarray(z["ring/succs"]),
                fingers=(jnp.asarray(z["ring/fingers"])
                         if "ring/fingers" in z else None),
                max_hops=int(z["meta/max_hops"]),
            )
        store = None
        if "store/keys" in z:
            store = FragmentStore(
                keys=jnp.asarray(z["store/keys"]),
                frag_idx=jnp.asarray(z["store/frag_idx"]),
                holder=jnp.asarray(z["store/holder"]),
                values=jnp.asarray(z["store/values"]),
                length=jnp.asarray(z["store/length"]),
                used=jnp.asarray(z["store/used"]),
                n_used=jnp.asarray(z["store/n_used"]),
            )
    return ring, store

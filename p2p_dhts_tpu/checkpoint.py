"""Checkpoint / resume of device ring + store state (SURVEY.md §5.5).

The reference's peers are memory-only; its nearest persistence analogs
are fragment/file writes (ida.cpp:105-118, data_fragment.cpp:34-47),
which the host layer mirrors in `ida.py`. This module adds what the
reference never had and SURVEY §5.5 directs the rebuild to provide: a
whole-simulation snapshot. A RingState / FragmentStore is a flat pytree
of device arrays plus static metadata, so a checkpoint is one npz file —
device->host gather on save, host->device upload on restore.

Format: a single .npz whose keys are `ring/<field>`, `store/<field>`,
plus `meta/*` scalars (format version, max_hops). `fingers` may be
absent (computed-finger mode). Either section may be omitted. A store
may be a single-device FragmentStore or a holder-sharded
ShardedFragmentStore (dhash/sharded.py) — the shard axis is preserved
in the arrays and flagged in `meta/store_sharded`; pass `mesh=` on load
to re-place the blocks over a same-width device mesh (restoring onto a
different mesh width: load without mesh, `unshard_store`, then
`shard_store` onto the new one).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

import jax.numpy as jnp

from p2p_dhts_tpu.core.ring import RingState
from p2p_dhts_tpu.dhash.store import FragmentStore
from p2p_dhts_tpu.dhash.sharded import ShardedFragmentStore, place_store

FORMAT_VERSION = 1          # plain payloads
FORMAT_VERSION_SHARDED = 2  # sharded-store payloads (new array rank —
                            # pre-sharding loaders must refuse, not
                            # misparse)

_RING_FIELDS = ("ids", "alive", "n_valid", "min_key", "preds", "succs")
_STORE_FIELDS = ("keys", "frag_idx", "holder", "values", "length", "used",
                 "n_used")


def save_checkpoint(path: str, ring: Optional[RingState] = None,
                    store=None, extra: Optional[dict] = None) -> None:
    """Write ring and/or store state to `path` (.npz, atomic rename).
    `store` is a FragmentStore or a ShardedFragmentStore. `extra` maps
    names to int scalars persisted under `extra/<name>` (e.g. the
    facade's IDA parameters — state a restore must agree on)."""
    if ring is None and store is None:
        raise ValueError("nothing to checkpoint")
    sharded = isinstance(store, ShardedFragmentStore)
    payload = {"meta/version": np.int64(
        FORMAT_VERSION_SHARDED if sharded else FORMAT_VERSION)}
    for k, v in (extra or {}).items():
        payload[f"extra/{k}"] = np.int64(v)
    if store is not None:
        payload["meta/store_sharded"] = np.bool_(sharded)
    if ring is not None:
        for f in _RING_FIELDS:
            payload[f"ring/{f}"] = np.asarray(getattr(ring, f))
        if ring.fingers is not None:
            payload["ring/fingers"] = np.asarray(ring.fingers)
        payload["meta/max_hops"] = np.int64(ring.max_hops)
    if store is not None:
        for f in _STORE_FIELDS:
            payload[f"store/{f}"] = np.asarray(getattr(store, f))
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez_compressed(fh, **payload)
    os.replace(tmp, path)


def load_checkpoint(path: str, mesh=None, axis: str = "peer",
                    with_extra: bool = False):
    """Read a checkpoint; returns (ring or None, store or None). The
    store comes back as whichever type was saved (with_extra=True adds
    a third element: the `extra` int scalars written at save time); for
    a sharded store,
    `mesh` (same shard-axis width as at save time) re-places the blocks
    with their row sharding — without it the blocks load unsharded on
    the default device (unshard_store/shard_store re-partition onto a
    different mesh width)."""
    with np.load(path) as z:
        version = int(z["meta/version"])
        if version not in (FORMAT_VERSION, FORMAT_VERSION_SHARDED):
            raise ValueError(
                f"checkpoint format {version} not in "
                f"{(FORMAT_VERSION, FORMAT_VERSION_SHARDED)}")
        ring = None
        if "ring/ids" in z:
            ring = RingState(
                ids=jnp.asarray(z["ring/ids"]),
                alive=jnp.asarray(z["ring/alive"]),
                n_valid=jnp.asarray(z["ring/n_valid"]),
                min_key=jnp.asarray(z["ring/min_key"]),
                preds=jnp.asarray(z["ring/preds"]),
                succs=jnp.asarray(z["ring/succs"]),
                fingers=(jnp.asarray(z["ring/fingers"])
                         if "ring/fingers" in z else None),
                max_hops=int(z["meta/max_hops"]),
            )
        store = None
        if "store/keys" in z:
            sharded = ("meta/store_sharded" in z
                       and bool(z["meta/store_sharded"]))
            cls = ShardedFragmentStore if sharded else FragmentStore
            fields = {f: jnp.asarray(z[f"store/{f}"]) for f in _STORE_FIELDS}
            store = cls(**fields)
            if sharded and mesh is not None:
                # Mesh layout lives in ONE place: dhash/sharded.py.
                store = place_store(store, mesh, axis)
        if with_extra:
            extra = {k[len("extra/"):]: int(z[k])
                     for k in z.files if k.startswith("extra/")}
            return ring, store, extra
    return ring, store

"""Core protocol layers: ring state, lookup kernels, churn ops."""

from p2p_dhts_tpu.core.ring import (  # noqa: F401
    RingState,
    build_ring,
    find_successor,
    get_n_successors,
    owner_of,
)
from p2p_dhts_tpu.core.churn import (  # noqa: F401
    fail,
    join,
    leave,
    stabilize_sweep,
)

"""Peer-axis scale-out: explicit shard_map lookup + sharded maintenance.

This is SURVEY.md §7 stage 7 — the TPU-native replacement for the
reference's entire distribution story (one OS process per peer, TCP
JSON-RPC between them, chord_peer.cpp:42-43): the sorted id table, finger
matrix, succ lists and alive mask are sharded row-wise ("peer" axis)
across a jax.sharding.Mesh, and cross-shard communication is XLA
collectives over ICI instead of sockets.

Two distribution regimes, chosen per op the way the scaling-book recipe
prescribes:

  * The *lookup hop loop* (latency-critical, irregular access) is an
    explicit `shard_map` kernel with a hand-placed collective schedule:
    every device holds the full (replicated) lane state and its own table
    shard; per hop each shard computes its local successor candidate by
    binary search and the winner is an `lax.pmin` over the peer axis
    (candidates are global row indices, and the table is globally sorted,
    so min-row == min-id — no id exchange needed). Row gathers from
    sharded tables are one-hot masked reads + `lax.psum`.
  * The *churn sweep* (bulk-parallel, regular) runs the single-device
    `stabilize_sweep`/`join`/`leave`/`fail` programs on sharded arrays and
    lets GSPMD insert the collectives — sharding annotations via
    `shard_ring`.

Parity: the hop loop reproduces the converged-ring route of
`ring.find_successor` exactly (tests assert equality of owners and hop
counts on an 8-device virtual mesh), which in turn carries the pinned
reference semantics (finger_table.h:115-130's containing-range scan,
chord_peer.cpp:194-196's self-hit correction).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from p2p_dhts_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2p_dhts_tpu.core.ring import (
    RingState,
    live_mask,
    next_alive_map,
    placement_converged,
    two_phase_hop_loop,
)
from p2p_dhts_tpu.ops import u128

# Python int on purpose — a module-scope jnp constant would initialize the
# default backend at import time (see core/ring.py:_BIG).
_INT_MAX = 2**31 - 1


def peer_mesh(devices=None, axis: str = "peer") -> Mesh:
    """1-D mesh over the peer axis (all local devices by default)."""
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (axis,))


def shard_ring(state: RingState, mesh: Mesh, axis: str = "peer"
               ) -> RingState:
    """Place a RingState row-sharded over `axis` (scalars replicated).

    Capacity must divide evenly by the axis size — build the ring with
    `capacity=` rounded up to a multiple of the device count.
    """
    d = mesh.shape[axis]
    n = state.ids.shape[0]
    if n % d != 0:
        raise ValueError(f"capacity {n} not divisible by {d} devices; "
                         f"pass capacity=ceil(n/{d})*{d} to build_ring")
    row = NamedSharding(mesh, P(axis))
    row2d = NamedSharding(mesh, P(axis, None))
    repl = NamedSharding(mesh, P())
    return state._replace(
        ids=jax.device_put(state.ids, row2d),
        alive=jax.device_put(state.alive, row),
        n_valid=jax.device_put(state.n_valid, repl),
        min_key=jax.device_put(state.min_key, row2d),
        preds=jax.device_put(state.preds, row),
        succs=jax.device_put(state.succs, row2d),
        fingers=None if state.fingers is None
        else jax.device_put(state.fingers, row2d),
    )


# Top-id-bits bucket tables are sized on the GLOBAL id count via
# u128.bucket_bits_for (~2^3 ids per occupied bucket, <= 4 MiB of starts
# per shard), exact search; see the note at the kernel's bucket build.


def routing_converged(state: RingState) -> jax.Array:
    """Scalar bool: is the state converged ENOUGH for the sharded kernel?

    Delegates to `ring.placement_converged` (live rows carry their alive
    ring predecessor — the self-hit correction target,
    chord_peer.cpp:194-196 — and the matching custody boundary);
    fail()/sweep-pending states violate it; leave()/join() repair
    placement inline (both finger modes — preds/min_key handover is
    unconditional in churn.leave/join). For materialized fingers this
    guard additionally spot-checks the head finger (finger 0 == next
    alive row), a cheap necessary condition for a swept table — and
    leave() deliberately keeps stale FINGER entries (quirk parity with
    the reference's no-op LeaveHandler finger adjustment), so it is the
    finger spot-check, not placement, that rejects a materialized-mode
    state between a leave() and the next stabilize_sweep. Higher fingers
    are trusted as the sweep's output. Plain GSPMD ops, one O(N/D)
    elementwise pass per shard.
    """
    ok = placement_converged(state)
    if state.fingers is not None:
        n = state.ids.shape[0]
        live = live_mask(state)
        rows = jnp.arange(n, dtype=jnp.int32)
        want_f0 = next_alive_map(state)[jnp.minimum(rows + 1, n)]
        ok = ok & ~jnp.any(live & (state.fingers[:, 0] != want_f0))
    return ok


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "max_hops",
                                              "check_converged"))
def find_successor_sharded(state: RingState, keys: jax.Array,
                           start: jax.Array, mesh: Mesh,
                           axis: str = "peer",
                           max_hops: Optional[int] = None,
                           check_converged: bool = True
                           ) -> Tuple[jax.Array, jax.Array]:
    """Batched GetSuccessor over a peer-axis-sharded converged ring.

    The scale-out twin of `ring.find_successor`'s fast path (same route,
    same hop counts — see module doc): lane state replicated, table
    sharded, one pmin + one fused psum of [B]-shaped data per hop over
    ICI. Supports both finger modes; computed mode is the memory-free
    path to 10M+ peers (no [N,128] matrix anywhere).

    The three fast-path optimizations (each matters at 10M, where every
    B-sized HBM gather is the unit of cost):
      * bucketed successor search — a per-shard 2^16-bucket table cuts
        each binary search from log2(block) to ~log2(occupancy) gather
        steps (u128.bucket_starts);
      * fused per-hop gathers — id lanes + predecessor ride ONE psum
        ([B,5] i32) instead of two collectives;
      * two-phase straggler compaction — hop counts are ~log2(N)
        distributed, so after the bulk resolves the loop repacks the
        <= B/8 stragglers into a prefix and finishes at 1/8 width
        (`ring._fast_lookup`'s trick, replicated lane state makes the
        permutation shard-safe).

    Converged rings only (run the sweep first after churn): dead rows are
    skipped by the successor search exactly as computed fingers skip them
    (`ring.py`: always-converged finger targets), so post-sweep routing
    matches the general single-device loop. The precondition is GUARDED
    by default: `routing_converged` runs first and an un-swept state
    fails every lane loudly (all -1) instead of returning silently wrong
    routes. The guard costs a handful of O(N/D) passes PER CALL — at 10M
    peers that is real serve-path work for an invariant that cannot
    change between lookups on the same state, so a serving loop should
    verify ONCE per swept state (`assert bool(routing_converged(s))`)
    and then pass check_converged=False (static: retraces once).
    keys [B,4] u32, start [B] i32 -> (owner [B] i32, hops [B] i32, -1 on
    hop-budget exhaustion or an unconverged ring).
    """
    if max_hops is None:
        max_hops = state.max_hops  # static metadata stamped by build_ring
    d = mesh.shape[axis]
    n = state.ids.shape[0]
    block = n // d
    materialized = state.fingers is not None

    # preds ARE shipped here, unlike ring._fast_lookup's structured
    # (row - 1) % n_valid: this kernel's guard (routing_converged) admits
    # swept states with dead rows left in place, where the alive
    # predecessor of a self-hit row is NOT row - 1 — only the
    # strictly-all-alive fast path may drop the table.
    tables = (state.ids, state.preds, state.alive) + (
        (state.fingers,) if materialized else ())

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=((P(axis, None), P(axis), P(axis)) + ((P(axis, None),)
                                                       if materialized
                                                       else ()),
                  P(), P(None, None), P(None)),
        out_specs=(P(None), P(None)),
        check_vma=False)
    def kernel(tables, n_valid, keys, start):
        ids_blk = tables[0]
        preds_blk = tables[1]
        alive_blk = tables[2]
        off = jax.lax.axis_index(axis).astype(jnp.int32) * block

        # Local next-alive map: for local slot j, the smallest ALIVE
        # local row >= j (suffix cummin over alive positions), _INT_MAX
        # if the suffix holds none — the per-shard piece of
        # ring.next_alive_map.
        slots = jnp.arange(block, dtype=jnp.int32)
        live_blk = alive_blk & (off + slots < n_valid)
        pos = jnp.where(live_blk, slots, _INT_MAX)
        suffix = jnp.flip(jax.lax.cummin(jnp.flip(pos)))
        suffix_ext = jnp.concatenate(
            [suffix, jnp.full((1,), _INT_MAX, jnp.int32)])
        first_alive = jnp.where(suffix[0] == _INT_MAX, _INT_MAX,
                                off + suffix[0])
        global_first = jax.lax.pmin(first_alive, axis)

        # Bits sized on the GLOBAL id count: buckets key on global top
        # bits while this block holds a contiguous 1/d slice of the
        # sorted table, so ids-per-OCCUPIED-bucket is n/2^bits
        # regardless of d — block-based sizing would inflate occupancy
        # by a factor of d.
        bbits = u128.bucket_bits_for(n)
        bstarts = u128.bucket_starts(ids_blk, bbits)

        def ring_succ(q):
            """Global alive ring-successor row of q: bucketed local
            binary search, local next-alive skip, then pmin over shards
            (the table is globally sorted, so min valid global row ==
            min id); no candidate anywhere wraps to the globally-first
            alive row."""
            j = u128.searchsorted_bucketed(ids_blk, q, bstarts,
                                           bbits)  # [B] in [0, block]
            jj = suffix_ext[j]                           # alive slot >= j
            cand = jnp.where(jj == _INT_MAX, _INT_MAX, off + jj)
            best = jax.lax.pmin(cand, axis)
            return jnp.where(best == _INT_MAX, global_first, best)

        def gather_ids_pred(rows):
            """ids + predecessor at global rows in ONE fused psum:
            [B,5] i32 (4 id lanes reinterpreted + pred row). Exactly one
            shard contributes non-zero per lane, so the modular int32 add
            is exact."""
            loc = rows - off
            own = (loc >= 0) & (loc < block)
            loc_c = jnp.clip(loc, 0, block - 1)
            v = jnp.concatenate(
                [ids_blk[loc_c].astype(jnp.int32),
                 preds_blk[loc_c][:, None]], axis=1)
            v = jnp.where(own[:, None], v, 0)
            out = jax.lax.psum(v, axis)
            return out[:, :4].astype(jnp.uint32), out[:, 4]

        def gather_finger(rows, fi):
            f_blk = tables[3]
            loc = rows - off
            own = (loc >= 0) & (loc < block)
            v = f_blk[jnp.clip(loc, 0, block - 1), fi]
            return jax.lax.psum(jnp.where(own, v, 0), axis)

        owner0 = ring_succ(keys)

        def body_for(keys_, owner0_):
            def body(carry):
                cur, hops, it = carry
                done = cur == owner0_
                cur_ids, cur_pred = gather_ids_pred(cur)
                dist = u128.sub(keys_, cur_ids)
                fi = jnp.maximum(u128.bit_length(dist) - 1, 0)
                if materialized:
                    nxt = gather_finger(cur, fi)
                else:
                    starts = u128.add(cur_ids, u128.pow2(fi))
                    nxt = ring_succ(starts)
                # Self-hit -> predecessor (chord_peer.cpp:194-196).
                nxt = jnp.where(nxt == cur, cur_pred, nxt)
                cur = jnp.where(done, cur, nxt)
                hops = jnp.where(done, hops, hops + 1)
                return cur, hops, it + 1
            return body

        # Shared straggler-compacted driver (ring.two_phase_hop_loop):
        # every lane-state input is replicated across shards, so the
        # partition permutation is identical everywhere and the psum/pmin
        # collectives inside body_for stay aligned.
        cur0 = jnp.asarray(start, jnp.int32)
        cur, hops = two_phase_hop_loop(body_for, keys, owner0, cur0,
                                       max_hops)

        failed = cur != owner0
        return (jnp.where(failed, -1, cur), jnp.where(failed, -1, hops))

    # Guard BEFORE the kernel (lax.cond: only the taken branch executes):
    # an un-swept state fails every lane with one O(N/D) predicate pass
    # instead of spinning the full hop loop just to discard it.
    starts_i = jnp.asarray(start, jnp.int32)
    if not check_converged:
        return kernel(tables, state.n_valid, keys, starts_i)

    def fail_all():
        neg = jnp.full((keys.shape[0],), -1, jnp.int32)
        return neg, neg

    return jax.lax.cond(
        routing_converged(state),
        lambda: kernel(tables, state.n_valid, keys, starts_i),
        fail_all)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def owner_of_sharded(state: RingState, keys: jax.Array, mesh: Mesh,
                     axis: str = "peer") -> jax.Array:
    """Sharded omniscient ownership (`ring.owner_of` twin): local binary
    search per shard + pmin — the 0-hop placement primitive used by the
    dhash layer at scale."""
    d = mesh.shape[axis]
    block = state.ids.shape[0] // d

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(), P(None, None)),
        out_specs=P(None), check_vma=False)
    def kernel(ids_blk, n_valid, keys):
        off = jax.lax.axis_index(axis).astype(jnp.int32) * block
        j = u128.searchsorted(ids_blk, keys)
        grow = off + j
        valid = (j < block) & (grow < n_valid)
        best = jax.lax.pmin(jnp.where(valid, grow, _INT_MAX), axis)
        return jnp.where(best == _INT_MAX, 0, best)

    return kernel(state.ids, state.n_valid, keys)

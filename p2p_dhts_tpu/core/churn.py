"""Churn: join / leave / fail / stabilize-rectify as batched array ops.

The reference repairs the ring with per-peer background threads doing RPC
rounds every 5 s (StabilizeLoop, chord_peer.cpp:213-240): IsAlive probes,
Notify handshakes (abstract_chord_peer.cpp:138-190), succ-list pred-walks
(UpdateSuccList, :507-562), full finger re-derivation
(PopulateFingerTable, :564-613) and Zave's Rectify broadcast on failure
(:647-698). Here the same repair is ONE jittable whole-ring sweep over the
RingState arrays (SURVEY.md §2 maps "maintenance thread per peer" to
"batched whole-ring stabilize/rectify sweep ops").

Design notes / deliberate deviations (same fixpoint, different cadence):
  * The sweep computes repair targets from ring-global next/prev-alive
    scan maps instead of bounded-depth RPC discovery, so any density of
    simultaneous failures is repaired in one sweep where the reference
    may need several 5 s cycles (its succ lists are only S deep). The
    reference's tests only pin the *converged* state (after sleep(20) /
    sleep(40) — chord_test.cpp:731,795); parity tests here assert the
    identical fixpoint: sweep^k(churned state) == build_ring(alive ids),
    including min_key custody boundaries.
  * fail() is the reference's Fail() (chord_peer.cpp:293-300): the peer
    vanishes silently; every reference to it goes stale until a sweep.
  * leave() applies LeaveHandler's immediate effects
    (abstract_chord_peer.cpp:228-260): the alive successor inherits the
    leaver's range (NEW_MIN) and predecessor (NEW_PRED); successor-list
    entries are dropped. Fingers stay stale — faithfully: the reference's
    LeaveHandler reads request["NEW_SUCC"] which Leave() never sets
    (the SURVEY §7 quirks catalog), so its finger adjustment is a no-op.
  * join() inserts a sorted batch of new ids (merge + index remap over the
    capacity-padded table), gives each new peer its converged pred /
    succ-list / fingers (what Join + PopulateFingerTable(true) produce,
    abstract_chord_peer.cpp:83-117), and applies the Notify custody
    transfer to each new peer's successor (HandleNotifyFromPred,
    chord_peer.cpp:256-280: pred, min_key, AdjustFingers). Other peers'
    fingers stay stale until a sweep — the reference's FixOtherFingers
    also only patches O(log N) peers immediately.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from p2p_dhts_tpu.core.ring import (
    RingState,
    fingers_for_ids,
    live_mask,
    next_alive_map,
    prev_alive_map,
)
from p2p_dhts_tpu.ops import u128


def _alive_succ_of_row(na: jax.Array, rows: jax.Array, n: int) -> jax.Array:
    """Alive ring successor row of a peer row (strictly after it)."""
    return na[jnp.minimum(rows + 1, n)]


def _alive_pred_of_row(pa: jax.Array, rows: jax.Array, n: int) -> jax.Array:
    """Alive ring predecessor row of a peer row (strictly before it)."""
    return jnp.where(rows > 0, pa[jnp.maximum(rows - 1, 0)], pa[n - 1])


def _succ_chain(na: jax.Array, rows: jax.Array, s: int, n: int) -> jax.Array:
    """[R, S] successor lists: chain the next-alive map S times from each
    row, masking wrap-to-self and duplicate entries with -1 (Insert dedups
    by id, remote_peer_list.cpp:56-58). Single implementation shared by
    stabilize_sweep and join."""
    cols = []
    cur = rows
    for _ in range(s):
        cur = na[jnp.minimum(cur + 1, n)]
        cols.append(cur)
    out = jnp.stack(cols, axis=1)
    out = jnp.where(out == rows[:, None], -1, out)
    for j in range(1, s):
        dup = (out[:, j:j + 1] == out[:, :j]).any(axis=1)
        out = out.at[:, j].set(jnp.where(dup, -1, out[:, j]))
    return out


# ---------------------------------------------------------------------------
# fail / leave
# ---------------------------------------------------------------------------

@jax.jit
def fail(state: RingState, rows: jax.Array) -> RingState:
    """Silent failure of a batch of peers (ref Fail(),
    chord_peer.cpp:293-300): only the alive bit changes; every stale
    reference stays until stabilize_sweep repairs it.

    Rows >= capacity are masked no-op lanes (the membership control
    plane's churn_apply resolves ids to rows on device and routes
    not-found / wrong-op lanes to the capacity sentinel); mode="drop"
    discards them instead of clamping onto a real peer."""
    return state._replace(
        alive=state.alive.at[rows].set(False, mode="drop"))


@jax.jit
def leave(state: RingState, rows: jax.Array) -> RingState:
    """Graceful leave of a batch of peers (ref Leave/LeaveHandler,
    abstract_chord_peer.cpp:192-260).

    Immediate effects on each leaver's alive successor: inherit the
    leaver's min_key (NEW_MIN — for a chain of simultaneous leavers, the
    lowest min_key of the chain) and predecessor (NEW_PRED -> the closest
    alive predecessor). Successor-list entries naming leavers are cleared
    (RemotePeerList::Delete). Fingers: untouched (the reference's
    LeaveHandler finger adjustment is a no-op quirk, see module doc).

    Rows >= capacity are masked no-op lanes (see fail()): their alive
    bit, custody scatter, and notify scatter are all dropped, so the
    membership churn_apply kernel can pad/route rejected lanes to the
    capacity sentinel without corrupting a live peer's state.
    """
    n = state.ids.shape[0]
    lane_ok = rows < n
    rows_c = jnp.minimum(rows, n - 1)
    state = state._replace(
        alive=state.alive.at[rows].set(False, mode="drop"))
    na = next_alive_map(state)
    pa = prev_alive_map(state)

    # Successor of each leaver among survivors; its new custody/pred.
    # Masked lanes (and an all-dead ring's -1 maps) route to n, which
    # mode="drop" discards — a negative scatter index would wrap.
    succ_rows = _alive_succ_of_row(na, rows_c, n)
    succ_rows = jnp.where(lane_ok & (succ_rows >= 0), succ_rows, n)
    pred_rows = _alive_pred_of_row(pa, rows_c, n)
    # For leaver chains, several leavers share one alive successor; the
    # correct inherited min_key is (alive pred id + 1), which equals the
    # chain-lowest NEW_MIN. Scatter both (duplicate scatters agree).
    new_min = u128.add_scalar(state.ids[jnp.maximum(pred_rows, 0)], 1)
    min_key = state.min_key.at[succ_rows].set(new_min, mode="drop")
    preds = state.preds.at[succ_rows].set(pred_rows, mode="drop")

    # RemotePeerList::Delete of every leaver from every succ list.
    # Membership is resolved by BINARY SEARCH into the sorted [K] leaver
    # set, not by gathering a [N]-bool mask at the [N*S] entry values:
    # on the XLA TPU compiler a large-index gather from a large 1-D
    # table is shape-sensitively pathological — the same HLO compiled in
    # 8 s at capacity 10,016,768 and 20+ MINUTES at 10,016,384 (round
    # 3 bisect; round 2's 19-minute churn was the same cliff). The
    # searchsorted form reads only the K-sized table (VMEM-resident)
    # and compiles in ~1 s at every shape tried.
    if rows.shape[0] == 0:  # static shape: nothing left the ring
        return state._replace(min_key=min_key, preds=preds)
    srt = jnp.sort(rows)
    flat = state.succs.reshape(-1)
    pos = jnp.searchsorted(srt, flat, side="left")
    hit = (srt[jnp.minimum(pos, rows.shape[0] - 1)] == flat) & (flat >= 0)
    succs = jnp.where(hit, -1, flat).reshape(state.succs.shape)
    return state._replace(min_key=min_key, preds=preds, succs=succs)


# ---------------------------------------------------------------------------
# stabilize / rectify sweep
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("refresh_fingers",))
def stabilize_sweep(state: RingState,
                    refresh_fingers: bool = True) -> RingState:
    """One whole-ring maintenance round: the batched analog of every peer
    concurrently running Stabilize + UpdateSuccList +
    PopulateFingerTable(false) + Rectify (abstract_chord_peer.cpp:460-698).

    Repairs, for every live peer p:
      * preds[p]   <- alive ring predecessor (notify fixpoint)
      * min_key[p] <- pred id + 1 where the pred changed or was dead
        (HandleNotifyFromPred custody, chord_peer.cpp:256-280; dead-range
        absorption after Rectify)
      * succs[p]   <- the S closest alive peers clockwise (UpdateSuccList
        pred-walk fixpoint)
      * fingers    <- alive ring successor of id + 2^i for every entry
        (PopulateFingerTable(false) + ReplaceDeadPeer fixpoint), when
        refresh_fingers and the state materializes fingers.

    Idempotent: sweep(sweep(s)) == sweep(s); on a fully-converged ring it
    is the identity (tests pin both).
    """
    n = state.ids.shape[0]
    live = live_mask(state)
    na = next_alive_map(state)
    pa = prev_alive_map(state)
    rows = jnp.arange(n, dtype=jnp.int32)

    new_pred = _alive_pred_of_row(pa, rows, n)
    pred_changed = new_pred != state.preds
    preds = jnp.where(live, new_pred, state.preds)

    # Custody follows the pred boundary (min_key = pred.id + 1); only
    # peers whose pred link was repaired move their boundary — matching
    # HandleNotifyFromPred. (A lone survivor gets pred = itself, so
    # min_key = id + 1 = full custody, exactly StartChord's invariant.)
    pred_ids = state.ids[jnp.maximum(new_pred, 0)]
    new_min = u128.add_scalar(pred_ids, 1)
    upd_min = live & pred_changed & (new_pred >= 0)
    min_key = jnp.where(upd_min[:, None], new_min, state.min_key)

    # Successor list: the S closest alive peers clockwise.
    succs = _succ_chain(na, rows, state.succs.shape[1], n)
    succs = jnp.where(live[:, None], succs, state.succs)

    fingers = state.fingers
    if refresh_fingers and state.fingers is not None:
        fresh = fingers_for_ids(state.ids, state.n_valid, state.ids,
                                state.fingers.shape[1], na=na)
        fingers = jnp.where(live[:, None], fresh, state.fingers)

    return state._replace(preds=preds, min_key=min_key, succs=succs,
                          fingers=fingers)


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------

@jax.jit
def join(state: RingState, new_ids: jax.Array,
         mask: Optional[jax.Array] = None
         ) -> Tuple[RingState, jax.Array]:
    """Batched join of K new peers (ref Join + JoinHandler + Notify,
    abstract_chord_peer.cpp:83-190).

    new_ids: [K, 4] u32. `mask` ([K] bool, optional) marks which lanes
    are real join requests: masked-out lanes are treated exactly like
    rejected lanes (row -1, zero state mutation). The sort carries the
    mask bit as a TRAILING key, so the batch stays globally id-sorted
    (the merge searchsorted depends on that) while real lanes precede
    masked ones within an equal-id run — and a lane only counts as an
    intra-batch duplicate when the equal neighbor before it is a REAL
    lane, so a masked fail/leave of id X can never shadow a real join
    of X. This is what lets the membership churn_apply kernel run a
    MIXED op batch through one join call.

    Preconditions are ENFORCED, not assumed: a lane whose id
    equals an ALIVE table row, or an earlier lane of the same batch, is
    rejected (its returned row is -1, the state untouched by it) — a
    silent duplicate insert would corrupt the sorted-table invariant every
    searchsorted kernel depends on. Inserts beyond the table's remaining
    capacity are likewise rejected lane-by-lane in sorted order (a full
    table must refuse peers, not evict them). A lane matching a DEAD
    table row is a REJOIN: the row is resurrected in place, the device analog of the
    reference's restarted process joining again under the same
    SHA1(ip:port) id (abstract_chord_peer.cpp:13-28 — the id is a pure
    function of the address, so rejoin-with-same-id is its normal mode).

    Returns (new state, rows [K] i32: the joined/resurrected peer's row,
    -1 for rejected lanes, aligned to the SORTED batch). Each admitted
    peer receives its converged pred / min_key / succ list / fingers (the
    outcome of Join's PopulateFingerTable(true)); its alive successor
    applies the HandleNotifyFromPred custody handover (pred <- new peer,
    min_key <- new id + 1, AdjustFingers). Remaining peers' fingers stay
    stale until stabilize_sweep — as in the reference between maintenance
    cycles.
    """
    n = state.ids.shape[0]
    k = new_ids.shape[0]

    # Sort the incoming batch (lexicographic over lanes, msb first).
    # With a mask, ~mask rides as a FIFTH key: ids stay globally sorted
    # and real lanes sort before masked lanes of the same id.
    if mask is None:
        sort_ops = [new_ids[:, 3], new_ids[:, 2], new_ids[:, 1],
                    new_ids[:, 0], jnp.arange(k, dtype=jnp.int32)]
        *_, perm = jax.lax.sort(sort_ops, num_keys=4)
    else:
        sort_ops = [new_ids[:, 3], new_ids[:, 2], new_ids[:, 1],
                    new_ids[:, 0], (~mask).astype(jnp.int32),
                    jnp.arange(k, dtype=jnp.int32)]
        *_, perm = jax.lax.sort(sort_ops, num_keys=5)
    new_sorted = new_ids[perm]
    mask_sorted = (jnp.ones((k,), bool) if mask is None
                   else mask[perm])
    # A lane's duplicate-predecessor only counts when it is REAL: a
    # masked lane between two real duplicates cannot occur (reals sort
    # first within an equal-id run), and a masked lane never shadows a
    # real join. Shift via roll (GSPMD-safe; a concat of a slice is
    # the jax-0.4.x partitioner miscompile class, see module notes).
    prev_real = jnp.roll(mask_sorted, 1).at[0].set(False)

    # Lane triage: insert (fresh id) / resurrect (matches a dead table
    # row) / reject (matches an alive row or an earlier lane). The table
    # probe is a searchsorted + one K-sized gather — never a
    # capacity-sized gather (the TPU compile cliff, see leave()).
    intra_dup = jnp.concatenate(
        [jnp.zeros((1,), bool),
         u128.eq(new_sorted[1:], new_sorted[:-1])]) & prev_real
    pos = u128.searchsorted(state.ids, new_sorted, state.n_valid)  # [K]
    pos_c = jnp.minimum(pos, n - 1)
    in_table = (pos < state.n_valid) & u128.eq(state.ids[pos_c], new_sorted)
    resurrect = in_table & ~state.alive[pos_c] & ~intra_dup & mask_sorted
    insert = ~in_table & ~intra_dup & mask_sorted
    # Capacity guard: only as many inserts as the table has padding rows
    # are admitted (in sorted order); the rest are rejected (-1) like
    # duplicates. Without this, a full table EVICTS its highest-id
    # peers through the dropped scatters — silent ring corruption.
    room = jnp.int32(n) - state.n_valid
    insert = insert & (jnp.cumsum(insert.astype(jnp.int32)) <= room)

    # Merge positions: old row r moves to r + (# INSERTED new ids < id_r);
    # inserted id j lands at searchsorted(old, new_j) + (# inserted lanes
    # before j). Rows >= n_valid (padding) and non-insert lanes are routed
    # to index n, which is out of bounds and DROPPED by the mode="drop"
    # scatters below (never clamped).
    q = u128.searchsorted(new_sorted, state.ids)              # [N] in [0, K]
    ins_cum = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(insert.astype(jnp.int32))])
    shift = ins_cum[q]  # # inserted ids < id_r (K+1-sized table: VMEM)
    valid_row = jnp.arange(n, dtype=jnp.int32) < state.n_valid
    old_dest = jnp.where(valid_row,
                         jnp.arange(n, dtype=jnp.int32) + shift, n)
    rank = jnp.cumsum(insert.astype(jnp.int32)) - 1           # [K]
    new_dest = jnp.where(insert, pos + rank, n)

    remap = jnp.full((n + 1,), -1, jnp.int32)  # old row -> new row
    remap = remap.at[jnp.arange(n)].set(old_dest, mode="drop")

    def remap_idx(a):
        return jnp.where(a >= 0, remap[jnp.clip(a, 0, n)], a)

    ids = jnp.full_like(state.ids, 0xFFFFFFFF)
    ids = ids.at[old_dest].set(state.ids, mode="drop")
    ids = ids.at[new_dest].set(new_sorted, mode="drop")

    alive = jnp.zeros_like(state.alive)
    alive = alive.at[old_dest].set(state.alive, mode="drop")
    alive = alive.at[new_dest].set(True, mode="drop")

    min_key = jnp.zeros_like(state.min_key)
    min_key = min_key.at[old_dest].set(state.min_key, mode="drop")

    preds = jnp.full_like(state.preds, -1)
    preds = preds.at[old_dest].set(remap_idx(state.preds), mode="drop")

    succs = jnp.full_like(state.succs, -1)
    succs = succs.at[old_dest].set(remap_idx(state.succs), mode="drop")

    fingers = state.fingers
    if fingers is not None:
        fingers = jnp.full_like(state.fingers, -1)
        fingers = fingers.at[old_dest].set(remap_idx(state.fingers),
                                           mode="drop")

    # Resurrected rows (merged coordinates) come back alive here so the
    # alive-neighbor maps below see every admitted peer at once.
    res_rows = jnp.where(resurrect, old_dest[pos_c], n)
    alive = alive.at[res_rows].set(True, mode="drop")

    n_ins = insert.astype(jnp.int32).sum()
    mid = state._replace(ids=ids, alive=alive, n_valid=state.n_valid + n_ins,
                         min_key=min_key, preds=preds, succs=succs,
                         fingers=fingers)

    # -- converged state for the admitted peers + notify handover ----------
    na = next_alive_map(mid)
    pa = prev_alive_map(mid)
    rows = jnp.where(insert, new_dest, res_rows)  # n for rejected lanes
    admitted = rows < n

    new_pred = _alive_pred_of_row(pa, jnp.minimum(rows, n - 1), n)
    preds = mid.preds.at[rows].set(new_pred, mode="drop")
    new_min = u128.add_scalar(mid.ids[new_pred], 1)
    min_key = mid.min_key.at[rows].set(new_min, mode="drop")

    succs = mid.succs.at[rows].set(
        _succ_chain(na, jnp.minimum(rows, n - 1), mid.succs.shape[1], n),
        mode="drop")

    # Notify the successor: custody handover (HandleNotifyFromPred).
    # Rejected lanes mask their successor to n so the scatters drop —
    # without the mask they would corrupt a live peer's pred with n.
    succ_rows = jnp.where(admitted,
                          _alive_succ_of_row(na, jnp.minimum(rows, n - 1), n),
                          n)
    preds = preds.at[succ_rows].set(rows, mode="drop")
    min_key = min_key.at[succ_rows].set(
        u128.add_scalar(mid.ids[jnp.minimum(rows, n - 1)], 1), mode="drop")

    fingers = mid.fingers
    if fingers is not None:
        f = fingers.shape[1]
        rows_c = jnp.minimum(rows, n - 1)       # gather-safe lane rows
        succ_c = jnp.minimum(succ_rows, n - 1)
        # New peers: converged fingers (PopulateFingerTable(true)).
        fingers = fingers.at[rows].set(
            fingers_for_ids(mid.ids, mid.n_valid, mid.ids[rows_c], f, na=na),
            mode="drop")
        # Notified successors: AdjustFingers — entries whose range start
        # lands in [new_min, new_id] now point at the new peer.
        fs = jnp.arange(f, dtype=jnp.int32)
        starts = u128.add(mid.ids[succ_c][:, None, :],
                          u128.pow2(fs)[None, :, :])          # [K, F, 4]
        hit = u128.in_between(starts, new_min[:, None, :],
                              mid.ids[rows_c][:, None, :], True)
        cur_entries = fingers[succ_c]
        fingers = fingers.at[succ_rows].set(
            jnp.where(hit, rows[:, None], cur_entries), mode="drop")

        # FixOtherFingers (abstract_chord_peer.cpp:615-645): the peers
        # whose finger ranges cover the new ranges are the ring
        # predecessors of new_id - 2^(i-1) for i = 1..F. The reference
        # sends each a Notify whose handler runs AdjustFingers; here those
        # rows get a full finger refresh against the merged table — a
        # superset of AdjustFingers (also clears unrelated stale entries),
        # same fixpoint. Without this, keys in a fresh peer's range are
        # unroutable from distant starts until a sweep — in the reference
        # such lookups would recurse between two stale peers and time out.
        targets = u128.sub(mid.ids[rows_c][:, None, :],
                           u128.pow2(fs)[None, :, :])         # [K, F, 4]
        jt = u128.searchsorted(mid.ids, targets.reshape(-1, u128.LANES),
                               mid.n_valid)
        notified = jnp.where(jt > 0, pa[jnp.maximum(jt - 1, 0)], pa[n - 1])
        # Rejected lanes notify NOBODY — their clamped-garbage targets
        # would otherwise refresh real peers' fingers, making a rejected
        # join observably mutate state (the docstring promises a no-op).
        notified = jnp.where(jnp.repeat(admitted, f), notified, n)
        # Sort-based dedup (jnp.unique lowers to a much heavier program):
        # duplicates become -1, which the scatter below drops.
        notified = jnp.sort(notified)
        first_of_run = jnp.concatenate(
            [jnp.ones((1,), bool), notified[1:] != notified[:-1]])
        notified = jnp.where(first_of_run, notified, -1)
        # -1 fills route to index n, which mode="drop" discards (negative
        # scatter indices would wrap numpy-style).
        notified = jnp.where(notified >= 0, notified, n)
        safe_rows = jnp.minimum(notified, n - 1)
        fresh_n = fingers_for_ids(mid.ids, mid.n_valid, mid.ids[safe_rows],
                                  f, na=na)
        fingers = fingers.at[notified].set(fresh_n, mode="drop")

    out = mid._replace(preds=preds, min_key=min_key, succs=succs,
                       fingers=fingers)
    return out, jnp.where(admitted, rows, -1)

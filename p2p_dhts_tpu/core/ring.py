"""The simulated Chord ring as device-resident arrays + the lookup kernel.

This is the north-star re-design (SURVEY.md §7): where the reference runs one
OS process per peer and resolves lookups by recursive JSON-RPC forwarding
(`AbstractChordPeer::GetSuccessor` -> `ChordPeer::ForwardRequest` ->
`FingerTable::Lookup`, a linear scan of 128 fingers per hop,
abstract_chord_peer.cpp:318-330 / chord_peer.cpp:185-211 /
finger_table.h:115-130), here the entire N-peer ring is one `RingState`
pytree in HBM and a batch of B lookups advances *all* hops in lockstep
inside a single `lax.while_loop` — one O(1) indexed gather per hop per key
instead of the reference's 128 InBetween evaluations on 256-bit ints + one
TCP round-trip.

Routing parity: the kernel reproduces the reference's exact non-textbook
semantics (pinned by tests/oracle.py + tests/test_ring.py):
  * finger i covers [id + 2^i, id + 2^(i+1) - 1]; the "containing range"
    scan collapses to i = bit_length(k - id) - 1 in O(1).
  * self-hit -> forward to predecessor if alive (chord_peer.cpp:194-196).
  * dead finger -> successor-list range lookup fallback, else the lookup
    fails (chord_peer.cpp:201-208, remote_peer_list.cpp:86-110).
  * termination: key in [min_key, id] clockwise-inclusive
    (abstract_chord_peer.cpp:720-725).

Two finger modes (RingConfig.finger_mode):
  * "materialized": fingers live as an [N, 128] int32 peer-index matrix
    (the direct analog of the reference's tables; 512 B/peer).
  * "computed": fingers are derived per hop as the next-ALIVE ring
    successor of id + 2^i by binary search + alive-scan map — no [N,128]
    matrix, the memory-free path to 10M+ simulated peers. Computed
    fingers are always-converged (what a materialized table holds after a
    stabilize sweep), so the dead-finger fallback path is unreachable by
    construction and churn needs no finger repair.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from p2p_dhts_tpu import keyspace
from p2p_dhts_tpu.config import RingConfig, DEFAULT_CONFIG
from p2p_dhts_tpu.ops import u128

LANES = keyspace.LANES


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=("ids", "alive", "n_valid", "min_key", "preds", "succs",
                 "fingers"),
    meta_fields=("max_hops",))
@dataclasses.dataclass(frozen=True)
class RingState:
    """Whole-ring state: what the reference scatters across N processes.

    Rows are peers, sorted ascending by id; rows >= n_valid are padding.
    All cross-references (preds/succs/fingers) are row indices, -1 = none.

    `max_hops` rides along as STATIC pytree metadata (not an array leaf):
    build_ring stamps it from RingConfig so every lookup op honors a
    custom config without threading it through each call site by hand.
    Being static, it is available at trace time for loop bounds and
    changing it retraces — the same contract as a static_argnames arg.
    """

    ids: jax.Array                 # [N, 4] u32, sorted ascending
    alive: jax.Array               # [N] bool
    n_valid: jax.Array             # scalar i32: number of real rows
    min_key: jax.Array             # [N, 4] u32: own range lower bound
    preds: jax.Array               # [N] i32: predecessor row
    succs: jax.Array               # [N, S] i32: successor-list rows
    fingers: Optional[jax.Array]   # [N, F] i32 or None (computed mode)
    max_hops: int = DEFAULT_CONFIG.max_hops

    @property
    def capacity(self) -> int:
        return self.ids.shape[0]

    def _replace(self, **kw) -> "RingState":
        """NamedTuple-style functional update (all call sites use this)."""
        return dataclasses.replace(self, **kw)


# ---------------------------------------------------------------------------
# construction (host side; ids change only at churn, SURVEY.md §7)
# ---------------------------------------------------------------------------

def _pad_ids(ids_lanes: np.ndarray, capacity: int) -> np.ndarray:
    out = np.full((capacity, LANES), 0xFFFFFFFF, dtype=np.uint32)
    out[: ids_lanes.shape[0]] = ids_lanes
    return out


def fingers_for_ids(table_ids: jax.Array, n_valid: jax.Array,
                    peer_ids: jax.Array, num_fingers: int,
                    na: Optional[jax.Array] = None,
                    chunk: int = 16) -> jax.Array:
    """Converged finger targets for a set of peers — [R, F] i32 rows.

    fingers[p, i] = row of the ring successor of peer_ids[p] + 2^i in the
    sorted table: what PopulateFingerTable converges to
    (abstract_chord_peer.cpp:564-613), computed as F chunked binary
    searches instead of N*F sequential GET_SUCC RPCs. With `na` (a
    next_alive_map), dead rows are skipped — the post-repair
    (ReplaceDeadPeer/Rectify) target. This is THE single implementation;
    build, stabilize sweep, and join all call it.
    """
    r = peer_ids.shape[0]
    n = table_ids.shape[0]
    # Big tables get a bucket table built once for all chunks: each of
    # the r*F searches drops from log2(n) to ~log2(occupancy) gathers
    # (u128.bucket_starts) — the bulk of a 1M+-ring materialization.
    big = n >= (1 << u128.DEFAULT_BUCKET_BITS)
    if big:
        bbits = u128.bucket_bits_for(n)  # size-scaled: ~2^3 occupancy
        bstarts = u128.bucket_starts(table_ids, bbits)
    cols = []
    for f0 in range(0, num_fingers, chunk):
        fs = jnp.arange(f0, min(f0 + chunk, num_fingers), dtype=jnp.int32)
        starts = u128.add(peer_ids[:, None, :], u128.pow2(fs)[None, :, :])
        q = starts.reshape(-1, LANES)
        if big:
            # Padding-safe without the n_valid bound: padding rows are
            # all-0xFF and sort last, so both searches agree everywhere
            # (see u128.ring_successor_bucketed).
            j = u128.searchsorted_bucketed(table_ids, q, bstarts, bbits)
        else:
            j = u128.searchsorted(table_ids, q, n_valid)
        if na is None:
            idx = jnp.where(j >= n_valid, 0, j)  # plain ring wrap
        else:
            idx = na[j]
        cols.append(idx.reshape(r, -1))
    return jnp.concatenate(cols, axis=1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_fingers", "chunk"))
def _materialize_fingers(ids: jax.Array, n_valid: jax.Array,
                         num_fingers: int, chunk: int = 16) -> jax.Array:
    """Build-time all-alive finger materialization — [N, F] i32."""
    return fingers_for_ids(ids, n_valid, ids, num_fingers, chunk=chunk)


@functools.partial(jax.jit, static_argnames=("num_fingers",))
def materialize_converged_fingers(state: RingState,
                                  num_fingers: int = 128) -> RingState:
    """Post-hoc converged finger blocks for a swept ring — the
    materialized-mode state a computed-mode ring would have if every
    peer re-ran PopulateFingerTable against the current alive set
    (abstract_chord_peer.cpp:564-613, post-repair targets via the
    next-alive map).

    The at-scale lookup accelerator: a computed-mode hop pays a
    ~log2(occupancy) bucketed binary search per lane; a materialized hop
    is ONE row gather. The [N, F] i32 matrix costs 4*F bytes/peer
    (5.1 GB at 10M/F=128 — fits one v5e chip; 1/D of that per shard
    under shard_ring), so the intended pattern at 10M is: churn and
    sweep in computed mode, materialize once, then serve lookups.

    num_fingers defaults to 128 = the full binary key length, the only
    geometry the device stack supports (build_ring rejects key_bits !=
    128; the u128 lane math is hardwired to it) — matching what
    build_ring(finger_mode="materialized") would produce.
    """
    na = next_alive_map(state)
    fingers = fingers_for_ids(state.ids, state.n_valid, state.ids,
                              num_fingers, na=na)
    # Dead/padding rows hold -1 like build_ring/ring_genesis materialized
    # mode, so the two construction paths stay bit-identical (routing
    # never reads them — lookups start at alive rows).
    fingers = jnp.where(live_mask(state)[:, None], fingers, -1)
    return state._replace(fingers=fingers)


def _lanes_add1(x: np.ndarray) -> np.ndarray:
    """(x + 1) mod 2^128 on [N, 4] u32 lanes — vectorized carry chain."""
    out = x.copy()
    carry = np.ones(x.shape[0], dtype=bool)
    for lane in range(LANES):
        out[:, lane] = np.where(carry, out[:, lane] + np.uint32(1),
                                out[:, lane])
        carry = carry & (out[:, lane] == 0)
    return out


def build_ring(ids, cfg: RingConfig = DEFAULT_CONFIG,
               capacity: Optional[int] = None) -> RingState:
    """Build a fully-converged RingState from 128-bit integer ids.

    `ids` is a sequence of python ints OR an [N, 4] uint32 lane array
    (little-endian lanes, as keyspace.ints_to_lanes produces) — the lane
    path is fully vectorized so 10M-peer rings build in seconds.

    The array analog of: every peer has StartChord/Join'ed, every
    stabilize/fix-fingers round has run to fixpoint. Single-peer rings get
    min_key = id + 1, i.e. the whole keyspace (abstract_chord_peer.cpp:66-71).
    """
    if cfg.key_bits != keyspace.KEY_BITS:
        # keyspace/u128 lane math is hardcoded to 128-bit ids; a narrower
        # finger table would silently degrade routing to an O(N) walk.
        raise ValueError(f"build_ring supports key_bits=128 only, "
                         f"got {cfg.key_bits}")
    if isinstance(ids, np.ndarray) and ids.ndim == 2:
        lanes = np.ascontiguousarray(ids, dtype=np.uint32)
    else:
        lanes = keyspace.ints_to_lanes(ids)
    # Sort ascending (lane 3 most significant) and dedup — the vectorized
    # twin of sorted(set(ids)).
    order = np.lexsort((lanes[:, 0], lanes[:, 1], lanes[:, 2], lanes[:, 3]))
    lanes = lanes[order]
    if lanes.shape[0] > 1:
        keep = np.concatenate(
            [[True], np.any(lanes[1:] != lanes[:-1], axis=1)])
        lanes = lanes[keep]
    ids_lanes = lanes
    n = ids_lanes.shape[0]
    if n == 0:
        raise ValueError("ring needs at least one peer")
    capacity = n if capacity is None else capacity
    if capacity < n:
        raise ValueError(f"capacity {capacity} < {n} peers")
    s = cfg.num_succs

    idx = np.arange(n)
    preds = np.full(capacity, -1, dtype=np.int32)
    preds[:n] = (idx - 1) % n

    succs = np.full((capacity, s), -1, dtype=np.int32)
    for k in range(1, min(s, max(n - 1, 1)) + 1):
        if n > 1:
            succs[:n, k - 1] = (idx + k) % n

    min_key = np.zeros((capacity, LANES), dtype=np.uint32)
    min_key[:n] = _lanes_add1(np.roll(ids_lanes, 1, axis=0) if n > 1
                              else ids_lanes)

    alive = np.zeros(capacity, dtype=bool)
    alive[:n] = True

    ids_arr = jnp.asarray(_pad_ids(ids_lanes, capacity))
    n_valid = jnp.int32(n)

    fingers = None
    if cfg.finger_mode == "materialized":
        # Materialize over the n valid rows only (padding rows are never a
        # current peer, so their fingers are never read); pad with -1.
        valid = _materialize_fingers(
            jnp.asarray(ids_lanes), n_valid, cfg.num_fingers)
        fingers = jnp.full((capacity, cfg.num_fingers), -1, jnp.int32
                           ).at[:n].set(valid)

    return RingState(
        ids=ids_arr,
        alive=jnp.asarray(alive),
        n_valid=n_valid,
        min_key=jnp.asarray(min_key),
        preds=jnp.asarray(preds),
        succs=jnp.asarray(succs),
        fingers=fingers,
        max_hops=cfg.max_hops,
    )


def build_ring_from_seeds(seeds: Sequence[Tuple[str, int]],
                          cfg: RingConfig = DEFAULT_CONFIG,
                          capacity: Optional[int] = None) -> RingState:
    """Build from (ip, port) pairs — ids are SHA-1 of "ip:port" exactly like
    peer construction in the reference (abstract_chord_peer.cpp:13-28)."""
    return build_ring([keyspace.peer_id(ip, port) for ip, port in seeds],
                      cfg, capacity)


@functools.partial(jax.jit, static_argnames=("cfg", "capacity"))
def ring_genesis(lanes: jax.Array, cfg: RingConfig = DEFAULT_CONFIG,
                 capacity: Optional[int] = None) -> RingState:
    """build_ring's device twin: derive a converged RingState from RAW
    (unsorted, possibly-duplicated) [K, 4] u32 id lanes as ONE XLA
    program — sort, dedup, neighbor derivation, optional finger
    materialization all on device.

    Exists because the host path's cost at scale is pure overhead: a
    10M-peer state is ~12 s of host rand+lexsort plus ~0.5 GB of
    `jnp.asarray` uploads at the tunnel's ~20 MB/s — the better part of
    a minute for data the device derives from the id draw in
    milliseconds. Duplicate ids compact to padding exactly like
    build_ring's host-side `sorted(set(ids))`, so `n_valid` is traced,
    not `K`.
    """
    k = lanes.shape[0]
    if k == 0:
        raise ValueError("ring needs at least one peer")
    capacity = k if capacity is None else capacity
    if capacity < k:
        raise ValueError(f"capacity {capacity} < {k} peers")
    s = cfg.num_succs

    # Sort by id (lane 3 most significant).
    l0, l1, l2, l3 = (lanes[:, i] for i in range(LANES))
    l3, l2, l1, l0 = jax.lax.sort((l3, l2, l1, l0), num_keys=4)
    srt = jnp.stack([l0, l1, l2, l3], axis=1)
    # Dedup: push duplicate rows to the end (stable sort on the dup
    # flag keeps the id order among survivors), pad them out. The lanes
    # ride the sort as values — sorting indices and gathering srt[perm]
    # would be a K-at-K gather, the shape-sensitive TPU compile cliff
    # churn.leave was rewritten to avoid.
    dup = jnp.concatenate(
        [jnp.zeros((1,), bool), jnp.all(srt[1:] == srt[:-1], axis=1)])
    dup_i, s0, s1, s2, s3 = jax.lax.sort(
        (dup.astype(jnp.int32), l0, l1, l2, l3), num_keys=1)
    srt = jnp.where(dup_i[:, None].astype(bool), _u32_max(),
                    jnp.stack([s0, s1, s2, s3], axis=1))
    n_valid = jnp.int32(k) - dup.sum().astype(jnp.int32)

    ids = jnp.full((capacity, LANES), 0xFFFFFFFF, jnp.uint32)
    ids = ids.at[:k].set(srt)

    rows = jnp.arange(capacity, dtype=jnp.int32)
    valid = rows < n_valid
    alive = valid

    preds = jnp.where(valid, (rows - 1) % n_valid, -1)

    # succs col k-1 = (row + k) % n_valid, only for k <= n_valid - 1: the
    # single-peer ring has an all-empty succ list, as build_ring's host
    # loop (guarded by n > 1) produces.
    reach = n_valid - 1
    succ_cols = []
    for j in range(1, s + 1):
        col = jnp.where(valid & (j <= reach), (rows + j) % n_valid, -1)
        succ_cols.append(col)
    succs = jnp.stack(succ_cols, axis=1)

    # preds at genesis is the pure (row - 1) % n_valid shift, so prev_ids
    # is structurally a roll — NOT ids[preds], a capacity-at-capacity
    # gather (the TPU compile-cliff op class; see churn.leave). The
    # single wrap row is a one-index gather, NOT a dynamic_slice: with
    # ids row-sharded over "peer", a dynamic-slice start derived from
    # traced data is the gspmd-dynamic-slice-traced-start miscompile.
    wrap_id = jnp.take(ids, n_valid - 1, axis=0)[None, :]  # ids[n_valid-1]
    prev_ids = jnp.where((rows > 0)[:, None],
                         jnp.roll(ids, 1, axis=0), wrap_id)
    min_key = jnp.where(valid[:, None],
                        u128.add_scalar(prev_ids, 1),
                        jnp.zeros((1, LANES), jnp.uint32))

    fingers = None
    if cfg.finger_mode == "materialized":
        fingers = fingers_for_ids(ids[:k], n_valid, ids[:k],
                                  cfg.num_fingers)
        fingers = jnp.where(valid[:k, None], fingers, -1)
        fingers = jnp.full((capacity, cfg.num_fingers), -1, jnp.int32
                           ).at[:k].set(fingers)

    return RingState(ids=ids, alive=alive, n_valid=n_valid,
                     min_key=min_key, preds=preds, succs=succs,
                     fingers=fingers, max_hops=cfg.max_hops)


def _u32_max() -> jax.Array:
    return jnp.full((LANES,), 0xFFFFFFFF, jnp.uint32)


def build_ring_random(prng_key: jax.Array, n_peers: int,
                      cfg: RingConfig = DEFAULT_CONFIG,
                      capacity: Optional[int] = None) -> RingState:
    """Genesis of an n-peer ring with uniform random ids, entirely on
    device — the at-scale construction path (zero bulk host->device
    transfer; see ring_genesis). The id draw is `jax.random.bits` under
    threefry, deterministic across backends: a host CPU process can
    replay the identical ids from the same key when it needs the table
    without a device->host download (parity tests pin this replay
    property in tests/test_ring.py)."""
    lanes = jax.random.bits(prng_key, (n_peers, LANES), jnp.uint32)
    return ring_genesis(lanes, cfg=cfg, capacity=capacity)


# ---------------------------------------------------------------------------
# alive-neighbor scan maps (shared with churn ops)
# ---------------------------------------------------------------------------

# Python int, NOT a jnp constant: a module-scope jnp.int32(...) creates a
# concrete device array at import time, which force-initializes the default
# backend the moment this module is imported — fatal in driver processes
# whose TPU runtime is unusable (MULTICHIP_r02 libtpu-mismatch crash).
_BIG = 2**31 - 1


def live_mask(state: RingState) -> jax.Array:
    n = state.ids.shape[0]
    return state.alive & (jnp.arange(n, dtype=jnp.int32) < state.n_valid)


def next_alive_map(state: RingState) -> jax.Array:
    """na[j] = smallest alive row >= j, wrapping past the end — [N+1] i32.

    na[searchsorted(q)] is the alive ring successor of key q: the batched
    analog of succ-list head skipping (Stabilize,
    abstract_chord_peer.cpp:475-480) + LookupLiving. -1 everywhere if no
    peer is alive.
    """
    live = live_mask(state)
    n = state.ids.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    pos = jnp.where(live, rows, _BIG)
    suffix_min = jnp.flip(jax.lax.cummin(jnp.flip(pos)))
    first = suffix_min[0]  # global min (or _BIG if none alive)
    # [N+1] extension via update-slice, NOT concatenate([arr, 1-elem]):
    # XLA's SPMD partitioner (jax 0.4.x) miscompiles a concat involving
    # slices/pieces of a sharded operand under GSPMD auto-sharding (see
    # two_phase_hop_loop's merge note); update-slice partitions right.
    ext = jnp.full((n + 1,), _BIG, jnp.int32).at[:n].set(suffix_min)
    wrapped = jnp.where(ext == _BIG, first, ext)
    return jnp.where(wrapped == _BIG, -1, wrapped)


def prev_alive_map(state: RingState) -> jax.Array:
    """pa[j] = largest alive row <= j, wrapping below 0 — [N] i32."""
    live = live_mask(state)
    n = state.ids.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    pos = jnp.where(live, rows, jnp.int32(-1))
    prefix_max = jax.lax.cummax(pos)
    last = prefix_max[-1]
    return jnp.where(prefix_max < 0, last, prefix_max)


# ---------------------------------------------------------------------------
# lookup kernel
# ---------------------------------------------------------------------------

def placement_converged(state: RingState) -> jax.Array:
    """Scalar bool: every LIVE row has its alive ring predecessor in
    `preds` and min_key == pred_id + 1 — i.e. custody boundaries tile the
    surviving ring exactly (the post-sweep invariant). Weaker than
    `_converged_all_alive` (dead rows allowed), strong enough that the
    i-th successor of any key is simply the i-th next-alive row after its
    owner — which licenses the O(n)-gather placement fast path in
    dhash.store (vs n sequential full lookup sweeps).

    pred_ids (the id of the nearest live row strictly before each
    position, ring-wrapped) is computed by a log-depth roll+select
    doubling reduction — the shard_map-safe spelling of the
    "carry the last live id" prefix pass. It used to be a
    `lax.associative_scan`, whose lowering is an interleave of
    concat-of-slices that jax 0.4.x's SPMD partitioner miscompiles
    under GSPMD auto-sharding (observed returning False on a converged
    ring — the safe direction, but it silently routed dhash placement
    to the slow exact walk on every sharded call). Rolls partition
    correctly on every path (the two_phase_hop_loop merge rule; the
    8-device dryrun asserts the post-sweep True), no [N]-index gather
    is introduced (the TPU compile-cliff op class, see churn.leave),
    and the ring wrap falls out of the rotation for free."""
    live = live_mask(state)
    n = state.ids.shape[0]
    pa = prev_alive_map(state)
    # pa[rows - 1] with ring wrap at row 0 is a pure shift of pa.
    want_pred = jnp.roll(pa[:n], 1)
    preds_ok = ~jnp.any(live & (state.preds != want_pred))
    # carried[i] = id of the nearest LIVE row at-or-before i, wrapping
    # past row 0 (Hillis-Steele doubling over the ring; log2(N) steps,
    # each one roll + select — shape-insensitive, GSPMD-safe).
    carried = jnp.where(live[:, None], state.ids,
                        jnp.zeros((1, LANES), jnp.uint32))
    have = live
    shift = 1
    while shift < n:
        carried = jnp.where(have[:, None], carried,
                            jnp.roll(carried, shift, axis=0))
        have = have | jnp.roll(have, shift)
        shift *= 2
    # Strictly-before = shift the at-or-before result by one row; the
    # wrap row 0 <- row n-1 is exactly the ring wrap (rows past the
    # last live row already carry the globally-last live id). All-dead
    # rings are vacuously converged via the `live &` masks.
    pred_ids = jnp.roll(carried, 1, axis=0)
    want_min = u128.add_scalar(pred_ids, 1)
    mk_ok = ~jnp.any(live & ~u128.eq(state.min_key, want_min))
    return preds_ok & mk_ok


def n_successors_converged(state: RingState, keys: jax.Array, n: int
                           ) -> jax.Array:
    """[B, n] i32 owners of keys on a placement-converged ring: the alive
    ring successor of each key, then n-1 next-alive steps — n single
    gathers per key instead of n full hop-loop sweeps. Stops with -1 when
    the walk wraps back to the first owner (GetNSuccessors'
    already-in-list break, abstract_chord_peer.cpp:345-373). Caller must
    hold `placement_converged(state)` (see dhash.store.placement_owners
    for the guarded dispatch)."""
    na = next_alive_map(state)
    nn = state.ids.shape[0]
    first = na[u128.searchsorted(state.ids, keys, state.n_valid)]
    b = keys.shape[0]
    cols = []
    cur = first
    stopped = first < 0  # no alive peer at all
    for _ in range(n):
        cols.append(jnp.where(stopped, -1, cur))
        nxt = na[jnp.minimum(jnp.maximum(cur, -1) + 1, nn)]
        stopped = stopped | (nxt == first)
        cur = nxt
    return jnp.stack(cols, axis=1)


def _converged_all_alive(state: RingState) -> jax.Array:
    """Scalar bool: every valid row alive AND min_key == pred_id + 1.

    Under these conditions the reference's StoredLocally test
    (key in [min_key, id], abstract_chord_peer.cpp:720-725) is equivalent
    to "cur is the ring successor of key", the self-hit predecessor is
    always alive, and the dead-finger fallback is unreachable — which is
    what licenses the lean lookup loop below. O(N) streaming check, no
    per-hop cost.
    """
    n = state.ids.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    valid = rows < state.n_valid
    all_alive = ~jnp.any(valid & ~state.alive)
    # On a fully-alive converged SORTED ring preds is exactly the shift
    # (i - 1) % n_valid; checking that form lets pred ids come from a
    # structured roll instead of an [N]-index gather from the id table
    # (the XLA TPU shape-sensitive compile-cliff op class — churn.leave).
    want_pred = jnp.where(rows > 0, rows - 1, state.n_valid - 1)
    preds_ok = ~jnp.any(valid & (state.preds != want_pred))
    last_id = jax.lax.dynamic_slice_in_dim(
        state.ids, jnp.maximum(state.n_valid - 1, 0), 1, axis=0)[0]
    pred_ids = jnp.where((rows > 0)[:, None],
                         jnp.roll(state.ids, 1, axis=0), last_id[None, :])
    want_min = u128.add_scalar(pred_ids, 1)
    mk_ok = ~jnp.any(valid & ~u128.eq(state.min_key, want_min))
    return all_alive & preds_ok & mk_ok


def two_phase_hop_loop(body_for, keys: jax.Array, owner0: jax.Array,
                       cur0: jax.Array, max_hops: int,
                       unroll: int = 1
                       ) -> Tuple[jax.Array, jax.Array]:
    """Straggler-compacted lockstep hop driver, shared by `_fast_lookup`
    and the shard_map kernel (core/sharded.py — all its lane state is
    replicated, so the permutation is shard-safe).

    body_for(keys, owner0) -> while_loop body over (cur, hops, it);
    termination is cur == owner0 per lane. Hop counts are ~log2(N)
    distributed, so a single full-width loop runs ~2x the mean trip count
    for a shrinking tail: phase 1 runs full-width until <= B/8 lanes
    remain, then a stable partition (two cumsums + one scatter, paid
    once) packs the stragglers into a B/8 prefix and phase 2 finishes at
    1/8 width. If phase 1 exits on the hop budget with > B/8 stragglers
    they are failed lookups anyway (max_hops == routing loop), so losing
    them past the prefix is safe: phase 2 runs zero trips and the final
    cur != owner0 test marks them failed. Returns (cur, hops).

    unroll > 1 chains that many guarded hop steps per while_loop
    iteration: identical routes and hop counts (every sub-step is
    per-lane done- AND budget-guarded — bodies must gate advancement on
    ``it < max_hops``, as _fast_lookup's does), but the loop condition,
    straggler count, and loop bookkeeping amortize over `unroll` hops.
    A measured serve variant (bench lookup_1m unroll2 field); default 1.
    """
    b = keys.shape[0]
    p = max(b // 8, 1)

    def chain(body):
        if unroll == 1:
            return body

        def chained(carry):
            for _ in range(unroll):
                carry = body(carry)
            return carry
        return chained

    def cond1(carry):
        cur, _, it = carry
        return (jnp.sum(cur != owner0) > p) & (it < max_hops)

    cur, hops, it = jax.lax.while_loop(
        cond1, chain(body_for(keys, owner0)),
        (cur0, jnp.zeros(b, jnp.int32), jnp.int32(0)))

    not_done = cur != owner0
    n_nd = jnp.cumsum(not_done)
    pos = jnp.where(not_done, n_nd - 1,
                    n_nd[-1] + jnp.cumsum(~not_done) - 1).astype(jnp.int32)
    inv = jnp.zeros(b, jnp.int32).at[pos].set(
        jnp.arange(b, dtype=jnp.int32))
    cur_c, hops_c = cur[inv], hops[inv]
    keys_c, owner0_c = keys[inv], owner0[inv]

    def cond2(carry):
        cur_p, _, it = carry
        return (~jnp.all(cur_p == owner0_c[:p])) & (it < max_hops)

    cur_p, hops_p, _ = jax.lax.while_loop(
        cond2, chain(body_for(keys_c[:p], owner0_c[:p])),
        (cur_c[:p], hops_c[:p], it))

    # Merge via dynamic-update-slice, NOT concatenate([head, tail[p:]]):
    # identical result, but XLA's SPMD partitioner (jax 0.4.x) miscompiles
    # a concat of two slices of a lane-sharded array under GSPMD
    # auto-sharding (outputs get summed across an unrelated mesh axis —
    # caught by the 8-device dryrun, __graft_entry__._dryrun_impl).
    # Update-slice partitions correctly on every path, including the
    # explicit shard_map kernel where lanes are shard-local anyway.
    cur = cur_c.at[:p].set(cur_p)[pos]
    hops = hops_c.at[:p].set(hops_p)[pos]
    return cur, hops


def _fast_lookup(state: RingState, keys: jax.Array, start: jax.Array,
                 max_hops: int,
                 structured_pred: bool = False,
                 unroll: int = 1) -> Tuple[jax.Array, jax.Array]:
    """Lean hop loop for converged all-alive rings — identical route and
    hop counts to the general loop (the parity obligation), minus
    everything that can't trigger there: per-hop min_key gathers (16 B),
    the succ-list fallback ([B,S] gathers + S-wide u128 compares, the
    round-1 profile's dominant cost), and alive-mask gathers. Termination
    is cur == ring_successor(key), precomputed once per lane; the loop
    itself is the shared straggler-compacted `two_phase_hop_loop`.
    Per-hop random traffic: ids[cur] 16 B + finger 4 B + pred 4 B.
    structured_pred=True drops the pred gather: on the converged sorted
    layout this path requires, pred(row) IS (row - 1) % n_valid — the
    exact invariant _converged_all_alive admits states by. It is a
    SEPARATE traced program (bench.py measures it alongside, firewalled)
    because the TPU persistent compile cache holds the gathered-pred
    programs from the round's one successful on-chip run and the remote
    compile service has been down since: changing the default's HLO would
    fail the cached-green chord16 config outright instead of serving it
    from cache. The default flips once the on-chip comparison lands.
    """
    ids, preds = state.ids, state.preds
    nv = state.n_valid
    materialized = state.fingers is not None
    # Big rings resolve successors through a bucket table (built once per
    # call, amortized over the batch): owner0 always, plus every hop in
    # computed-finger mode.
    big = ids.shape[0] >= (1 << u128.DEFAULT_BUCKET_BITS)
    if big:
        bbits = u128.bucket_bits_for(ids.shape[0])
        bstarts = u128.bucket_starts(ids, bbits)

        def ring_succ(q):
            return u128.ring_successor_bucketed(
                ids, q, bstarts, bbits, state.n_valid)
    else:
        def ring_succ(q):
            return u128.ring_successor(ids, q, state.n_valid)

    owner0 = ring_succ(keys)

    def body_for(keys_, owner0_):
        def body(carry):
            cur, hops, it = carry
            done = cur == owner0_
            cur_ids = ids[cur]
            dist = u128.sub(keys_, cur_ids)
            fi = jnp.maximum(u128.bit_length(dist) - 1, 0)
            if materialized:
                nxt = state.fingers[cur, fi]
            else:
                starts = u128.add(cur_ids, u128.pow2(fi))
                nxt = ring_succ(starts)
            # Self-hit -> predecessor (always alive here),
            # chord_peer.cpp:194-196.
            if structured_pred:
                pred_cur = jnp.where(cur > 0, cur - 1, nv - 1)
            else:
                pred_cur = preds[cur]
            nxt = jnp.where(nxt == cur, pred_cur, nxt)
            # Budget-guarded per sub-step so two_phase_hop_loop's unroll
            # preserves exact hop semantics (the loop cond alone checks
            # the budget only every `unroll` hops).
            live = (~done) & (it < max_hops)
            cur = jnp.where(live, nxt, cur)
            hops = jnp.where(live, hops + 1, hops)
            return cur, hops, it + 1
        return body

    cur0 = jnp.asarray(start, dtype=jnp.int32)
    cur, hops = two_phase_hop_loop(body_for, keys, owner0, cur0, max_hops,
                                   unroll=unroll)

    failed = cur != owner0  # hop budget exhausted == routing loop
    owner = jnp.where(failed, -1, cur)
    hops = jnp.where(failed, -1, hops)
    return owner, hops


def _succ_list_candidate(state: RingState, cur: jax.Array,
                         keys: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Vectorized RemotePeerList::Lookup(key, succ=True)
    (remote_peer_list.cpp:86-110): first successor-list entry whose
    [prev_id, entry_id] range contains the key. Returns (row, found).

    -1 holes (left mid-list by churn.leave's RemotePeerList::Delete
    analog) are skipped when deriving each entry's range lower bound: the
    reference's list is COMPACT (Delete erases the element, neighbors
    become adjacent, remote_peer_list.cpp:134-150), so slot j's lower
    bound is the id of the last VALID entry before j (own id if none) —
    not the id of whatever row a hole's -1 would clamp-gather to.
    """
    entries = state.succs[cur]                          # [B, S]
    valid = entries >= 0
    entry_ids = state.ids[jnp.maximum(entries, 0)]      # [B, S, 4]
    own_ids = state.ids[cur]                            # [B, 4]
    s = entries.shape[1]
    prev_cols = []
    prev = own_ids                                      # [B, 4]
    for j in range(s):                                  # S is small (~8)
        prev_cols.append(prev)
        prev = jnp.where(valid[:, j:j + 1], entry_ids[:, j, :], prev)
    prev_ids = jnp.stack(prev_cols, axis=1)             # [B, S, 4]
    hit = valid & u128.in_between(keys[:, None, :], prev_ids, entry_ids, True)
    j = jnp.argmax(hit, axis=1)
    found = jnp.any(hit, axis=1)
    row = jnp.take_along_axis(entries, j[:, None], axis=1)[:, 0]
    return row, found


def _general_lookup(state: RingState, keys: jax.Array,
                    start: jax.Array, max_hops: int
                    ) -> Tuple[jax.Array, jax.Array]:
    """Full-semantics hop loop: min_key termination, self-hit correction,
    dead-finger succ-list fallback — exact behavior under churn."""
    ids, alive, preds = state.ids, state.alive, state.preds
    materialized = state.fingers is not None
    if not materialized:
        # Computed fingers are always-converged: the target of finger i is
        # the alive ring successor of id + 2^i (what a materialized table
        # holds after a stabilize sweep). Without the alive mask, dead
        # rows would act as permanently-stale entries no sweep can repair.
        na = next_alive_map(state)

    def cond(carry):
        _, _, done, _, it = carry
        return (~jnp.all(done)) & (it < max_hops)

    def body(carry):
        cur, hops, done, failed, it = carry
        cur_s = jnp.maximum(cur, 0)
        cur_ids = ids[cur_s]
        local = u128.in_between(keys, state.min_key[cur_s], cur_ids, True)
        done_now = done | local

        # Finger choice: containing-range scan == bit_length(dist) - 1.
        dist = u128.sub(keys, cur_ids)
        fi = jnp.maximum(u128.bit_length(dist) - 1, 0)
        if materialized:
            nxt = state.fingers[cur_s, fi]
        else:
            starts = u128.add(cur_ids, u128.pow2(fi))
            nxt = na[u128.searchsorted(ids, starts, state.n_valid)]
        nxt = jnp.maximum(nxt, 0)

        # Self-hit -> predecessor when alive (chord_peer.cpp:194-196).
        pred_rows = preds[cur_s]
        self_hit = (nxt == cur_s) & alive[jnp.maximum(pred_rows, 0)] \
            & (pred_rows >= 0)
        nxt = jnp.where(self_hit, pred_rows, nxt)

        # Dead finger -> succ-list fallback (chord_peer.cpp:201-208).
        need_fb = (~self_hit) & (~alive[nxt])
        fb_row, fb_found = _succ_list_candidate(state, cur_s, keys)
        fb_ok = fb_found & alive[jnp.maximum(fb_row, 0)] & (fb_row >= 0)
        fail_now = (~done_now) & need_fb & (~fb_ok)
        nxt = jnp.where(need_fb, jnp.where(fb_ok, fb_row, cur_s), nxt)

        advance = (~done_now) & (~fail_now)
        cur = jnp.where(advance, nxt, cur)
        hops = jnp.where(advance, hops + 1, hops)
        failed = failed | fail_now
        done = done_now | fail_now
        return cur, hops, done, failed, it + 1

    b = keys.shape[0]
    cur0 = jnp.asarray(start, dtype=jnp.int32)
    hops0 = jnp.zeros(b, dtype=jnp.int32)
    done0 = jnp.zeros(b, dtype=bool)
    failed0 = jnp.zeros(b, dtype=bool)
    cur, hops, done, failed, _ = jax.lax.while_loop(
        cond, body, (cur0, hops0, done0, failed0, jnp.int32(0)))

    # Lanes still in flight when the budget ran out get one final local
    # check: a route of exactly max_hops hops needs max_hops+1 body
    # iterations (the last one only to observe termination), so without
    # this a boundary-length route would be misreported as failed.
    cur_s = jnp.maximum(cur, 0)
    local_fin = u128.in_between(keys, state.min_key[cur_s], ids[cur_s], True)
    resolved = done | (~failed & local_fin)
    failed = failed | ~resolved  # hop budget exhausted == routing loop
    owner = jnp.where(failed, -1, cur)
    hops = jnp.where(failed, -1, hops)
    return owner, hops


@functools.partial(jax.jit, static_argnames=("max_hops",))
def find_successor(state: RingState, keys: jax.Array,
                   start: jax.Array, max_hops: Optional[int] = None
                   ) -> Tuple[jax.Array, jax.Array]:
    """Batched GetSuccessor: resolve B keys from B starting peers at once.

    keys:  [B, 4] u32
    start: [B] i32 row indices of the originating peers
    returns (owner [B] i32, hops [B] i32); failed lookups (the reference
    throws "Lookup failed", chord_peer.cpp:206) come back as owner -1,
    hops -1. Lanes that exceed max_hops (a routing loop the reference would
    recurse on forever) also fail.

    Each while_loop iteration advances EVERY unresolved lane by one hop —
    the device analog of one recursive GET_SUCC RPC per key. Dispatches at
    runtime (lax.cond — only the taken branch executes) between the lean
    converged-ring loop and the full-semantics loop; both produce
    identical routes and hop counts wherever both are defined.

    max_hops defaults to the value build_ring stamped into the state from
    its RingConfig (static pytree metadata), so a custom
    RingConfig(max_hops=...) is honored everywhere without explicit
    threading; an explicit argument still overrides per call.
    """
    if max_hops is None:
        max_hops = state.max_hops
    return jax.lax.cond(
        _converged_all_alive(state),
        # structured_pred=True (flipped round 5): the fast branch runs
        # exactly when _converged_all_alive holds — the invariant under
        # which pred(row) IS (row-1) % n_valid — so the per-hop preds
        # gather is pure overhead there (+34% serve on the 1M-peer CPU
        # rehearsal, BENCH_NOTES_r04). The gathered-pred loop survives
        # as find_successor_gathered_pred; bench.py measures both.
        lambda: _fast_lookup(state, keys, start, max_hops,
                             structured_pred=True),
        lambda: _general_lookup(state, keys, start, max_hops),
    )


@functools.partial(jax.jit, static_argnames=("max_hops",))
def find_successor_gathered_pred(state: RingState, keys: jax.Array,
                                 start: jax.Array,
                                 max_hops: Optional[int] = None
                                 ) -> Tuple[jax.Array, jax.Array]:
    """The all-alive fast serve loop with the per-hop preds GATHER for
    the self-hit correction (chord_peer.cpp:194-196) — the pre-round-5
    default, kept as the measured fallback (bench.py reports it as
    gathered_pred_lookups_s). Callers must guarantee a converged
    all-alive ring; there is no runtime dispatch here. Identical routes
    and hop counts to find_successor on such rings."""
    if max_hops is None:
        max_hops = state.max_hops
    return _fast_lookup(state, keys, start, max_hops, structured_pred=False)


@functools.partial(jax.jit, static_argnames=("max_hops",))
def find_successor_unroll2(state: RingState, keys: jax.Array,
                           start: jax.Array,
                           max_hops: Optional[int] = None
                           ) -> Tuple[jax.Array, jax.Array]:
    """The all-alive fast serve loop with TWO budget-guarded hops per
    while_loop iteration (two_phase_hop_loop unroll=2): identical routes
    and hop counts to find_successor on converged all-alive rings, but
    the loop condition, straggler count, and loop bookkeeping amortize
    over two hops — a measured candidate for when per-iteration overhead
    (not gather bandwidth) dominates the serve (bench lookup_1m emits it
    as unroll2_lookups_s; flips into the default only on chip
    evidence). Callers must guarantee a converged all-alive ring."""
    if max_hops is None:
        max_hops = state.max_hops
    return _fast_lookup(state, keys, start, max_hops,
                        structured_pred=True, unroll=2)


@jax.jit
def finger_index_batch(keys: jax.Array, starts: jax.Array) -> jax.Array:
    """Batched finger-table entry index: for each (key, table_start)
    lane pair, bit_length((key - start) mod 2^128) - 1 — the closed form
    of FingerTable::Lookup's 128-entry containing-range scan
    (finger_table.h:115-130), -1 for the zero-distance LookupError case.

    keys / starts: [B, 4] u32 lane vectors. THE single device-side copy
    of the overlay bridge op: serve.ServeEngine's "finger_index" kind
    and the fused multi-kind read kernels (chordax-fuse) both resolve
    through it, so the closed form can never fork.
    """
    return u128.bit_length(u128.sub(keys, starts)) - 1


@functools.partial(jax.jit, static_argnames=("max_hops",))
def fused_lookup_batch(state: RingState, fs_keys: jax.Array,
                       fs_starts: jax.Array, fi_keys: jax.Array,
                       fi_starts: jax.Array,
                       max_hops: Optional[int] = None
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """chordax-fuse: the store-less multi-kind super-batch program —
    successor search and the finger closed form under ONE jit, so a
    mixed FIND_SUCCESSOR + FINGER_INDEX burst costs one XLA dispatch
    instead of one per kind.

    Per-kind input blocks (fs_keys/fs_starts for the lookup lanes,
    fi_keys/fi_starts for the finger lanes) are padded by the caller to
    one shared bucket; the per-lane kind selector lives host-side in
    the ServeEngine's fused batch plan (it decides block membership and
    result fan-out — the device program stays selector-free so each
    sub-computation only touches its own block's lanes, keeping the
    fused program's arithmetic equal to the per-kind dispatches it
    replaces). Returns (owner [B], hops [B], finger_idx [B]) —
    byte-identical to find_successor + finger_index_batch run apart.
    The store-carrying triple lives in dhash.store.fused_read_batch.
    """
    owner, hops = find_successor(state, fs_keys, fs_starts, max_hops)
    return owner, hops, finger_index_batch(fi_keys, fi_starts)


@functools.partial(jax.jit, static_argnames=())
def owner_of(state: RingState, keys: jax.Array) -> jax.Array:
    """Omniscient 0-hop ownership: row of the ring successor of each key.

    Not a protocol op — the O(log N) "god's eye" resolution used for
    placement math and as the correctness cross-check for find_successor.
    """
    return u128.ring_successor(state.ids, keys, state.n_valid)


@functools.partial(jax.jit, static_argnames=("n", "max_hops"))
def get_n_successors(state: RingState, keys: jax.Array, start: jax.Array,
                     n: int, max_hops: Optional[int] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Batched GetNSuccessors (abstract_chord_peer.cpp:345-373).

    Walks succ(key), succ(owner_id + 1), ... n times, breaking (per lane)
    when the walk wraps back to the first owner — the reference's
    already-in-list break. Returns (owners [B, n] i32 with -1 past the
    break, hops [B, n] i32 per-lookup hop counts, -1 past the break).
    """
    def step(carry, _):
        q, first_owner, stopped = carry
        owner, hops = find_successor(state, q, start, max_hops)
        is_first = first_owner < 0
        wrapped = (~is_first) & (owner == first_owner)
        stopped = stopped | wrapped | (owner < 0)
        out_owner = jnp.where(stopped, -1, owner)
        out_hops = jnp.where(stopped, -1, hops)
        first_owner = jnp.where(is_first, owner, first_owner)
        next_q = u128.add_scalar(state.ids[jnp.maximum(owner, 0)], 1)
        q = jnp.where(stopped[:, None], q, next_q)
        return (q, first_owner, stopped), (out_owner, out_hops)

    b = keys.shape[0]
    carry0 = (keys,
              jnp.full(b, -1, dtype=jnp.int32),
              jnp.zeros(b, dtype=bool))
    _, (owners, hops) = jax.lax.scan(step, carry0, None, length=n)
    return jnp.moveaxis(owners, 0, 1), jnp.moveaxis(hops, 0, 1)


# ---------------------------------------------------------------------------
# host conveniences
# ---------------------------------------------------------------------------

def keys_from_ints(values: Sequence[int]) -> jax.Array:
    """Python ints -> [B, 4] u32 device keys."""
    return jnp.asarray(keyspace.ints_to_lanes(values))


def keys_from_plaintext(texts: Sequence[str]) -> jax.Array:
    """SHA-1 hash plaintexts to device keys (host-side hashing, ids only
    change at ingestion — SURVEY.md §7 hard-parts)."""
    return keys_from_ints([keyspace.sha1_id(t) for t in texts])

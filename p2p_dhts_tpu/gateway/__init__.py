"""chordax-gateway: the multi-ring serving front door (ISSUE 4).

Fronts N named rings/stores with one router, coalesces all inbound RPC
traffic into each ring's ServeEngine, and degrades gracefully per ring:

  net/rpc.py Server ──> Gateway (route/health/admission) ──> ServeEngine
                                                             ──> device

Modules:
  router       ring registry, key-range routing, health state machine
               (healthy -> degraded -> ejected, periodic re-probe)
  admission    per-ring bounded admission, deadline propagation,
               single-flight duplicate suppression
  frontend     the Gateway itself + the FIND_SUCCESSOR / GET / PUT /
               FINGER_INDEX / SYNC_RANGE / REPAIR_STATUS RPC handlers
               + the process-global instance. PUT optionally fans to
               n rings at quorum w (Gateway.set_replication, backed by
               p2p_dhts_tpu.repair — the chordax-repair subsystem).
  metrics_ext  per-ring/per-op counters, gauges, p50/p99 histograms

Importing this package never initializes a jax backend (overlay
etiquette); device work happens only once requests flow.
"""

from p2p_dhts_tpu.gateway.admission import (  # noqa: F401
    Deadline,
    NO_DEADLINE,
    RingAdmission,
    RingBusyError,
    SingleFlight,
)
from p2p_dhts_tpu.gateway.cache import HotKeyCache  # noqa: F401
from p2p_dhts_tpu.gateway.frontend import (  # noqa: F401
    FINGER_RING_ID,
    GATEWAY_COMMANDS,
    Gateway,
    global_gateway,
    install_gateway_handlers,
)
from p2p_dhts_tpu.gateway.metrics_ext import GatewayMetrics  # noqa: F401
from p2p_dhts_tpu.gateway.router import (  # noqa: F401
    DEGRADED,
    EJECTED,
    HEALTHY,
    RingBackend,
    RingRouter,
    RingUnavailableError,
    UnknownRingError,
)

"""Multi-ring routing + per-ring health for the chordax gateway.

A *ring* is one named serving backend: a device ring (RingState +
optionally a FragmentStore) fronted by its own ServeEngine. The router
holds the registry and answers "which backend serves this request" by
explicit ring_id, by key-range ownership on the 2^128 identifier
circle, or by the default ring — the router-in-front-of-batched-
backends shape of every continuous-batching serving stack, carrying
Chord/DHash semantics (Stoica et al. 2001; Cates 2003) instead of
transformer steps.

Each backend carries a three-state health machine —

    healthy --failure--> degraded --EJECT_AFTER consecutive--> ejected
       ^                    |  ^                                  |
       +----probe success---+  +------- probe failure -----------+
       +--------------------- probe success ----------------------+

— mirroring the VISIBLE-degradation pattern overlay/finger_table.py
established: a failure is logged once (with traceback), flips the
state, and the device path is re-probed every `reprobe_s` by ONE
prober at a time so a dead backend never eats an exception storm.
DEGRADED rings keep serving through the gateway's fallback path
(frontend._fallback_serve — the legacy-bridge analog); EJECTED rings
fail fast so their traffic cannot convoy the healthy rings' worker
threads.

LOCK ORDER (audited by chordax-lint pass 3 and the runtime watchdog;
extend this note if the order ever grows):

  * `RingRouter._lock` and `RingBackend._health_lock` are both LEAVES:
    neither is ever held across an engine call, a device dispatch, any
    blocking wait, or the other lock. `route()` copies the backend
    reference out and releases before the caller touches it; health
    transitions collect their state-change callback and fire it AFTER
    release.
  * Hot add/remove: `add_ring`/`remove_ring` touch only `_lock`;
    `remove_ring` returns the backend so the caller drains/closes its
    engine OUTSIDE the lock (a draining engine blocks for seconds).

This module never imports jax.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from p2p_dhts_tpu.keyspace import KEYS_IN_RING

logger = logging.getLogger(__name__)

#: Health states, in degradation order.
HEALTHY = "healthy"
DEGRADED = "degraded"
EJECTED = "ejected"

#: Numeric codes for the `gateway.health.<ring>` gauge.
HEALTH_CODE = {HEALTHY: 0, DEGRADED: 1, EJECTED: 2}


class UnknownRingError(RuntimeError):
    """No registered ring matches the request's ring_id / key."""


class RingUnavailableError(RuntimeError):
    """The routed ring is ejected (or has no usable serving path)."""


def key_in_range(key_int: int, lo: int, hi: int) -> bool:
    """Clockwise-inclusive [lo, hi] membership on the 2^128 circle
    (the overlay's Key.in_between rule, key.h:103-131, for plain
    ints). lo == hi matches exactly that one key."""
    key_int %= KEYS_IN_RING
    lo %= KEYS_IN_RING
    hi %= KEYS_IN_RING
    if lo <= hi:
        return lo <= key_int <= hi
    return key_int >= lo or key_int <= hi


def keys_in_range_mask(lanes, lo: int, hi: int):
    """Vectorized key_in_range over a whole [N, LANES] uint32 key
    array (chordax-fastlane): one boolean mask, zero per-key python —
    the rule above, computed on the wire's zero-copy lane view."""
    from p2p_dhts_tpu.keyspace import lanes_in_range_mask
    return lanes_in_range_mask(lanes, lo, hi)


def split_key_range(key_range: Optional[Tuple[int, int]]
                    ) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """Halve a clockwise-inclusive [lo, hi] arc into two adjacent
    arcs — ((lo, mid), (mid+1, hi)) on the 2^128 circle. None (the
    range-less default ring) splits as the FULL circle. The halves
    are exact complements: merge_key_ranges inverts this (the
    chordax-elastic SPLIT/MERGE algebra)."""
    if key_range is None:
        lo, hi = 0, KEYS_IN_RING - 1
    else:
        lo = int(key_range[0]) % KEYS_IN_RING
        hi = int(key_range[1]) % KEYS_IN_RING
    span = (hi - lo) % KEYS_IN_RING + 1
    if span < 2:
        raise ValueError(f"key range ({lo:#x}, {hi:#x}) spans {span} "
                         "key(s); nothing to split")
    mid = (lo + span // 2 - 1) % KEYS_IN_RING
    return (lo, mid), ((mid + 1) % KEYS_IN_RING, hi)


def merge_key_ranges(a: Tuple[int, int],
                     b: Tuple[int, int]) -> Tuple[int, int]:
    """Join two ADJACENT clockwise-inclusive arcs back into one
    (either argument order). Raises ValueError for non-adjacent arcs —
    a merge across a gap would silently claim keys neither ring owns."""
    a_lo, a_hi = (int(a[0]) % KEYS_IN_RING, int(a[1]) % KEYS_IN_RING)
    b_lo, b_hi = (int(b[0]) % KEYS_IN_RING, int(b[1]) % KEYS_IN_RING)
    if (a_hi + 1) % KEYS_IN_RING == b_lo:
        return (a_lo, b_hi)
    if (b_hi + 1) % KEYS_IN_RING == a_lo:
        return (b_lo, a_hi)
    raise ValueError(
        f"key ranges ({a_lo:#x}, {a_hi:#x}) and ({b_lo:#x}, {b_hi:#x}) "
        "are not adjacent")


class RingBackend:
    """One named serving backend: engine + key range + health machine.

    `engine` is a started ServeEngine (any object with the engine's
    submit/submit_many contract works — tests inject stubs). The
    backend itself never calls the engine: the frontend asks
    `admit_device_path()` for a verdict, runs the request, and reports
    back via `record_success`/`record_failure` — so no backend lock is
    ever held across device work.
    """

    #: Consecutive device-path failures before degraded becomes ejected.
    EJECT_AFTER = 5
    #: Seconds between device-path re-probes while degraded/ejected.
    REPROBE_S = 30.0

    def __init__(self, ring_id: str, engine,
                 key_range: Optional[Tuple[int, int]] = None,
                 reprobe_s: Optional[float] = None,
                 on_state_change: Optional[
                     Callable[[str, str], None]] = None,
                 state=None):
        self.ring_id = str(ring_id)
        self.engine = engine
        #: The ring's device RingState (None for stateless backends,
        #: e.g. the finger front). The frontend's DEGRADED fallback
        #: dispatches find_successor directly against it, bypassing the
        #: engine — the per-table-bridge shape, kept as the fallback.
        #: (`state` the property is HEALTH state; hence the prefix.)
        self.ring_state = state
        self.key_range = (
            (int(key_range[0]) % KEYS_IN_RING,
             int(key_range[1]) % KEYS_IN_RING)
            if key_range is not None else None)
        self.reprobe_s = float(reprobe_s if reprobe_s is not None
                               else self.REPROBE_S)
        self._on_state_change = on_state_change
        self._health_lock = threading.Lock()
        self._state = HEALTHY
        self._consecutive_failures = 0
        self._retry_at = 0.0
        self._probe_inflight = False
        self._degraded_logged = False
        #: Attached MembershipManager (chordax-membership, ISSUE 7):
        #: set by the manager's constructor. While present, the
        #: fallback find_successor path during a handoff window serves
        #: from the manager's host mirror instead of the (possibly
        #: stale) ring_state snapshot.
        self.membership = None
        # Ownership-handoff window depth: >0 while a churn batch is in
        # flight between the engine and the metadata updates
        # (ring_state swap + mirror). Guarded by _health_lock (a leaf;
        # begin/end never nest with anything).
        self._handoff_depth = 0

    # -- routing -------------------------------------------------------------
    def owns_key(self, key_int: int) -> bool:
        if self.key_range is None:
            return False
        return key_in_range(key_int, *self.key_range)

    def owns_keys_mask(self, lanes):
        """Vectorized ownership over an [N, LANES] uint32 key array:
        one boolean mask (all-False for range-less backends), zero
        per-key python — the fast lane's routing primitive. The
        key_range read is one reference; set_key_range swaps it
        atomically, so a concurrent re-split yields either the old
        complete range or the new one, never a torn pair."""
        rng = self.key_range
        if rng is None:
            import numpy as np
            return np.zeros(lanes.shape[0], dtype=bool)
        return keys_in_range_mask(lanes, *rng)

    # -- elasticity (chordax-membership) --------------------------------------
    def set_ring_state(self, state) -> None:
        """Atomic swap of the fallback-path RingState (one reference
        assignment) — the membership manager installs the post-churn
        snapshot here after each applied batch so a degraded-ring
        direct dispatch never resolves against a retired table."""
        self.ring_state = state

    def begin_handoff(self) -> None:
        with self._health_lock:
            self._handoff_depth += 1

    def end_handoff(self) -> None:
        with self._health_lock:
            self._handoff_depth = max(self._handoff_depth - 1, 0)

    @property
    def in_handoff(self) -> bool:
        with self._health_lock:
            return self._handoff_depth > 0

    # -- health machine ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._health_lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._health_lock:
            return self._consecutive_failures

    def admit_device_path(self) -> str:
        """Verdict for one request: "engine" (healthy), "probe" (this
        caller is THE one re-prober of a degraded/ejected backend —
        it MUST report back via record_success/record_failure or
        probe_release), "fallback" (degraded, serve the fallback path),
        or "ejected" (fail fast)."""
        with self._health_lock:
            if self._state == HEALTHY:
                return "engine"
            if (time.monotonic() >= self._retry_at
                    and not self._probe_inflight):
                self._probe_inflight = True
                return "probe"
            return "ejected" if self._state == EJECTED else "fallback"

    def record_success(self, probing: bool = False) -> None:
        fire = None
        with self._health_lock:
            if probing:
                self._probe_inflight = False
            if self._state != HEALTHY:
                logger.warning("gateway ring %r device path recovered "
                               "(was %s)", self.ring_id, self._state)
                self._state = HEALTHY
                self._degraded_logged = False
                fire = HEALTHY
            self._consecutive_failures = 0
        if fire is not None:
            from p2p_dhts_tpu.health import FLIGHT
            FLIGHT.record("gateway", "ring_recovered", ring=self.ring_id)
            if self._on_state_change is not None:
                self._on_state_change(self.ring_id, fire)

    def record_failure(self, exc: Optional[BaseException] = None,
                       probing: bool = False) -> str:
        """Count one device-path failure; returns the resulting state.
        Logged ONCE per degradation episode, with traceback — the
        visible-degradation contract."""
        fire = None
        with self._health_lock:
            if probing:
                self._probe_inflight = False
            self._consecutive_failures += 1
            self._retry_at = time.monotonic() + self.reprobe_s
            new_state = (EJECTED
                         if self._consecutive_failures >= self.EJECT_AFTER
                         else DEGRADED)
            if not self._degraded_logged:
                logger.warning(
                    "gateway ring %r device path failed (%s); state -> "
                    "%s, re-probe in %.1fs", self.ring_id,
                    type(exc).__name__ if exc is not None else "failure",
                    new_state, self.reprobe_s,
                    exc_info=exc if exc is not None else None)
                self._degraded_logged = True
            if new_state != self._state:
                self._state = new_state
                fire = new_state
            state = self._state
        if fire is not None:
            # Health transitions are exactly the events an incident
            # replay needs first — feed the flight recorder outside
            # the health lock (leaf discipline).
            from p2p_dhts_tpu.health import FLIGHT
            FLIGHT.record(
                "gateway", "ring_state", ring=self.ring_id, state=fire,
                error=type(exc).__name__ if exc is not None else None)
            if self._on_state_change is not None:
                self._on_state_change(self.ring_id, fire)
        return state

    def probe_release(self) -> None:
        """Release the probe slot WITHOUT a health verdict (e.g. the
        probe's deadline expired before the engine answered — neither
        evidence of recovery nor of failure)."""
        with self._health_lock:
            self._probe_inflight = False


class RingRouter:
    """Registry of named RingBackends with hot add/remove."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rings: Dict[str, RingBackend] = {}
        self._default: Optional[str] = None
        # Topology epoch (chordax-mesh): bumped on every ownership-
        # moving registry change — the cheap "did anything move?"
        # cursor route/mesh observers poll instead of diffing ranges.
        self._epoch = 0
        # Topology listeners (chordax-fastlane): fired AFTER any change
        # that can move a key's owner — add/remove/set_key_range — so
        # the gateway's hot-key cache can epoch-invalidate (a cached
        # answer must never survive a membership change). Fired
        # OUTSIDE the router lock; callbacks must be cheap and never
        # call back into the router.
        self._topology_listeners: List[Callable[[str], None]] = []

    def add_topology_listener(self, cb: Callable[[str], None]) -> None:
        """Register cb(change_kind) to fire after every ownership-
        moving registry change ("add_ring" / "remove_ring" /
        "set_key_range")."""
        with self._lock:
            self._topology_listeners.append(cb)

    def remove_topology_listener(self, cb: Callable[[str], None]) -> None:
        """Unregister a listener (idempotent). A Gateway closing on a
        SHARED router must detach its cache listener here, or every
        closed gateway's cache stays pinned and fires forever."""
        with self._lock:
            try:
                self._topology_listeners.remove(cb)
            except ValueError:
                pass

    def _fire_topology(self, change: str) -> None:
        with self._lock:
            self._epoch += 1
            listeners = list(self._topology_listeners)
        for cb in listeners:
            cb(change)

    @property
    def epoch(self) -> int:
        """Monotonic count of ownership-moving registry changes."""
        with self._lock:
            return self._epoch

    # -- registry ------------------------------------------------------------
    def add_ring(self, backend: RingBackend, default: bool = False) -> None:
        with self._lock:
            if backend.ring_id in self._rings:
                raise ValueError(
                    f"ring {backend.ring_id!r} is already registered")
            self._rings[backend.ring_id] = backend
            if default or self._default is None:
                self._default = backend.ring_id
        self._fire_topology("add_ring")

    def remove_ring(self, ring_id: str) -> RingBackend:
        """Unregister and RETURN the backend; the caller closes its
        engine outside this router's lock (draining blocks)."""
        with self._lock:
            backend = self._rings.pop(ring_id, None)
            if backend is None:
                raise UnknownRingError(f"no ring {ring_id!r}")
            if self._default == ring_id:
                self._default = next(iter(self._rings), None)
        self._fire_topology("remove_ring")
        return backend

    def get(self, ring_id: str) -> RingBackend:
        with self._lock:
            backend = self._rings.get(ring_id)
        if backend is None:
            raise UnknownRingError(f"no ring {ring_id!r}")
        return backend

    def set_key_range(self, ring_id: str,
                      key_range: Optional[Tuple[int, int]]) -> None:
        """Atomically update one ring's key-range ownership entry
        while traffic flows (elastic re-partitioning: a membership
        change that re-splits the keyspace across rings lands as one
        reference swap — a concurrent route() sees either the old
        complete range or the new one, never a torn pair)."""
        with self._lock:
            backend = self._rings.get(ring_id)
            if backend is None:
                raise UnknownRingError(f"no ring {ring_id!r}")
            backend.key_range = (
                (int(key_range[0]) % KEYS_IN_RING,
                 int(key_range[1]) % KEYS_IN_RING)
                if key_range is not None else None)
        self._fire_topology("set_key_range")

    def set_key_ranges(
            self,
            changes: Dict[str, Optional[Tuple[int, int]]]) -> None:
        """Atomically update SEVERAL rings' ownership entries in one
        lock acquisition + ONE topology epoch bump (chordax-elastic:
        a split hands the top half to the child in the same instant
        the parent's range shrinks — no window where both own the
        half, or neither does). All ids are validated before any entry
        mutates, so a bad id leaves the registry untouched."""
        if not changes:
            return
        with self._lock:
            backends = {}
            for ring_id in changes:
                backend = self._rings.get(ring_id)
                if backend is None:
                    raise UnknownRingError(f"no ring {ring_id!r}")
                backends[ring_id] = backend
            for ring_id, key_range in changes.items():
                backends[ring_id].key_range = (
                    (int(key_range[0]) % KEYS_IN_RING,
                     int(key_range[1]) % KEYS_IN_RING)
                    if key_range is not None else None)
        self._fire_topology("set_key_range")

    def route(self, key_int: Optional[int] = None,
              ring_id: Optional[str] = None) -> RingBackend:
        """Resolve one request to a backend: explicit ring_id wins;
        else the first registered ring whose key_range owns the key;
        else the default ring."""
        with self._lock:
            if ring_id is not None:
                backend = self._rings.get(ring_id)
                if backend is None:
                    raise UnknownRingError(f"no ring {ring_id!r}")
                return backend
            if key_int is not None:
                for backend in self._rings.values():
                    if backend.owns_key(int(key_int)):
                        return backend
            if self._default is not None:
                return self._rings[self._default]
        raise UnknownRingError("no ring routes this request (empty "
                               "router, or no key-range owner and no "
                               "default ring)")

    def snapshot(self) -> Tuple[List[RingBackend],
                                Optional[RingBackend]]:
        """(registered backends in insertion order, default backend) in
        ONE lock acquisition — the batch-routing prologue classifies a
        whole key vector against this instead of taking the router lock
        once per key."""
        with self._lock:
            backends = list(self._rings.values())
            default = (self._rings.get(self._default)
                       if self._default is not None else None)
        return backends, default

    # -- introspection -------------------------------------------------------
    def ring_ids(self) -> List[str]:
        with self._lock:
            return list(self._rings)

    @property
    def default_ring_id(self) -> Optional[str]:
        with self._lock:
            return self._default

    def health_snapshot(self) -> Dict[str, dict]:
        with self._lock:
            backends = list(self._rings.values())
        return {
            b.ring_id: {
                "state": b.state,
                "consecutive_failures": b.consecutive_failures,
                "key_range": b.key_range,
            }
            for b in backends
        }

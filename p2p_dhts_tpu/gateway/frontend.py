"""The gateway front door: RPC traffic -> router -> ServeEngine batches.

This is the serving milestone ROADMAP queued after PR 2: the batched
ServeEngine existed, but the actual network entry point still spoke the
per-table bridge. The Gateway closes that gap — every inbound
FIND_SUCCESSOR / GET / PUT / FINGER_INDEX RPC resolves through a
registered ring's ServeEngine, so concurrent wire requests coalesce
into device batches exactly like direct engine callers (one TCP request
may also carry a VECTOR of keys; the reference's one-key-per-request
shape stays supported — batching is additive, never required).

Request path, in order:

  1. deadline   — client timeout -> DEADLINE_MS on the wire -> a
                  Deadline here -> the engine slot (expired work is
                  dropped before device dispatch, counted per ring).
  2. route      — explicit RING, else key-range ownership, else the
                  default ring (gateway/router.py).
  3. health     — healthy rings go to their engine; degraded rings
                  serve the FALLBACK path (direct kernel dispatch for
                  find_successor, the host closed form for
                  finger_index — the legacy-bridge analog, exactly
                  like overlay/finger_table.py's visible degradation);
                  ejected rings fail fast so they cannot convoy the
                  healthy rings. One prober at a time retries the
                  engine each reprobe interval.
  4. admission  — a bounded per-ring in-flight budget DISTINCT from
                  the engine queue: a slow ring rejects (RingBusyError)
                  instead of queueing the other rings' worker threads
                  behind it.
  5. engine     — ServeEngine.submit/submit_many; identical answers to
                  a direct engine caller (parity is tested over 1000
                  keys), zero steady-state retraces included.

Mutating ops (PUT) and store reads (GET) never fall back: a degraded
ring must not fork its device store by applying writes through a side
path, so they fail visibly instead (the reference's RPC error
envelope).

LOCK ORDER: the Gateway adds no locks of its own beyond `_rings_lock`
(admission-table bookkeeping, leaf) — routing, health, admission each
synchronize internally and nothing is held across an engine call or a
slot wait. Audited with the rest of the gateway in chordax-lint pass 3.

jax is imported ONLY inside the degraded-fallback dispatch; building a
Gateway (and installing its handlers on every overlay peer's server)
never touches a backend — the import-hygiene rule of __graft_entry__.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from p2p_dhts_tpu.gateway.admission import (Deadline, NO_DEADLINE,
                                            RingAdmission, RingBusyError,
                                            SingleFlight)
from p2p_dhts_tpu.gateway.cache import HotKeyCache
from p2p_dhts_tpu.gateway.metrics_ext import GatewayMetrics
from p2p_dhts_tpu.gateway.router import (RingBackend, RingRouter,
                                         RingUnavailableError,
                                         UnknownRingError)
from p2p_dhts_tpu.health import FLIGHT
from p2p_dhts_tpu.keyspace import KEYS_IN_RING, LANES
from p2p_dhts_tpu.metrics import Metrics
from p2p_dhts_tpu.net import wire
from p2p_dhts_tpu import trace as trace_mod
from p2p_dhts_tpu.serve import (DeadlineExpiredError, ServeEngine,
                                gather_vector)

#: Ops that may serve through the fallback path while a ring is
#: degraded. Lookups are idempotent and have a semantics-identical
#: direct form; store mutations/reads do not (no silent store forks).
_FALLBACK_KINDS = frozenset({"find_successor", "finger_index"})

#: The reserved backend id for the stateless finger front (the shared
#: process-global finger engine, serve.global_finger_engine).
FINGER_RING_ID = "__finger__"

#: Wire commands install_gateway_handlers registers. SYNC_RANGE and
#: REPAIR_STATUS are the chordax-repair control verbs (ISSUE 6);
#: JOIN_RING / HEARTBEAT / MEMBER_STATUS are the chordax-membership
#: control verbs (ISSUE 7): admission-bounded join intake, the failure
#: detector's liveness signal, and the per-ring membership snapshot.
#: METRICS / TRACE_STATUS / HEALTH are the chordax-scope introspection
#: verbs (ISSUE 8): the whole metrics registry, the tracing plane's
#: status/spans, and the unified loop-health snapshot — all queryable
#: over the wire on every gateway server. PULSE is the chordax-pulse
#: continuous-telemetry verb (ISSUE 11): series tails, SLO verdicts +
#: burn rates, and Prometheus-style exposition of the live registry.
#: CAPACITY is the chordax-lens verb (ISSUE 14): every ring's derived
#: busy-fraction / capacity / headroom row plus (COSTS) the engines'
#: per-(kind, bucket) cost tables and compile-cause ledgers — the
#: subscription surface the elastic policy loop consumes.
#: MESH_ROUTES is the chordax-mesh gossip/observability verb
#: (ISSUE 15): the attached MeshPlane's epoch-stamped shard -> address
#: table (any mesh gateway answers it; peers pull it when a heartbeat
#: reply's ROUTES_EPOCH moves). HAVOC is the mesh chaos-control verb:
#: install/uninstall a seeded FaultPlan in THIS process over the wire,
#: so a multi-process scenario (partition one whole gateway) is seeded
#: into every process replayably — a test/bench control surface, same
#: trust domain as the metrics/trace verbs. TRACE_PULL is the
#: chordax-tower collection verb (ISSUE 20): the bounded, since-cursor
#: incremental span pull the fleet collector advances through — each
#: reply carries the resume cursor, the eviction gap, and the serving
#: process's wall clock (the collector's clock-offset sample).
GATEWAY_COMMANDS = ("FIND_SUCCESSOR", "GET", "PUT", "FINGER_INDEX",
                    "SYNC_RANGE", "REPAIR_STATUS", "JOIN_RING",
                    "HEARTBEAT", "MEMBER_STATUS", "METRICS",
                    "TRACE_STATUS", "TRACE_PULL", "HEALTH", "PULSE",
                    "CAPACITY", "MESH_ROUTES", "HAVOC")


def _key_int(v) -> int:
    """Wire key form: hex string (the overlay's Key serialization) or
    plain int."""
    return (int(v, 16) if isinstance(v, str) else int(v)) % KEYS_IN_RING


def _lift_key_lanes(keys) -> np.ndarray:
    """Legacy list-form KEYS under a mesh: lift to a lane array ONCE —
    the split/forward machinery is array-native, and the JSON encoder
    lowers the arrays back on the way out. One home for the rule so
    the FIND_SUCCESSOR and GET handlers cannot drift."""
    from p2p_dhts_tpu import keyspace
    return keyspace.ints_to_lanes([_key_int(k) for k in keys])


class _VectorRun:
    """Array-native payload for the serving core (chordax-fastlane,
    ISSUE 12): a full-length [N, LANES] uint32 key array (plus the
    kind's start array) standing where a per-request payload list
    would — len() is the admission/metrics/deadline unit, and
    _engine_serve routes it through ServeEngine.submit_vector instead
    of per-key slots. The zero-copy decode of a binary KEYS section
    flows through one of these untouched from wire to device."""

    __slots__ = ("keys", "starts")

    def __init__(self, keys: np.ndarray,
                 starts: Optional[np.ndarray] = None):
        self.keys = keys
        self.starts = starts

    def __len__(self) -> int:
        return self.keys.shape[0]


class Gateway:
    """Multi-ring serving front door over ServeEngine backends."""

    #: Slot-wait bound when the caller set no deadline: the gateway
    #: must never park an RPC worker thread forever on a wedged engine.
    DEFAULT_WAIT_S = 60.0

    def __init__(self, router: Optional[RingRouter] = None,
                 metrics: Optional[Metrics] = None,
                 single_flight_capacity: int = 4096,
                 cache_capacity: int = 4096,
                 name: str = "gateway"):
        self.name = name
        self.router = router if router is not None else RingRouter()
        self.metrics = GatewayMetrics(metrics)
        self._rings_lock = threading.Lock()
        self._admission: Dict[str, RingAdmission] = {}
        self._single_flight = SingleFlight(single_flight_capacity)
        # chordax-fastlane (ISSUE 12): bounded read-side hot-key result
        # cache BEHIND single-flight (a storm populates one entry),
        # epoch-invalidated wholesale by every PUT-side write and
        # every ownership-moving change — churn_apply, stabilize,
        # maintenance, set_key_range, ring add/remove — so a cached
        # answer never survives a write or a membership change.
        # cache_capacity=0 disables it (every read goes to the engine).
        self._cache: Optional[HotKeyCache] = (
            HotKeyCache(cache_capacity, metrics=self.metrics.base)
            if cache_capacity else None)
        self._topology_cb = None
        if self._cache is not None:
            cache = self._cache
            self._topology_cb = lambda change: cache.invalidate(change)
            self.router.add_topology_listener(self._topology_cb)
        self._finger_backend: Optional[RingBackend] = None
        # DHash replication params rings default to; DHashPeer wiring
        # sets these so device rings added afterwards match the
        # process's overlay replication config.
        self._default_ida = (14, 10, 257)
        # chordax-repair wiring (ISSUE 6): PUT fan-out policy/writer and
        # any attached anti-entropy schedulers (REPAIR_STATUS's view).
        # All repair imports are lazy — the repair package imports this
        # module, and a plain gateway must not pay for the subsystem.
        self._repl_policy = None
        self._repl_writer = None
        self._repair_scheds: List[Any] = []
        # chordax-membership wiring (ISSUE 7): per-ring managers (the
        # JOIN_RING / HEARTBEAT / MEMBER_STATUS verbs' dispatch table)
        # and the optional auto-enrolling repair scheduler that router
        # hot add/remove keeps in sync with the registered store rings.
        self._memberships: Dict[str, Any] = {}
        self._auto_repair: Optional[Any] = None
        # chordax-pulse wiring (ISSUE 11): the attached PulseSampler
        # the PULSE verb serves (lifecycle stays with whoever built
        # it; the gateway only holds the read-side reference).
        self._pulse: Optional[Any] = None
        # chordax-lens wiring (ISSUE 14): the attached LensLoop the
        # CAPACITY verb serves (same read-side-reference rule).
        self._lens: Optional[Any] = None
        # chordax-mesh wiring (ISSUE 15): the attached MeshPlane — the
        # ownership lookup -> local-or-forward split every no-explicit-
        # ring FIND_SUCCESSOR/GET/PUT consults. Lifecycle stays with
        # whoever built it (the detach-never-close rule).
        self._mesh: Optional[Any] = None
        # chordax-tower wiring (ISSUE 20): the attached elastic
        # DecisionLedger the HEALTH verb's LEDGER_SINCE cursor serves
        # (read-side reference only).
        self._ledger: Optional[Any] = None

    # -- ring lifecycle ------------------------------------------------------
    def set_default_ida(self, n: int, m: int, p: int) -> None:
        self._default_ida = (int(n), int(m), int(p))

    # -- hot-key read cache (chordax-fastlane, ISSUE 12) ---------------------
    @property
    def cache(self) -> Optional[HotKeyCache]:
        return self._cache

    def _invalidate_reads(self, reason: str) -> None:
        """Epoch-bump the read cache after anything that can change a
        read's answer. Runs in a finally on every write path: a write
        that FAILED may still have partially applied (a quorum write
        with some acked replicas, a churn batch that rolled back after
        installing), so the bump must not depend on success."""
        if self._cache is not None:
            self._cache.invalidate(reason)

    # -- replication policy (chordax-repair) ---------------------------------
    def set_replication(self, policy) -> None:
        """Install (or, with None, remove) the PUT replication policy
        (repair.replication.ReplicationPolicy). While set, a PUT with
        no explicit ring_id fans to policy.n_replicas rings and returns
        at quorum w; an explicit ring_id always writes that one ring
        (the repair scheduler and the reference-shape wire form rely on
        that bypass)."""
        from p2p_dhts_tpu.repair.replication import ReplicatedWriter
        with self._rings_lock:
            old = self._repl_writer
            self._repl_policy = policy
            self._repl_writer = (
                ReplicatedWriter(self, policy, metrics=self.metrics.base)
                if policy is not None else None)
        if old is not None:
            old.close()

    @property
    def replication_policy(self):
        with self._rings_lock:
            return self._repl_policy

    def _writer(self):
        with self._rings_lock:
            return self._repl_writer

    def attach_repair(self, scheduler) -> None:
        """Register a RepairScheduler for REPAIR_STATUS visibility and
        for teardown with the gateway (close() closes it first)."""
        with self._rings_lock:
            self._repair_scheds.append(scheduler)

    def repair_status(self) -> dict:
        """The chordax-repair observability snapshot: the replication
        policy, every attached scheduler's status, and the repair.*
        counter family."""
        with self._rings_lock:
            policy = self._repl_policy
            scheds = list(self._repair_scheds)
        return {
            "replication": policy.as_dict() if policy is not None else None,
            "schedulers": [s.status() for s in scheds],
            "counters": self.metrics.base.counters_with_prefix("repair."),
        }

    # -- pulse telemetry plane (chordax-pulse, ISSUE 11) ---------------------
    def attach_pulse(self, sampler) -> None:
        """Register (or, with None, detach) the PulseSampler the PULSE
        verb serves. The sampler's lifecycle — start/close — belongs
        to its creator; the gateway never stops it."""
        with self._rings_lock:
            self._pulse = sampler

    def pulse_sampler(self):
        with self._rings_lock:
            return self._pulse

    # -- capacity / lens plane (chordax-lens, ISSUE 14) ----------------------
    def attach_lens(self, lens) -> None:
        """Register (or, with None, detach) the LensLoop the CAPACITY
        verb serves. Lifecycle stays with whoever built it — the
        gateway never starts or stops the loop."""
        with self._rings_lock:
            self._lens = lens

    def lens_model(self):
        with self._rings_lock:
            return self._lens

    # -- decision ledger (chordax-tower, ISSUE 20) ---------------------------
    def attach_ledger(self, ledger) -> None:
        """Register (or, with None, detach) the elastic DecisionLedger
        the HEALTH verb's LEDGER_SINCE cursor serves — the fleet
        collector's wire path to this process's policy decisions.
        Lifecycle stays with whoever built it (the detach-never-close
        rule)."""
        with self._rings_lock:
            self._ledger = ledger

    def decision_ledger(self):
        with self._rings_lock:
            return self._ledger

    # -- mesh plane (chordax-mesh, ISSUE 15) ---------------------------------
    def attach_mesh(self, mesh) -> None:
        """Register (or, with None, detach) the MeshPlane that shards
        this gateway into a multi-process topology. The plane's
        lifecycle — close() — belongs to its creator."""
        with self._rings_lock:
            self._mesh = mesh

    def mesh_plane(self):
        with self._rings_lock:
            return self._mesh

    def _mesh_for(self, ring_id, fwd: bool = False):
        """The mesh split applies to NO-EXPLICIT-RING requests on a
        routed mesh (an explicit RING always serves locally — the
        repair/membership control paths are per-process by design).
        Forwarded requests still consult the plane (the one-hop
        owner-side check), hence fwd."""
        with self._rings_lock:
            mesh = self._mesh
        if mesh is None or (ring_id is not None and not fwd):
            return None
        return mesh if (fwd or len(mesh.routes)) else None

    # -- membership control plane (chordax-membership, ISSUE 7) --------------
    def attach_membership(self, manager) -> None:
        """Register a MembershipManager as its ring's churn authority:
        the JOIN_RING / HEARTBEAT / MEMBER_STATUS verbs dispatch to it
        and close() tears it down with the gateway."""
        with self._rings_lock:
            self._memberships[manager.ring_id] = manager

    def membership_for(self, ring_id: str):
        with self._rings_lock:
            return self._memberships.get(ring_id)

    def _membership_required(self, ring_id: Optional[str]):
        with self._rings_lock:
            if ring_id is not None:
                mgr = self._memberships.get(str(ring_id))
            elif len(self._memberships) == 1:
                mgr = next(iter(self._memberships.values()))
            else:
                mgr = None
        if mgr is None:
            raise UnknownRingError(
                f"no membership manager for ring {ring_id!r} (elastic "
                f"rings need an attached MembershipManager)")
        return mgr

    def churn_apply_many(self, entries: Sequence[tuple], *, ring_id: str,
                         timeout: Optional[float] = None,
                         deadline: Optional[Deadline] = None
                         ) -> List[bool]:
        """Apply [(op_code, member_id)] membership rows against one
        named ring as one engine batch — FIFO-ordered with in-flight
        lookups/puts, epoch-rolled-back on failure, never replicated
        (membership is per-ring by definition)."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self.router.get(ring_id)
        payloads = [(int(op), _key_int(member)) for op, member in entries]
        try:
            return self._serve_many(backend, "churn_apply", payloads, dl)
        finally:
            self._invalidate_reads("churn_apply")

    def stabilize_ring(self, ring_id: str, *,
                       timeout: Optional[float] = None,
                       deadline: Optional[Deadline] = None) -> bool:
        """One whole-ring stabilize/rectify sweep through the named
        ring's engine; returns the post-sweep placement_converged
        verdict."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self.router.get(ring_id)
        try:
            return bool(self._serve_many(backend, "stabilize_sweep", [()],
                                         dl)[0])
        finally:
            self._invalidate_reads("stabilize_sweep")

    def dhash_maintain(self, ring_id: str, *,
                       timeout: Optional[float] = None,
                       deadline: Optional[Deadline] = None) -> int:
        """One local-maintenance pass on the named ring's store (purge
        dead-held rows, regenerate missing fragments from >= m
        survivors); returns the regenerated-row count. The purge makes
        holder-death visible to the content-level Merkle digests, so
        the cross-ring repair pairs can heal what regeneration
        couldn't."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self.router.get(ring_id)
        try:
            return int(self._serve_many(backend, "dhash_maintain", [()],
                                        dl)[0])
        finally:
            self._invalidate_reads("dhash_maintain")

    def nudge_repair(self, ring_id: str) -> int:
        """Wake the repair pairs covering `ring_id` (their loops drop
        converged/stalled and resume active pacing) — how an applied
        churn batch's transferred ranges enqueue targeted heals.
        Returns the number of pair loops nudged."""
        with self._rings_lock:
            scheds = list(self._repair_scheds)
        n = 0
        for sched in scheds:
            nudge = getattr(sched, "nudge", None)
            if nudge is not None:
                n += nudge(ring_id)
        return n

    def enable_auto_repair(self, **sched_kwargs):
        """Create (once) the DYNAMIC repair scheduler that router hot
        add/remove keeps enrolled: every store ring added after this
        call pairs with every other registered store ring, and
        remove_ring retires its pairs — no manual attach_repair per
        ring (the PR-6 open item). kwargs pass through to
        RepairScheduler. Returns the scheduler."""
        from p2p_dhts_tpu.repair.scheduler import RepairScheduler
        with self._rings_lock:
            if self._auto_repair is not None:
                return self._auto_repair
        sched = RepairScheduler(self, [], dynamic=True,
                                metrics=self.metrics.base,
                                **sched_kwargs)
        with self._rings_lock:
            if self._auto_repair is None:
                self._auto_repair = sched
                self._repair_scheds.append(sched)
            sched = self._auto_repair
        # Rings registered before enable_auto_repair enroll now.
        for backend in self.router.snapshot()[0]:
            self._auto_enroll(backend)
        return sched

    def _store_ring_ids(self) -> List[str]:
        return [b.ring_id for b in self.router.snapshot()[0]
                if getattr(b.engine, "has_store", False)]

    def _auto_enroll(self, backend: RingBackend) -> None:
        with self._rings_lock:
            sched = self._auto_repair
        if sched is None or not getattr(backend.engine, "has_store",
                                        False):
            return
        for other in self._store_ring_ids():
            if other != backend.ring_id:
                sched.add_pair((other, backend.ring_id))

    def _auto_retire(self, ring_id: str) -> None:
        with self._rings_lock:
            sched = self._auto_repair
        if sched is not None:
            sched.remove_ring(ring_id)

    def add_ring(self, ring_id: str, state=None, store=None, *,
                 key_range: Optional[Tuple[int, int]] = None,
                 default: bool = False,
                 engine: Optional[ServeEngine] = None,
                 max_inflight: int = 4096,
                 max_wait_s: Optional[float] = None,
                 reprobe_s: Optional[float] = None,
                 warmup: Optional[Sequence[str]] = None,
                 **engine_kw) -> RingBackend:
        """Register a ring (hot — safe while traffic flows). Builds a
        ServeEngine over (state, store) unless one is passed in;
        `warmup` pre-traces the named kinds so the ring's steady state
        never compiles."""
        built_here = engine is None
        if engine is None:
            n, m, p = self._default_ida
            engine = ServeEngine(state, store, n=n, m=m, p=p,
                                 name=f"gw-{ring_id}", **engine_kw)
            engine.start()
        if state is None:
            state = getattr(engine, "_state", None)
        backend = RingBackend(ring_id, engine, key_range=key_range,
                              reprobe_s=reprobe_s,
                              on_state_change=self.metrics.gauge_health,
                              state=state)
        with self._rings_lock:
            # Remember what was there so a FAILED add (duplicate id,
            # warmup error) restores it: clobber-then-pop would destroy
            # a LIVE ring's configured admission object and silently
            # replace it with a default-bound one on the next request.
            prev_adm = self._admission.get(backend.ring_id)
            self._admission[backend.ring_id] = RingAdmission(
                backend.ring_id, max_inflight=max_inflight,
                max_wait_s=max_wait_s)
        try:
            if warmup:
                engine.warmup(list(warmup))
            self.router.add_ring(backend, default=default)
        except BaseException:
            with self._rings_lock:
                if prev_adm is not None:
                    self._admission[backend.ring_id] = prev_adm
                else:
                    self._admission.pop(backend.ring_id, None)
            if built_here:
                # The engine was OURS and never got registered: a
                # failed add must not leak its dispatcher/completion
                # threads and device buffers.
                engine.close(drain=False)
            raise
        self.metrics.gauge_health(backend.ring_id, backend.state)
        # Hot add auto-enrolls the new store ring's repair pairs (the
        # PR-6 open item): no manual attach_repair per ring.
        self._auto_enroll(backend)
        return backend

    def remove_ring(self, ring_id: str, drain: bool = True,
                    close_engine: bool = True) -> RingBackend:
        """Unregister a ring; in-flight requests finish (the engine
        drains outside every gateway lock). Auto-enrolled repair pairs
        covering the ring retire first so no heal round lands on a
        closing engine."""
        self._auto_retire(ring_id)
        backend = self.router.remove_ring(ring_id)
        with self._rings_lock:
            self._admission.pop(ring_id, None)
            mgr = self._memberships.pop(ring_id, None)
        if mgr is not None:
            mgr.close()
        if close_engine:
            backend.engine.close(drain=drain)
        # Stale-telemetry hygiene (chordax-scope): a retired ring's
        # per-ring counters/gauges/hists leave the registry with it, so
        # dashboards and the METRICS verb never read a dead ring.
        self.metrics.retire_ring(ring_id)
        return backend

    def _admission_for(self, ring_id: str) -> RingAdmission:
        with self._rings_lock:
            adm = self._admission.get(ring_id)
            if adm is None:
                # A backend registered directly on the router (tests,
                # embedding) still gets bounded admission.
                adm = self._admission[ring_id] = RingAdmission(ring_id)
        return adm

    def finger_engine(self) -> ServeEngine:
        """The process-shared stateless finger engine (one dispatch
        loop batching finger lookups across every table AND the wire)."""
        return self._get_finger_backend().engine

    def finger_resolver(self, starting_key: int):
        """A FingerTable device resolver bound to the gateway's shared
        finger engine — the overlay's lookup path and the RPC path
        coalesce into the same batches."""
        from p2p_dhts_tpu.serve import EngineFingerResolver
        return EngineFingerResolver(int(starting_key),
                                    engine=self.finger_engine())

    def _get_finger_backend(self) -> RingBackend:
        with self._rings_lock:
            backend = self._finger_backend
        if backend is not None:
            return backend
        from p2p_dhts_tpu.serve import global_finger_engine
        engine = global_finger_engine()
        with self._rings_lock:
            if self._finger_backend is None:
                self._finger_backend = RingBackend(
                    FINGER_RING_ID, engine,
                    on_state_change=self.metrics.gauge_health)
                self._admission.setdefault(
                    FINGER_RING_ID, RingAdmission(FINGER_RING_ID))
            backend = self._finger_backend
        return backend

    # -- the serving core ----------------------------------------------------
    def _serve_many(self, backend: RingBackend, kind: str,
                    payloads: Sequence[tuple],
                    deadline: Deadline = NO_DEADLINE) -> List[Any]:
        """Health -> admission -> engine (or fallback) for one same-kind
        run routed to one ring. Returns per-request results in order.
        chordax-scope: while tracing, the whole pass records as a
        `gateway.<kind>` span (child of the RPC server span when the
        request came over the wire; the engine's request spans parent
        under it)."""
        if not trace_mod.enabled():
            return self._serve_many_inner(backend, kind, payloads,
                                          deadline)
        with trace_mod.span(f"gateway.{kind}", cat="gateway",
                            ring=backend.ring_id, n=len(payloads)):
            return self._serve_many_inner(backend, kind, payloads,
                                          deadline)

    def _serve_many_inner(self, backend: RingBackend, kind: str,
                          payloads: Sequence[tuple],
                          deadline: Deadline = NO_DEADLINE) -> List[Any]:
        rid = backend.ring_id
        n = len(payloads)
        # Admission weight: a payload list charges one slot per
        # request (each becomes an engine slot); a _VectorRun charges
        # one slot per ENGINE CHUNK — that is the queue pressure the
        # ring actually faces, and it is what lets a 1M-key vector
        # (123 chunks at bucket 8192) fit a 4096-slot budget instead
        # of being structurally rejected. Latency samples follow the
        # same unit (one per chunk, not one per key).
        if isinstance(payloads, _VectorRun):
            rows = getattr(backend.engine, "bucket_max", 8192)
            adm_n = max(1, -(-n // int(rows)))
        else:
            adm_n = n
        t0 = time.perf_counter()
        if deadline.expired():
            self.metrics.count_deadline_dropped(rid, n)
            raise DeadlineExpiredError(
                f"ring {rid!r}: deadline passed before admission")
        verdict = backend.admit_device_path()
        if verdict == "ejected":
            self.metrics.count_ejected_fastfail(rid, n)
            FLIGHT.record("gateway", "ejected_fastfail", ring=rid, n=n)
            raise RingUnavailableError(
                f"ring {rid!r} is ejected (re-probe pending)")
        probing = verdict == "probe"
        adm = self._admission_for(rid)
        try:
            if trace_mod.enabled():
                with trace_mod.span("gateway.admission", cat="gateway",
                                    ring=rid):
                    adm.acquire(adm_n, deadline)
            else:
                adm.acquire(adm_n, deadline)
        except RingBusyError:
            # (admission.py records the budget-full flight event at
            # the source, with occupancy attached.)
            if probing:
                backend.probe_release()
            self.metrics.count_rejected(rid, n)
            raise
        except DeadlineExpiredError:
            if probing:
                backend.probe_release()
            self.metrics.count_deadline_dropped(rid, n)
            raise
        self.metrics.gauge_inflight(rid, adm.inflight)
        # ONE health verdict per request: an engine failure followed by
        # a fallback failure is one failed lookup, not two steps toward
        # EJECT_AFTER.
        failure_counted = False
        try:
            self.metrics.count_requests(kind, rid, n)
            if verdict in ("engine", "probe"):
                try:
                    results = self._engine_serve(backend, kind, payloads,
                                                 deadline)
                except DeadlineExpiredError:
                    if probing:
                        backend.probe_release()
                    self.metrics.count_deadline_dropped(rid, n)
                    raise
                except (ValueError, TypeError):
                    # Caller-payload errors (submit_many validation):
                    # not evidence about the RING's health, and a probe
                    # that never reached the device proves nothing.
                    if probing:
                        backend.probe_release()
                    raise
                except BaseException as exc:  # noqa: BLE001 — verdict fans into health state
                    backend.record_failure(exc, probing=probing)
                    failure_counted = True
                    self.metrics.count_errors(kind, rid, n)
                    if kind not in _FALLBACK_KINDS:
                        raise RingUnavailableError(
                            f"ring {rid!r}: device path failed for "
                            f"{kind!r} ({type(exc).__name__}: {exc})"
                        ) from exc
                else:
                    backend.record_success(probing=probing)
                    self.metrics.observe_latency(
                        kind, rid,
                        [time.perf_counter() - t0] * adm_n)
                    return results
            # Fallback path: the ring is degraded (or the attempt above
            # just failed) and the op has a semantics-identical direct
            # form.
            if kind not in _FALLBACK_KINDS:
                raise RingUnavailableError(
                    f"ring {rid!r} is degraded and {kind!r} has no "
                    f"fallback path (store ops never fork the device "
                    f"store)")
            if deadline.expired():
                self.metrics.count_deadline_dropped(rid, n)
                raise DeadlineExpiredError(
                    f"ring {rid!r}: deadline passed before fallback "
                    f"dispatch")
            try:
                results = self._fallback_serve(backend, kind, payloads)
            except BaseException as exc:  # noqa: BLE001 — verdict fans into health state
                if not failure_counted:
                    backend.record_failure(exc)
                self.metrics.count_errors(kind, rid, n)
                raise RingUnavailableError(
                    f"ring {rid!r}: fallback path failed too "
                    f"({type(exc).__name__}: {exc})") from exc
            self.metrics.count_fallback(kind, rid, n)
            self.metrics.observe_latency(
                kind, rid, [time.perf_counter() - t0] * adm_n)
            return results
        finally:
            adm.release(adm_n)
            self.metrics.gauge_inflight(rid, adm.inflight)

    def _engine_serve(self, backend: RingBackend, kind: str,
                      payloads: Sequence[tuple],
                      deadline: Deadline) -> List[Any]:
        if isinstance(payloads, _VectorRun):
            # chordax-fastlane: the key array rides to the engine
            # whole — no per-key slots, no per-key waits; the result
            # is the concatenated chunk arrays.
            slots = backend.engine.submit_vector(
                kind, payloads.keys, payloads.starts,
                deadline=deadline.at)
        else:
            slots = backend.engine.submit_many(kind, list(payloads),
                                               deadline=deadline.at)
        wait_s = deadline.clamp(self.DEFAULT_WAIT_S)
        try:
            if isinstance(payloads, _VectorRun):
                return gather_vector(slots, wait_s)
            return [slot.wait(wait_s) for slot in slots]
        except TimeoutError:
            # A wait bounded by the CALLER's deadline says nothing
            # about the ring's health — one impatient client must not
            # degrade a healthy ring. Only a DEFAULT_WAIT_S timeout
            # (no caller deadline) is engine-wedged evidence.
            if deadline.at is not None and deadline.expired():
                raise DeadlineExpiredError(
                    f"caller deadline lapsed waiting on ring "
                    f"{backend.ring_id!r}") from None
            raise

    def _fallback_serve(self, backend: RingBackend, kind: str,
                        payloads: Sequence[tuple]) -> List[Any]:
        """The legacy-path twins: finger_index's host closed form
        (dependency-free, always available) and find_successor's direct
        kernel dispatch (the per-table-bridge shape — one jit call on
        the calling thread, no engine)."""
        if isinstance(payloads, _VectorRun):
            return self._fallback_serve_vector(backend, kind, payloads)
        if kind == "finger_index":
            out = []
            for key_int, start_int in payloads:
                dist = (int(key_int) - int(start_int)) % KEYS_IN_RING
                out.append(dist.bit_length() - 1 if dist else -1)
            return out
        # find_successor during an ownership-handoff window: the
        # backend's ring_state snapshot may predate the in-flight churn
        # batch, so serve from the membership manager's HOST MIRROR
        # closed form instead (counted, never wrong — the mirror is the
        # applied-batches fixpoint; the omniscient resolution costs 0
        # hops, like core.ring.owner_of).
        mgr = backend.membership
        if mgr is not None and backend.in_handoff:
            self.metrics.base.inc(
                f"membership.handoff_failover.{backend.ring_id}",
                len(payloads))
            return [(mgr.owner_row(int(p[0])), 0) for p in payloads]
        # find_successor, directly against the backend's RingState.
        if backend.ring_state is None:
            raise RingUnavailableError(
                f"ring {backend.ring_id!r} has no RingState for a "
                f"direct fallback dispatch")
        import numpy as np

        import jax.numpy as jnp

        from p2p_dhts_tpu import keyspace
        from p2p_dhts_tpu.core.ring import find_successor
        keys = jnp.asarray(
            keyspace.ints_to_lanes([int(p[0]) for p in payloads]))
        starts = jnp.asarray(
            np.asarray([int(p[1]) for p in payloads], np.int32))
        owner, hops = find_successor(backend.ring_state, keys, starts)
        owner, hops = np.asarray(owner), np.asarray(hops)
        return [(int(owner[j]), int(hops[j]))
                for j in range(len(payloads))]

    def _fallback_serve_vector(self, backend: RingBackend, kind: str,
                               run: _VectorRun):
        """Vector twin of _fallback_serve, returning the engine-shaped
        result ARRAYS. The direct find_successor dispatch stays fully
        vectorized (the kernel takes lanes); the handoff-mirror and
        finger closed forms convert once through lanes_to_ints — the
        DEGRADED path trades the zero-copy guarantee for availability,
        by design."""
        from p2p_dhts_tpu import keyspace
        if kind == "finger_index":
            key_ints = keyspace.lanes_to_ints(run.keys)
            start_ints = keyspace.lanes_to_ints(run.starts)
            out = np.empty(len(key_ints), np.int32)
            for j, (ki, si) in enumerate(zip(key_ints, start_ints)):
                dist = (ki - si) % KEYS_IN_RING
                out[j] = dist.bit_length() - 1 if dist else -1
            return out
        mgr = backend.membership
        if mgr is not None and backend.in_handoff:
            self.metrics.base.inc(
                f"membership.handoff_failover.{backend.ring_id}",
                len(run))
            owners = np.asarray(
                [mgr.owner_row(k)
                 for k in keyspace.lanes_to_ints(run.keys)], np.int64)
            return owners, np.zeros(len(run), np.int32)
        if backend.ring_state is None:
            raise RingUnavailableError(
                f"ring {backend.ring_id!r} has no RingState for a "
                f"direct fallback dispatch")
        import jax.numpy as jnp

        from p2p_dhts_tpu.core.ring import find_successor
        owner, hops = find_successor(
            backend.ring_state,
            jnp.asarray(np.ascontiguousarray(run.keys)),
            jnp.asarray(run.starts))
        return np.asarray(owner), np.asarray(hops)

    # -- public ops ----------------------------------------------------------
    def find_successor(self, key, start_row: int = 0, *,
                       ring_id: Optional[str] = None,
                       timeout: Optional[float] = None,
                       deadline: Optional[Deadline] = None
                       ) -> Tuple[int, int]:
        """(owner_row, hops) for one key — single-flighted: a storm of
        identical lookups on a hot key collapses to one engine
        submission."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        k = _key_int(key)
        backend = self.router.route(key_int=k, ring_id=ring_id)
        return self._find_successor_routed(backend, k, int(start_row), dl)

    def _find_successor_routed(self, backend: RingBackend, k: int,
                               start_row: int, dl: Deadline,
                               nocache: bool = False
                               ) -> Tuple[int, int]:
        # chordax-fastlane: cache first (a hot key's steady state is a
        # host dict hit), single-flight behind it (a cold storm still
        # collapses to ONE engine flight, whose leader fills the
        # entry), the engine last. HEALTHY rings only, both directions:
        # a degraded ring's requests must keep reaching the serving
        # core or its re-probe (and recovery) would starve behind
        # cache hits — and a fallback-path answer, computed off a
        # possibly-stale snapshot, must never be memoized. `nocache`
        # (the wire NOCACHE flag, chordax-tower ISSUE 20) bypasses
        # BOTH directions — a canary probe must measure the serving
        # path, not the cache, and must not fill it either.
        from p2p_dhts_tpu.gateway.router import HEALTHY
        cache = (self._cache if self._cache is not None
                 and not nocache and backend.state == HEALTHY
                 else None)
        ckey = ("fs", backend.ring_id, k, start_row)
        if cache is not None:
            hit, val = cache.get(ckey)
            if hit:
                return val

        def _flight() -> Tuple[int, int]:
            ep = cache.epoch if cache is not None else 0
            res = self._serve_many(
                backend, "find_successor", [(k, start_row)], dl)[0]
            # Re-check at fill time: the ring may have DEGRADED inside
            # this very flight (engine failure -> fallback answer) and
            # a fallback result must not be memoized.
            if cache is not None and backend.state == HEALTHY:
                cache.put(ep, ckey, res)
            return res

        sf_key = ("find_successor", backend.ring_id, k, start_row)
        try:
            return self._single_flight.run(
                sf_key, _flight, dl,
                on_hit=self.metrics.count_single_flight_hit)
        except (DeadlineExpiredError, RingBusyError):
            # A shared flight fails with the LEADER's budget/admission
            # luck. If THIS caller's own deadline still has room, its
            # lookup deserves its own attempt rather than inheriting a
            # stranger's failure.
            if dl.expired():
                raise
            return _flight()

    def find_successor_many(self, payloads: Sequence[tuple], *,
                            ring_id: Optional[str] = None,
                            timeout: Optional[float] = None,
                            deadline: Optional[Deadline] = None
                            ) -> List[Tuple[int, int, str]]:
        """Vector form: [(key, start_row)] -> [(owner, hops, ring_id)].
        Keys may span rings (routed individually); each ring's run is
        served as one engine batch. A failing ring fails only ITS
        lanes: they come back as (-1, -1, ring_id) — the engine's own
        failed-lookup convention — so one degraded ring cannot void a
        mixed batch."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        norm = [(_key_int(k), int(s)) for k, s in payloads]
        groups, backends = self._group_by_ring([k for k, _ in norm],
                                               ring_id)
        out: List[Optional[Tuple[int, int, str]]] = [None] * len(norm)
        for rid, idxs in groups.items():
            try:
                res = self._serve_many(
                    backends[rid], "find_successor",
                    [norm[i] for i in idxs], dl)
            except (RingUnavailableError, RingBusyError,
                    DeadlineExpiredError):
                for i in idxs:
                    out[i] = (-1, -1, rid)
                continue
            for i, (owner, hops) in zip(idxs, res):
                out[i] = (owner, hops, rid)
        return out  # type: ignore[return-value]

    def _finger_backend_for(self, ring_id: Optional[str]) -> RingBackend:
        """chordax-fuse (ISSUE 13): finger_index is stateless, so a
        caller naming a RING serves it through that ring's engine —
        landing finger lookups in the SAME fused queue as the ring's
        FIND_SUCCESSOR/GET traffic, where a mixed burst coalesces into
        one multi-kind program. Identical answers either way (one
        closed form, core.ring.finger_index_batch); callers opting in
        should warm "finger_index" on that ring. Default (no ring):
        the process-shared finger engine, unchanged."""
        if ring_id is not None:
            return self.router.get(ring_id)
        return self._get_finger_backend()

    def finger_index(self, key, table_start, *,
                     ring_id: Optional[str] = None,
                     timeout: Optional[float] = None,
                     deadline: Optional[Deadline] = None) -> int:
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self._finger_backend_for(ring_id)
        return self._serve_many(
            backend, "finger_index",
            [(_key_int(key), _key_int(table_start))], dl)[0]

    def finger_index_many(self, payloads: Sequence[tuple], *,
                          ring_id: Optional[str] = None,
                          timeout: Optional[float] = None,
                          deadline: Optional[Deadline] = None
                          ) -> List[int]:
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self._finger_backend_for(ring_id)
        return self._serve_many(
            backend, "finger_index",
            [(_key_int(k), _key_int(s)) for k, s in payloads], dl)

    def dhash_get(self, key, *, ring_id: Optional[str] = None,
                  timeout: Optional[float] = None,
                  deadline: Optional[Deadline] = None,
                  failover: Optional[bool] = None,
                  nocache: bool = False):
        """Read one block. REPLICA-AWARE by default when a replication
        policy is installed and no ring is named: the read tries the
        fastest healthy replica first (the routed primary among the
        healthy rings, then the rest in target order) and fails over
        to the next replica on a miss, a busy ring, or a ring-level
        failure — counted `repair.read_failover.<ring>` per replica
        moved past — instead of demanding an explicit ring_id. A
        truly-absent key therefore costs one read PER replica (a miss
        on one replica is not authoritative while replicas can lag —
        that is the semantics the failover exists for); negative-
        lookup-heavy callers who prefer the single probe pass
        failover=False or an explicit ring_id. failover=True demands
        a policy."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        k = _key_int(key)
        writer = self._writer()
        if failover and ring_id is not None:
            raise ValueError("failover=True and an explicit ring_id "
                             "are contradictory; drop one")
        use_fo = (failover if failover is not None
                  else (writer is not None and ring_id is None))
        # The wire NOCACHE flag (chordax-tower, ISSUE 20): canary
        # probes bypass the hot-key cache in BOTH directions — neither
        # served from it nor filling it.
        cache = None if nocache else self._cache
        if not use_fo:
            backend = self.router.route(key_int=k, ring_id=ring_id)
            # HEALTHY rings only (the _find_successor_routed rule): a
            # sick ring's reads keep reaching the probe machinery.
            from p2p_dhts_tpu.gateway.router import HEALTHY
            if cache is not None and backend.state != HEALTHY:
                cache = None
            ckey = ("get", backend.ring_id, k)
            if cache is not None:
                hit, val = cache.get(ckey)
                if hit:
                    return val
            ep = cache.epoch if cache is not None else 0
            res = self._serve_many(backend, "dhash_get", [(k,)], dl)[0]
            if cache is not None:
                cache.put(ep, ckey, res)
            return res
        if writer is None:
            raise ValueError("failover=True but no replication policy "
                             "is set (Gateway.set_replication)")
        # Replica-aware reads cache under their own key family ("any
        # healthy replica's answer"), distinct from explicit-ring
        # reads; misses cache too — the next PUT invalidates them.
        ckey = ("get*", k)
        if cache is not None:
            hit, val = cache.get(ckey)
            if hit:
                return val
        ep = cache.epoch if cache is not None else 0
        # Health-ordered replica set: healthy rings keep their
        # primary-first target order; degraded/ejected rings move to
        # the back (they would only cost a failed attempt first).
        from p2p_dhts_tpu.gateway.router import HEALTHY
        targets = sorted(writer.targets_for(k),
                         key=lambda b: 0 if b.state == HEALTHY else 1)
        miss = None
        last_exc: Optional[BaseException] = None
        for j, backend in enumerate(targets):
            if dl.expired():
                raise DeadlineExpiredError(
                    "replica-aware GET: deadline lapsed between "
                    "replicas")
            try:
                seg, ok = self._serve_many(backend, "dhash_get",
                                           [(k,)], dl)[0]
            except (RingUnavailableError, RingBusyError) as exc:
                last_exc = exc
                self.metrics.base.inc(
                    f"repair.read_failover.{backend.ring_id}")
                continue
            if ok:
                if cache is not None:
                    cache.put(ep, ckey, (seg, ok))
                return seg, ok
            miss = (seg, ok)
            if j < len(targets) - 1:
                self.metrics.base.inc(
                    f"repair.read_failover.{backend.ring_id}")
        if miss is not None:
            if cache is not None and last_exc is None:
                # A clean readable-nowhere verdict is cacheable; one
                # that only holds because a replica was down is not.
                cache.put(ep, ckey, miss)
            return miss  # readable nowhere: a plain miss, not an error
        assert last_exc is not None
        raise RingUnavailableError(
            f"replica-aware GET: every replica failed "
            f"({type(last_exc).__name__}: {last_exc})") from last_exc

    def dhash_put(self, key, segments, length: int, start_row: int = 0, *,
                  ring_id: Optional[str] = None,
                  timeout: Optional[float] = None,
                  deadline: Optional[Deadline] = None,
                  replicate: Optional[bool] = None) -> bool:
        """Store one block. With a replication policy installed and no
        explicit ring_id, the PUT fans to n_replicas rings and returns
        at quorum w (repair.replication); `replicate=False` forces the
        single-ring path, True demands the policy be set."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        k = _key_int(key)
        writer = self._writer()
        if replicate and ring_id is not None:
            # The documented contract is "an explicit ring_id always
            # writes that one ring" — honoring replicate=True here
            # would silently fan a targeted write elsewhere.
            raise ValueError("replicate=True and an explicit ring_id "
                             "are contradictory; drop one")
        use_repl = (replicate if replicate is not None
                    else (writer is not None and ring_id is None))
        try:
            if use_repl:
                if writer is None:
                    raise ValueError("replicate=True but no replication "
                                     "policy is set "
                                     "(Gateway.set_replication)")
                return writer.put(k, segments, int(length),
                                  int(start_row), dl)
            backend = self.router.route(key_int=k, ring_id=ring_id)
            return self._serve_many(
                backend, "dhash_put",
                [(k, segments, int(length), int(start_row))], dl)[0]
        finally:
            self._invalidate_reads("dhash_put")

    # -- batched store ops on ONE explicit ring (the repair heal path) -------
    def dhash_get_many(self, keys: Sequence, *, ring_id: str,
                       timeout: Optional[float] = None,
                       deadline: Optional[Deadline] = None) -> List[Any]:
        """[(segments, ok)] for a key list against one named ring, as
        one engine batch."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self.router.get(ring_id)
        return self._serve_many(
            backend, "dhash_get", [(_key_int(k),) for k in keys], dl)

    def dhash_put_many(self, entries: Sequence[tuple], *, ring_id: str,
                       timeout: Optional[float] = None,
                       deadline: Optional[Deadline] = None) -> List[bool]:
        """[(key, segments, length, start_row)] -> [ok] against one
        named ring, as one engine batch (never replicated — the heal
        path targets a specific under-replicated ring)."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self.router.get(ring_id)
        payloads = [(_key_int(k), seg, int(length), int(start))
                    for k, seg, length, start in entries]
        try:
            return self._serve_many(backend, "dhash_put", payloads, dl)
        finally:
            self._invalidate_reads("dhash_put_many")

    # -- repair control ops (chordax-repair, ISSUE 6) ------------------------
    def sync_digest(self, ring_id: str, *,
                    timeout: Optional[float] = None,
                    deadline: Optional[Deadline] = None):
        """The named ring's Merkle index (host arrays), engine-ordered
        after every put submitted before this call."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self.router.get(ring_id)
        return self._serve_many(backend, "sync_digest", [()], dl)[0]

    def repair_reindex(self, ring_id: str, *,
                       timeout: Optional[float] = None,
                       deadline: Optional[Deadline] = None) -> int:
        """Run the duplicate-index re-pair pass on the named ring's
        store; returns rewritten-row count."""
        dl = deadline if deadline is not None \
            else Deadline.from_timeout(timeout)
        backend = self.router.get(ring_id)
        try:
            return self._serve_many(backend, "repair_reindex", [()],
                                    dl)[0]
        finally:
            self._invalidate_reads("repair_reindex")

    # -- stats ---------------------------------------------------------------
    def stats(self) -> dict:
        ring_ids = self.router.ring_ids()
        out = self.metrics.snapshot(ring_ids)
        out["health"] = self.router.health_snapshot()
        out["default_ring"] = self.router.default_ring_id
        if self._cache is not None:
            out["cache"] = self._cache.stats()
        with self._rings_lock:
            managers = list(self._memberships.values())
        if managers:
            out["membership"] = {m.ring_id: m.status() for m in managers}
        return out

    # -- RPC handlers (net/rpc.py Server command surface) --------------------
    # chordax-wire note: vector fields arrive as hex-string lists over
    # the legacy JSON transport and as packed binary (wire.U128Keys /
    # numpy views) over the binary transport — _key_int and len() serve
    # both shapes, so ONE handler body answers both wires. Vector
    # RESULTS stay numpy: the binary transport ships them as raw
    # buffers and the JSON encoder (rpc._json_default) lowers them to
    # the exact nested lists the legacy envelope always carried.
    def handle_find_successor(self, req: dict) -> dict:
        dl = Deadline.from_budget_ms(req.get("DEADLINE_MS"))
        ring_id = req.get("RING")
        # chordax-mesh (ISSUE 15): with a routed MeshPlane attached,
        # every no-explicit-ring request takes the ownership lookup ->
        # local-or-forward split; FWD-marked requests take the OWNER
        # side (answer locally or bounce NOT_OWNED — the one-hop rule).
        fwd = bool(req.get("FWD"))
        mesh = self._mesh_for(ring_id, fwd)
        if "KEYS" in req:
            lanes = self._vector_lanes(req["KEYS"])
            if lanes is None and mesh is not None and req["KEYS"]:
                lanes = _lift_key_lanes(req["KEYS"])
            if lanes is not None:
                if mesh is not None:
                    out = mesh.find_successor_vector(req, lanes, dl,
                                                     fwd)
                    # chordax-edge (ISSUE 17): every mesh vector reply
                    # carries the serving process's route epoch — the
                    # heartbeat piggyback rule applied to the data
                    # path, so a route-caching client detects a stale
                    # table without waiting for a NOT_OWNED bounce.
                    out["ROUTES_EPOCH"] = mesh.routes.epoch
                    return out
                # chordax-fastlane: the binary transport's packed u128
                # run flows to the device as ONE lane-array view —
                # zero per-key python on this path (guarded by test).
                return self._handle_find_successor_fast(req, lanes,
                                                        ring_id, dl)
            keys = [_key_int(k) for k in req["KEYS"]]
            # No `or`-fallback: a numpy STARTS vector has no truth
            # value (the binary transport delivers one).
            starts = req.get("STARTS")
            if starts is None or len(starts) == 0:
                starts = [0] * len(keys)
            if len(starts) != len(keys):
                raise ValueError("STARTS length must match KEYS")
            res = self.find_successor_many(
                list(zip(keys, starts)), ring_id=ring_id, deadline=dl)
            return {"OWNERS": np.asarray([r[0] for r in res],
                                         dtype=np.int64),
                    "HOPS": np.asarray([r[1] for r in res],
                                       dtype=np.int32),
                    "RINGS": [r[2] for r in res]}
        key = _key_int(req["KEY"])
        if mesh is not None and not mesh.owns_local(key):
            if fwd:
                raise mesh.not_owner_error(key)
            owner, hops, label = mesh.find_successor_one(
                key, int(req.get("START", 0)), dl)
            return {"OWNER": owner, "HOPS": hops, "RING": label}
        backend = self.router.route(key_int=key, ring_id=ring_id)
        owner, hops = self._find_successor_routed(
            backend, key, int(req.get("START", 0)), dl,
            nocache=bool(req.get("NOCACHE")))
        return {"OWNER": owner, "HOPS": hops, "RING": backend.ring_id}

    def _handle_find_successor_fast(self, req: dict, lanes: np.ndarray,
                                    ring_id: Optional[str],
                                    dl: Deadline) -> dict:
        """The zero-copy vector FIND_SUCCESSOR lane: numpy end-to-end
        (lanes in, OWNERS/HOPS arrays out), vectorized routing, whole-
        array engine submission. Per-ring failure semantics match
        find_successor_many: a failing ring's lanes come back
        (-1, -1, ring) without voiding the rest."""
        n = lanes.shape[0]
        if n == 0:
            return {"OWNERS": np.zeros(0, np.int64),
                    "HOPS": np.zeros(0, np.int32), "RINGS": []}
        starts = req.get("STARTS")
        if starts is None or len(starts) == 0:
            starts_arr = None
        else:
            starts_arr = np.asarray(starts, dtype=np.int32)
            if starts_arr.shape != (n,):
                raise ValueError("STARTS length must match KEYS")
        owners = np.full(n, -1, np.int64)
        hops = np.full(n, -1, np.int32)
        rings = np.empty(n, dtype=object)
        for backend, idxs in self._group_by_ring_vec(lanes, ring_id):
            sub_keys = lanes if idxs is None else lanes[idxs]
            if starts_arr is None:
                sub_starts = np.zeros(sub_keys.shape[0], np.int32)
            else:
                sub_starts = (starts_arr if idxs is None
                              else starts_arr[idxs])
            run = _VectorRun(sub_keys, sub_starts)
            if idxs is None:
                rings[:] = backend.ring_id
            else:
                rings[idxs] = backend.ring_id
            try:
                o, h = self._serve_many(backend, "find_successor", run,
                                        dl)
            except (RingUnavailableError, RingBusyError,
                    DeadlineExpiredError):
                continue  # this ring's lanes stay (-1, -1, ring)
            if idxs is None:
                owners[:] = o
                hops[:] = h
            else:
                owners[idxs] = o
                hops[idxs] = h
        return {"OWNERS": owners, "HOPS": hops,
                "RINGS": rings.tolist()}

    @staticmethod
    def _vector_lanes(keys) -> Optional[np.ndarray]:
        """A KEYS field in LANE-NATIVE form -> [N, LANES] uint32 array
        for the zero-copy fast lane (wire.U128Keys: one frombuffer
        view; an already-lane-shaped ndarray: as-is). None for the
        legacy list forms (hex strings / ints), which keep the
        _key_int adapter path."""
        if isinstance(keys, wire.U128Keys):
            return keys.lanes()
        if isinstance(keys, np.ndarray) and keys.ndim == 2 \
                and keys.shape[1] == LANES:
            return (keys if keys.dtype == np.uint32
                    else keys.astype(np.uint32))
        return None

    def _group_by_ring_vec(self, lanes: np.ndarray,
                           ring_id: Optional[str]
                           ) -> List[Tuple[RingBackend,
                                           Optional[np.ndarray]]]:
        """Vectorized _group_by_ring: [(backend, row_index_array)]
        with None standing for ALL rows (the single-ring common case —
        no index materialization, no copy). Same semantics — explicit
        ring_id wins, else first-owner-wins in registration order
        against ONE router snapshot, else the default ring — with
        ownership resolved as whole-array range masks instead of a
        python test per key."""
        if ring_id is not None:
            return [(self.router.get(ring_id), None)]
        ring_list, default = self.router.snapshot()
        n = lanes.shape[0]
        ranged = [b for b in ring_list if b.key_range is not None]
        if not ranged:
            if default is None:
                raise UnknownRingError(
                    "no ring routes this request (empty router, or no "
                    "key-range owner and no default ring)")
            return [(default, None)]
        assigned = np.full(n, -1, np.int32)
        backends: List[RingBackend] = []
        for b in ranged:
            mask = b.owns_keys_mask(lanes) & (assigned < 0)
            if mask.any():
                backends.append(b)
                assigned[mask] = len(backends) - 1
        rest = assigned < 0
        if rest.any():
            if default is None:
                j = int(np.nonzero(rest)[0][0])
                from p2p_dhts_tpu.keyspace import lanes_to_int
                raise UnknownRingError(
                    f"no ring owns key {lanes_to_int(lanes[j]):#x} and "
                    f"no default ring is registered")
            try:
                di = next(i for i, b in enumerate(backends)
                          if b is default)
            except StopIteration:
                backends.append(default)
                di = len(backends) - 1
            assigned[rest] = di
        if len(backends) == 1:
            return [(backends[0], None)]
        return [(b, np.nonzero(assigned == i)[0])
                for i, b in enumerate(backends)]

    def _group_by_ring(self, key_ints: Sequence[int],
                       ring_id: Optional[str]
                       ) -> Tuple[Dict[str, List[int]],
                                  Dict[str, RingBackend]]:
        """Route EVERY key individually (an explicit ring_id still
        wins): a batched store op must never read/write a lane through
        the wrong ring's store just because it shared a request with a
        differently-owned key. Classification runs against ONE router
        snapshot — same first-owner-wins/default semantics as route(),
        without a router-lock acquisition per key."""
        if ring_id is not None:
            backend = self.router.get(ring_id)
            return ({backend.ring_id: list(range(len(key_ints)))},
                    {backend.ring_id: backend})
        ring_list, default = self.router.snapshot()
        groups: Dict[str, List[int]] = {}
        backends: Dict[str, RingBackend] = {}
        for idx, k in enumerate(key_ints):
            backend = next(
                (b for b in ring_list if b.owns_key(int(k))), default)
            if backend is None:
                raise UnknownRingError(
                    f"no ring owns key {int(k):#x} and no default "
                    f"ring is registered")
            backends.setdefault(backend.ring_id, backend)
            groups.setdefault(backend.ring_id, []).append(idx)
        return groups, backends

    def handle_get(self, req: dict) -> dict:
        dl = Deadline.from_budget_ms(req.get("DEADLINE_MS"))
        ring_id = req.get("RING")
        fwd = bool(req.get("FWD"))
        mesh = self._mesh_for(ring_id, fwd)
        if "KEYS" in req:
            lanes = self._vector_lanes(req["KEYS"])
            if lanes is None and mesh is not None and req["KEYS"]:
                lanes = _lift_key_lanes(req["KEYS"])
            if lanes is not None:
                if mesh is not None:
                    out = mesh.get_vector(lanes, dl, fwd)
                    # Route-epoch piggyback on the vector data path
                    # (chordax-edge, ISSUE 17 — see FIND_SUCCESSOR).
                    out["ROUTES_EPOCH"] = mesh.routes.epoch
                    return out
                return self._handle_get_fast(lanes, ring_id, dl)
            keys = [_key_int(k) for k in req["KEYS"]]
            if not keys:
                return {"SEGMENTS": [], "OK": [], "RINGS": []}
            groups, backends = self._group_by_ring(keys, ring_id)
            segs_out: List[list] = [[] for _ in keys]
            ok_out = [False] * len(keys)
            rings_out = [""] * len(keys)
            ring_errors: Dict[str, str] = {}
            for rid, idxs in groups.items():
                for i in idxs:
                    rings_out[i] = rid
                try:
                    res = self._serve_many(backends[rid], "dhash_get",
                                           [(keys[i],) for i in idxs],
                                           dl)
                except (RingUnavailableError, RingBusyError,
                        DeadlineExpiredError) as exc:
                    # One down ring fails only ITS lanes; RING_ERRORS
                    # distinguishes that from a plain missing key.
                    ring_errors[rid] = str(exc)
                    continue
                for i, (seg, ok) in zip(idxs, res):
                    # numpy stays numpy (chordax-wire): the binary
                    # transport ships the fragment matrix as one raw
                    # buffer; the JSON encoder lowers it to the legacy
                    # nested lists at serialization time.
                    segs_out[i] = seg
                    ok_out[i] = bool(ok)
            out = {"SEGMENTS": segs_out, "OK": ok_out,
                   "RINGS": rings_out}
            if ring_errors:
                out["RING_ERRORS"] = ring_errors
            return out
        key = _key_int(req["KEY"])
        if mesh is not None and not mesh.owns_local(key):
            if fwd:
                raise mesh.not_owner_error(key)
            segs, ok = mesh.get_one(key, dl)
            return {"SEGMENTS": segs, "OK": bool(ok)}
        segs, ok = self.dhash_get(req["KEY"], ring_id=ring_id,
                                  deadline=dl,
                                  nocache=bool(req.get("NOCACHE")))
        return {"SEGMENTS": segs, "OK": bool(ok)}

    def _handle_get_fast(self, lanes: np.ndarray,
                         ring_id: Optional[str], dl: Deadline) -> dict:
        """The zero-copy vector GET lane: SEGMENTS returns as ONE
        stacked [N, S, m] int32 array (the binary transport ships it
        as a single raw — and, negotiated, compressed — section; the
        JSON encoder lowers it to the same per-key nested lists the
        legacy envelope carried, so resp["SEGMENTS"][i] indexes
        identically on both wires). Same per-ring failure semantics as
        the legacy vector path: a down ring zeroes only ITS lanes and
        reports under RING_ERRORS. Heterogeneous multi-ring segment
        shapes (differing store max_segments) fall back to the per-key
        list form — correctness over layout there."""
        n = lanes.shape[0]
        if n == 0:
            return {"SEGMENTS": [], "OK": [], "RINGS": []}
        groups = self._group_by_ring_vec(lanes, ring_id)
        rings = np.empty(n, dtype=object)
        ring_errors: Dict[str, str] = {}
        results: List[Tuple[RingBackend, Optional[np.ndarray],
                            np.ndarray, np.ndarray]] = []
        for backend, idxs in groups:
            sub_keys = lanes if idxs is None else lanes[idxs]
            if idxs is None:
                rings[:] = backend.ring_id
            else:
                rings[idxs] = backend.ring_id
            try:
                segs, ok = self._serve_many(backend, "dhash_get",
                                            _VectorRun(sub_keys), dl)
            except (RingUnavailableError, RingBusyError,
                    DeadlineExpiredError) as exc:
                ring_errors[backend.ring_id] = str(exc)
                continue
            results.append((backend, idxs, segs, ok))
        out: dict
        shapes = {r[2].shape[1:] for r in results}
        ok_out = np.zeros(n, dtype=bool)
        if len(shapes) == 1 and not ring_errors:
            # The hot path: every lane answered with one segment
            # geometry — SEGMENTS ships as ONE stacked section.
            shape = results[0][2].shape[1:]
            segs_out = np.zeros((n,) + shape, np.int32)
            for _, idxs, segs, ok in results:
                if idxs is None:
                    segs_out[:] = segs
                    ok_out[:] = ok
                else:
                    segs_out[idxs] = segs
                    ok_out[idxs] = ok
            out = {"SEGMENTS": segs_out, "OK": ok_out,
                   "RINGS": rings.tolist()}
        else:
            # Partial failure or mixed per-ring segment geometry:
            # per-key list assembly, the LEGACY shape — a failed
            # ring's lanes stay [] exactly as the adapter path
            # returns them (a zero-filled matrix would read as a
            # plausible engine answer, not a down ring).
            segs_list: List[Any] = [[] for _ in range(n)]
            for _, idxs, segs, ok in results:
                rows = range(n) if idxs is None else idxs
                for local_j, i in enumerate(rows):
                    segs_list[int(i)] = segs[local_j]
                    ok_out[int(i)] = bool(ok[local_j])
            out = {"SEGMENTS": segs_list, "OK": ok_out,
                   "RINGS": rings.tolist()}
        if ring_errors:
            out["RING_ERRORS"] = ring_errors
        return out

    def handle_put(self, req: dict) -> dict:
        dl = Deadline.from_budget_ms(req.get("DEADLINE_MS"))
        ring_id = req.get("RING")
        fwd = bool(req.get("FWD"))
        mesh = self._mesh_for(ring_id, fwd)
        if "ENTRIES" in req:
            entries = req["ENTRIES"]
            if not entries:
                return {"OK": [], "RINGS": []}
            try:
                if mesh is not None:
                    out = mesh.put_entries(
                        entries, dl, fwd,
                        key_of=lambda e: _key_int(e["KEY"]))
                    if out is not None:
                        return out
                return self._handle_put_entries(entries, ring_id, dl)
            finally:
                # Vector PUT (both the replicated and the grouped
                # direct form) invalidates the read cache exactly like
                # the single-key paths.
                self._invalidate_reads("put_entries")
        segments = req["SEGMENTS"]
        if mesh is not None:
            key = _key_int(req["KEY"])
            # put_is_remote raises on a forwarded write we don't own
            # (the one-hop rule: writes get no silent re-resolution).
            addr = mesh.put_is_remote(key, fwd)
            if addr is not None:
                ok = mesh.forward_put_one(
                    addr, key, segments,
                    int(req.get("LENGTH", len(segments))),
                    int(req.get("START", 0)), dl)
                return {"OK": bool(ok),
                        "RING": f"mesh:{addr[0]}:{addr[1]}"}
        ok = self.dhash_put(req["KEY"], segments,
                            int(req.get("LENGTH", len(segments))),
                            int(req.get("START", 0)),
                            ring_id=ring_id, deadline=dl)
        return {"OK": bool(ok)}

    def _handle_put_entries(self, entries, ring_id,
                            dl: Deadline) -> dict:
        """The ENTRIES vector-PUT body of handle_put (replicated
        fan-out or per-key-routed direct writes), split out so the
        caller's finally owns the cache invalidation."""
        payloads = [(_key_int(e["KEY"]), e["SEGMENTS"],
                     int(e.get("LENGTH", len(e["SEGMENTS"]))),
                     int(e.get("START", 0))) for e in entries]
        writer = self._writer()
        if writer is not None and ring_id is None:
            # Replicated vector PUT. Entries are grouped by OWNING
            # ring first (same per-key routing as the non-replicated
            # path — a key-range owner must stay each entry's
            # primary replica) and each group fans to its owner +
            # the next registered rings; per-entry OK is the
            # w-quorum verdict at return time (stragglers finish
            # asynchronously).
            groups, _ = self._group_by_ring(
                [p[0] for p in payloads], None)
            ok_out = [False] * len(payloads)
            rings_out = [""] * len(payloads)
            target_union: List[str] = []
            group_reports = []
            for rid, idxs in groups.items():
                outcome = writer.put_many([payloads[i] for i in idxs],
                                          dl)
                for i, ok in zip(idxs, outcome.per_entry_ok):
                    ok_out[i] = bool(ok)
                    rings_out[i] = outcome.targets[0]
                for t in outcome.targets:
                    if t not in target_union:
                        target_union.append(t)
                group_reports.append({
                    "PRIMARY": outcome.targets[0],
                    "TARGETS": outcome.targets,
                    "ACKED": outcome.acked_rings,
                    "FAILED": outcome.failed_rings,
                    "ENTRIES": len(idxs)})
            return {"OK": ok_out, "RINGS": rings_out,
                    "REPLICATION": {
                        "TARGETS": target_union,
                        "GROUPS": group_reports,
                        "W": writer.policy.w}}
        groups, backends = self._group_by_ring(
            [p[0] for p in payloads], ring_id)
        ok_out = [False] * len(payloads)
        rings_out = [""] * len(payloads)
        ring_errors: Dict[str, str] = {}
        for rid, idxs in groups.items():
            for i in idxs:
                rings_out[i] = rid
            try:
                res = self._serve_many(backends[rid], "dhash_put",
                                       [payloads[i] for i in idxs],
                                       dl)
            except (RingUnavailableError, RingBusyError,
                    DeadlineExpiredError) as exc:
                ring_errors[rid] = str(exc)
                continue
            for i, ok in zip(idxs, res):
                ok_out[i] = bool(ok)
        out = {"OK": ok_out, "RINGS": rings_out}
        if ring_errors:
            out["RING_ERRORS"] = ring_errors
        return out

    def handle_sync_range(self, req: dict) -> dict:
        """One on-demand anti-entropy round between two named rings —
        the wire form of the repair scheduler's round (the reference's
        whole XCHNG_NODE recursion behind a single verb)."""
        dl = Deadline.from_budget_ms(req.get("DEADLINE_MS"))
        from p2p_dhts_tpu.repair.scheduler import run_sync_round
        res = run_sync_round(
            self, req["RING_A"], req["RING_B"],
            max_keys=int(req.get("MAX_KEYS", 256)),
            reindex=bool(req.get("REINDEX", True)),
            deadline=dl, metrics=self.metrics.base)
        return {
            "CONVERGED": bool(res.converged),
            "LEAF_DIFFS": int(res.leaf_diffs),
            "NODES_EXCHANGED": int(res.nodes_exchanged),
            "CANDIDATES": int(res.candidates),
            "HEALED": {k: int(v) for k, v in res.healed.items()},
            "CANONICALIZED": int(res.canonicalized),
            "REINDEXED": {k: int(v) for k, v in res.reindexed.items()},
            "UNHEALABLE": int(res.unhealable),
            "DEFERRED": int(res.deferred),
        }

    def handle_repair_status(self, req: dict) -> dict:
        return {"STATUS": self.repair_status()}

    # -- membership verbs (chordax-membership, ISSUE 7) ----------------------
    def handle_join_ring(self, req: dict) -> dict:
        """Admission-bounded join intake. MEMBER is the joining peer's
        128-bit id (hex or int); alternatively IP + PORT derive the
        reference's SHA1("ip:port") id (abstract_chord_peer.cpp:13-28).
        ACCEPTED=false is the visible admission refusal, not an RPC
        error — the joiner backs off and retries."""
        mgr = self._membership_required(req.get("RING"))
        if "MEMBER" in req:
            member = _key_int(req["MEMBER"])
        elif "IP" in req and "PORT" in req:
            from p2p_dhts_tpu.keyspace import peer_id
            member = peer_id(str(req["IP"]), int(req["PORT"]))
        else:
            raise ValueError("JOIN_RING needs MEMBER or IP+PORT")
        accepted = mgr.request_join(member)
        # chordax-mesh: a joiner that announced IP+PORT is a mesh PEER
        # — its address feeds the coordinator's shard book, so an
        # applied join re-splits the route table without any side
        # channel.
        mesh = self.mesh_plane()
        if accepted and mesh is not None and "IP" in req \
                and "PORT" in req:
            mesh.note_peer(member, str(req["IP"]), int(req["PORT"]))
        return {"ACCEPTED": bool(accepted), "RING": mgr.ring_id,
                "MEMBER": format(member, "x"),
                "HEARTBEAT_S": mgr.heartbeat_interval_s}

    def handle_heartbeat(self, req: dict) -> dict:
        """The failure detector's liveness signal: a member the
        manager knows refreshes its phi clock; KNOWN=false tells a
        restarted peer to JOIN_RING again."""
        mgr = self._membership_required(req.get("RING"))
        known = mgr.heartbeat(_key_int(req["MEMBER"]))
        out = {"KNOWN": bool(known), "RING": mgr.ring_id}
        # chordax-mesh: the heartbeat reply piggybacks the route
        # epoch — a peer whose table is older pulls MESH_ROUTES next,
        # so gossip costs one extra int until something changes.
        mesh = self.mesh_plane()
        if mesh is not None:
            out["ROUTES_EPOCH"] = mesh.routes.epoch
        return out

    def handle_member_status(self, req: dict) -> dict:
        """Membership observability: one ring's status, or every
        attached manager's when RING is omitted."""
        ring_id = req.get("RING")
        if ring_id is not None:
            return {"STATUS": self._membership_required(ring_id).status()}
        with self._rings_lock:
            managers = list(self._memberships.values())
        return {"STATUS": {m.ring_id: m.status() for m in managers}}

    # -- introspection verbs (chordax-scope, ISSUE 8) ------------------------
    def handle_metrics(self, req: dict) -> dict:
        """The metrics registry over the wire: the full snapshot, or —
        with PREFIX — the bounded counter family under one dotted
        prefix (the cheap periodic-poll form)."""
        base = self.metrics.base
        # chordax-tower (ISSUE 20): the operator flip for exemplar
        # capture — the bench's overhead gate toggles a whole live
        # fleet over the wire without a restart.
        flip = req.get("SET_EXEMPLARS")
        if flip is not None:
            base.set_exemplars(bool(flip))
        prefix = req.get("PREFIX")
        if prefix is not None:
            return {"COUNTERS": base.counters_with_prefix(str(prefix))}
        out = {"METRICS": base.snapshot()}
        # chordax-tower (ISSUE 20): the exemplar rings — (value,
        # trace_id, t) outlier pointers per histogram — ride along
        # only when asked for (a periodic METRICS poll stays cheap).
        if req.get("EXEMPLARS"):
            out["EXEMPLARS"] = base.exemplars()
        return out

    def handle_trace_status(self, req: dict) -> dict:
        """The tracing plane's status (enabled flag, span-store
        occupancy/evictions, distinct traces); with TRACE_ID, that
        trace's retained spans; with EXPORT, the Chrome trace-event
        JSON document (parsed, so the reply stays one JSON value)."""
        import json as _json
        out: dict = {"STATUS": trace_mod.status()}
        tid = req.get("TRACE_ID")
        if tid is not None:
            spans = []
            for s in trace_mod.store().spans(str(tid)):
                row = dict(s)
                row["args"] = dict(s["args"]) if s.get("args") else {}
                row["links"] = list(s.get("links") or ())
                spans.append(row)
            out["SPANS"] = spans
        if req.get("EXPORT"):
            out["CHROME"] = _json.loads(trace_mod.store().export_chrome())
        return out

    def handle_trace_pull(self, req: dict) -> dict:
        """The chordax-tower collection verb (ISSUE 20): a bounded,
        duplicate-free incremental span pull. SINCE is the span-store
        sequence cursor (0 or absent starts from the oldest retained
        span); LIMIT bounds the reply (default 2048, capped 8192).
        The reply carries SPANS (oldest first, each with its `seq` and
        completion `wall` stamp), NEXT (the resume cursor), GAP (spans
        the ring evicted before the cursor read them — eviction-
        visible, never a silent skip), EVICTED (store-lifetime
        eviction count), and WALL (this process's wall clock at reply
        build — the RTT-midpoint sample the collector's per-peer
        clock-offset estimate averages over)."""
        limit = req.get("LIMIT")
        limit = 2048 if limit is None else int(limit)
        limit = max(1, min(limit, 8192))
        st = trace_mod.store()
        spans, nxt, gap = st.spans_since(
            int(req.get("SINCE", 0) or 0), limit)
        rows = []
        for s in spans:
            row = dict(s)
            row["args"] = dict(s["args"]) if s.get("args") else {}
            row["links"] = list(s.get("links") or ())
            rows.append(row)
        return {"SPANS": rows, "NEXT": nxt, "GAP": gap,
                "EVICTED": st.evicted, "WALL": time.time()}

    def handle_health(self, req: dict) -> dict:
        """The unified health plane in one verb: every registered
        background loop's run/backoff/stall snapshot (HealthRegistry),
        this gateway's per-ring health machine states, the flight
        recorder's occupancy (TAIL > 0 inlines that many events), and
        — chordax-pulse (ISSUE 11), closing the PR-10 open thread —
        the NET section: per-destination wire-breaker state, per-
        server connection flow-control occupancy, BUSY shed counters,
        and the engine quarantine count, all pollable by the
        watcher."""
        from p2p_dhts_tpu.health import (FLIGHT as _FLIGHT, HEALTH,
                                         net_snapshot)
        out = {
            "LOOPS": HEALTH.snapshot(),
            "RINGS": self.router.health_snapshot(),
            "FLIGHT": {"events": len(_FLIGHT),
                       "recorded": _FLIGHT.recorded},
            "NET": net_snapshot(),
        }
        # chordax-mesh (ISSUE 15): per-ring engine telemetry rows —
        # trace counts + steady-state retraces pollable over the wire,
        # so a mesh watcher can assert "zero retraces in EVERY
        # process" without a local engine handle.
        engines = {}
        for backend in self.router.snapshot()[0]:
            row_fn = getattr(backend.engine, "telemetry_row", None)
            if row_fn is not None:
                engines[backend.ring_id] = row_fn()
        out["ENGINES"] = engines
        tail = int(req.get("TAIL", 0) or 0)
        since = req.get("SINCE")
        if since is not None:
            # chordax-tower (ISSUE 20): the since-cursor TAIL form —
            # duplicate-free across polls (each event carries its
            # `seq`; NEXT_SEQ resumes the pull) and eviction-visible
            # (GAP counts events the ring dropped past the cursor).
            events, nxt, gap = _FLIGHT.recent_since(
                int(since), tail if tail > 0 else None)
            out["FLIGHT"]["tail"] = events
            out["FLIGHT"]["next_seq"] = nxt
            out["FLIGHT"]["gap"] = gap
        elif tail > 0:
            out["FLIGHT"]["tail"] = _FLIGHT.recent(tail)
        # chordax-tower (ISSUE 20): the attached DecisionLedger's
        # incremental rows — LEDGER_SINCE is the collector's cursor
        # (same NEXT/GAP contract as the flight tail). No ledger
        # attached means no LEDGER section, never an RPC error.
        ledger_since = req.get("LEDGER_SINCE")
        if ledger_since is not None:
            ledger = self.decision_ledger()
            if ledger is not None:
                rows, lnxt, lgap = ledger.entries_since(
                    int(ledger_since))
                out["LEDGER"] = {"rows": rows, "next_seq": lnxt,
                                 "gap": lgap}
        resp = {"HEALTH": out}
        self._merge_mesh_rows("HEALTH", req, resp)
        return resp

    def _merge_mesh_rows(self, command: str, req: dict,
                         out: dict) -> None:
        """MESH:true on an introspection verb (CAPACITY / HEALTH /
        PULSE) additionally collects every live route peer's own
        answer (chordax-mesh): the merged decision input the elastic
        loop reads from any ONE gateway. An unreachable peer's row is
        the plane's TYPED stale marker ({"STALE": true, "ERROR": ...,
        age-stamped "LAST_GOOD"}), so `elastic.MeshPolicy` never
        parses an error string; no mesh attached means no MESH
        section, never an RPC error."""
        if not req.get("MESH"):
            return
        mesh = self.mesh_plane()
        if mesh is not None:
            out["MESH"] = mesh.collect_peer_rows(command, req)

    def handle_pulse(self, req: dict) -> dict:
        """The chordax-pulse verb (ISSUE 11). Payload sections, each
        opt-in so a periodic poll stays cheap:

          SERIES: series-id prefix (or true/"*" for all) -> the
              matching rings' tails, TAIL points each (default 32),
              as [[t, value], ...] rows.
          SLO: true -> every objective's verdict row (OK/WARN/BREACH
              + short/long-window burn rates).
          PROM: true -> Prometheus-style text exposition of the live
              metrics registry (works with no sampler attached).

        ATTACHED=false means no sampler is wired to this gateway —
        series/SLO sections are then absent, never an RPC error."""
        from p2p_dhts_tpu import pulse as pulse_mod
        sampler = self.pulse_sampler()
        out: dict = {"ATTACHED": sampler is not None}
        if sampler is not None:
            out["STATUS"] = sampler.status()
            sel = req.get("SERIES")
            if sel is not None:
                tail = req.get("TAIL")
                # TAIL: 0 is a real request (ids only, no points) —
                # only an ABSENT field takes the default.
                tail = 32 if tail is None else int(tail)
                prefix = None if sel in (True, "*", "") else str(sel)
                out["SERIES"] = {
                    sid: [[round(t, 3), v] for t, v in pts]
                    for sid, pts in sampler.series_tail(prefix,
                                                        tail).items()}
            if req.get("SLO"):
                out["SLO"] = sampler.verdicts()
        if req.get("PROM"):
            out["PROM"] = pulse_mod.expose_prometheus(self.metrics.base)
        self._merge_mesh_rows("PULSE", req, out)
        return out

    def handle_capacity(self, req: dict) -> dict:
        """The chordax-lens verb (ISSUE 14): every ring's derived
        capacity row (busy fraction, capacity/headroom keys/s, queue
        delay, saturation verdict, kind mix) from the attached
        LensLoop — the elastic policy loop's one-poll decision input.
        With RING, only that ring's row. With COSTS, the raw engine
        view rides along even without a lens attached: each ring's
        per-(kind, bucket) cost table (bucket keys stringified — one
        JSON shape on both transports) and its compile-cause ledger.
        ATTACHED=false means no lens is wired to this gateway —
        never an RPC error."""
        lens = self.lens_model()
        out: dict = {"ATTACHED": lens is not None}
        if lens is not None:
            report = lens.capacity_report()
            ring = req.get("RING")
            if ring is not None:
                rings = report.get("rings", {})
                report = dict(report)
                report["rings"] = (
                    {str(ring): rings[str(ring)]}
                    if str(ring) in rings else {})
            out["CAPACITY"] = report
        if req.get("COSTS"):
            costs: Dict[str, dict] = {}
            for backend in self.router.snapshot()[0]:
                table_fn = getattr(backend.engine, "cost_table", None)
                ledger_fn = getattr(backend.engine, "compile_ledger",
                                    None)
                if table_fn is None and ledger_fn is None:
                    continue
                table = table_fn() if table_fn is not None else {}
                costs[backend.ring_id] = {
                    "cost_table": {
                        kind: {str(b): row for b, row in rows.items()}
                        for kind, rows in table.items()},
                    "compiles": (ledger_fn()
                                 if ledger_fn is not None else []),
                }
            out["COSTS"] = costs
        self._merge_mesh_rows("CAPACITY", req, out)
        return out

    # -- mesh verbs (chordax-mesh, ISSUE 15) ---------------------------------
    def handle_mesh_routes(self, req: dict) -> dict:
        """The mesh gossip/observability verb: the attached plane's
        epoch-stamped shard -> address table (any mesh gateway answers
        from its own view — peers pull from the seed, watchers from
        anyone). SET_COALESCE toggles the forward coalescer between
        its configured batching and the per-key-forward baseline (the
        bench's A/B knob). ATTACHED=false means no mesh plane — never
        an RPC error."""
        mesh = self.mesh_plane()
        if mesh is None:
            return {"ATTACHED": False}
        if "SET_COALESCE" in req:
            mesh.coalescer.set_coalesce(bool(req["SET_COALESCE"]))
        out = {"ATTACHED": True, "STATUS": mesh.mesh_status()}
        out.update(mesh.routes_doc())
        return out

    def handle_havoc(self, req: dict) -> dict:
        """Chaos control over the wire: install/uninstall a seeded
        havoc FaultPlan in THIS process — how a multi-process mesh
        scenario (partition one whole gateway) is seeded into every
        process from one driver, replayably (the plan is (seed, spec);
        the reply carries the describe() line the incident log wants).
        A test/bench control surface in the same trust domain as the
        metrics/trace verbs."""
        from p2p_dhts_tpu import havoc as havoc_mod
        action = str(req.get("ACTION", "describe")).lower()
        if action == "install":
            plan = havoc_mod.FaultPlan(int(req["SEED"]),
                                       dict(req.get("SPEC") or {}))
            # One plan at a time (the replay contract): an install
            # over a live plan supersedes it visibly.
            prev = havoc_mod.uninstall()
            havoc_mod.install(plan)
            return {"ACTIVE": plan.describe(),
                    "SUPERSEDED": (prev.describe()
                                   if prev is not None else None)}
        if action == "uninstall":
            plan = havoc_mod.uninstall()
            return {"ACTIVE": None,
                    "UNINSTALLED": (plan.describe()
                                    if plan is not None else None)}
        return {"ACTIVE": havoc_mod.describe_active()}

    def handle_finger_index(self, req: dict) -> dict:
        dl = Deadline.from_budget_ms(req.get("DEADLINE_MS"))
        # chordax-fuse: RING opts the lookup into that ring's engine
        # (and its fused multi-kind queue); absent RING keeps the
        # shared finger engine — the reference wire shape unchanged.
        ring_id = req.get("RING")
        if "KEYS" in req:
            keys = req["KEYS"]
            # Explicit None/empty check: numpy TABLE_STARTS (binary
            # transport) has no truth value.
            starts = req.get("TABLE_STARTS")
            lanes = self._vector_lanes(keys)
            if lanes is not None:
                # Zero-copy fast lane: both 128-bit vectors ride as
                # lane arrays (absent TABLE_STARTS = all-zero starts).
                if starts is None or len(starts) == 0:
                    slanes = np.zeros_like(lanes)
                else:
                    slanes = self._vector_lanes(starts)
                if slanes is not None:
                    if slanes.shape[0] != lanes.shape[0]:
                        raise ValueError(
                            "TABLE_STARTS length must match KEYS")
                    if lanes.shape[0] == 0:
                        return {"INDICES": np.zeros(0, np.int32)}
                    backend = self._finger_backend_for(ring_id)
                    idx = self._serve_many(
                        backend, "finger_index",
                        _VectorRun(lanes, slanes), dl)
                    return {"INDICES": np.asarray(idx, np.int32)}
                # Mixed forms (lane keys, list starts): the adapter
                # path below serves it.
            if starts is None or len(starts) == 0:
                starts = [0] * len(keys)
            if len(starts) != len(keys):
                raise ValueError("TABLE_STARTS length must match KEYS")
            idx = self.finger_index_many(list(zip(keys, starts)),
                                         ring_id=ring_id, deadline=dl)
            return {"INDICES": np.asarray(idx, dtype=np.int32)}
        return {"INDEX": self.finger_index(
            req["KEY"], req.get("TABLE_START", 0), ring_id=ring_id,
            deadline=dl)}

    def close(self, drain: bool = True) -> None:
        """Close every registered ring's engine (the shared finger
        engine is process-global and stays up). Attached repair
        schedulers and the replication writer stop FIRST so no repair
        round lands on a half-torn-down router."""
        with self._rings_lock:
            scheds = list(self._repair_scheds)
            self._repair_scheds.clear()
            self._auto_repair = None
            managers = list(self._memberships.values())
            self._memberships.clear()
            writer, self._repl_writer = self._repl_writer, None
            self._repl_policy = None
            # Detach (never close) the pulse sampler, the lens loop
            # and the mesh plane: their lifecycles belong to whoever
            # built them.
            self._pulse = None
            self._lens = None
            self._mesh = None
            self._ledger = None
        # Membership loops stop FIRST (they submit churn batches and
        # nudge schedulers); then repair, then the writer.
        scheds = managers + scheds
        # A wedged scheduler/writer must not abort the rest of the
        # teardown (leaked engines + pool threads outlive one stuck
        # pair loop); remember the first error, finish, then re-raise.
        first_exc: Optional[BaseException] = None
        for closer in [s.close for s in scheds] + (
                [writer.close] if writer is not None else []):
            try:
                closer()
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                if first_exc is None:
                    first_exc = exc
        for ring_id in self.router.ring_ids():
            try:
                self.remove_ring(ring_id, drain=drain)
            except UnknownRingError:
                pass  # concurrently removed
        # Detach the cache's topology listener LAST (the remove_ring
        # loop above still wants its invalidations): on a SHARED
        # router, a closed gateway must not stay subscribed forever.
        if self._topology_cb is not None:
            self.router.remove_topology_listener(self._topology_cb)
            self._topology_cb = None
        if first_exc is not None:
            raise first_exc


# ---------------------------------------------------------------------------
# process-global gateway + handler install
# ---------------------------------------------------------------------------

_GLOBAL_LOCK = threading.Lock()
_GLOBAL_GATEWAY: Optional[Gateway] = None


def global_gateway() -> Gateway:
    """The process-wide gateway every overlay peer's RPC server routes
    through — one router, one set of rings, shared engine batches."""
    global _GLOBAL_GATEWAY
    with _GLOBAL_LOCK:
        if _GLOBAL_GATEWAY is None:
            _GLOBAL_GATEWAY = Gateway(name="global")
        return _GLOBAL_GATEWAY


def install_gateway_handlers(server, gateway: Optional[Gateway] = None
                             ) -> Gateway:
    """Register the gateway command surface on a net/rpc.py Server (or
    anything with its update_handlers contract). Safe on a LIVE server:
    update_handlers swaps the handler map atomically. Returns the
    gateway actually installed (the process-global one by default)."""
    gw = gateway if gateway is not None else global_gateway()
    server.update_handlers({
        "FIND_SUCCESSOR": gw.handle_find_successor,
        "GET": gw.handle_get,
        "PUT": gw.handle_put,
        "FINGER_INDEX": gw.handle_finger_index,
        "SYNC_RANGE": gw.handle_sync_range,
        "REPAIR_STATUS": gw.handle_repair_status,
        "JOIN_RING": gw.handle_join_ring,
        "HEARTBEAT": gw.handle_heartbeat,
        "MEMBER_STATUS": gw.handle_member_status,
        "METRICS": gw.handle_metrics,
        "TRACE_STATUS": gw.handle_trace_status,
        "TRACE_PULL": gw.handle_trace_pull,
        "HEALTH": gw.handle_health,
        "PULSE": gw.handle_pulse,
        "CAPACITY": gw.handle_capacity,
        "MESH_ROUTES": gw.handle_mesh_routes,
        "HAVOC": gw.handle_havoc,
    })
    return gw

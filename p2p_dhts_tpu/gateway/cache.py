"""chordax-fastlane hot-key result cache (ISSUE 12).

The gateway has identified hot-key storms since PR 4 — single-flight
collapses concurrent duplicates to one engine submission — but every
RESOLVED storm re-executed on its next wave. This is the memo-cache
step the reference never needed (it had no batched front door): a
bounded LRU of read-side results (FIND_SUCCESSOR, replica-aware GET)
keyed by (ring-epoch, op, ring, key), sitting BEHIND single-flight so
a storm populates exactly one entry and every later wave is a host
dict hit instead of an engine round trip.

CORRECTNESS RULE — epoch invalidation, never per-key patching: any
write or topology change that could move a key's answer (a PUT on any
ring, a churn_apply batch, a stabilize sweep, a store-mutating
maintenance/reindex pass, RingRouter.set_key_range, ring add/remove)
bumps the cache epoch, which invalidates the WHOLE cache in O(1).
Entries fill with the epoch captured BEFORE their engine flight, and a
stale-epoch fill is dropped — so a result computed against a pre-write
store/ring can never land after the write invalidated it, and a cached
answer can never survive a membership change (the PR-7 handoff
discipline applied to memoization). Wholesale invalidation trades hit
rate under write-heavy load for an unbeatable staleness argument;
read-heavy hot-key traffic (the Zipf storm this exists for) keeps its
>80% hit rate because epochs only move when writes do.

LOCK ORDER: one leaf lock around the OrderedDict; never held across
an engine call, a fill computation, or any other lock (the admission
module's discipline). This module never imports jax.

Metrics (`gateway.cache.*`): hits / misses / evictions (capacity) /
invalidations (epoch bumps), plus a size gauge.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Optional, Tuple

from p2p_dhts_tpu.metrics import METRICS, Metrics


class HotKeyCache:
    """Bounded LRU of read results, invalidated wholesale by epoch."""

    def __init__(self, capacity: int = 4096,
                 metrics: Optional[Metrics] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._metrics = metrics if metrics is not None else METRICS
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Any, Any]" = OrderedDict()
        self._epoch = 0

    @property
    def epoch(self) -> int:
        """The current invalidation epoch. Callers capture this BEFORE
        computing a fill; put() drops fills from older epochs."""
        with self._lock:
            return self._epoch

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: Any) -> Tuple[bool, Any]:
        """(hit, value). A hit refreshes LRU order; metrics count both
        outcomes so the hit rate is one counter division away."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                value = self._entries[key]
                hit = True
            else:
                value, hit = None, False
        if hit:
            self._metrics.inc("gateway.cache.hits")
        else:
            self._metrics.inc("gateway.cache.misses")
        return hit, value

    def put(self, epoch: int, key: Any, value: Any) -> bool:
        """Install one result computed under `epoch`. A fill whose
        epoch is no longer current is DROPPED (the write/topology
        change that bumped the epoch may have changed this very
        answer); returns whether the entry landed."""
        evicted = 0
        with self._lock:
            if epoch != self._epoch:
                return False
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
            size = len(self._entries)
        if evicted:
            self._metrics.inc("gateway.cache.evictions", evicted)
        self._metrics.gauge("gateway.cache.size", size)
        return True

    def invalidate(self, reason: str = "") -> int:
        """Bump the epoch and drop every entry (wholesale — the
        correctness rule). Returns the number of entries dropped.
        Cheap when already empty, so redundant bumps (a PUT that also
        fired the router's topology listener) cost a lock hop."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._epoch += 1
        self._metrics.inc("gateway.cache.invalidations")
        self._metrics.gauge("gateway.cache.size", 0)
        return n

    def stats(self) -> dict:
        with self._lock:
            size, epoch = len(self._entries), self._epoch
        return {
            "size": size,
            "capacity": self.capacity,
            "epoch": epoch,
            "hits": self._metrics.counter("gateway.cache.hits"),
            "misses": self._metrics.counter("gateway.cache.misses"),
            "evictions": self._metrics.counter("gateway.cache.evictions"),
            "invalidations": self._metrics.counter(
                "gateway.cache.invalidations"),
        }

"""Gateway observability: per-ring / per-op counters, gauges, histograms.

Thin, bounded naming layer over the package metrics registry
(p2p_dhts_tpu.metrics.Metrics — the reservoir/quantile machinery lives
there; this module only owns the KEY SCHEMA and the per-ring summary
view). Ring ids and op names are operator-chosen and finite, so every
key family below is bounded:

  counters   gateway.requests.<op>.<ring>          admitted requests
             gateway.errors.<op>.<ring>            device-path failures
             gateway.fallback.<op>.<ring>          served via fallback
             gateway.deadline_dropped.<ring>       shed before dispatch
             gateway.rejected.<ring>               RingBusy admissions
             gateway.ejected_fastfail.<ring>       refused while ejected
             gateway.single_flight_hits            duplicate collapses
  gauges     gateway.health.<ring>                 0 healthy / 1 degraded
                                                   / 2 ejected
             gateway.inflight.<ring>               admission occupancy
  histograms gateway.latency_ms.<op>.<ring>        request latency
                                                   (admission -> answer)

`ring_stats(ring)` folds these into one plain dict (counts + p50/p99)
— what `bench.py --config gateway`, the dryrun's gateway stage, and
the tests assert against.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from p2p_dhts_tpu.metrics import METRICS, Metrics

#: The gateway op vocabulary (the engine's kinds, served over the wire;
#: sync_digest / repair_reindex are the chordax-repair control ops,
#: churn_apply / stabilize_sweep the membership/actuation control ops —
#: policy-driven split/merge cycles count them per ring, so retirement
#: must enumerate them too or a retired child leaks its rows).
OPS = ("find_successor", "dhash_get", "dhash_put", "finger_index",
       "sync_digest", "repair_reindex", "churn_apply",
       "stabilize_sweep")

#: Every per-ring membership key family (membership.<fam>.<ring> —
#: manager.py's schema, mirrored in README's metric-key inventory).
#: retire_ring enumerates this so a removed ring's membership
#: telemetry leaves the registry with its manager.
MEMBERSHIP_FAMS = (
    "join_requests", "join_rejected", "heartbeats", "heartbeat_unknown",
    "suspects", "suspicion_cleared", "failures_detected", "batches",
    "rows_applied", "rows_regenerated", "ranges_transferred",
    "heal_enqueued", "stalled_rounds", "round_failures",
    "handoff_failover", "pending", "members_alive", "converged",
    "fail_vetoed", "flap_suppressed", "rejoins", "listener_errors")

#: Per-ring repair key families (repair.<fam>.<ring> /
#: repair.replication.<fam>.<ring>). Pair-keyed repair telemetry
#: (backlog/converged/tokens/round_ms.<a>-<b>) retires with its loop
#: in RepairScheduler.remove_ring; these are the RING-keyed leftovers.
REPAIR_RING_FAMS = ("keys_healed", "reindexed", "read_failover",
                    "drift_healed")
REPAIR_REPLICATION_FAMS = ("lag_ms", "replica_ok", "replica_failed")


class GatewayMetrics:
    """Namespaced recording + per-ring summary over a Metrics registry."""

    def __init__(self, base: Optional[Metrics] = None):
        self.base = base if base is not None else METRICS

    # -- recording -----------------------------------------------------------
    def count_requests(self, op: str, ring_id: str, n: int = 1) -> None:
        self.base.inc(f"gateway.requests.{op}.{ring_id}", n)

    def count_errors(self, op: str, ring_id: str, n: int = 1) -> None:
        self.base.inc(f"gateway.errors.{op}.{ring_id}", n)

    def count_fallback(self, op: str, ring_id: str, n: int = 1) -> None:
        self.base.inc(f"gateway.fallback.{op}.{ring_id}", n)

    def count_deadline_dropped(self, ring_id: str, n: int = 1) -> None:
        self.base.inc(f"gateway.deadline_dropped.{ring_id}", n)

    def count_rejected(self, ring_id: str, n: int = 1) -> None:
        self.base.inc(f"gateway.rejected.{ring_id}", n)

    def count_ejected_fastfail(self, ring_id: str, n: int = 1) -> None:
        self.base.inc(f"gateway.ejected_fastfail.{ring_id}", n)

    def count_single_flight_hit(self, n: int = 1) -> None:
        self.base.inc("gateway.single_flight_hits", n)

    def gauge_health(self, ring_id: str, state: str) -> None:
        from p2p_dhts_tpu.gateway.router import HEALTH_CODE
        self.base.gauge(f"gateway.health.{ring_id}",
                        HEALTH_CODE.get(state, -1))

    def gauge_inflight(self, ring_id: str, n: int) -> None:
        self.base.gauge(f"gateway.inflight.{ring_id}", n)

    def observe_latency(self, op: str, ring_id: str,
                        latencies_s: Iterable[float]) -> None:
        self.base.observe_hist_many(
            f"gateway.latency_ms.{op}.{ring_id}",
            [v * 1e3 for v in latencies_s])

    # -- retirement ----------------------------------------------------------
    def retire_ring(self, ring_id: str) -> int:
        """Drop every per-ring key a removed ring left behind —
        counters, gauges AND hists, across the gateway.* AND
        membership.* families (the ring's manager closes with it).
        Bounded enumeration over the fixed key schema;
        Metrics.remove_prefix is dotted-segment-exact, so ring "a" can
        never collaterally retire ring "ab". Returns keys removed."""
        removed = 0
        for fam in ("requests", "errors", "fallback", "latency_ms"):
            for op in OPS:
                removed += self.base.remove_prefix(
                    f"gateway.{fam}.{op}.{ring_id}")
        for fam in ("deadline_dropped", "rejected", "ejected_fastfail",
                    "health", "inflight"):
            removed += self.base.remove_prefix(
                f"gateway.{fam}.{ring_id}")
        for fam in MEMBERSHIP_FAMS:
            removed += self.base.remove_prefix(
                f"membership.{fam}.{ring_id}")
        for fam in REPAIR_RING_FAMS:
            removed += self.base.remove_prefix(
                f"repair.{fam}.{ring_id}")
        for fam in REPAIR_REPLICATION_FAMS:
            removed += self.base.remove_prefix(
                f"repair.replication.{fam}.{ring_id}")
        return removed

    # -- summary views -------------------------------------------------------
    def ring_stats(self, ring_id: str) -> Dict[str, object]:
        """One ring's gateway-level view: per-op request/error/fallback
        counts and latency percentiles, plus the ring-wide shed/reject
        counters. One prefix scan of the registry instead of a lock
        acquisition per key."""
        c = self.base.counters_with_prefix("gateway.")
        out: Dict[str, object] = {"ring": ring_id}
        for op in OPS:
            reqs = c.get(f"gateway.requests.{op}.{ring_id}", 0)
            if not reqs:
                continue
            p50, p99 = self.base.quantiles(
                f"gateway.latency_ms.{op}.{ring_id}")
            out[op] = {
                "requests": reqs,
                "errors": c.get(f"gateway.errors.{op}.{ring_id}", 0),
                "fallback": c.get(f"gateway.fallback.{op}.{ring_id}", 0),
                "p50_ms": round(p50, 3) if p50 is not None else None,
                "p99_ms": round(p99, 3) if p99 is not None else None,
            }
        out["deadline_dropped"] = c.get(
            f"gateway.deadline_dropped.{ring_id}", 0)
        out["rejected"] = c.get(f"gateway.rejected.{ring_id}", 0)
        out["ejected_fastfail"] = c.get(
            f"gateway.ejected_fastfail.{ring_id}", 0)
        return out

    def snapshot(self, ring_ids: Iterable[str]) -> Dict[str, object]:
        return {
            "rings": {r: self.ring_stats(r) for r in ring_ids},
            "single_flight_hits": self.base.counter(
                "gateway.single_flight_hits"),
        }

"""Front-door admission policy: per-ring bounds, deadlines, single-flight.

Three mechanisms, each answering one overload question:

  * `RingAdmission` — a bounded per-ring in-flight counter, DISTINCT
    from the engine's global queue: every ring gets its own admission
    budget, so a slow or held ring fills ITS budget and starts
    rejecting (RingBusyError) while the other rings' requests never
    queue behind it. Waiting for a slot is bounded by `max_wait_s` AND
    by the request's deadline, whichever is tighter — admission can
    delay a request, never wedge it.
  * `Deadline` — one absolute time.perf_counter() instant threaded
    end-to-end: client timeout -> gateway budget -> engine slot
    (serve.ServeEngine drops expired slots pre-dispatch). `None` means
    no deadline (the reference's 5 s client timeout still bounds the
    TCP wait).
  * `SingleFlight` — duplicate suppression for idempotent lookups: a
    FIND_SUCCESSOR storm on one hot key collapses to ONE engine
    submission whose answer fans out to every concurrent duplicate.
    Entries live only while the leader is in flight (no staleness — a
    completed answer is never re-served), and a full table degrades to
    pass-through, never to blocking.

LOCK ORDER: `RingAdmission` waits only on its own condition (which
releases its own lock — the lockcheck-exempt pattern) and `SingleFlight`
holds its lock only for dict bookkeeping; the leader's engine call and
the followers' event wait both run lock-free. Neither lock ever nests
with the router's or a backend's. This module never imports jax.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional

from p2p_dhts_tpu.serve import DeadlineExpiredError


class RingBusyError(RuntimeError):
    """The ring's admission budget stayed full past the caller's wait
    bound — per-ring backpressure, surfaced instead of queued."""


class Deadline:
    """An absolute time.perf_counter() instant (or None = unbounded)."""

    __slots__ = ("at",)

    def __init__(self, at: Optional[float]):
        self.at = at

    @classmethod
    def from_timeout(cls, timeout_s: Optional[float]) -> "Deadline":
        if timeout_s is None:
            return cls(None)
        return cls(time.perf_counter() + float(timeout_s))

    @classmethod
    def from_budget_ms(cls, budget_ms) -> "Deadline":
        """Wire-form budget (the RPC request's DEADLINE_MS field)."""
        if budget_ms is None:
            return cls(None)
        return cls.from_timeout(float(budget_ms) / 1e3)

    def remaining(self) -> Optional[float]:
        if self.at is None:
            return None
        return self.at - time.perf_counter()

    def expired(self) -> bool:
        return self.at is not None and time.perf_counter() >= self.at

    def clamp(self, timeout_s: Optional[float]) -> Optional[float]:
        """timeout_s bounded by the remaining budget (None = neither)."""
        rem = self.remaining()
        if rem is None:
            return timeout_s
        if timeout_s is None:
            return max(rem, 0.0)
        return max(min(timeout_s, rem), 0.0)


#: The no-deadline singleton callers may share.
NO_DEADLINE = Deadline(None)


class RingAdmission:
    """Bounded in-flight budget for one ring's front door."""

    #: Default bound on the wait for an admission slot; the deadline
    #: tightens it, never widens it.
    MAX_WAIT_S = 0.25

    def __init__(self, ring_id: str, max_inflight: int = 4096,
                 max_wait_s: Optional[float] = None):
        if max_inflight <= 0:
            raise ValueError(f"max_inflight must be positive, got "
                             f"{max_inflight}")
        self.ring_id = str(ring_id)
        self.max_inflight = int(max_inflight)
        self.max_wait_s = float(max_wait_s if max_wait_s is not None
                                else self.MAX_WAIT_S)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inflight = 0

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def acquire(self, n: int = 1,
                deadline: Deadline = NO_DEADLINE) -> None:
        """Take n admission slots; raises RingBusyError when the budget
        stays full past min(max_wait_s, deadline), DeadlineExpiredError
        when the deadline lapses first. A request larger than the whole
        budget is rejected outright (it could never be admitted)."""
        if n > self.max_inflight:
            raise RingBusyError(
                f"ring {self.ring_id!r}: batch of {n} exceeds the "
                f"admission budget ({self.max_inflight})")
        wait_until = time.perf_counter() + self.max_wait_s
        try:
            with self._cond:
                while self._inflight + n > self.max_inflight:
                    if deadline.expired():
                        raise DeadlineExpiredError(
                            f"ring {self.ring_id!r}: deadline passed "
                            f"while waiting for admission")
                    now = time.perf_counter()
                    if now >= wait_until:
                        raise RingBusyError(
                            f"ring {self.ring_id!r}: admission budget "
                            f"({self.max_inflight}) full for "
                            f"{self.max_wait_s:.3f}s")
                    slice_s = wait_until - now
                    rem = deadline.remaining()
                    if rem is not None:
                        slice_s = min(slice_s, rem)
                    self._cond.wait(max(slice_s, 0.0))
                self._inflight += n
        except RingBusyError:
            # chordax-scope: a budget-full rejection is a first-class
            # incident event — recorded at the source, OUTSIDE the
            # condition lock (leaf discipline; the recorder has its
            # own leaf lock). Lazy import: admission must stay
            # importable without the health plane loaded.
            from p2p_dhts_tpu.health import FLIGHT
            FLIGHT.record("gateway", "admission_full",
                          ring=self.ring_id, n=n,
                          max_inflight=self.max_inflight,
                          waited_s=round(self.max_wait_s, 3))
            raise

    def release(self, n: int = 1) -> None:
        with self._cond:
            self._inflight -= n
            self._cond.notify_all()

    @contextlib.contextmanager
    def admit(self, n: int = 1,
              deadline: Deadline = NO_DEADLINE) -> Iterator[None]:
        self.acquire(n, deadline)
        try:
            yield
        finally:
            self.release(n)


class _SFEntry:
    __slots__ = ("ev", "result", "error")

    def __init__(self) -> None:
        self.ev = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None


class SingleFlight:
    """Collapse concurrent identical idempotent requests to one flight.

    `run(key, fn, deadline)`: the first caller for a key becomes the
    leader and executes fn(); concurrent callers with the same key wait
    on the leader's outcome (result OR exception — a failed flight
    fails every duplicate, exactly as if each had flown). The entry is
    removed the moment the flight completes, so answers are never
    served stale. A table at capacity passes through (duplicate work
    over blocked work).
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._inflight: Dict[Any, _SFEntry] = {}
        self._hits = 0

    @property
    def hits(self) -> int:
        with self._lock:
            return self._hits

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def run(self, key: Any, fn: Callable[[], Any],
            deadline: Deadline = NO_DEADLINE,
            on_hit: Optional[Callable[[], None]] = None) -> Any:
        """`on_hit` fires exactly once per FOLLOWER (a caller whose
        request collapsed onto an existing flight) — the accurate
        dedup metric; callers must not diff the shared `hits` counter
        themselves (concurrent deltas over-count)."""
        with self._lock:
            entry = self._inflight.get(key)
            if entry is None:
                if len(self._inflight) >= self.capacity:
                    entry = None  # full: pass through below
                else:
                    entry = self._inflight[key] = _SFEntry()
                    lead = True
            else:
                lead = False
                self._hits += 1
        if entry is None:
            return fn()
        if not lead and on_hit is not None:
            on_hit()
        if lead:
            try:
                entry.result = fn()
            except BaseException as exc:  # noqa: BLE001 — fanned out
                entry.error = exc
                raise
            finally:
                with self._lock:
                    self._inflight.pop(key, None)
                entry.ev.set()
            return entry.result
        if not entry.ev.wait(deadline.clamp(None)):
            raise DeadlineExpiredError(
                "single-flight wait outlived the request deadline")
        if entry.error is not None:
            raise entry.error
        return entry.result

"""Structured metrics + profiler tracing (SURVEY.md §5.1 green field).

The reference's only observability is `AbstractChordPeer::Log` — raw
stdout lines (abstract_chord_peer.cpp:714-718) — plus the Server's
optional 32-entry request ring buffer (server.h:364-378, mirrored in
net/rpc.py RequestLog). This module adds what the reference never had:

  * `Metrics` — a process-wide, thread-safe registry of counters and
    latency timers. The RPC server counts every dispatched command and
    error; clients time requests; overlay maintenance ops count rounds.
    `snapshot()` returns a plain dict for tests/bench JSON.
  * `timed(name)` — context manager / decorator recording wall-clock
    latency (count / total / max) under `timers`.
  * `device_trace(path)` — context manager around `jax.profiler` for
    TPU timeline capture of the device kernels (no-op if the profiler
    is unavailable on the platform, e.g. the CPU test mesh).

Everything is stdlib + optional jax.profiler; recording a metric is a
dict update under one lock — cheap enough for the RPC dispatch path.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional


class Metrics:
    """Thread-safe counters + timers registry."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, Dict[str, float]] = {}

    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            t = self._timers.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            t["count"] += 1
            t["total_s"] += seconds
            t["max_s"] = max(t["max_s"], seconds)

    @contextlib.contextmanager
    def timed(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timers": {k: dict(v) for k, v in self._timers.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()


#: Process-wide default registry (the RPC layer and overlay peers record
#: here; tests may swap in their own Metrics instance).
METRICS = Metrics()


@contextlib.contextmanager
def device_trace(path: str, enabled: bool = True) -> Iterator[None]:
    """jax.profiler trace of everything inside the block to `path`
    (TensorBoard format). Degrades to a no-op when profiling is
    unsupported on the active platform."""
    if not enabled:
        yield
        return
    try:
        import jax
        jax.profiler.start_trace(path)
    except Exception:
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass

"""Structured metrics + profiler tracing (SURVEY.md §5.1 green field).

The reference's only observability is `AbstractChordPeer::Log` — raw
stdout lines (abstract_chord_peer.cpp:714-718) — plus the Server's
optional 32-entry request ring buffer (server.h:364-378, mirrored in
net/rpc.py RequestLog). This module adds what the reference never had:

  * `Metrics` — a process-wide, thread-safe registry of counters,
    latency timers, gauges, and bounded-reservoir histograms. The RPC
    server counts every dispatched command and error; clients time
    requests; overlay maintenance ops count rounds; the serve engine
    records queue depth / window size gauges and per-request latency
    histograms. `snapshot()` returns a plain dict for tests/bench JSON
    (the `gauges`/`hists` sections appear only when non-empty, so
    pre-gauge consumers see the exact historical shape).
  * `timed(name)` — context manager / decorator recording wall-clock
    latency (count / total / max) under `timers`.
  * `gauge(name, value)` — last-write-wins instantaneous value (queue
    depth, adaptive window size, batch fill ratio).
  * `observe_hist(name, value)` — append to a bounded reservoir (newest
    `HIST_CAP` samples) from which `quantiles()`/`snapshot()` derive
    p50/p99 — the per-request latency percentiles the serve bench
    reports.
  * `device_trace(path)` — context manager around `jax.profiler` for
    TPU timeline capture of the device kernels (no-op if the profiler
    is unavailable on the platform, e.g. the CPU test mesh).

Everything is stdlib + optional jax.profiler; recording a metric is a
dict update under one lock — cheap enough for the RPC dispatch path.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from typing import Dict, Iterator, Optional, Sequence, Tuple


def nearest_rank(sorted_samples: Sequence[float],
                 q: float) -> Optional[float]:
    """Nearest-rank quantile over an ASCENDING-sorted sample list (None
    when empty) — THE percentile rule for every latency summary in this
    package (Metrics, ServeEngine, bench); keep one copy so reported
    percentiles can never diverge between reporters."""
    n = len(sorted_samples)
    if not n:
        return None
    return sorted_samples[min(int(q * n), n - 1)]


class Metrics:
    """Thread-safe counters + timers + gauges + histograms registry."""

    #: Reservoir bound per histogram: newest samples win. Bounded so the
    #: registry can sit on the per-request serve hot path forever.
    HIST_CAP = 4096

    #: Exemplar ring bound per histogram (chordax-tower, ISSUE 20):
    #: the newest (value, trace_id) pairs recorded while a SAMPLED
    #: trace was active — the bridge from a p99 outlier to its full
    #: stitched trace. Small: an exemplar is a pointer, not a sample.
    EXEMPLAR_CAP = 8

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, Dict[str, float]] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, collections.deque] = {}
        # Monotonic per-hist appended-sample totals and value sums
        # (never decremented by reservoir eviction): the totals are
        # the snapshot-delta cursor chordax-pulse's windowed
        # percentiles advance through, and totals+sums back the
        # Prometheus summary `_count`/`_sum` samples (which must be
        # cumulative, not reservoir-capped). Each hist AND counter
        # also carries an INCARNATION stamp (one process-unique
        # creation counter): a key deleted by remove_prefix and later
        # re-created restarts under a NEW stamp, so a pulse cursor
        # from the old incarnation can never alias a valid position
        # in the new one (even when the new value/total has already
        # grown past the old cursor).
        self._hist_totals: Dict[str, int] = {}
        self._hist_sums: Dict[str, float] = {}
        self._hist_epochs: Dict[str, int] = {}
        self._counter_epochs: Dict[str, int] = {}
        self._creations = 0
        # Exemplars are OPT-IN (chordax-tower): the disabled path is
        # ONE attribute read on top of the plain hist append — the
        # PR-14 cost_accounting=False discipline, bound-tested in
        # tests/test_metrics.py.
        self._exemplars_on = False
        self._exemplars: Dict[str, collections.deque] = {}

    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            prev = self._counters.get(name)
            if prev is None:
                prev = 0
                self._creations += 1
                self._counter_epochs[name] = self._creations
            self._counters[name] = prev + value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            t = self._timers.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            t["count"] += 1
            t["total_s"] += seconds
            t["max_s"] = max(t["max_s"], seconds)

    def gauge(self, name: str, value: float) -> None:
        """Set an instantaneous value (last write wins)."""
        with self._lock:
            self._gauges[name] = float(value)

    def counter(self, name: str) -> int:
        """Read one counter (0 if never incremented) — the accessor the
        gateway's per-ring stat views and tests use instead of reaching
        into snapshot()'s whole dict."""
        with self._lock:
            return self._counters.get(name, 0)

    def counters_with_prefix(self, prefix: str) -> Dict[str, int]:
        """All counters under a dotted prefix (e.g. "gateway.") — the
        bounded per-subsystem view snapshot() is too coarse for."""
        with self._lock:
            return {k: v for k, v in self._counters.items()
                    if k.startswith(prefix)}

    def remove_prefix(self, prefix: str) -> int:
        """Delete every counter/timer/gauge/hist under a dotted prefix
        (the key itself, or any `prefix.`-extended key — so removing
        "gateway.health.a" can never collaterally remove
        "gateway.health.ab"). Ring retirement calls this so a removed
        ring's per-ring gauges and hists stop haunting dashboards.
        Returns the number of keys removed."""
        dotted = prefix + "."

        def _match(k: str) -> bool:
            return k == prefix or k.startswith(dotted)

        removed = 0
        with self._lock:
            for fam in (self._counters, self._timers, self._gauges,
                        self._hists):
                for k in [k for k in fam if _match(k)]:
                    del fam[k]
                    removed += 1
            # Cursors/stamps/sums die with their key (uncounted: they
            # are bookkeeping for keys already counted above); a later
            # re-created key restarts under a FRESH incarnation stamp,
            # which is what tells pulse's cursors to re-seed rather
            # than read a cross-incarnation delta.
            for fam in (self._hist_totals, self._hist_sums,
                        self._hist_epochs, self._counter_epochs,
                        self._exemplars):
                for k in [k for k in fam if _match(k)]:
                    del fam[k]
        return removed

    def _hist_locked(self, name: str) -> collections.deque:
        """The named reservoir, created (with a fresh incarnation
        stamp) on first use. Caller holds the lock."""
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = collections.deque(
                maxlen=self.HIST_CAP)
            self._creations += 1
            self._hist_epochs[name] = self._creations
        return h

    # -- exemplars (chordax-tower, ISSUE 20) --------------------------------
    def set_exemplars(self, on: bool) -> None:
        """Flip exemplar capture. When ON, every `observe_hist`/
        `observe_hist_many` that runs under an ACTIVE SAMPLED trace
        appends one (value, trace_id, t) exemplar to that hist's
        bounded ring (newest `EXEMPLAR_CAP` win) — the p99-outlier →
        stitched-trace bridge the tower collector walks. When OFF
        (the default) the record path is untouched beyond one
        attribute read."""
        self._exemplars_on = bool(on)

    @property
    def exemplars_enabled(self) -> bool:
        return self._exemplars_on

    @staticmethod
    def _active_trace_id() -> Optional[str]:
        """The current thread's SAMPLED trace id, or None. Lazy
        import: metrics must stay importable without (and below)
        trace in the module graph."""
        from p2p_dhts_tpu import trace as _trace
        if not _trace.enabled():
            return None
        ctx = _trace.current()
        return ctx.trace_id if ctx is not None else None

    def _exemplar_locked(self, name: str, value: float,
                         trace_id: str) -> None:
        ring = self._exemplars.get(name)
        if ring is None:
            ring = self._exemplars[name] = collections.deque(
                maxlen=self.EXEMPLAR_CAP)
        ring.append({"value": value, "trace_id": trace_id,
                     "t": time.time()})

    def exemplars(self, name: Optional[str] = None
                  ) -> Dict[str, list]:
        """{hist name: [exemplar dicts, oldest first]} — the METRICS
        verb's EXEMPLARS section (whole registry, or one hist)."""
        with self._lock:
            if name is not None:
                ring = self._exemplars.get(name)
                return {name: [dict(e) for e in ring]} if ring else {}
            return {k: [dict(e) for e in dq]
                    for k, dq in self._exemplars.items()}

    def observe_hist(self, name: str, value: float) -> None:
        """Append one sample to a bounded reservoir histogram."""
        value = float(value)
        tid = self._active_trace_id() if self._exemplars_on else None
        with self._lock:
            self._hist_locked(name).append(value)
            self._hist_totals[name] = self._hist_totals.get(name, 0) + 1
            self._hist_sums[name] = \
                self._hist_sums.get(name, 0.0) + value
            if tid is not None:
                self._exemplar_locked(name, value, tid)

    def observe_hist_many(self, name: str, values: Sequence[float]) -> None:
        """Append a batch of samples under ONE lock acquisition — the
        serve engine's fan-out path records a whole batch's latencies
        at once instead of contending per request. With exemplars on,
        the batch contributes its SLOWEST sample as one exemplar (a
        per-value capture would let one batch flush the whole ring)."""
        vals = [float(v) for v in values]
        tid = (self._active_trace_id()
               if self._exemplars_on and vals else None)
        with self._lock:
            self._hist_locked(name).extend(vals)
            self._hist_totals[name] = \
                self._hist_totals.get(name, 0) + len(vals)
            self._hist_sums[name] = \
                self._hist_sums.get(name, 0.0) + sum(vals)
            if tid is not None:
                self._exemplar_locked(name, max(vals), tid)

    def state(self) -> dict:
        """The CHEAP whole-registry state: counters + gauges +
        monotonic per-hist totals/sums + the per-key incarnation
        stamps, copied under ONE lock acquisition with NO percentile
        computation and NO reservoir copy — the per-tick read
        chordax-pulse's sampler takes instead of snapshot() (whose
        hists section sorts every reservoir)."""
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "hist_totals": dict(self._hist_totals),
                    "hist_sums": dict(self._hist_sums),
                    "hist_epochs": dict(self._hist_epochs),
                    "counter_epochs": dict(self._counter_epochs)}

    def hist_delta(self, name: str, since_total: int
                   ) -> Tuple[list, int]:
        """(new samples, new total): every sample appended to `name`
        AFTER the reservoir had recorded `since_total` appends — the
        snapshot-delta read behind windowed interval percentiles. Only
        the TAIL is copied (an idle tick copies nothing); when more
        samples arrived than the reservoir retains, the overflow is
        gone and the newest HIST_CAP stand in (the same newest-win
        rule the reservoir itself applies)."""
        with self._lock:
            total = self._hist_totals.get(name, 0)
            h = self._hists.get(name)
            n_new = total - int(since_total)
            if h is None or n_new <= 0:
                return [], total
            n = len(h)
            n_new = min(n_new, n)
            # ONE traversal for the tail copy (per-index deque access
            # would re-walk blocks from the nearer end per element).
            return list(itertools.islice(h, n - n_new, n)), total

    def quantiles(self, name: str,
                  qs: Sequence[float] = (0.5, 0.99)
                  ) -> Tuple[Optional[float], ...]:
        """Quantiles over the current reservoir (None if no samples).
        Nearest-rank on the retained window — an operational latency
        summary, not an exact full-history percentile."""
        with self._lock:
            samples = sorted(self._hists.get(name, ()))
        return tuple(nearest_rank(samples, q) for q in qs)

    @contextlib.contextmanager
    def timed(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "counters": dict(self._counters),
                "timers": {k: dict(v) for k, v in self._timers.items()},
            }
            # Conditional sections: absent when empty so the historical
            # two-section shape (and its consumers) is undisturbed.
            if self._gauges:
                out["gauges"] = dict(self._gauges)
            if self._hists:
                hists = {}
                for k, dq in self._hists.items():
                    samples = sorted(dq)
                    hists[k] = {
                        "count": len(samples),
                        "p50": nearest_rank(samples, 0.5),
                        "p99": nearest_rank(samples, 0.99),
                        "max": samples[-1] if samples else None,
                    }
                out["hists"] = hists
            return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()
            self._gauges.clear()
            self._hists.clear()
            self._hist_totals.clear()
            self._hist_sums.clear()
            self._hist_epochs.clear()
            self._counter_epochs.clear()
            self._exemplars.clear()


#: Process-wide default registry (the RPC layer and overlay peers record
#: here; tests may swap in their own Metrics instance).
METRICS = Metrics()


@contextlib.contextmanager
def device_trace(path: str, enabled: bool = True) -> Iterator[None]:
    """jax.profiler trace of everything inside the block to `path`
    (TensorBoard format). Degrades to a no-op when profiling is
    unsupported on the active platform."""
    if not enabled:
        yield
        return
    try:
        import jax
        jax.profiler.start_trace(path)
    # chordax-lint: disable=bare-except -- profiling is optional; degrade to a no-op on any platform failure
    except Exception:
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        # chordax-lint: disable=bare-except -- stop_trace cleanup must not mask the traced block's result
        except Exception:
            pass

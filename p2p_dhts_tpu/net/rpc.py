"""One-shot TCP JSON-RPC client + threaded server, wire-parity with the
reference (src/networking/client.{h,cpp}, server.h).

Protocol (exactly the reference's):
  * request: one minified JSON object; client half-closes its send side
    after writing (client.cpp:60-65); server reads to EOF.
  * dispatch on req["COMMAND"] against a handler map; unknown command ->
    error (server.h:193-210).
  * response envelope: handler result + {"SUCCESS": true}; handler
    exception -> {"SUCCESS": false, "ERRORS": str} (server.h:151-165);
    parse failure -> same with the parse error.
  * client reads the full reply with a 5 s timeout (client.cpp:67-76) and
    sanitizes trailing garbage after the final '}' (client.cpp:36-49).
  * liveness = TCP connect probe (client.cpp:98-112) — the system-wide
    failure detector.
  * optional request logging into a bounded ring buffer of 32 entries
    (server.h:119-121,242,364-378).

The reference runs 3 io_context worker threads per server
(server.h:294-307); here a thread pool of the same default size serves
parsed connections, with one acceptor thread.
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

from p2p_dhts_tpu import trace as trace_mod
from p2p_dhts_tpu.health import FLIGHT
from p2p_dhts_tpu.metrics import METRICS

JsonObj = dict
Handler = Callable[[JsonObj], JsonObj]

DEFAULT_TIMEOUT_S = 5.0  # client.cpp:68
REQUEST_LOG_SIZE = 32    # server.h:242


class RpcError(RuntimeError):
    """Transport- or protocol-level RPC failure."""


class DeferredResponse:
    """Handler return marker: finish this request OFF the server's
    worker pool.

    A handler that must issue nested RPCs (the JOIN handler's
    recursive pred-resolution) returning one of these frees its server
    worker immediately: the connection's ownership moves to `executor`,
    which runs `fn(request)`, wraps the result in the normal
    SUCCESS/ERRORS envelope, and sends the reply. With the reference's
    3 io workers per server (server.h:294-307), >3 simultaneous JOINs
    used to occupy every worker while each join's nested GET_PRED to
    the same server starved behind them — a wedge the reference sleeps
    out (sleep(20)/sleep(40) in its tests) and this dissolves.

    Only servers advertising `supports_deferred` honor it (the native
    C++ engine's dispatch is synchronous); handlers must check before
    returning one."""

    __slots__ = ("fn", "executor")

    def __init__(self, fn: Handler, executor):
        self.fn = fn
        self.executor = executor


def sanitize_json(payload: str) -> str:
    """Drop garbage after the final '}' (ref SanitizeJson,
    client.cpp:36-49). The C++ version appends '}' per split chunk — which
    leaves one trailing brace that JsonCpp's lenient parser (failIfExtra
    defaults off) ignores; the equivalent here is truncating at the last
    '}' and letting raw_decode ignore any remainder."""
    end = payload.rfind("}")
    return payload[: end + 1] if end >= 0 else payload


def parse_reply(raw: str) -> JsonObj:
    """Reply-path parse: sanitize, then take the first JSON value ignoring
    trailing bytes (JsonCpp failIfExtra=false behavior). The single home of
    this rule — rpc.Client and native_rpc.NativeClient both route through
    it, so the wire-parity contract cannot silently fork."""
    try:
        obj, _ = json.JSONDecoder().raw_decode(sanitize_json(raw))
        return obj
    except json.JSONDecodeError as exc:
        raise RpcError(f"Error parsing response: {exc}") from exc


class RequestLog:
    """Fixed-size FIFO of parsed requests (ref ThreadSafeQueue<Json::Value>,
    thread_safe_queue.h:23-148): PushBack evicts the oldest when full."""

    def __init__(self, max_size: int = REQUEST_LOG_SIZE):
        self._buf: deque = deque(maxlen=max_size)
        self._lock = threading.Lock()

    def push_back(self, item: JsonObj) -> None:
        with self._lock:
            self._buf.append(item)

    def pop_front(self) -> JsonObj:
        with self._lock:
            return self._buf.popleft()

    def at(self, i: int) -> JsonObj:
        with self._lock:
            return self._buf[i]

    def get_buffer(self) -> List[JsonObj]:
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


class Client:
    """One-shot request client (ref class Client, client.h:24-46)."""

    #: Retry backoff base. The k-th retry sleeps a JITTERED slice of
    #: base * 2^k: N clients that all saw the same failure at the same
    #: instant must not come back in lockstep (a retry storm re-wedges
    #: the 3-worker server pool that caused the failure), so the sleep
    #: is uniform in [base*2^k / 4, base*2^k] rather than fixed.
    RETRY_BACKOFF_S = 0.05

    @staticmethod
    def make_request(ip_addr: str, port: int, request: JsonObj,
                     timeout: Optional[float] = None, *,
                     retries: int = 0,
                     deadline: Optional[float] = None) -> JsonObj:
        """One-shot request, optionally retried.

        `retries=0` (the default) is the reference behavior: one
        attempt, transport failure raises RpcError. With retries > 0,
        transport-level RpcErrors are retried up to that many times
        with jittered exponential backoff (never fixed sleeps — see
        RETRY_BACKOFF_S). `deadline` is an absolute time.perf_counter()
        instant honored END-TO-END: each attempt's socket timeout is
        clamped to the remaining budget, backoff sleeps never overrun
        it, and an expired deadline raises RpcError immediately — this
        is the client half of the gateway's deadline propagation
        (client timeout -> gateway budget -> engine slot).

        chordax-scope: while tracing is enabled, this call opens the
        request's ROOT span and rides the context in the request's
        TRACE field, so the server/gateway/engine spans of this request
        share one trace_id (the caller's request dict is never
        mutated)."""
        if trace_mod.enabled():
            with trace_mod.span(
                    f"rpc.client.{request.get('COMMAND', '')}",
                    cat="rpc", peer=f"{ip_addr}:{port}") as ctx:
                # ctx is None if tracing was disabled between the check
                # above and span() re-reading the flag — the request
                # must degrade to untraced, never error.
                if ctx is not None:
                    request = dict(request)
                    request[trace_mod.WIRE_KEY] = ctx.to_wire()
                return Client._request_with_retries(
                    ip_addr, port, request, timeout,
                    retries=retries, deadline=deadline)
        return Client._request_with_retries(
            ip_addr, port, request, timeout,
            retries=retries, deadline=deadline)

    @staticmethod
    def _request_with_retries(ip_addr: str, port: int, request: JsonObj,
                              timeout: Optional[float] = None, *,
                              retries: int = 0,
                              deadline: Optional[float] = None) -> JsonObj:
        # Default resolved at CALL time so a harness can lower
        # rpc.DEFAULT_TIMEOUT_S process-wide: deep recursive handler
        # chains right after mass churn can exhaust the 3-per-server
        # worker pool (a reference-faithful design, server.h:294-307) and
        # those requests only un-wedge via this timeout — the reference's
        # tests wait out the same stalls with sleep(20)/sleep(40).
        if timeout is None:
            timeout = DEFAULT_TIMEOUT_S
        attempt = 0
        while True:
            if deadline is not None:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    METRICS.inc("rpc.client.deadline_expired")
                    raise RpcError("RPC deadline expired")
                eff_timeout = min(timeout, remaining)
            else:
                eff_timeout = timeout
            METRICS.inc("rpc.client.requests")
            t0 = time.perf_counter()
            try:
                resp = Client._make_request_inner(ip_addr, port, request,
                                                  eff_timeout)
            except RpcError:
                # Observe the ATTEMPT's latency before any backoff
                # sleep — the histogram measures requests, not the
                # retry policy's deliberate waiting.
                METRICS.observe("rpc.client.request",
                                time.perf_counter() - t0)
                METRICS.inc("rpc.client.errors")
                if attempt >= retries:
                    raise
                attempt += 1
                METRICS.inc("rpc.client.retries")
                base = Client.RETRY_BACKOFF_S * (2 ** (attempt - 1))
                delay = random.uniform(base * 0.25, base)
                if deadline is not None:
                    # Never sleep more than HALF the remaining budget:
                    # sleeping it all would guarantee the deadline miss
                    # the retry exists to beat — the re-attempt must
                    # still fit. An exhausted budget skips the sleep
                    # and lets the loop's next pass raise.
                    delay = min(delay,
                                max(deadline - time.perf_counter(), 0.0)
                                * 0.5)
                if delay > 0:
                    time.sleep(delay)
            else:
                METRICS.observe("rpc.client.request",
                                time.perf_counter() - t0)
                return resp

    @staticmethod
    def _make_request_inner(ip_addr: str, port: int, request: JsonObj,
                            timeout: float) -> JsonObj:
        payload = json.dumps(request, separators=(",", ":")).encode()
        # Every transport failure surfaces as RpcError (a RuntimeError):
        # the reference throws boost::system::system_error, which IS-A
        # std::runtime_error, so its catch(runtime_error) recovery paths
        # absorb peers dying mid-request (client.cpp:51-96). A raw
        # ConnectionRefused/ResetError here would bypass every
        # `except RuntimeError` in the overlay and crash stabilize().
        try:
            with socket.create_connection((ip_addr, port),
                                          timeout=timeout) as sock:
                sock.sendall(payload)
                sock.shutdown(socket.SHUT_WR)
                sock.settimeout(timeout)
                chunks = []
                try:
                    while True:
                        chunk = sock.recv(65536)
                        if not chunk:
                            break
                        chunks.append(chunk)
                except socket.timeout:
                    raise RpcError("RPC reply timed out")
        except RpcError:
            raise
        except OSError as exc:
            raise RpcError(f"RPC transport failure: {exc}") from exc
        return parse_reply(b"".join(chunks).decode("utf-8", errors="replace"))

    @staticmethod
    def is_alive(ip_addr: str, port: int, timeout: float = 1.0) -> bool:
        """TCP connect probe (ref Client::IsAlive, client.cpp:98-112)."""
        try:
            with socket.create_connection((ip_addr, port), timeout=timeout):
                return True
        except OSError:
            return False


class Server:
    """Threaded request server (ref class Server, server.h:216-431)."""

    #: This server honors DeferredResponse handler returns (the native
    #: C++ server does not — its dispatch callback is synchronous).
    supports_deferred = True

    def __init__(self, port: int, handlers: Dict[str, Handler],
                 num_threads: int = 3, logging_enabled: bool = False,
                 host: str = "127.0.0.1"):
        self.port = port
        # Handler map is COPY-ON-WRITE: `_handlers` is only ever
        # REPLACED (never mutated in place) under `_handlers_lock`, so
        # worker threads read one immutable snapshot per request and a
        # hot handler install (the gateway's update_handlers while
        # traffic is in flight) can never expose a half-updated map or
        # let the membership check and the dispatch read disagree.
        self._handlers: Dict[str, Handler] = dict(handlers)
        self._handlers_lock = threading.Lock()
        self.logging_enabled = logging_enabled
        self.request_log = RequestLog()
        self._pool = ThreadPoolExecutor(max_workers=num_threads)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        if port == 0:
            self.port = self._sock.getsockname()[1]
        self._alive = True
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def run_in_background(self) -> None:
        """ref Server::RunInBackground (server.h:312-320)."""
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"rpc-server-{self.port}")
        self._accept_thread.start()

    def kill(self) -> None:
        """Close the acceptor and all in-flight sessions (ref Server::Kill,
        server.h:354-361). Deterministic: after kill() returns, the accept
        thread has exited and no socket owned by this server is open, so a
        connect probe gets an immediate refusal rather than racing a
        half-dead acceptor."""
        if not self._alive:
            return
        self._alive = False
        try:
            # shutdown() wakes a thread blocked in accept(2) — close()
            # alone does NOT on Linux (the blocked syscall pins the open
            # file description), which would leave a zombie accept that
            # consumes the first post-kill connect probe.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # ENOTCONN on some platforms; close still follows
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread is not None and \
                self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=DEFAULT_TIMEOUT_S)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                # shutdown(), not close(): close() from this thread leaves
                # a worker blocked in recv() (same accept(2) fact as above)
                # and frees the fd number for reuse by another server in
                # this process; shutdown() wakes the worker and lets its
                # own `with conn:` do the close.
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._pool.shutdown(wait=False)

    def install_signal_handlers(self) -> Callable[[], None]:
        """Kill this server gracefully on SIGINT/SIGTERM/SIGQUIT, then
        re-deliver the signal to the previous handler.

        The reference registers exactly these three signals on an asio
        signal_set at construction "so threads shut down gracefully"
        (server.h:244-248,278-280) — but never arms async_wait, so its
        registration only SWALLOWS the signals and nothing shuts down:
        dead code with a live comment. This implements the comment's
        intent instead, as a documented fix. Opt-in and main-thread-only
        (CPython restricts signal.signal to the main thread; peers in
        tests run dozens of servers per process, so constructor-time
        registration would be wrong here anyway). Returns a restore()
        callable that reinstates the previous handlers."""
        import signal as _signal

        prev = {}

        def _on_signal(signum, frame):
            self.kill()
            handler = prev.get(signum)
            if callable(handler):
                handler(signum, frame)
            elif handler != _signal.SIG_IGN:
                # SIG_DFL — or None, a C-level handler signal.signal
                # can neither call nor reinstall: fall through to the
                # default action so the signal is never swallowed.
                _signal.signal(signum, _signal.SIG_DFL)
                _signal.raise_signal(signum)

        for sig in (_signal.SIGINT, _signal.SIGTERM, _signal.SIGQUIT):
            prev[sig] = _signal.signal(sig, _on_signal)

        def restore() -> None:
            for sig, handler in prev.items():
                # None = C-level handler, not expressible to
                # signal.signal; SIG_DFL is the closest restorable state.
                _signal.signal(
                    sig, handler if handler is not None else _signal.SIG_DFL)

        return restore

    def is_alive(self) -> bool:
        return self._alive

    @property
    def handlers(self) -> Dict[str, Handler]:
        """The CURRENT handler-map snapshot. Read-only by contract:
        mutate via update_handlers (which swaps the reference whole) —
        in-place writes here would reintroduce the torn-read race the
        copy-on-write design removes."""
        return self._handlers

    def update_handlers(self, handlers: Dict[str, Handler]) -> None:
        """Register additional command handlers (peers construct the server
        first — the bound port feeds their id — then attach handlers).
        Safe while the server is LIVE: builds a merged copy and swaps
        the reference atomically, so concurrent _process dispatches see
        either the old complete map or the new complete map, never a
        mid-update hybrid (the gateway installs its handlers through
        here on servers already carrying traffic)."""
        with self._handlers_lock:
            merged = dict(self._handlers)
            merged.update(handlers)
            self._handlers = merged

    def get_log(self) -> List[JsonObj]:
        """ref Server::GetLog (server.h:399-402)."""
        return self.request_log.get_buffer()

    # -- internals ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # killed
            with self._conns_lock:
                self._conns.add(conn)
            try:
                self._pool.submit(self._serve_connection, conn)
            except RuntimeError:
                with self._conns_lock:
                    self._conns.discard(conn)
                conn.close()
                return  # pool shut down

    def _serve_connection(self, conn: socket.socket) -> None:
        deferred = False
        try:
            conn.settimeout(DEFAULT_TIMEOUT_S)
            chunks = []
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                chunks.append(chunk)
            raw = b"".join(chunks).decode("utf-8", errors="replace")
            resp: JsonObj
            req: Optional[JsonObj] = None
            try:
                req = json.loads(raw)
            except json.JSONDecodeError as exc:
                resp = {"SUCCESS": False, "ERRORS": str(exc)}
            else:
                if self.logging_enabled:
                    self.request_log.push_back(req)
                    # chordax-scope: the flight recorder subsumes the
                    # reference's 32-entry RequestLog — same opt-in
                    # flag, but the events land in the process-wide
                    # ring the HEALTH plane and dump-on-error read.
                    # Routine per-request chatter goes to the CHATTER
                    # ring so it can never evict incident events.
                    FLIGHT.record_routine(
                        "rpc", "request", port=self.port,
                        command=req.get("COMMAND", "")
                        if isinstance(req, dict) else "?")
                resp = self._process(req)
            if isinstance(resp, DeferredResponse):
                # Connection ownership moves to the deferred executor;
                # THIS worker is free for the next request (the nested
                # RPCs the deferred work issues may land right here).
                deferred = True
                try:
                    resp.executor.submit(self._finish_deferred, conn,
                                         req, resp.fn)
                except RuntimeError:
                    # Executor shut down (teardown race): finish
                    # inline — slower, but the caller still gets its
                    # reply and the connection never leaks.
                    self._finish_deferred(conn, req, resp.fn)
                return
            self._send_reply(conn, resp)
        except OSError:
            pass  # connection dropped; one-shot protocol, nothing to do
        finally:
            if not deferred:
                self._release_conn(conn)

    def _send_reply(self, conn: socket.socket, resp: JsonObj) -> None:
        conn.sendall(json.dumps(resp, separators=(",", ":")).encode())
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _release_conn(self, conn: socket.socket) -> None:
        with self._conns_lock:
            self._conns.discard(conn)
        try:
            conn.close()
        except OSError:
            pass

    def _finish_deferred(self, conn: socket.socket, req: JsonObj,
                         fn: Handler) -> None:
        """Run a deferred handler on its executor thread and complete
        the envelope + reply (the tail of _process/_serve_connection,
        off the worker pool)."""
        try:
            try:
                resp = fn(req) or {}
                resp["SUCCESS"] = True
            # chordax-lint: disable=bare-except -- reference envelope parity, the _process rule applied to deferred completion
            except Exception as exc:
                METRICS.inc("rpc.server.handler_error")
                FLIGHT.record("rpc", "handler_error", port=self.port,
                              command=req.get("COMMAND", "")
                              if isinstance(req, dict) else "?",
                              deferred=True, error=str(exc))
                resp = {"SUCCESS": False, "ERRORS": str(exc)}
            self._send_reply(conn, resp)
        except OSError:
            pass  # client went away; one-shot protocol
        finally:
            self._release_conn(conn)

    def _process(self, req: JsonObj) -> JsonObj:
        """Dispatch + envelope (ref Session::HandleRead/ProcessRequest,
        server.h:128-210), with structured metrics the reference lacks
        (SURVEY.md §5.1): per-command counters + dispatch latency.
        Everything including the COMMAND read stays inside the try so a
        valid-JSON non-object body ([1,2], "hi") still gets the
        SUCCESS:false envelope, as it did via the reference's
        exception-to-envelope path. Counter keys are bounded to KNOWN
        commands (peer-supplied garbage would otherwise grow the metrics
        dict without limit); unknown ones share one counter."""
        # ONE snapshot per request: the membership check (metrics key
        # bounding) and the dispatch must read the SAME map, or a
        # concurrent update_handlers swap between them miscounts — or
        # dispatches a handler the counter called invalid.
        handlers = self._handlers
        try:
            command = req.get("COMMAND", "")
            if command in handlers:
                METRICS.inc(f"rpc.server.command.{command}")
            else:
                METRICS.inc("rpc.server.invalid_command")
            with METRICS.timed("rpc.server.dispatch"):
                handler = handlers.get(command)
                if handler is None:
                    raise RuntimeError("Invalid command.")
                resp = self._dispatch_traced(handler, req, command)
            if isinstance(resp, DeferredResponse):
                # Envelope + send happen in _finish_deferred on the
                # deferred executor; the caller routes the connection.
                return resp
            resp["SUCCESS"] = True
            return resp
        # chordax-lint: disable=bare-except -- reference envelope parity: handler errors become SUCCESS:false (server.h:151-165)
        except Exception as exc:  # handler errors -> SUCCESS false
            METRICS.inc("rpc.server.handler_error")
            FLIGHT.record("rpc", "handler_error", port=self.port,
                          command=req.get("COMMAND", "")
                          if isinstance(req, dict) else "?",
                          error=str(exc))
            return {"SUCCESS": False, "ERRORS": str(exc)}

    def _dispatch_traced(self, handler: Handler, req: JsonObj,
                         command: str):
        """Run one handler, re-activating a wire-carried trace context
        (chordax-scope): the server span chains under the client's root
        span, and everything the handler does — gateway routing, engine
        submission — parents under the server span. Untraced requests
        (or tracing off) dispatch with zero extra work."""
        if trace_mod.enabled():
            ctx = trace_mod.TraceContext.from_wire(
                req.get(trace_mod.WIRE_KEY))
            if ctx is not None:
                with trace_mod.activate(ctx):
                    with trace_mod.span(f"rpc.server.{command}",
                                        cat="rpc", port=self.port) as sctx:
                        resp = handler(req) or {}
                        if isinstance(resp, DeferredResponse) \
                                and sctx is not None:
                            # The real work happens later on the
                            # deferred executor (another thread): carry
                            # the SERVER span's context there so the
                            # continuation's spans stay in this trace
                            # instead of orphaning into fresh ids.
                            resp = self._defer_traced(resp, sctx,
                                                      command)
                        return resp
        return handler(req) or {}

    def _defer_traced(self, resp: DeferredResponse,
                      sctx: "trace_mod.TraceContext",
                      command: str) -> DeferredResponse:
        """Wrap a deferred continuation so it re-activates the server
        span's trace context on the executor thread and records its own
        `rpc.server.<CMD>.deferred` span (the server span itself only
        covers the synchronous dispatch)."""
        inner = resp.fn

        def traced_fn(r):
            with trace_mod.activate(sctx):
                with trace_mod.span(f"rpc.server.{command}.deferred",
                                    cat="rpc", port=self.port):
                    return inner(r)

        return DeferredResponse(traced_fn, resp.executor)
